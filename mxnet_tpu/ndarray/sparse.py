"""Sparse NDArrays: ``row_sparse`` and ``csr`` storage types.

Reference: ``include/mxnet/ndarray.h:?`` (kRowSparseStorage/kCSRStorage),
``src/operator/tensor/cast_storage-inl.h:?``, sparse FComputeEx kernels in
``src/operator/tensor/dot.cc:?`` / ``elemwise_binary_op_basic.cc:?``.

TPU-native redesign: a RowSparseNDArray keeps ``(indices, values)`` as two
dense jax arrays — the exact layout the reference uses — so gather/scatter
ops lower to XLA dynamic-slice/scatter which TPU executes natively.  CSR
keeps (indptr, indices, data).  Dense bridges use jnp scatter/gather; the
BCOO interop (jax.experimental.sparse) is exposed via ``to_bcoo`` for ops
that want XLA's sparse matmul path.  This module covers the storage types +
conversion + the row_sparse paths the optimizer/kvstore need; the wider
sparse op algebra grows in later rounds (SURVEY §7 stage 8).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .ndarray import NDArray


def _as_index_array(indices):
    """Coerce indices to an int64 NDArray (the reference stores aux indices
    as int64; float inputs — e.g. ``nd.array([...])`` defaults — are cast)."""
    if isinstance(indices, NDArray):
        if np.issubdtype(indices.dtype, np.integer):
            return indices
        return NDArray(indices._data.astype(np.int64))
    return NDArray(np.asarray(indices).astype(np.int64))


class BaseSparseNDArray:
    @property
    def stype(self):
        raise NotImplementedError

    def asnumpy(self):
        return self.todense().asnumpy()

    def wait_to_read(self):
        return self


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair: values[i] is the dense row at indices[i].

    Reference: RowSparseNDArray (python/mxnet/ndarray/sparse.py:?).
    """

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indices = _as_index_array(indices)
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    def todense(self) -> NDArray:
        import jax.numpy as jnp

        idx = self.indices._data.astype(np.int32)
        out = jnp.zeros(self._shape, self.data.dtype)
        out = out.at[idx].set(self.data._data)
        return NDArray(out)

    tostype_dense = todense

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == "row_sparse":
            return self
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data.copy()
            other.indices = self.indices.copy()
            other._shape = self._shape
            return other
        return self.todense().copyto(other)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {'x'.join(map(str, self._shape))} "
                f"nnz-rows={self.indices.shape[0]}>")

    def retain(self, indices):
        """Keep only the requested rows (reference ``sparse.retain``)."""
        import jax.numpy as jnp

        want = indices._data if isinstance(indices, NDArray) else \
            jnp.asarray(indices)
        mask = jnp.isin(self.indices._data, want)
        keep = np.asarray(mask)
        idx = np.asarray(self.indices._data)[keep]
        vals = np.asarray(self.data._data)[keep]
        return RowSparseNDArray(NDArray(vals), NDArray(idx, dtype=np.int64),
                                self._shape)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (indptr, indices, data).

    Reference: CSRNDArray (python/mxnet/ndarray/sparse.py:?).
    """

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indices = _as_index_array(indices)
        self.indptr = _as_index_array(indptr)
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    def todense(self) -> NDArray:
        import jax.numpy as jnp

        indptr = np.asarray(self.indptr._data)
        cols = self.indices._data.astype(np.int32)
        nnz = cols.shape[0]
        # expand indptr to per-nnz row ids on host (indptr is host-small)
        rows = np.repeat(np.arange(self._shape[0]), np.diff(indptr))
        out = jnp.zeros(self._shape, self.data.dtype)
        out = out.at[jnp.asarray(rows), cols].set(self.data._data)
        return NDArray(out)

    def to_bcoo(self):
        """Bridge to jax.experimental.sparse BCOO for XLA sparse matmul —
        built straight from the CSR triplet (no densify round trip).

        Cached: the indptr expansion costs a blocking device→host read, and
        hot loops (FM training) hit the same CSR batch several times.  CSR
        batches are treated as immutable (reference NDArray CSR chunks
        likewise never mutate in place)."""
        cached = getattr(self, "_bcoo_cache", None)
        if cached is not None:
            return cached
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        indptr = np.asarray(self.indptr._data)
        rows = np.repeat(np.arange(self._shape[0]), np.diff(indptr))
        idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                         self.indices._data.astype(jnp.int32)], axis=1)
        self._bcoo_cache = jsparse.BCOO((self.data._data, idx),
                                        shape=self._shape)
        return self._bcoo_cache

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == "csr":
            return self
        raise MXNetError(f"cannot convert csr to {stype}")

    def __repr__(self):
        return (f"\n<CSRNDArray {'x'.join(map(str, self._shape))} "
                f"nnz={self.data.shape[0]}>")


# --- constructors (reference mx.nd.sparse.*) --------------------------------

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(NDArray(data, dtype=dtype),
                                NDArray(indices, dtype=np.int64), shape)
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(arg1, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(NDArray(data, dtype=dtype),
                          NDArray(indices, dtype=np.int64),
                          NDArray(indptr, dtype=np.int64), shape)
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(arg1, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(data, stype):
    """Reference ``cast_storage`` (cast_storage-inl.h:?)."""
    if stype == "default":
        if isinstance(data, BaseSparseNDArray):
            return data.todense()
        return data
    dense = data.asnumpy() if not isinstance(data, np.ndarray) else data
    if stype == "row_sparse":
        nz_rows = np.where(np.any(dense != 0,
                                  axis=tuple(range(1, dense.ndim))))[0]
        return RowSparseNDArray(NDArray(dense[nz_rows]),
                                NDArray(nz_rows.astype(np.int64)),
                                dense.shape)
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr requires 2D")
        indptr = [0]
        indices, vals = [], []
        for r in range(dense.shape[0]):
            cols = np.nonzero(dense[r])[0]
            indices.extend(cols.tolist())
            vals.extend(dense[r][cols].tolist())
            indptr.append(len(indices))
        return CSRNDArray(
            NDArray(np.asarray(vals, dtype=dense.dtype)),
            NDArray(np.asarray(indices, dtype=np.int64)),
            NDArray(np.asarray(indptr, dtype=np.int64)), dense.shape)
    raise MXNetError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    dt = dtype or np.float32
    if stype == "row_sparse":
        return RowSparseNDArray(NDArray(np.zeros((0,) + tuple(shape[1:]), dt)),
                                NDArray(np.zeros((0,), np.int64)), shape)
    if stype == "csr":
        return CSRNDArray(NDArray(np.zeros((0,), dt)),
                          NDArray(np.zeros((0,), np.int64)),
                          NDArray(np.zeros((shape[0] + 1,), np.int64)), shape)
    from . import zeros as dense_zeros

    return dense_zeros(shape, ctx=ctx, dtype=dtype)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot: csr × dense routes through BCOO (XLA sparse path);
    row_sparse densifies (reference FComputeEx dispatch,
    src/operator/tensor/dot.cc:?)."""
    from . import dot as dense_dot

    if isinstance(lhs, CSRNDArray) and not isinstance(rhs,
                                                      BaseSparseNDArray):
        bcoo = lhs.to_bcoo()
        raw = rhs._data
        if transpose_a:
            bcoo = bcoo.T
        out = bcoo @ (raw.T if transpose_b else raw)
        return NDArray(out)
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return dense_dot(l, r, transpose_a=transpose_a, transpose_b=transpose_b)
