"""Sparse NDArrays: ``row_sparse`` and ``csr`` storage types.

Reference: ``include/mxnet/ndarray.h:?`` (kRowSparseStorage/kCSRStorage),
``src/operator/tensor/cast_storage-inl.h:?``, sparse FComputeEx kernels in
``src/operator/tensor/dot.cc:?`` / ``elemwise_binary_op_basic.cc:?``.

TPU-native redesign: a RowSparseNDArray keeps ``(indices, values)`` as two
dense jax arrays — the exact layout the reference uses — so gather/scatter
ops lower to XLA dynamic-slice/scatter which TPU executes natively.  CSR
keeps (indptr, indices, data).  Dense bridges use jnp scatter/gather; the
BCOO interop (jax.experimental.sparse) is exposed via ``to_bcoo`` for ops
that want XLA's sparse matmul path.  The module covers the storage types,
``cast_storage`` across all stype pairs, the row_sparse optimizer/kvstore
paths, sparse ``dot``, and an FComputeEx-style elemwise algebra
(``dispatch_binary`` / ``dispatch_unary``, wired into the ``mx.nd``
elemwise surface): binary kernels stay sparse where the math allows
(union merge for ±, intersection for ×, stored-entry kernels against
dense/scalars) and fall back to densify otherwise — mirroring the
reference's storage-fallback behavior.

Index-set merges (union/intersection/searchsorted) run on HOST numpy —
they are data-dependent-shape operations that XLA cannot tile — while all
VALUE arithmetic stays on device.  Imperative-only, like the reference's
sparse NDArray surface: these ops do not record autograd tape.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .ndarray import NDArray


def _as_index_array(indices):
    """Coerce indices to an int64 NDArray (the reference stores aux indices
    as int64; float inputs — e.g. ``nd.array([...])`` defaults — are cast)."""
    if isinstance(indices, NDArray):
        if np.issubdtype(indices.dtype, np.integer):
            return indices
        return NDArray(indices._data.astype(np.int64))
    return NDArray(np.asarray(indices).astype(np.int64))


class BaseSparseNDArray:
    @property
    def stype(self):
        raise NotImplementedError

    def asnumpy(self):
        return self.todense().asnumpy()

    def wait_to_read(self):
        return self

    # arithmetic routes through the stype-dispatching nd elemwise ops
    # (sparse kernels where they exist, storage fallback otherwise)
    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(other, self)

    def __sub__(self, other):
        return subtract(self, other)

    def __rsub__(self, other):
        return subtract(other, self)

    def __mul__(self, other):
        return multiply(self, other)

    def __rmul__(self, other):
        return multiply(other, self)

    def __truediv__(self, other):
        return divide(self, other)

    def __rtruediv__(self, other):
        return divide(other, self)

    def __neg__(self):
        return _with_values(self, -self.data._data)


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair: values[i] is the dense row at indices[i].

    Reference: RowSparseNDArray (python/mxnet/ndarray/sparse.py:?).
    """

    def __init__(self, data, indices, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indices = _as_index_array(indices)
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    def todense(self) -> NDArray:
        import jax.numpy as jnp

        idx = self.indices._data.astype(np.int32)
        out = jnp.zeros(self._shape, self.data.dtype)
        out = out.at[idx].set(self.data._data)
        return NDArray(out)

    tostype_dense = todense

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == "row_sparse":
            return self
        return cast_storage(self, stype)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data.copy()
            other.indices = self.indices.copy()
            other._shape = self._shape
            return other
        return self.todense().copyto(other)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {'x'.join(map(str, self._shape))} "
                f"nnz-rows={self.indices.shape[0]}>")

    def retain(self, indices):
        """Keep only the requested rows (reference ``sparse.retain``)."""
        import jax.numpy as jnp

        want = indices._data if isinstance(indices, NDArray) else \
            jnp.asarray(indices)
        mask = jnp.isin(self.indices._data, want)
        keep = np.asarray(mask)
        idx = np.asarray(self.indices._data)[keep]
        vals = np.asarray(self.data._data)[keep]
        return RowSparseNDArray(NDArray(vals), NDArray(idx, dtype=np.int64),
                                self._shape)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (indptr, indices, data).

    Reference: CSRNDArray (python/mxnet/ndarray/sparse.py:?).
    """

    def __init__(self, data, indices, indptr, shape):
        self.data = data if isinstance(data, NDArray) else NDArray(data)
        self.indices = _as_index_array(indices)
        self.indptr = _as_index_array(indptr)
        self._shape = tuple(shape)

    @property
    def stype(self):
        return "csr"

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def context(self):
        return self.data.context

    def todense(self) -> NDArray:
        import jax.numpy as jnp

        cols = self.indices._data.astype(np.int32)
        # expand indptr to per-nnz row ids on host (indptr is host-small)
        rows = _csr_rows(self)
        out = jnp.zeros(self._shape, self.data.dtype)
        out = out.at[jnp.asarray(rows), cols].set(self.data._data)
        return NDArray(out)

    def to_bcoo(self):
        """Bridge to jax.experimental.sparse BCOO for XLA sparse matmul —
        built straight from the CSR triplet (no densify round trip).

        Cached: the indptr expansion costs a blocking device→host read, and
        hot loops (FM training) hit the same CSR batch several times.  CSR
        batches are treated as immutable (reference NDArray CSR chunks
        likewise never mutate in place)."""
        cached = getattr(self, "_bcoo_cache", None)
        if cached is not None:
            return cached
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse

        rows = _csr_rows(self)
        idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                         self.indices._data.astype(jnp.int32)], axis=1)
        self._bcoo_cache = jsparse.BCOO((self.data._data, idx),
                                        shape=self._shape)
        return self._bcoo_cache

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == "csr":
            return self
        return cast_storage(self, stype)

    def __repr__(self):
        return (f"\n<CSRNDArray {'x'.join(map(str, self._shape))} "
                f"nnz={self.data.shape[0]}>")


# --- constructors (reference mx.nd.sparse.*) --------------------------------

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(NDArray(data, dtype=dtype),
                                NDArray(indices, dtype=np.int64), shape)
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(arg1, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(NDArray(data, dtype=dtype),
                          NDArray(indices, dtype=np.int64),
                          NDArray(indptr, dtype=np.int64), shape)
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(arg1, dtype=dtype)
    return cast_storage(dense, "csr")


def _csr_rows(csr):
    """Per-nnz row ids (host int64) expanded from indptr."""
    indptr = np.asarray(csr.indptr._data)
    return np.repeat(np.arange(csr.shape[0], dtype=np.int64),
                     np.diff(indptr))


def _rows_to_indptr(rows, n_rows):
    """Sorted per-nnz row ids -> CSR indptr (host)."""
    return np.concatenate([[0], np.cumsum(
        np.bincount(rows, minlength=n_rows)).astype(np.int64)])


def cast_storage(data, stype):
    """Reference ``cast_storage`` (cast_storage-inl.h:?): convert between
    default/row_sparse/csr.  The nonzero PATTERN is fetched to host (a
    data-dependent-shape step XLA cannot express); the values are
    gathered on device."""
    import jax.numpy as jnp

    if stype == "default":
        if isinstance(data, BaseSparseNDArray):
            return data.todense()
        return data
    if isinstance(data, BaseSparseNDArray):
        if data.stype == stype:
            return data
        if isinstance(data, RowSparseNDArray) and stype == "csr":
            if len(data.shape) != 2:
                raise MXNetError("csr requires 2D")
            # rsp -> csr: each stored row contributes its nonzero cols.
            # Mask computed on DEVICE, only the bool pattern crosses to
            # host; values are gathered on device below.
            mask = np.asarray(data.data._data != 0)
            r_in, cols = np.nonzero(mask)
            rows = np.asarray(data.indices._data)[r_in]
            order = np.argsort(rows, kind="stable")
            flat = data.data._data.reshape(-1)
            take = jnp.asarray((r_in * data.shape[1] + cols)[order])
            return CSRNDArray(
                NDArray(jnp.take(flat, take)),
                NDArray(cols[order].astype(np.int64)),
                NDArray(_rows_to_indptr(rows[order], data.shape[0])),
                data.shape)
        if isinstance(data, CSRNDArray) and stype == "row_sparse":
            rows = _csr_rows(data)
            nz_rows = np.unique(rows)
            pos = np.searchsorted(nz_rows, rows)
            cols = np.asarray(data.indices._data)
            out = jnp.zeros((len(nz_rows), data.shape[1]),
                            data.data._data.dtype)
            out = out.at[jnp.asarray(pos), jnp.asarray(cols)].set(
                data.data._data)
            return RowSparseNDArray(NDArray(out), NDArray(nz_rows),
                                    data.shape)
        raise MXNetError(f"cannot cast {data.stype} to {stype}")
    raw = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    if stype == "row_sparse":
        mask = np.asarray(jnp.any(
            raw != 0, axis=tuple(range(1, raw.ndim))))  # small bool fetch
        nz_rows = np.where(mask)[0]
        return RowSparseNDArray(
            NDArray(jnp.take(raw, jnp.asarray(nz_rows), axis=0)),
            NDArray(nz_rows.astype(np.int64)), raw.shape)
    if stype == "csr":
        if raw.ndim != 2:
            raise MXNetError("csr requires 2D")
        mask = np.asarray(raw != 0)
        rows, cols = np.nonzero(mask)  # row-major order, rows sorted
        flat_idx = jnp.asarray(rows * raw.shape[1] + cols)
        return CSRNDArray(
            NDArray(jnp.take(raw.reshape(-1), flat_idx)),
            NDArray(cols.astype(np.int64)),
            NDArray(_rows_to_indptr(rows, raw.shape[0])), raw.shape)
    raise MXNetError(f"unknown stype {stype}")


def zeros(stype, shape, ctx=None, dtype=None):
    dt = dtype or np.float32
    if stype == "row_sparse":
        return RowSparseNDArray(NDArray(np.zeros((0,) + tuple(shape[1:]), dt)),
                                NDArray(np.zeros((0,), np.int64)), shape)
    if stype == "csr":
        return CSRNDArray(NDArray(np.zeros((0,), dt)),
                          NDArray(np.zeros((0,), np.int64)),
                          NDArray(np.zeros((shape[0] + 1,), np.int64)), shape)
    from . import zeros as dense_zeros

    return dense_zeros(shape, ctx=ctx, dtype=dtype)


# --- FComputeEx-style elemwise algebra --------------------------------------
# Reference: sparse FComputeEx kernels + storage-type fallback in
# src/operator/tensor/elemwise_binary_op_basic.cc:? and
# elemwise_unary_op_basic.cc:?.  Dispatch keys on (op, lhs stype, rhs
# stype); anything without a sparse kernel densifies, exactly like the
# reference's FallBackCompute.

#: unary ops with f(0) == 0: applying f to the stored values alone is
#: exact, so structure and indices are preserved.
_ZERO_PRESERVING = frozenset({
    "abs", "sign", "ceil", "floor", "rint", "round", "trunc", "fix",
    "sqrt", "cbrt", "square", "negative", "relu", "softsign", "sin",
    "tan", "arcsin", "arctan", "sinh", "tanh", "arcsinh", "arctanh",
    "expm1", "log1p", "erf", "erfinv", "degrees", "radians", "identity",
})


def _with_values(sp, new_vals):
    """Same structure, new stored values."""
    if isinstance(sp, RowSparseNDArray):
        return RowSparseNDArray(NDArray(new_vals), sp.indices, sp.shape)
    return CSRNDArray(NDArray(new_vals), sp.indices, sp.indptr, sp.shape)


def dispatch_unary(name, jf, data):
    """Sparse unary: zero-preserving ops map over stored values; others
    have dense output by definition → densify (storage fallback)."""
    if name in _ZERO_PRESERVING:
        return _with_values(data, jf(data.data._data))
    return NDArray(jf(data.todense()._data))


def _rsp_union(jf, a, b):
    """rsp ± rsp -> rsp over the UNION of stored rows (jf(x,0)=x-shaped
    ops: add/sub)."""
    import jax.numpy as jnp

    ia = np.asarray(a.indices._data)
    ib = np.asarray(b.indices._data)
    union = np.union1d(ia, ib)
    pa = np.searchsorted(union, ia)
    pb = np.searchsorted(union, ib)
    width = a.shape[1:]
    dt = np.promote_types(a.dtype, b.dtype)
    va = jnp.zeros((len(union),) + width, dt).at[jnp.asarray(pa)].set(
        a.data._data.astype(dt))
    vb = jnp.zeros((len(union),) + width, dt).at[jnp.asarray(pb)].set(
        b.data._data.astype(dt))
    return RowSparseNDArray(NDArray(jf(va, vb)), NDArray(union), a.shape)


def _rsp_intersection(jf, a, b):
    """rsp × rsp -> rsp over the INTERSECTION of stored rows (both-zero
    annihilating ops: multiply)."""
    import jax.numpy as jnp

    ia = np.asarray(a.indices._data)
    ib = np.asarray(b.indices._data)
    common, ca, cb = np.intersect1d(ia, ib, return_indices=True)
    va = jnp.take(a.data._data, jnp.asarray(ca), axis=0)
    vb = jnp.take(b.data._data, jnp.asarray(cb), axis=0)
    return RowSparseNDArray(NDArray(jf(va, vb)), NDArray(common), a.shape)


def _csr_coo_keys(csr):
    """Host flat coordinate keys (row-major) of the stored entries.
    Cached on the array (CSR batches are treated as immutable, same
    contract as ``to_bcoo``): the expansion costs a blocking
    device→host read that hot elemwise loops would otherwise pay per
    op per operand."""
    cached = getattr(csr, "_coo_keys_cache", None)
    if cached is not None:
        return cached
    rows = _csr_rows(csr)
    cols = np.asarray(csr.indices._data)
    csr._coo_keys_cache = rows * csr.shape[1] + cols
    return csr._coo_keys_cache


def _csr_from_keys(keys, vals, shape):
    rows = (keys // shape[1]).astype(np.int64)
    cols = (keys % shape[1]).astype(np.int64)
    return CSRNDArray(NDArray(vals), NDArray(cols),
                      NDArray(_rows_to_indptr(rows, shape[0])), shape)


def _csr_union(jf, a, b):
    import jax.numpy as jnp

    ka, kb = _csr_coo_keys(a), _csr_coo_keys(b)
    union = np.union1d(ka, kb)
    pa = np.searchsorted(union, ka)
    pb = np.searchsorted(union, kb)
    dt = np.promote_types(a.dtype, b.dtype)
    va = jnp.zeros((len(union),), dt).at[jnp.asarray(pa)].set(
        a.data._data.astype(dt))
    vb = jnp.zeros((len(union),), dt).at[jnp.asarray(pb)].set(
        b.data._data.astype(dt))
    return _csr_from_keys(union, jf(va, vb), a.shape)


def _csr_intersection(jf, a, b):
    import jax.numpy as jnp

    ka, kb = _csr_coo_keys(a), _csr_coo_keys(b)
    common, ca, cb = np.intersect1d(ka, kb, return_indices=True)
    va = jnp.take(a.data._data, jnp.asarray(ca))
    vb = jnp.take(b.data._data, jnp.asarray(cb))
    return _csr_from_keys(common, jf(va, vb), a.shape)


def _dense_on_tape(x):
    """True when ``x`` is a dense operand inside an active
    autograd.record() scope: the stored-entry kernels would silently
    sever its tape (sparse outputs carry no tape node), so dispatch
    must take the fallback — dense output through apply_op — to keep
    gradients correct.  Mirrors apply_op's recording check
    (ops/registry.py)."""
    from .. import autograd as ag
    from ..ops.registry import _in_graph

    return ag.is_recording() and _in_graph(x)


def _gather_dense_at(sp, dense_raw):
    """Values of ``dense_raw`` at the sparse array's stored coordinates."""
    import jax.numpy as jnp

    if isinstance(sp, RowSparseNDArray):
        return jnp.take(dense_raw, jnp.asarray(
            np.asarray(sp.indices._data)), axis=0)
    keys = _csr_coo_keys(sp)
    return jnp.take(dense_raw.reshape(-1), jnp.asarray(keys))


def dispatch_binary(name, jf, lhs, rhs):
    """FComputeEx dispatch for the elemwise binary family.

    Sparse kernels (everything else falls back to densify):
      rsp ± rsp -> rsp (union)        csr ± csr -> csr (union)
      rsp × rsp -> rsp (intersect)    csr × csr -> csr (intersect)
      sparse × dense -> sparse        sparse ÷ dense -> sparse
      (stored-entry kernels; same shape only)
      sparse × scalar, sparse ÷ scalar, sparse ± 0 -> sparse
    Division against dense/scalar is defined on the STORED entries (the
    implicit zeros stay zero), matching the reference's sparse division
    semantics rather than IEEE 0/0."""
    l_sp = isinstance(lhs, BaseSparseNDArray)
    r_sp = isinstance(rhs, BaseSparseNDArray)
    if l_sp and r_sp:
        if lhs.shape != rhs.shape or lhs.stype != rhs.stype:
            return _fallback_binary(jf, lhs, rhs)
        if name in ("add", "subtract"):
            merge = _rsp_union if lhs.stype == "row_sparse" else _csr_union
            return merge(jf, lhs, rhs)
        if name == "multiply":
            merge = (_rsp_intersection if lhs.stype == "row_sparse"
                     else _csr_intersection)
            return merge(jf, lhs, rhs)
        return _fallback_binary(jf, lhs, rhs)
    if l_sp and isinstance(rhs, NDArray):
        if name in ("multiply", "divide") and rhs.shape == lhs.shape:
            if not _dense_on_tape(rhs):
                vals = jf(lhs.data._data,
                          _gather_dense_at(lhs, rhs._data))
                return _with_values(lhs, vals)
            if name == "divide":
                # tape path must MATCH the stored-entry semantics
                # (implicit zeros stay zero — a plain dense 0/0 would
                # produce NaN at unstored coords and poison the loss;
                # explicit stored zeros behave as unstored here)
                import jax.numpy as jnp

                def mjf(s, d):
                    # double-where: a bare where(mask, s/d, 0) still
                    # evaluates s/d at 0/0 coords and its vjp turns
                    # 0*NaN into NaN gradients — sanitize d first
                    mask = s != 0
                    safe_d = jnp.where(mask, d, jnp.ones((), d.dtype))
                    return jnp.where(mask, jf(s, safe_d),
                                     jnp.zeros((), jnp.result_type(s, d)))
                return _fallback_binary(mjf, lhs, rhs)
        return _fallback_binary(jf, lhs, rhs)
    if r_sp and isinstance(lhs, NDArray):
        if name == "multiply" and lhs.shape == rhs.shape \
                and not _dense_on_tape(lhs):
            vals = jf(_gather_dense_at(rhs, lhs._data), rhs.data._data)
            return _with_values(rhs, vals)
        return _fallback_binary(jf, lhs, rhs)
    # sparse vs python scalar
    if l_sp and np.isscalar(rhs):
        if name in ("multiply", "divide") or \
                (name in ("add", "subtract") and rhs == 0):
            return _with_values(lhs, jf(lhs.data._data, rhs))
        return _fallback_binary(jf, lhs, rhs)
    if r_sp and np.isscalar(lhs):
        if name == "multiply" or (name == "add" and lhs == 0):
            return _with_values(rhs, jf(lhs, rhs.data._data))
        return _fallback_binary(jf, lhs, rhs)
    return _fallback_binary(jf, lhs, rhs)


def _fallback_binary(jf, lhs, rhs):
    """Storage fallback: densify sparse operands, dense output.  Routes
    through apply_op so a DENSE operand inside autograd.record() keeps
    its tape node (the densified sparse operand is a constant, like the
    reference's sparse fallback)."""
    from ..ops.registry import apply_op

    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    if isinstance(l, NDArray) and isinstance(r, NDArray):
        return apply_op(jf, l, r, name="sparse_fallback")
    if isinstance(l, NDArray):
        c = r
        return apply_op(lambda a: jf(a, c), l, name="sparse_fallback")
    c = l
    return apply_op(lambda b: jf(c, b), r, name="sparse_fallback")


def _ew(name):
    """The stype-dispatching nd-level elemwise op (lazy import: the ops
    module imports this one)."""
    from ..ops import elemwise as _e

    return getattr(_e, name)


def add(lhs, rhs):
    return _ew("add")(lhs, rhs)


def subtract(lhs, rhs):
    return _ew("subtract")(lhs, rhs)


def multiply(lhs, rhs):
    return _ew("multiply")(lhs, rhs)


def divide(lhs, rhs):
    return _ew("divide")(lhs, rhs)


def retain(data, indices):
    """Module-level ``mx.nd.sparse.retain`` (reference parity)."""
    return data.retain(indices)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot: csr × dense routes through BCOO (XLA sparse
    path); row_sparse densifies (reference FComputeEx dispatch,
    src/operator/tensor/dot.cc:?).

    Autograd: the DENSE operand's gradient flows (the BCOO matmul is
    routed through apply_op, and jax's BCOO rules supply the vjp wrt
    the dense side); the sparse operand is a constant — same contract
    as the sparse elemwise algebra."""
    from . import dot as dense_dot

    if isinstance(lhs, CSRNDArray) and not isinstance(rhs,
                                                      BaseSparseNDArray):
        from ..ops.registry import apply_op

        bcoo = lhs.to_bcoo()
        if transpose_a:
            bcoo = bcoo.T

        def f(r_raw):
            return bcoo @ (r_raw.T if transpose_b else r_raw)

        return apply_op(f, rhs, name="sparse_dot")
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return dense_dot(l, r, transpose_a=transpose_a, transpose_b=transpose_b)
