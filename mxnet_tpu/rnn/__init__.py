"""Top-level ``mx.rnn`` namespace — bucketing utilities + legacy cell
aliases.

Reference: ``python/mxnet/rnn/{io,rnn_cell}.py:?`` — ``BucketSentenceIter``
feeds ``BucketingModule`` (SURVEY §2.3 D8: bucketing is the reference's
whole sequence-length story); the legacy cell API predates gluon.rnn.

TPU notes: each bucket length is its own static shape → its own XLA
executable, exactly matching the reference's per-bucket bound executors
(bucketing_module.py).  Batches are padded INSIDE a bucket so shapes stay
static.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc
from ..ndarray import NDArray

# legacy cell names alias the gluon implementations (reference kept two
# parallel cell APIs; here one implementation serves both surfaces)
from ..gluon.rnn import (LSTMCell, GRUCell, RNNCell,  # noqa: F401
                         SequentialRNNCell)

__all__ = ["BucketSentenceIter", "LSTMCell", "GRUCell", "RNNCell",
           "SequentialRNNCell"]


class BucketSentenceIter:
    """Reference ``mx.rnn.BucketSentenceIter``: bucket variable-length
    token sequences by length; each batch comes from ONE bucket, padded
    to that bucket's length, with ``bucket_key`` for BucketingModule."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="int32",
                 layout="NT", seed=1):
        if not buckets:
            lengths = [len(s) for s in sentences]
            ladder = (8, 16, 32, 64, 128, 256, 512)
            fitting = [l for l in lengths if l <= ladder[-1]]
            if not fitting:
                raise MXNetError("no bucket can hold the given sentences")
            # smallest ladder entry covering the longest FITTING sentence
            # caps the ladder (default_bucket_key and its XLA executable
            # stay small); overlong sentences warn-and-discard below
            top = next(b for b in ladder if max(fitting) <= b)
            buckets = [b for b in ladder if b <= top]
        self.buckets = sorted(buckets)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self._dtype = np.dtype(dtype)
        # assign each sentence to the smallest bucket that fits
        self.data = [[] for _ in self.buckets]
        ndiscard = 0
        for s in sentences:
            idx = next((i for i, b in enumerate(self.buckets)
                        if len(s) <= b), None)
            if idx is None:
                ndiscard += 1
                continue
            buf = np.full((self.buckets[idx],), invalid_label, self._dtype)
            buf[:len(s)] = s
            self.data[idx].append(buf)
        if ndiscard:
            print(f"WARNING: discarded {ndiscard} sentences longer than "
                  f"the largest bucket")
        self.data = [np.asarray(x) for x in self.data]
        self.default_bucket_key = max(self.buckets)
        self._plan = []
        self._shuffled = [None] * len(self.buckets)
        # one RNG across resets: every epoch gets a fresh shuffle, whole
        # runs stay reproducible via `seed`
        self._rng = np.random.RandomState(seed)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key))]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self._plan = []
        rng = self._rng
        for i, arr in enumerate(self.data):
            if len(arr) == 0:
                continue
            order = rng.permutation(len(arr))
            self._shuffled[i] = arr[order]
            for lo in range(0, len(arr) - self.batch_size + 1,
                            self.batch_size):
                self._plan.append((i, lo))
        rng.shuffle(self._plan)
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._cursor >= len(self._plan):
            raise StopIteration
        i, lo = self._plan[self._cursor]
        self._cursor += 1
        buf = self._shuffled[i][lo:lo + self.batch_size]
        # label = data shifted one step left (language-model contract)
        label = np.full_like(buf, self.invalid_label)
        label[:, :-1] = buf[:, 1:]
        return DataBatch(
            data=[NDArray(buf)], label=[NDArray(label)], pad=0,
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, buf.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)])
