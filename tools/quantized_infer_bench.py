#!/usr/bin/env python
"""int8 end-to-end inference benchmark: quantized ResNet-18 vs bf16
(VERDICT r3 item 4 — int8 on the MXU as a deployed path, not a CPU test
fixture.  Reference: the quantization flow was a real inference
deployment path, src/operator/quantization/:? via MKLDNN/cuDNN).

Measures batched inference img/s for the SAME resnet18_v1:
  1. bf16 AMP, hybridized            (the baseline the README quotes)
  2. int8 via contrib quantize_net   (quantize->int8 conv/fc->dequantize
                                      chains, naive calibration)
plus top-1 agreement between the two on the benched batches (the
accuracy-proxy for synthetic weights).

Window protocol: hard host-fetch sync (bench.py's _hard_sync — through
the remote tunnel block_until_ready returns at dispatch).

Run: python tools/quantized_infer_bench.py  (env: BENCH_BATCH=64
BENCH_STEPS=50 BENCH_REPEATS=3 BENCH_PLATFORM=cpu for local smoke)
Prints one JSON line; the driver-facing artifact is OPPERF_r04.json's
int8 rows + the README line this feeds.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _win(fn, batch, steps, repeats):
    """bench.py's window protocol verbatim — ONE definition of the
    measurement (hard-sync best-of-N) so a protocol fix lands
    everywhere at once."""
    from bench import _best_window, _hard_sync

    _hard_sync(fn())  # compile + warm
    return _best_window(fn, batch, steps, repeats=repeats)[0]


def main():
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import amp, nd
    from mxnet_tpu.contrib import quantization as qz
    from mxnet_tpu.gluon.model_zoo import vision

    batch = int(os.environ.get("BENCH_BATCH", "64"))
    steps = int(os.environ.get("BENCH_STEPS", "50"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    image = int(os.environ.get("BENCH_IMAGE", "224"))

    mx.random.seed(0)
    x = mx.random.uniform(shape=(batch, 3, image, image))

    net = vision.get_model("resnet18_v1", classes=1000)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 3, 32, 32)))  # resolve deferred shapes
    amp.init(target_dtype="bfloat16")
    net.hybridize(static_alloc=True, static_shape=True)
    bf16_ips = _win(lambda: net(x), batch, steps, repeats)
    ref_top1 = np.argmax(net(x).asnumpy(), axis=-1)

    import tempfile

    qnet = vision.get_model("resnet18_v1", classes=1000)
    qnet.initialize(mx.init.Xavier())
    qnet(nd.ones((1, 3, 32, 32)))
    with tempfile.TemporaryDirectory() as td:
        pfile = os.path.join(td, "w.params")
        net.save_parameters(pfile)  # identical weights for both nets
        qnet.load_parameters(pfile)
    qz.quantize_net(qnet, calib_data=[x], calib_mode="naive")
    qnet.hybridize(static_alloc=True, static_shape=True)
    int8_ips = _win(lambda: qnet(x), batch, steps, repeats)
    q_top1 = np.argmax(qnet(x).asnumpy(), axis=-1)

    print(json.dumps({
        "metric": "resnet18_v1_infer_images_per_sec_per_chip",
        "bf16": round(bf16_ips, 2),
        "int8_quantized": round(int8_ips, 2),
        "int8_speedup": round(int8_ips / bf16_ips, 3),
        "top1_agreement": round(float((ref_top1 == q_top1).mean()), 4),
        "batch": batch,
        "aggregation": f"best_of_{repeats}x{steps}-step windows, "
                       "hard host-fetch sync",
    }))


if __name__ == "__main__":
    main()
