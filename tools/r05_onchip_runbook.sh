#!/bin/bash
# Round-5 on-chip sequence — run on an IDLE host the moment the tunnel
# is back (each step serial; dispatch is host-bound on 1 core).
# Usage: bash tools/r05_onchip_runbook.sh [outdir]
set -u
cd /root/repo
OUT=${1:-/tmp/r05_onchip}
mkdir -p "$OUT"
log() { echo "[runbook $(date +%H:%M:%S)] $*"; }

log "1/9 sync probe (device kind, dispatch-vs-completion, achievable peak)"
timeout 900 python tools/sync_probe.py > "$OUT/sync_probe.txt" 2>&1
cat "$OUT/sync_probe.txt"

log "2/9 bench.py (hard-sync protocol, synthetic + recordio + BERT)"
timeout 2400 python bench.py > "$OUT/bench.json" 2> "$OUT/bench.err"
cat "$OUT/bench.json"

log "3/9 on-chip parity lane (tests_tpu, derived MXU tolerances)"
MXT_TEST_TPU=1 MXT_TPU_PARITY_OUT=/root/repo/TPU_PARITY_r05.json \
  timeout 3600 python -m pytest tests_tpu/ -q > "$OUT/parity.txt" 2>&1
tail -3 "$OUT/parity.txt"

log "4/9 opperf (adaptive chains + int8 rows + bf16-bwd customvjp A/B)"
timeout 5400 python benchmark/opperf.py > /root/repo/OPPERF_r05.json \
  2> "$OUT/opperf.err"
tail -5 /root/repo/OPPERF_r05.json

log "5/9 quantized ResNet-18 inference e2e (int8 vs bf16)"
timeout 1800 python tools/quantized_infer_bench.py \
  > "$OUT/quantized_infer.json" 2> "$OUT/quantized_infer.err"
cat "$OUT/quantized_infer.json"

log "6/9 pallas conv fusion probe (fused 1x1conv+BN+ReLU vs XLA)"
timeout 1800 python tools/pallas_conv_probe.py \
  > "$OUT/pallas_probe.json" 2> "$OUT/pallas_probe.err"
cat "$OUT/pallas_probe.json"

log "7/9 llama 1.17B short re-measure (hard-sync tok/s)"
STEPS=60 LOG_EVERY=20 timeout 3600 python examples/train_llama_1b.py \
  > "$OUT/llama1b.txt" 2>&1
tail -3 "$OUT/llama1b.txt"

log "8/9 llama 1.17B scan_layers A/B (compile time + tok/s)"
SCAN_LAYERS=1 STEPS=60 LOG_EVERY=20 timeout 3600 \
  python examples/train_llama_1b.py > "$OUT/llama1b_scan.txt" 2>&1
tail -3 "$OUT/llama1b_scan.txt"

log "9/9 llama 1.17B pallas-flash-backward A/B (tok/s, kill-switch off)"
MXT_PALLAS_FLASH_BWD=0 STEPS=60 LOG_EVERY=20 timeout 3600 \
  python examples/train_llama_1b.py > "$OUT/llama1b_chunked_bwd.txt" 2>&1
tail -3 "$OUT/llama1b_chunked_bwd.txt"

log "runbook complete -> $OUT"
