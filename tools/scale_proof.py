#!/usr/bin/env python
"""Production-mesh scale proofs: AOT-compile REAL train steps on large
virtual device meshes and let XLA's memory analysis carry the HBM-fit
claim (VERDICT r3 item 5 — replaces hand byte-math as the load-bearing
number).

Two workloads:

- ``llama8b32``: Llama-3-8B full train step on a 32-virtual-device
  dp4 x tp8 mesh (the production v5e-32 layout the r3 artifact only
  byte-mathed), per-chip batch 2 x seq 4096, PER-LAYER remat, bf16
  params, f32 Adam moments, donated buffers.  LLAMA8B_LOWER_r04.json.
- ``mixtral``: Mixtral-8x7B (46.7B total, top-2 of 8 experts) full
  train step on a 64-virtual-device dp2 x ep8 x tp4 mesh, per-layer
  remat, bf16 params, f32 SGD momentum (Adam's f32 m+v cannot fit 16
  GiB at this scale — recorded in the artifact), topk router with
  fixed-capacity dispatch.  MIXTRAL_LOWER_r04.json.

No parameter array is ever materialized: parameters enter the jitted
step as ``jax.ShapeDtypeStruct`` avals sharded by the SAME partition
engine the real placement path uses (``parallel.PartitionRules`` family
tables — what ``Trainer(..., partition_rules=...)`` and ``shard_llama``
place with), so what compiles here is exactly what would run on the
slice.  The artifact records XLA's per-device memory analysis
(argument/temp/output bytes), the post-SPMD collective counts, and the
rule-coverage report of the placement.

Run: ``python tools/scale_proof.py llama8b32|mixtral [out.json]``
(self-contained: forces the virtual CPU device count before jax init).
"""
import json
import os
import re
import sys
import time

WORKLOADS = {
    "llama8b32": dict(n_devices=32, mesh={"dp": 4, "tp": 8},
                      tpu_topology="v5e:4x8"),
    "mixtral": dict(n_devices=64, mesh={"dp": 2, "ep": 8, "tp": 4},
                    tpu_topology="v5e:8x8"),
}

_DUMP_DIR = "/tmp/scale_proof_dump"

#: SP_BACKEND=tpu compiles against an OFFLINE libtpu topology client
#: (jax.experimental.topologies) instead of a virtual CPU mesh: the
#: memory analysis is then the REAL XLA:TPU buffer assignment — native
#: bf16 MXU dots, no CPU f32-upcast artifact, no correction term.
_BACKEND = os.environ.get("SP_BACKEND", "cpu")

def _apply_mesh_override(spec, which):
    """SP_MESH="dp=1,ep=8,tp=8" overrides THE SELECTED workload's mesh
    (the lever for mesh-change fit experiments).  The axis product must
    match the workload's device count — a silent fallback to the
    baseline mesh would emit a load-bearing fit artifact for the wrong
    config."""
    raw = os.environ.get("SP_MESH")
    if not raw:
        return
    m = {k: int(v) for k, v in
         (kv.split("=") for kv in raw.split(","))}
    prod = 1
    for v in m.values():
        prod *= v
    if prod != spec["n_devices"]:
        raise SystemExit(
            f"SP_MESH={raw!r}: axis product {prod} != {which}'s "
            f"n_devices {spec['n_devices']}")
    spec["mesh"] = m

if __name__ == "__main__":
    _w = sys.argv[1] if len(sys.argv) > 1 else "llama8b32"
    import shutil

    shutil.rmtree(_DUMP_DIR, ignore_errors=True)
    flags = f" --xla_dump_to={_DUMP_DIR}"
    if _BACKEND != "tpu":
        flags += (" --xla_force_host_platform_device_count="
                  f"{WORKLOADS[_w]['n_devices']}")
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + flags

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# The lowering machinery (shell params, scan-over-stacked-layers remat
# forward, memory harvest, CPU-upcast correction, verdict construction)
# lives in the library so the runtime HBM planner shares it; this tool
# is the CLI that turns it into committed artifacts.
from mxnet_tpu.memory.lowering import (  # noqa: E402
    LAYER0_PREFIX, cpu_upcast_artifact_bytes, fit_verdict,
    harvest_memory, remat_forward, shell_params)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "llama8b32"
    out_path = sys.argv[2] if len(sys.argv) > 2 else None
    spec = WORKLOADS[which]
    _apply_mesh_override(spec, which)

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel
    from mxnet_tpu.models import llama

    sp_layers = int(os.environ.get("SP_LAYERS", "0"))
    sp_attn = os.environ.get("SP_ATTN", "flash")

    t0 = time.time()
    if which == "llama8b32":
        net = llama.llama3_8b(attn_mode=sp_attn)
        if sp_layers:  # memory-scaling experiments only
            net = llama.LlamaForCausalLM(llama.LlamaConfig(
                **{**llama.LLAMA_CONFIGS["llama3_8b"],
                   "num_layers": sp_layers, "attn_mode": sp_attn}))
        optimizer = "adam_f32_moments"
        n_state = 2  # m, v
        per_chip_batch, seq = 2, 4096
    else:
        net = llama.mixtral_8x7b(attn_mode="flash")
        optimizer = "sgd_f32_momentum"
        n_state = 1  # momentum
        per_chip_batch, seq = 1, 4096
    # SP_BATCH overrides the PER-CHIP batch (global batch is still
    # per_chip_batch * dp) — used to hold the global workload fixed
    # across mesh experiments that change dp
    per_chip_batch = int(os.environ.get("SP_BATCH", per_chip_batch))
    cfg = net._cfg

    if _BACKEND == "tpu":
        from _tpu_topology import topology_mesh

        mesh = topology_mesh(spec["tpu_topology"], spec["mesh"])
    else:
        mesh = parallel.make_mesh(spec["mesh"])
    dp = spec["mesh"].get("dp", 1)
    batch = per_chip_batch * dp

    params, shapes, shells, n_params = shell_params(net)
    # the partition ENGINE derives every spec — the same family table
    # Trainer(partition_rules=...) places real arrays with; no specs
    # are hand-rolled in this tool
    from mxnet_tpu.parallel import partition as pt

    family = "mixtral" if which == "mixtral" else "llama"
    rules = pt.PartitionRules.for_family(family)
    coverage = pt.Coverage()
    pspecs = rules.specs(shapes, mesh, coverage=coverage)
    if cfg.tie_embeddings:
        pspecs.pop("lm_head.weight", None)
    # abstract step arguments: non-layer params by name, plus ONE
    # layer-stacked (L, ...) entry per layer-0 parameter (scan operand);
    # stacking shifts the layer-0 pspec right of an unsharded stack axis
    n_layers = cfg.num_layers
    abs_shapes, abs_specs = {}, {}
    for name, shp in shapes.items():
        if name.startswith("model.layers."):
            if not name.startswith(LAYER0_PREFIX):
                continue
            sfx = name[len(LAYER0_PREFIX):]
            abs_shapes["stacked_layers." + sfx] = (n_layers,) + shp
            abs_specs["stacked_layers." + sfx] = \
                pt.stacked_spec(pspecs.get(name, ()))
        else:
            abs_shapes[name] = shp
            abs_specs[name] = tuple(pspecs.get(name, ()))
    shard = {name: NamedSharding(mesh, P(*abs_specs[name]))
             for name in abs_shapes}

    # SP_* env knobs: memory-shape experiments (debugging what drives
    # XLA's temp_size); the committed artifact uses the defaults.
    no_remat = bool(int(os.environ.get("SP_NO_REMAT", "0")))
    remat_tier = "none" if no_remat else "layer"
    no_opt = bool(int(os.environ.get("SP_NO_OPT", "0")))
    ce_chunks = int(os.environ.get("SP_CE_CHUNKS", "0"))

    act_sharding = (None if int(os.environ.get("SP_NO_ACT_PIN", "0"))
                    else NamedSharding(mesh, P("dp", None, None)))

    def _ce(logits, labels_r):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels_r.astype(jnp.int32)[..., None], axis=-1)
        return nll.sum()

    def loss_fn(p_raws, ids_r, labels_r):
        if ce_chunks:
            # chunk the vocab-wide CE over the sequence axis so the
            # (B, T, V) f32 logits never exist whole: per chunk,
            # recompute head-projection + CE under jax.checkpoint
            h = remat_forward(net, shells, p_raws, ids_r,
                              head=False, remat=remat_tier,
                              act_sharding=act_sharding)
            w = (p_raws["model.embed_tokens.weight"]
                 if net._cfg.tie_embeddings
                 else p_raws["lm_head.weight"])

            def chunk_ce(hc, lc):
                return _ce(hc @ w.T, lc)

            total = 0.0
            t_len = h.shape[1]
            step = t_len // ce_chunks
            for c in range(ce_chunks):
                sl = slice(c * step, (c + 1) * step)
                total = total + jax.checkpoint(chunk_ce)(
                    h[:, sl], labels_r[:, sl])
            return total / (batch * seq)
        logits = remat_forward(net, shells, p_raws, ids_r,
                               remat=remat_tier,
                               act_sharding=act_sharding)
        return _ce(logits, labels_r) / (batch * seq)

    if no_opt:
        def train_step(p_raws, ids_r, labels_r):
            return jax.value_and_grad(loss_fn)(p_raws, ids_r, labels_r)

        donate = ()
        n_state = 0
    elif which == "llama8b32":
        def train_step(p_raws, m, v, ids_r, labels_r):
            loss, grads = jax.value_and_grad(loss_fn)(p_raws, ids_r,
                                                      labels_r)
            new_m = jax.tree.map(
                lambda mm, g: 0.9 * mm + 0.1 * g.astype(jnp.float32),
                m, grads)
            new_v = jax.tree.map(
                lambda vv, g: 0.999 * vv
                + 0.001 * jnp.square(g.astype(jnp.float32)), v, grads)
            new_p = jax.tree.map(
                lambda p, mm, vv: (
                    p.astype(jnp.float32) - 1e-4 * mm
                    / (jnp.sqrt(vv) + 1e-8)).astype(p.dtype),
                p_raws, new_m, new_v)
            return loss, new_p, new_m, new_v

        donate = (0, 1, 2)
    else:
        def train_step(p_raws, mom, ids_r, labels_r):
            loss, grads = jax.value_and_grad(loss_fn)(p_raws, ids_r,
                                                      labels_r)
            new_mom = jax.tree.map(
                lambda mm, g: 0.9 * mm - 1e-3 * g.astype(jnp.float32),
                mom, grads)
            new_p = jax.tree.map(
                lambda p, mm: (p.astype(jnp.float32)
                               + mm).astype(p.dtype),
                p_raws, new_mom)
            return loss, new_p, new_mom

        donate = (0, 1)

    abs_p = {n: jax.ShapeDtypeStruct(abs_shapes[n], jnp.bfloat16,
                                     sharding=shard[n])
             for n in abs_shapes}
    abs_s = {n: jax.ShapeDtypeStruct(abs_shapes[n], jnp.float32,
                                     sharding=shard[n])
             for n in abs_shapes}
    data_sharding = NamedSharding(mesh, P("dp", None))
    abs_ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                   sharding=data_sharding)

    with parallel.mesh_scope(mesh):
        jitted = jax.jit(train_step, donate_argnums=donate)
        state_args = (abs_s,) * n_state
        lowered = jitted.lower(abs_p, *state_args, abs_ids, abs_ids)
    lower_sec = time.time() - t0

    t1 = time.time()
    compiled = lowered.compile()
    compile_sec = time.time() - t1
    hlo = compiled.as_text()
    if _BACKEND == "tpu":
        # guard the load-bearing number: a sharding-plumbing regression
        # would silently compile CPU and skip the CPU-artifact
        # correction at the same time
        from _tpu_topology import assert_tpu_hlo

        assert_tpu_hlo(hlo, f"scale_proof {which}")
    collectives = {k: len(re.findall(k, hlo)) for k in
                   ("all-reduce", "collective-permute", "all-gather",
                    "reduce-scatter", "all-to-all")}
    mem = harvest_memory(compiled)

    cpu_artifact_b, cpu_artifact_slots = (0, []) if _BACKEND == "tpu" \
        else cpu_upcast_artifact_bytes(cfg.num_layers, _DUMP_DIR)

    verdict = fit_verdict(mem, _BACKEND, cpu_artifact_b,
                          cpu_artifact_slots)

    backend_desc = (
        f"{spec['n_devices']}-chip OFFLINE TPU topology "
        f"({spec['tpu_topology']}, libtpu AOT client; chunked-jnp "
        "attention — same O(T*block) memory profile as the pallas "
        "flash kernel, which gates on a live TPU backend)"
        if _BACKEND == "tpu" else
        f"{spec['n_devices']} virtual devices")
    artifact = {
        "proof": f"{which}: full train step AOT-compiled on "
                 f"{backend_desc} "
                 f"(mesh {spec['mesh']}), per-layer remat, no arrays "
                 "materialized — XLA memory analysis is the "
                 "load-bearing HBM-fit number",
        "backend": _BACKEND,
        "config": {"hidden": cfg.hidden_size, "layers": cfg.num_layers,
                   "heads": cfg.num_heads, "kv_heads": cfg.num_kv_heads,
                   "ffn": cfg.intermediate_size, "vocab": cfg.vocab_size,
                   "num_experts": cfg.num_experts,
                   "experts_per_tok": cfg.num_experts_per_tok,
                   "attn_mode": "flash"},
        "n_params": n_params,
        "partition_rules": family,
        "partition_coverage": coverage.summary(),
        "mesh": spec["mesh"],
        "n_devices": spec["n_devices"],
        "global_batch_x_seq": [batch, seq],
        "per_chip_batch": per_chip_batch,
        "param_dtype": "bfloat16",
        "optimizer": optimizer,
        "remat": ("none" if no_remat
                  else "per-decoder-layer jax.checkpoint"),
        "donated": "params + optimizer state",
        "lower_sec": round(lower_sec, 1),
        "compile_sec": round(compile_sec, 1),
        "spmd_collectives": collectives,
        "xla_memory_analysis_per_device": mem,
        "fit_verdict": verdict,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    blob = json.dumps(artifact, indent=1)
    print(blob)
    if out_path:
        with open(out_path, "w") as f:
            f.write(blob + "\n")


if __name__ == "__main__":
    main()
