#!/usr/bin/env python
"""Llama-3-8B scale proof, part (a): AOT-lower the TRUE 8B config's
dp×tp×sp train step on a virtual 8-device mesh and emit a committed
artifact (BASELINE config 5, SURVEY §7 step 12; VERDICT r2 missing #3).

No 8B array is ever materialized: parameters enter the jitted train step
as ``jax.ShapeDtypeStruct`` avals (every Llama parameter declares its
shape at construction), sharded by the SAME rule table the real
placement path uses (``models.llama.llama_param_pspecs``), so what
lowers here is exactly what would run on a v5e slice.  The step is a
full training step: forward (ring attention over ``sp``, megatron TP
matmuls), causal-LM cross-entropy, backward, and an Adam update with
f32 moments over bf16 parameters.

The artifact records: parameter count, the per-HLO collective counts
after SPMD partitioning (proof GSPMD actually derived the dp psum, tp
all-reduces and sp collective-permutes), XLA's own per-device memory
analysis when available, and the manual per-shard HBM byte math for the
lowering mesh AND a production v5e-32 (dp4×tp8) layout vs the 16 GiB
budget.

Run: ``python tools/llama8b_proof.py [out.json]`` (self-contained: forces
the virtual CPU mesh before jax init, like __graft_entry__'s dryrun).
"""
import json
import os
import re
import sys
import time

if __name__ == "__main__":
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(out_path=None):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel
    from mxnet_tpu.models import llama
    from mxnet_tpu.ndarray import NDArray

    MESH = {"dp": 2, "tp": 2, "sp": 2}
    BATCH, SEQ = 4, 4096
    P_DTYPE = jnp.bfloat16

    t0 = time.time()
    net = llama.llama3_8b(attn_mode="ring")
    cfg = net._cfg
    params = net._collect_params_with_prefix()
    shapes = {}
    for name, p in params.items():
        shape = tuple(int(s) for s in (p.shape or ()))
        assert shape and all(s > 0 for s in shape), \
            f"{name} shape not fully declared: {p.shape}"
        shapes[name] = shape
    n_params = sum(int(np.prod(s)) for s in shapes.values())

    mesh = parallel.make_mesh(MESH)
    pspecs = llama.llama_param_pspecs(net, mesh)
    shard = {name: NamedSharding(mesh, P(*pspecs.get(name, ())))
             for name in shapes}

    # shell NDArray handles: tracing swaps tracers into ._data, so the
    # parameters never need real storage (the CachedOp machinery's
    # handle-swap trick, gluon/block.py _CachedGraph._pure)
    shells = {}
    for name, p in params.items():
        a = NDArray.__new__(NDArray)
        a._data = None
        a._node = None
        a._oidx = 0
        a._req_grad = False
        a._grad = None
        a._grad_req = "null"
        p._data = a
        shells[name] = a

    def loss_fn(p_raws, ids_r, labels_r):
        for name, sh in shells.items():
            sh._data = p_raws[name]
        logits = net(NDArray(ids_r))._data  # (B, T, V)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, labels_r.astype(jnp.int32)[..., None], axis=-1)
        return nll.mean()

    def train_step(p_raws, m, v, ids_r, labels_r):
        loss, grads = jax.value_and_grad(loss_fn)(p_raws, ids_r,
                                                  labels_r)
        new_m = jax.tree.map(
            lambda mm, g: 0.9 * mm + 0.1 * g.astype(jnp.float32),
            m, grads)
        new_v = jax.tree.map(
            lambda vv, g: 0.999 * vv
            + 0.001 * jnp.square(g.astype(jnp.float32)), v, grads)
        new_p = jax.tree.map(
            lambda p, mm, vv: (p.astype(jnp.float32) - 1e-4 * mm
                               / (jnp.sqrt(vv) + 1e-8)).astype(p.dtype),
            p_raws, new_m, new_v)
        return loss, new_p, new_m, new_v

    abs_p = {n: jax.ShapeDtypeStruct(shapes[n], P_DTYPE,
                                     sharding=shard[n])
             for n in shapes}
    abs_m = {n: jax.ShapeDtypeStruct(shapes[n], jnp.float32,
                                     sharding=shard[n])
             for n in shapes}
    data_sharding = NamedSharding(mesh, P("dp", None))
    abs_ids = jax.ShapeDtypeStruct((BATCH, SEQ), jnp.int32,
                                   sharding=data_sharding)

    with parallel.mesh_scope(mesh):
        jitted = jax.jit(train_step)
        lowered = jitted.lower(abs_p, abs_m, abs_m, abs_ids, abs_ids)
    lower_sec = time.time() - t0
    stablehlo = lowered.as_text()

    t1 = time.time()
    compiled = lowered.compile()
    compile_sec = time.time() - t1
    hlo = compiled.as_text()
    collectives = {k: len(re.findall(k, hlo)) for k in
                   ("all-reduce", "collective-permute", "all-gather",
                    "reduce-scatter", "all-to-all")}
    mem = {}
    try:
        ma = compiled.memory_analysis()
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, k, None)
            if v is not None:
                mem[k] = int(v)
    except Exception as e:
        mem["unavailable"] = str(e)

    # manual per-shard HBM math for a production v5e-32 layout: dp4×tp8,
    # remat (layer-boundary activations only), bf16 params/grads, f32
    # Adam moments, per-chip batch 2 × seq 4096
    tp = 8
    b_local, seq = 2, 4096
    sharded = {n: s for n, s in shapes.items()
               if pspecs.get(n) and any(a == "tp" for a in pspecs[n])}
    p_shard = sum(int(np.prod(s)) // tp for n, s in sharded.items())
    p_repl = n_params - sum(int(np.prod(s)) for s in sharded.values())
    per_chip_params = p_shard + p_repl
    bf16_b = 2 * per_chip_params
    moments_b = 2 * 4 * per_chip_params
    act_b = cfg.num_layers * b_local * seq * cfg.hidden_size * 2
    logits_b = b_local * seq * cfg.vocab_size * 2 // tp
    budget = {
        "mesh": "v5e-32 dp4 x tp8",
        "per_chip_batch_x_seq": [b_local, seq],
        "params_bf16_gib": round(bf16_b / 2 ** 30, 2),
        "grads_bf16_gib": round(bf16_b / 2 ** 30, 2),
        "adam_moments_f32_gib": round(moments_b / 2 ** 30, 2),
        "remat_layer_activations_gib": round(act_b / 2 ** 30, 2),
        "logits_vocab_sharded_gib": round(logits_b / 2 ** 30, 2),
    }
    total = 2 * bf16_b + moments_b + act_b + logits_b
    budget["total_gib"] = round(total / 2 ** 30, 2)
    budget["hbm_budget_gib"] = 16.0
    budget["fits"] = bool(total < 16 * 2 ** 30)

    artifact = {
        "proof": "llama3-8b dp2xtp2xsp2 train step AOT lowering + SPMD "
                 "compile on 8 virtual devices (no arrays materialized)",
        "config": {"hidden": cfg.hidden_size, "layers": cfg.num_layers,
                   "heads": cfg.num_heads, "kv_heads": cfg.num_kv_heads,
                   "ffn": cfg.intermediate_size,
                   "vocab": cfg.vocab_size, "attn_mode": "ring"},
        "n_params": n_params,
        "lowering_mesh": MESH,
        "batch_seq": [BATCH, SEQ],
        "param_dtype": "bfloat16",
        "adam_moments_dtype": "float32",
        "lower_sec": round(lower_sec, 1),
        "compile_sec": round(compile_sec, 1),
        "stablehlo_bytes": len(stablehlo),
        "spmd_collectives": collectives,
        "xla_memory_analysis_per_device": mem,
        "v5e32_byte_math": budget,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    blob = json.dumps(artifact, indent=1)
    print(blob)
    if out_path:
        with open(out_path, "w") as f:
            f.write(blob + "\n")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
