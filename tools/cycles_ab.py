#!/usr/bin/env python
"""Compile-level A/B of the round-4 bf16-backward custom-vjp lever on
the REAL XLA:TPU cost model (offline topology client; VERDICT r4 #5's
measured half still needs the chip — this is the compiler's prediction
of it).

A: fwd+bwd of a Dense chain through ``nn_ops._mxu_matmul`` (dtype-
   preserving custom vjp — bf16 cotangents, f32 MXU accumulation).
B: the naive ``dot(pet=f32).astype(bf16)`` pattern — jax's derived vjp
   hands every backward dot an f32 cotangent: at the StableHLO level 4
   of 6 contractions are genuinely f32xf32.

FINDING (r5, revising the r4 expectation): XLA:TPU CANONICALIZES the
naive pattern — every contraction in its optimized TPU HLO consumes
bf16 operands (zero f32xf32 left; verified by operand-def dtype scan),
and cycles/bytes ratios come out 1.0.  The "3x MXU passes" hazard and
the -26%% bytes win (MFU_AUDIT_r04) were measured on CPU-backend
pricing, where the upcasts DO survive (and LICM hoists f32 stacks out
of scanned loops).  On the TPU backend the custom vjp is
compiler-predicted ~NEUTRAL for standalone chains; it remains the
right hygiene (the bf16 contract no longer depends on a backend
canonicalization) and the on-chip runbook A/B stays the final word.

The artifact records both sides' estimated_cycles/flops/bytes, the
ratios, and the post-optimization operand-dtype scan for the naive
side.  Writes one JSON blob to stdout (and argv[1] if given).
Single-process (libtpu lockfile).
"""
import json
import re
import sys


def main():
    import os

    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _tpu_topology import compile_tpu_checked, topology_mesh

    from mxnet_tpu.ops.nn_ops import _mxu_matmul

    mesh = topology_mesh("v5e:1x1")
    out = {"topology": "v5e:1x1 (offline libtpu AOT client)",
           "cases": {}}

    def naive_matmul(x, w):
        from jax import lax

        return lax.dot_general(
            x, w, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)

    def measure(name, mm, shapes):
        B, K, N = shapes

        def loss(x, w1, w2):
            h = mm(x, w1)
            y = mm(h, w2)
            return (y.astype(jnp.float32) ** 2).mean()

        fn = jax.value_and_grad(loss, argnums=(0, 1, 2))
        avals = [jax.ShapeDtypeStruct((B, K), jnp.bfloat16),
                 jax.ShapeDtypeStruct((N, K), jnp.bfloat16),
                 jax.ShapeDtypeStruct((N, N), jnp.bfloat16)]
        comp, hlo = compile_tpu_checked(fn, avals, mesh, what=name)
        ca = comp.cost_analysis() or {}
        from _tpu_topology import estimated_cycles_sum

        cycles, _n = estimated_cycles_sum(hlo, required=True)
        # post-optimization operand dtypes of every contraction: the
        # canonicalization evidence (defs keyed by FULL name)
        defs = dict(re.findall(r"%([\w.\-]+) = (\w+)\[", hlo))
        dtypes = []
        for m in re.finditer(
                r"= \w+\[[^\]]*\]\S* (?:convolution|dot)\(([^)]*)\)",
                hlo):
            ops = re.findall(r"%([\w.\-]+)", m.group(1))
            dtypes.append([defs.get(o) for o in ops])
        out["cases"][name] = {
            "estimated_cycles_sum": cycles,
            "flops": ca.get("flops"),
            "bytes_accessed": ca.get("bytes accessed"),
            "optimized_contraction_operand_dtypes": dtypes,
            "f32xf32_contractions": sum(
                1 for d in dtypes if d and all(t == "f32" for t in d)),
        }
        return cycles, ca.get("bytes accessed")

    # llama-1.17B-ish per-layer geometry: tokens x hidden @ (ffn, hidden)
    shapes = (8192, 2304, 6144)
    a_cyc, a_bytes = measure("customvjp_bf16_bwd", _mxu_matmul, shapes)
    b_cyc, b_bytes = measure("naive_pet_f32_astype", naive_matmul,
                             shapes)
    out["shapes_tokens_hidden_ffn"] = list(shapes)
    out["cycle_ratio_customvjp_vs_naive"] = round(a_cyc / b_cyc, 3)
    out["bytes_ratio_customvjp_vs_naive"] = (
        round(a_bytes / b_bytes, 3) if a_bytes and b_bytes else None)

    blob = json.dumps(out, indent=1)
    print(blob)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            f.write(blob + "\n")


if __name__ == "__main__":
    main()
