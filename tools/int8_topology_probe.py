#!/usr/bin/env python
"""Does XLA:TPU engage an int8 MXU path? (VERDICT r4 #6, HLO-evidence
half — the throughput half needs the live chip and lives in
benchmark/opperf.py int8 rows.)

Compiles int8xint8->int32 matmul and conv against an OFFLINE libtpu
v5e topology client (no tunnel needed).  CRITICAL mechanics: every aval
must carry a sharding over the TOPOLOGY's devices — bare avals compile
against the process's default CPU backend and the "TPU evidence" would
silently be CPU HLO (caught by review in r5).  TPU provenance is
asserted via the TPU-only tiled layouts (``{...:T(8,128)...}``) in the
optimized HLO.

Verdict signals, per case:
- ``native_s8_contraction``: an s32-output dot/convolution exists AND
  no ``convert`` widens an s8 operand anywhere in the module (on TPU
  the int8 matmul lowers to ``s32 convolution(s8, s8)`` through pure
  bitcast fusions, with the int8-packed ``T(8,128)(4,1)`` layout — 4
  bytes per 32-bit word);
- ``estimated_cycles``: XLA:TPU's own cost estimate from the fusion
  backend_config — comparing the int8 case against the bf16 control of
  the SAME shape shows whether the compiler prices int8 faster;
- the contraction HLO lines themselves, for the artifact.

Writes one JSON blob to stdout (and to argv[1] if given).
Single-process: libtpu holds a /tmp lockfile — don't run concurrently
with tools/scale_proof.py SP_BACKEND=tpu.
"""
import json
import re
import sys


def _dot_lines(hlo):
    keep = []
    for ln in hlo.splitlines():
        s = ln.strip()
        if re.search(r"= \S+ (dot|convolution)\(", s) or \
                re.search(r"= \S+ convert\(", s):
            keep.append(s[:200])
    return keep


def main():
    import os

    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _tpu_topology import compile_tpu_checked, topology_mesh

    mesh = topology_mesh("v5e:1x1")

    out = {"topology": "v5e:1x1 (offline libtpu AOT client)",
           "cases": {}}

    def probe(name, fn, *avals):
        comp, hlo = compile_tpu_checked(fn, avals, mesh, what=name)
        # keyed by FULL instruction name: stripping the .N suffix would
        # collapse same-named defs of different dtypes (%fusion.1 s32
        # vs %fusion.2 s8) and let the widening scan resolve a
        # convert's operand to the wrong dtype
        defs = dict(re.findall(r"%([\w.\-]+) = (\w+)\[", hlo))
        has_s32_contraction = bool(re.search(
            r"= s32\[[^\]]*\]\S* (?:dot|convolution)\(", hlo))
        # any convert that WIDENS an s8 value disqualifies nativeness
        widening_convert = False
        for m in re.finditer(
                r"= (\w+)\[[^\]]*\]\S* convert\(%([\w.\-]+)\)", hlo):
            to_t, op = m.group(1), m.group(2)
            if defs.get(op) == "s8" and to_t != "s8":
                widening_convert = True
        cycles = [int(c) for c in
                  re.findall(r'"estimated_cycles":"(\d+)"', hlo)]
        ca = comp.cost_analysis() or {}
        out["cases"][name] = {
            "native_s8_contraction": bool(
                has_s32_contraction and not widening_convert),
            "estimated_cycles": max(cycles) if cycles else None,
            "int8_packed_layout_T8_128_4_1": "(4,1)" in hlo,
            "contraction_hlo": _dot_lines(hlo)[:12],
            "flops": ca.get("flops"),
        }

    M = 512
    probe("int8_matmul_s32acc",
          lambda a, b: lax.dot_general(
              a, b, (((1,), (0,)), ((), ())),
              preferred_element_type=jnp.int32),
          jax.ShapeDtypeStruct((M, M), jnp.int8),
          jax.ShapeDtypeStruct((M, M), jnp.int8))
    probe("bf16_matmul_f32acc_control",
          lambda a, b: lax.dot_general(
              a, b, (((1,), (0,)), ((), ())),
              preferred_element_type=jnp.float32),
          jax.ShapeDtypeStruct((M, M), jnp.bfloat16),
          jax.ShapeDtypeStruct((M, M), jnp.bfloat16))
    probe("int8_conv_s32acc",
          lambda x, k: lax.conv_general_dilated(
              x, k, (1, 1), "SAME",
              dimension_numbers=("NHWC", "HWIO", "NHWC"),
              preferred_element_type=jnp.int32),
          jax.ShapeDtypeStruct((1, 28, 28, 64), jnp.int8),
          jax.ShapeDtypeStruct((3, 3, 64, 64), jnp.int8))

    i8 = out["cases"]["int8_matmul_s32acc"]["estimated_cycles"]
    bf = out["cases"]["bf16_matmul_f32acc_control"]["estimated_cycles"]
    if i8 and bf:
        out["int8_vs_bf16_matmul_cycle_ratio"] = round(i8 / bf, 3)

    blob = json.dumps(out, indent=1)
    print(blob)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            f.write(blob + "\n")


if __name__ == "__main__":
    main()
