"""Per-fusion HBM-traffic breakdown for a benched workload's train step.

The r5 measured BERT number (796 samp/s × 59.1 GB/step ÷ 64 ≈ 734 GB/s)
sits at ~90% of v5e HBM bandwidth (819 GB/s): the workload is
BANDWIDTH-bound, so the only lever left is cutting bytes/step.  This
tool says WHERE the bytes are: it lowers the same composed step
``tools/mfu_audit.py`` audits (net forward + bench loss + optimizer
update) against the offline XLA:TPU topology client, then walks the
optimized HLO's entry computation charging each fusion / custom-call /
copy the HBM bytes of its operands + result (VMEM-resident data inside
a fusion is free — fusion boundaries are exactly where HBM traffic
happens, which is why the per-instruction sum lands within ~15% of
``cost_analysis()['bytes accessed']``).

Usage:
    python tools/bytes_breakdown.py bert_base   [TOP=30] [BATCH=64]
    python tools/bytes_breakdown.py resnet50

Prints one JSON object: total bytes (instruction-walk vs cost_analysis
cross-check) and the TOP instructions by bytes with their shapes and
estimated cycles, so a bandwidth fix can be judged before it's written.

Runtime-registry mode: ``telemetry.costs.dump("COSTS.json")`` from an
instrumented run holds every executed artifact's bytes already;

    python tools/bytes_breakdown.py --from-registry COSTS.json

ranks those artifacts by ``bytes_accessed`` (with output/temp/argument
splits from ``memory_analysis``) instead of re-lowering and walking HLO
text.  A missing/empty dump falls back to the HLO-walk path above.
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_DTYPE_BYTES = {
    "pred": 1, "s4": 0.5, "u4": 0.5, "s8": 1, "u8": 1, "s16": 2,
    "u16": 2, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2": 1, "f8e5m2fnuz": 1, "f8e3m4": 1,
}

#: f8 variants first: ``[suf]\d+`` would stop at "f8" and miss the
#: exponent/mantissa suffix before the shape bracket
_SHAPE_RE = re.compile(
    r"\b(pred|f8e\w+|[suf]\d+|bf16|c64|c128)\[([\d,]*)\]")


def shape_bytes(type_str):
    """Total bytes of every array shape mentioned in an HLO type string
    (handles tuples by summing members).  Unknown dtypes charge 0 bytes
    instead of crashing the walk: an exotic type in one instruction
    should skew the breakdown, not kill it."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 0)
    return int(total)


def _split_result_type(rest):
    """Split an HLO instruction's result type from the op that follows.

    Tuple result types — ``(f32[8,128]{1,0}, s32[])`` — contain spaces
    and nest, so ``rest.split(" ", 1)`` truncates them after the first
    member; scan balanced parens instead so the whole type reaches
    ``shape_bytes``.  Returns ``(type_str, remainder)``."""
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rest[:i + 1], rest[i + 1:].lstrip()
        return rest, ""
    parts = rest.split(" ", 1)
    return parts[0], parts[1] if len(parts) > 1 else ""


def entry_breakdown(hlo):
    """[(name, bytes, cycles, result_type, op)] for the entry
    computation's traffic-bearing instructions."""
    # entry computation: ENTRY %name ... { ... }
    m = re.search(r"ENTRY [^{]+\{(.*?)\n\}", hlo, re.S)
    assert m, "no ENTRY computation found"
    body = m.group(1)
    # name -> result-type bytes for operand lookup
    sizes = {}
    lines = []
    for line in body.splitlines():
        line = line.strip()
        mm = re.match(r"(?:ROOT )?%?([\w.\-]+) = (.*)", line)
        if not mm:
            continue
        name, rest = mm.groups()
        type_str, after = _split_result_type(rest)
        sizes[name] = shape_bytes(type_str)
        lines.append((name, type_str, after))
    rows = []
    for name, type_str, after in lines:
        op_m = re.match(r"([\w\-]+)\(", after)
        op = op_m.group(1) if op_m else "?"
        if op in ("parameter", "constant", "tuple", "get-tuple-element",
                  "bitcast"):
            continue
        operands = re.findall(r"%([\w.\-]+)", after)
        nbytes = sizes.get(name, 0) + sum(
            sizes.get(o, 0) for o in set(operands) if o != name)
        cyc_m = re.search(r'"estimated_cycles":"(\d+)"', after)
        rows.append({
            "name": name,
            "op": op,
            "bytes": nbytes,
            "est_cycles": int(cyc_m.group(1)) if cyc_m else None,
            "result": type_str[:60],
        })
    rows.sort(key=lambda r: -r["bytes"])
    return rows


def registry_breakdown(payload, top=30):
    """Ranked artifact rows from a runtime cost-registry dump — the
    per-compiled-program analog of the per-instruction HLO walk."""
    rows = []
    for e in payload.get("entries", []):
        rows.append({
            "kind": e["kind"],
            # compile-site identity (newer dumps; absent in pre-site
            # registry files, which must keep parsing)
            "site": e.get("site"),
            "key": e.get("key", "")[:80],
            "bytes": float(e.get("bytes_accessed", 0.0) or 0.0),
            "output_bytes": e.get("output_bytes", 0),
            "temp_bytes": e.get("temp_bytes", 0),
            "argument_bytes": e.get("argument_bytes", 0),
            "flops": e.get("flops", 0.0),
            "executions": e.get("executions", 0),
        })
    rows.sort(key=lambda r: -r["bytes"])
    total = sum(r["bytes"] for r in rows)
    return {
        "source": "runtime cost registry",
        "device_kind": payload.get("device_kind"),
        "registry_bytes_accessed": total,
        "n_artifacts": len(rows),
        "top": [dict(r, gbytes=round(r["bytes"] / 1e9, 3))
                for r in rows[:top]],
    }


def main():
    argv = list(sys.argv[1:])
    top = int(os.environ.get("TOP", "30"))
    if "--from-registry" in argv:
        i = argv.index("--from-registry")
        path = argv[i + 1] if i + 1 < len(argv) else "COSTS.json"
        import mfu_audit

        payload = mfu_audit.load_registry(path)
        if payload is not None:
            print(json.dumps(registry_breakdown(payload, top), indent=1))
            return
        print(f"registry dump {path!r} missing or empty; falling back "
              "to the HLO-walk path", file=sys.stderr)
        del argv[i:i + 2]
    workload = argv[0] if argv else "bert_base"
    os.environ["AUDIT_PLATFORM"] = "tpu_topology"
    os.environ.setdefault("THROUGHPUT", "1")  # not used here

    import mfu_audit

    # reuse the workload composer but intercept the compiled object:
    # _cost is where the lowering happens; monkeypatch to capture HLO
    captured = {}
    orig_cost = mfu_audit._cost

    def capturing_cost(jfn, ap, ast, ins, lab):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(mfu_audit._topology_mesh(), P())
        args = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=repl),
            (ap, ast, ins, lab))
        compiled = jfn.lower(*args).compile()
        captured["hlo"] = compiled.as_text()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        captured["cost"] = {
            "flops": float(ca.get("flops", float("nan"))),
            "bytes_accessed": float(ca.get("bytes accessed",
                                           ca.get("bytes_accessed",
                                                  float("nan")))),
        }
        return dict(captured["cost"],
                    tpu_estimated_cycles_sum=0, tpu_estimated_fusions=0)

    mfu_audit._cost = capturing_cost
    # silence _emit's print (we produce our own JSON); restore it in the
    # finally so importing this module in-process (tests, notebooks)
    # doesn't leave mfu_audit permanently muted
    orig_emit = mfu_audit._emit
    mfu_audit._emit = lambda *a, **k: None
    try:
        getattr(mfu_audit, f"audit_{workload}")()
    finally:
        mfu_audit._cost = orig_cost
        mfu_audit._emit = orig_emit

    from _tpu_topology import assert_tpu_hlo

    hlo = captured["hlo"]
    assert_tpu_hlo(hlo, "bytes_breakdown")
    rows = entry_breakdown(hlo)
    walk_total = sum(r["bytes"] for r in rows)
    print(json.dumps({
        "workload": workload,
        "cost_analysis_bytes": captured["cost"]["bytes_accessed"],
        "entry_walk_bytes": walk_total,
        "walk_vs_cost": round(
            walk_total / max(captured["cost"]["bytes_accessed"], 1), 3),
        "n_instructions": len(rows),
        "top": [dict(r, gbytes=round(r["bytes"] / 1e9, 3))
                for r in rows[:top]],
    }, indent=1))


if __name__ == "__main__":
    main()
