"""Render compile/retrace provenance from retrace-sanitizer records.

The runtime recompile sanitizer (``mxnet_tpu.telemetry.retrace``) emits
ONE ``{"record": "retrace", ...}`` line per new compile at a registered
site — action ``"baseline"`` for warmup/first-signature compiles,
``"warn"``/``"raise"`` for post-warmup retraces, each violation
carrying the structural ``diff`` against its nearest prior signature
and the Python ``where`` both compiles were triggered from.  This tool
joins those records back into per-site timelines a human can read:

    # every site's signature timeline (violations flagged)
    python tools/retrace_report.py telemetry.jsonl

    # one site only (substring match on the site identity)
    python tools/retrace_report.py telemetry.jsonl --site trainer

    # violations only, with full diffs
    python tools/retrace_report.py telemetry.jsonl --violations

    # re-diff two observed signatures of one site by index
    python tools/retrace_report.py telemetry.jsonl \
        --site cachedop --diff 0 2

The ``--diff`` path reuses the sanitizer's own structural differ
(``retrace.diff_components``), whose canonicalizer tolerates the
JSON round-trip (tuples come back as lists).  Input may be a telemetry
JSONL stream (any record mix; only ``record == "retrace"`` lines are
used) or a flight-recorder dump whose incidents carry retrace
contexts.  ``load_records`` / ``timelines`` / ``render_site`` are
importable for tests.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.telemetry import retrace as _retrace


def load_records(path):
    """Every retrace record in ``path`` — a telemetry JSONL stream or a
    flight-recorder dump (incident contexts) — in file order."""
    with open(path, "r", encoding="utf-8") as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                doc = json.load(f)
            except json.JSONDecodeError:
                f.seek(0)
            else:
                if doc.get("record") == "flight_recorder":
                    # one incident dump = one triggering context
                    ctx = doc.get("context")
                    return [ctx] if isinstance(ctx, dict) and \
                        ctx.get("record") == "retrace" else []
                return [doc] if doc.get("record") == "retrace" else []
        out = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("record") == "retrace":
                out.append(rec)
    return out


def timelines(records, site=None):
    """Group records into per-site timelines, file order preserved:
    ``{site_identity: [record, ...]}``.  ``site`` filters by substring
    on the site identity.  Sites observed under several live instances
    (id(self) reuse across runs) keep one timeline per instance."""
    out = {}
    for rec in records:
        ident = rec.get("site") or rec.get("kind") or "?"
        if site is not None and site.lower() not in ident.lower():
            continue
        inst = rec.get("instance")
        out.setdefault((ident, inst), []).append(rec)
    # collapse the instance discriminator when a site has only one
    merged = {}
    singles = {}
    for (ident, inst), recs in out.items():
        singles.setdefault(ident, []).append(inst)
    for (ident, inst), recs in out.items():
        label = ident if len(singles[ident]) == 1 \
            else f"{ident} #{inst}"
        merged[label] = recs
    return merged


def _fmt_components(comps, limit=100):
    text = ", ".join(f"{k}={comps[k]!r}" for k in sorted(comps))
    return text if len(text) <= limit else text[:limit] + "..."


def render_site(label, recs, show_components=False):
    """ASCII timeline for one site: one line per compile, violations
    flagged with the per-component diff indented under them."""
    lines = [label]
    for rec in recs:
        action = rec.get("action", "?")
        mark = " " if action == "baseline" else "!"
        lines.append(
            "  %s sig #%-2s step %-4s %-8s %s"
            % (mark, rec.get("signature_index", "?"),
               rec.get("step", "?"), action, rec.get("where", "?")))
        if show_components and isinstance(rec.get("components"), dict):
            lines.append("      " + _fmt_components(rec["components"]))
        against = rec.get("against")
        if against:
            lines.append("      vs sig #%s [%s]:"
                         % (against.get("signature_index", "?"),
                            against.get("where", "?")))
        for d in rec.get("diff") or []:
            lines.append("        " + d)
    return "\n".join(lines)


def diff_by_index(recs, i, j):
    """Re-diff two observed signatures of one site's timeline using the
    sanitizer's structural differ (JSON lists canonicalize to tuples,
    so round-tripped avals still diff field-by-field)."""
    try:
        a, b = recs[i], recs[j]
    except IndexError:
        raise SystemExit(
            f"site has {len(recs)} signatures; --diff wants {i} and {j}")
    return _retrace.diff_components(a.get("components") or {},
                                    b.get("components") or {})


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-site compile/retrace timelines from telemetry "
                    "JSONL")
    ap.add_argument("path", help="telemetry JSONL stream or "
                                 "flight-recorder dump")
    ap.add_argument("--site", default=None,
                    help="case-insensitive substring filter on the "
                         "site identity")
    ap.add_argument("--violations", action="store_true",
                    help="only sites with post-warmup retraces")
    ap.add_argument("--components", action="store_true",
                    help="print each signature's full components")
    ap.add_argument("--diff", nargs=2, type=int, metavar=("I", "J"),
                    help="diff signature #I against #J of the selected "
                         "site (requires --site matching exactly one)")
    args = ap.parse_args(argv)

    records = load_records(args.path)
    if not records:
        print(f"no retrace records in {args.path!r}", file=sys.stderr)
        return 1
    lanes = timelines(records, site=args.site)

    if args.diff is not None:
        if len(lanes) != 1:
            print("--diff needs --site selecting exactly one site; "
                  f"matched {len(lanes)}: {sorted(lanes)}",
                  file=sys.stderr)
            return 1
        ((label, recs),) = lanes.items()
        i, j = args.diff
        diff = diff_by_index(recs, i, j)
        print(f"{label}: sig #{i} -> sig #{j}")
        for d in diff or ["<structurally equal>"]:
            print("  " + d)
        return 0

    shown = 0
    for label in sorted(lanes):
        recs = lanes[label]
        if args.violations and not any(
                r.get("action") != "baseline" for r in recs):
            continue
        print(render_site(label, recs, show_components=args.components))
        shown += 1
    if shown == 0:
        print("no matching sites"
              + (" with violations" if args.violations else ""),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
