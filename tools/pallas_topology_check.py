#!/usr/bin/env python
"""AOT-compile every pallas kernel in the framework for a REAL v5e
target via the offline libtpu topology client (no tunnel, no chips).

Purpose: de-risk the on-chip lane.  A mosaic lowering error would
otherwise only surface when real chip time is available (and burn it).
Each kernel must compile to TPU HLO (asserted via the TPU-only tiled
layouts) carrying a mosaic custom-call.  XLA's estimated_cycles for its
own reference implementation of the same computation is recorded where
available as the bar the kernel has to beat on chip (custom-calls carry
no XLA cycle estimate — timing is the runbook's job).

Kernels covered:
- flash-attention forward (ops/flash_attention._fa_forward_pallas)
- fused matmul+affine+ReLU conv probe
  (tools/pallas_conv_probe.fused_matmul_affine_relu)

Writes one JSON blob to stdout (and argv[1] if given).  Single-process
(libtpu lockfile).
"""
import json
import re
import sys


def main():
    import os

    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _tpu_topology import (compile_tpu_checked, count_mosaic_calls,
                               topology_mesh)

    mesh = topology_mesh("v5e:1x1")

    out = {"topology": "v5e:1x1 (offline libtpu AOT client)",
           "kernels": {}}

    def record(name, fn, avals, ref_fn=None):
        try:
            _comp, hlo = compile_tpu_checked(fn, avals, mesh, what=name)
            mosaic = count_mosaic_calls(hlo)
            # compiling without a mosaic kernel means the pallas path
            # silently degraded — that's a failure for a DE-RISK tool
            rec = {
                "tpu_compile_ok": mosaic > 0,
                "mosaic_custom_calls": mosaic,
            }
            if mosaic == 0:
                rec["error"] = "compiled but no tpu_custom_call in HLO"
        except Exception as e:
            rec = {"tpu_compile_ok": False,
                   "error": f"{type(e).__name__}: {e}"[:400]}
        if ref_fn is not None:
            try:
                _rc, rhlo = compile_tpu_checked(ref_fn, avals, mesh,
                                                what=name + "_ref")
                cyc = [int(c) for c in re.findall(
                    r'"estimated_cycles":"(\d+)"', rhlo)]
                rec["xla_reference_estimated_cycles_sum"] = sum(cyc)
            except Exception as e:
                rec["xla_reference_error"] = str(e)[:200]
        out["kernels"][name] = rec

    # flash attention forward, llama-8B head geometry at T=2048
    from mxnet_tpu.ops import flash_attention as fa

    B, H, T, D = 1, 8, 2048, 128
    qkv = [jax.ShapeDtypeStruct((B, H, T, D), jnp.bfloat16)] * 3
    scale = 1 / float(np.sqrt(D))
    record("flash_attention_fwd_bf16_T2048",
           lambda q, k, v: fa._fa_forward_pallas(q, k, v, True, scale),
           qkv,
           ref_fn=lambda q, k, v: fa._sdpa_ref(q, k, v, True, scale))

    # fused 1x1conv(matmul)+BN-affine+ReLU probe kernel
    from pallas_conv_probe import fused_matmul_affine_relu

    M, K, N = 4096, 256, 512  # 64x64 spatial x 256ch -> 512ch 1x1 conv
    avals = [jax.ShapeDtypeStruct((M, K), jnp.bfloat16),
             jax.ShapeDtypeStruct((K, N), jnp.bfloat16),
             jax.ShapeDtypeStruct((N,), jnp.float32),
             jax.ShapeDtypeStruct((N,), jnp.float32)]

    def xla_ref(x, w, s, b):
        y = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return jnp.maximum(y * s + b, 0.0).astype(x.dtype)

    record("fused_matmul_affine_relu_bf16",
           fused_matmul_affine_relu, avals, ref_fn=xla_ref)

    blob = json.dumps(out, indent=1)
    print(blob)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            f.write(blob + "\n")
    if not all(k["tpu_compile_ok"] for k in out["kernels"].values()):
        sys.exit(1)


if __name__ == "__main__":
    main()
