#!/usr/bin/env python
"""AOT-compile every pallas kernel in the framework for a REAL v5e
target via the offline libtpu topology client (no tunnel, no chips).

Purpose: de-risk the on-chip lane.  A mosaic lowering error would
otherwise only surface when real chip time is available (and burn it).
Each kernel must compile to TPU HLO (asserted via the TPU-only tiled
layouts) carrying a mosaic custom-call.  XLA's estimated_cycles for its
own reference implementation of the same computation is recorded where
available as the bar the kernel has to beat on chip (custom-calls carry
no XLA cycle estimate — timing is the runbook's job).

Kernels covered:
- flash-attention forward (ops/flash_attention._fa_forward_pallas)
- fused matmul+affine+ReLU conv probe
  (tools/pallas_conv_probe.fused_matmul_affine_relu)

Writes one JSON blob to stdout (and argv[1] if given).  Single-process
(libtpu lockfile).
"""
import json
import re
import sys


def main():
    import os

    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _tpu_topology import (assert_tpu_hlo, compile_tpu_checked,
                               count_mosaic_calls, topology_mesh)

    mesh = topology_mesh("v5e:1x1")

    out = {"topology": "v5e:1x1 (offline libtpu AOT client)",
           "kernels": {}}

    def record(name, fn, avals, ref_fn=None):
        try:
            _comp, hlo = compile_tpu_checked(fn, avals, mesh, what=name)
            mosaic = count_mosaic_calls(hlo)
            # compiling without a mosaic kernel means the pallas path
            # silently degraded — that's a failure for a DE-RISK tool
            rec = {
                "tpu_compile_ok": mosaic > 0,
                "mosaic_custom_calls": mosaic,
            }
            if mosaic == 0:
                rec["error"] = "compiled but no tpu_custom_call in HLO"
        except Exception as e:
            rec = {"tpu_compile_ok": False,
                   "error": f"{type(e).__name__}: {e}"[:400]}
        if ref_fn is not None:
            try:
                _rc, rhlo = compile_tpu_checked(ref_fn, avals, mesh,
                                                what=name + "_ref")
                cyc = [int(c) for c in re.findall(
                    r'"estimated_cycles":"(\d+)"', rhlo)]
                rec["xla_reference_estimated_cycles_sum"] = sum(cyc)
            except Exception as e:
                rec["xla_reference_error"] = str(e)[:200]
        out["kernels"][name] = rec

    # flash attention forward, llama-8B head geometry at T=2048
    from mxnet_tpu.ops import flash_attention as fa

    B, H, T, D = 1, 8, 2048, 128
    qkv = [jax.ShapeDtypeStruct((B, H, T, D), jnp.bfloat16)] * 3
    scale = 1 / float(np.sqrt(D))
    record("flash_attention_fwd_bf16_T2048",
           lambda q, k, v: fa._fa_forward_pallas(q, k, v, True, scale),
           qkv,
           ref_fn=lambda q, k, v: fa._sdpa_ref(q, k, v, True, scale))

    # flash backward: the two-kernel dq/dkv design (forward saves lse)
    lse_aval = jax.ShapeDtypeStruct((B, H, T), jnp.float32)
    record("flash_attention_bwd_bf16_T2048",
           lambda q, k, v, o, g, lse: fa._fa_backward_pallas(
               q, k, v, o, g, lse, True, scale),
           qkv + [jax.ShapeDtypeStruct((B, H, T, D), jnp.bfloat16)] * 2
           + [lse_aval])

    # fused 1x1conv(matmul)+BN-affine+ReLU probe kernel
    from pallas_conv_probe import fused_matmul_affine_relu

    M, K, N = 4096, 256, 512  # 64x64 spatial x 256ch -> 512ch 1x1 conv
    avals = [jax.ShapeDtypeStruct((M, K), jnp.bfloat16),
             jax.ShapeDtypeStruct((K, N), jnp.bfloat16),
             jax.ShapeDtypeStruct((N,), jnp.float32),
             jax.ShapeDtypeStruct((N,), jnp.float32)]

    def xla_ref(x, w, s, b):
        y = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return jnp.maximum(y * s + b, 0.0).astype(x.dtype)

    record("fused_matmul_affine_relu_bf16",
           fused_matmul_affine_relu, avals, ref_fn=xla_ref)

    # sequence-parallel routes on a 4-chip sp mesh, flash forced on:
    # - ULYSSES reaches the flash kernel INSIDE the shard_map body
    #   (sdpa_raw after the head/seq all-to-all) — the exact scenario
    #   whose nested-shard_map ValueError round-5 review repro'd
    #   pre-fix, so a mosaic call is REQUIRED here;
    # - RING never engages the kernel by design (its per-rotation
    #   online-softmax einsum body IS the attention), so its entry is
    #   compile-success + collective-permute count only.
    os.environ["MXT_FORCE_PALLAS_FLASH"] = "1"
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu.parallel import mesh_scope
    from mxnet_tpu.parallel.ring import (ring_attention_raw,
                                         ulysses_attention_raw)

    sp_mesh = topology_mesh("v5e:2x2", {"sp": 4})
    # *_attention_raw take the llama head layout (B, H, T, D) and
    # shard T over the ring internally
    sp_shard = NamedSharding(sp_mesh, P(None, None, "sp", None))
    B, N, T, H = 1, 8, 2048, 128

    def sp_case(name, fn, mosaic_required, collective):
        """``collective``: (hlo_opcode, min_count) that PROVES the
        sequence-parallel route engaged — a silent fallback (axis
        rename, spec drift) otherwise compiles fine with zero
        collectives and would record a vacuous pass."""
        try:
            with mesh_scope(sp_mesh):
                shaped = [jax.ShapeDtypeStruct(
                    (B, N, T, H), jnp.bfloat16,
                    sharding=sp_shard)] * 3
                comp = jax.jit(fn).lower(*shaped).compile()
            hlo = comp.as_text()
            assert_tpu_hlo(hlo, what=name)
            mosaic = count_mosaic_calls(hlo)
            # count instruction DEFINITIONS (one per op; async pairs
            # count the -start only; async ops have TUPLE types with
            # spaces between '=' and the opcode) — a bare substring
            # count would also hit every USE of an %all-to-all.N name
            counts = {
                op: len(re.findall(
                    rf"= .* {op}(?:-start)?\(", hlo))
                for op in ("collective-permute", "all-to-all")}
            op, need = collective
            ok = counts[op] >= need and \
                (mosaic > 0 if mosaic_required else True)
            rec = {
                "tpu_compile_ok": ok,
                "mosaic_custom_calls": mosaic,
                "collective_permutes": counts["collective-permute"],
                "all_to_alls": counts["all-to-all"],
            }
            if not ok:
                rec["error"] = (
                    f"compiled but route degraded: {counts[op]} "
                    f"{op} (need >= {need}), {mosaic} mosaic calls"
                    f" (required: {mosaic_required})")
        except Exception as e:
            rec = {"tpu_compile_ok": False,
                   "error": f"{type(e).__name__}: {e}"[:400]}
        out["kernels"][name] = rec

    sp_case("ulysses_attention_sp4_flash",
            lambda q, k, v: ulysses_attention_raw(
                q, k, v, causal=True, mesh=sp_mesh),
            mosaic_required=True, collective=("all-to-all", 4))
    sp_case("ring_attention_sp4",
            lambda q, k, v: ring_attention_raw(
                q, k, v, causal=True, mesh=sp_mesh),
            mosaic_required=False,
            collective=("collective-permute", 2))

    # multi-axis mesh: operand vma ({'sp'} or {'dp','sp'}) is a strict
    # subset story — the kernel's out_shape must declare the OPERANDS'
    # axes, not all manual axes (review-caught over-claim)
    sp_mesh = topology_mesh("v5e:2x4", {"dp": 2, "sp": 4})
    sp_shard = NamedSharding(sp_mesh, P("dp", None, "sp", None))
    B = 2
    sp_case("ulysses_attention_dp2xsp4_flash",
            lambda q, k, v: ulysses_attention_raw(
                q, k, v, causal=True, mesh=sp_mesh),
            mosaic_required=True, collective=("all-to-all", 4))

    # the full sequence-parallel TRAIN direction: value_and_grad of the
    # ulysses loss — the flash custom-vjp backward (two mosaic kernels)
    # runs INSIDE the shard_map body, a2a count doubles (fwd q/k/v/out
    # + bwd cotangent trades)
    sp_mesh = topology_mesh("v5e:2x2", {"sp": 4})
    sp_shard = NamedSharding(sp_mesh, P(None, None, "sp", None))
    B = 1  # sp_case reads the geometry globals at call time — restore
    # the sp4 forward case's shapes so the recorded numbers compare

    def ulysses_loss_grad(q, k, v):
        return jax.value_and_grad(
            lambda a, b, c: (ulysses_attention_raw(
                a, b, c, causal=True, mesh=sp_mesh)
                .astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)

    sp_case("ulysses_sp4_value_and_grad_flash", ulysses_loss_grad,
            mosaic_required=True, collective=("all-to-all", 8))

    blob = json.dumps(out, indent=1)
    print(blob)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            f.write(blob + "\n")
    if not all(k["tpu_compile_ok"] for k in out["kernels"].values()):
        sys.exit(1)


if __name__ == "__main__":
    main()
