#!/usr/bin/env python
"""Multi-host launcher for distributed training.

Reference: ``tools/launch.py:?`` → dmlc tracker (``3rdparty/dmlc-core/
tracker/dmlc_tracker/{local,ssh,...}.py``) spawning scheduler + servers +
workers with ``DMLC_ROLE``/``DMLC_PS_ROOT_URI`` env (SURVEY §2.3 D11).

TPU-native redesign: there are no scheduler/server roles — every host runs
the SAME script and ``jax.distributed.initialize`` (driven by
``mxnet_tpu.parallel.initialize``) forms the process group over the
coordinator address; collectives ride ICI/DCN, not ZMQ.  This launcher
keeps the reference's CLI shape (``launch.py -n N python train.py``) for
script compatibility:

- ``--launcher local`` forks N processes on this machine with
  ``MXT_COORDINATOR``/``MXT_NUM_PROCESSES``/``MXT_PROCESS_ID`` set —
  the loopback test topology (the reference's ``--launcher local`` analog,
  used by the distributed tests, SURVEY §4);
- ``--launcher ssh`` SPAWNS one ssh per rank (round-robin over the
  hostfile), same as the reference's dmlc ssh tracker — with the env
  contract exported on the remote shell and the per-job secret delivered
  over ssh's stdin so it never appears in argv, logs, or shell history.
  ``--dry-run`` restores emit-only mode (one command per line, secret
  referenced as ``${MXT_PS_SECRET:?...}`` for an external runner);
  ``MXT_SSH`` overrides the ssh binary (pluggable spawner — the loopback
  test substitutes a local stub, and GKE/xpk-style runners can slot in a
  pod exec).

Every launch mints one ``MXT_PS_SECRET`` shared across ranks: the
dist_async parameter server HMAC-signs its frames with it (see
``mxnet_tpu/kvstore/dist_async.py``).
"""
from __future__ import annotations

import argparse
import os
import secrets
import shlex
import subprocess
import sys


def _spawn_group(n, cmd, coordinator, ps_secret, attempt):
    procs = []
    try:
        for rank in range(n):
            env = dict(os.environ)
            env.update({
                "MXT_COORDINATOR": coordinator,
                "MXT_NUM_PROCESSES": str(n),
                "MXT_PROCESS_ID": str(rank),
                "MXT_PS_SECRET": ps_secret,
                "MXT_LAUNCH_ATTEMPT": str(attempt),
                # loopback test topology runs every process on CPU
                "JAX_PLATFORMS": env.get("MXT_LAUNCH_PLATFORM", "cpu"),
            })
            procs.append(subprocess.Popen(cmd, env=env))
    except OSError:
        # partial group (EMFILE/EAGAIN mid-spawn): reap what spawned or
        # the orphans wait at the coordinator forever
        _reap(procs)
        raise
    return procs


def _reap(procs, grace=10.0):
    """SIGTERM the group, then SIGKILL stragglers after ``grace``.
    A rank blocked inside a collective may never run its SIGTERM
    handler — the hard kill is not optional."""
    import time

    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.time() + grace
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            try:
                p.kill()
                p.wait()
            except OSError:
                pass


def _wait_group(procs, poll_s=0.2):
    """Wait for all ranks; on the FIRST nonzero exit, reap the rest and
    return that rc.  Failure detection is what the reference's tracker
    gave for free (a dead dmlc worker tears down the job): without it, a
    surviving rank blocks forever inside its next collective waiting for
    the dead peer, and the job wedges instead of failing."""
    import time

    while True:
        live = 0
        for p in procs:
            rc = p.poll()
            if rc is None:
                live += 1
            elif rc != 0:
                _reap(procs)
                return rc
        if live == 0:
            return 0
        time.sleep(poll_s)


def launch_local(n, cmd, coordinator="127.0.0.1:12721", max_restarts=0):
    """Fork n local ranks and babysit them.

    On any rank's nonzero exit the whole group is reaped (failure
    detection).  ``max_restarts`` > 0 then relaunches the full group —
    ranks are expected to resume from their latest checkpoint
    (mxnet_tpu.checkpoint.resume), which
    tests/test_fault_injection.py proves reconverges to the
    uninterrupted run."""
    ps_secret = os.environ.get("MXT_PS_SECRET") or secrets.token_hex(16)
    attempt = 0
    while True:
        procs = _spawn_group(n, cmd, coordinator, ps_secret, attempt)
        rc = _wait_group(procs)
        if rc == 0 or attempt >= max_restarts:
            return rc
        attempt += 1
        print(f"launch.py: group failed (rc={rc}); "
              f"restart {attempt}/{max_restarts}", file=sys.stderr)


def emit_ssh(hosts, n, cmd, coordinator):
    # The secret is NOT embedded (emitted lines land in logs / shell
    # history / remote argv): the single-quoted ${...:?} expands on the
    # REMOTE shell, so the runner must export MXT_PS_SECRET on each host
    # out-of-band, and the command fails loudly if it is missing.
    lines = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        envs = (f"MXT_COORDINATOR={coordinator} MXT_NUM_PROCESSES={n} "
                f"MXT_PROCESS_ID={rank} "
                'MXT_PS_SECRET="${MXT_PS_SECRET:?export a shared '
                'MXT_PS_SECRET on each host}"')
        lines.append(f"ssh {host} '{envs} {' '.join(cmd)}'")
    return lines


def launch_ssh(hosts, n, cmd, coordinator):
    """Spawn one ssh per rank and wait (the dmlc ssh tracker analog).

    The per-job secret is piped to each remote's STDIN (``read -r`` on
    the far side), keeping it out of ssh argv — the round-2 security
    stance — while still making the launch one command end to end.
    ``MXT_SSH`` swaps the transport (e.g. a test stub or a pod exec)."""
    ssh = shlex.split(os.environ.get("MXT_SSH", "ssh"))
    ps_secret = os.environ.get("MXT_PS_SECRET") or secrets.token_hex(16)
    procs = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        exports = (f"export MXT_PS_SECRET; "
                   f"export MXT_COORDINATOR={shlex.quote(coordinator)}; "
                   f"export MXT_NUM_PROCESSES={n}; "
                   f"export MXT_PROCESS_ID={rank}; ")
        remote = ("read -r MXT_PS_SECRET; " + exports +
                  "exec " + " ".join(shlex.quote(c) for c in cmd))
        p = subprocess.Popen(ssh + [host, remote],
                             stdin=subprocess.PIPE)
        try:
            p.stdin.write((ps_secret + "\n").encode())
            p.stdin.flush()
            p.stdin.close()
        except OSError:
            pass  # fast-failing ssh: its wait() status reports the rank
        procs.append(p)
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", default="local",
                   choices=["local", "ssh"])
    p.add_argument("-H", "--hostfile", default=None)
    p.add_argument("--coordinator", default="127.0.0.1:12721")
    p.add_argument("--dry-run", action="store_true",
                   help="ssh launcher: print the per-host commands "
                        "instead of spawning")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="local launcher: relaunch the whole group up to "
                        "this many times after a rank failure (ranks "
                        "resume from their latest checkpoint)")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    if args.launcher == "local":
        if args.dry_run:
            p.error("--dry-run only applies to --launcher ssh")
        sys.exit(launch_local(args.num_workers, args.command,
                              args.coordinator,
                              max_restarts=args.max_restarts))
    if args.max_restarts:
        p.error("--max-restarts only applies to --launcher local")
    hosts = ["localhost"]
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [l.strip() for l in f if l.strip()]
    if args.dry_run:
        for line in emit_ssh(hosts, args.num_workers, args.command,
                             args.coordinator):
            print(line)
        return
    sys.exit(launch_ssh(hosts, args.num_workers, args.command,
                        args.coordinator))


if __name__ == "__main__":
    main()
