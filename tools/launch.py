#!/usr/bin/env python
"""Multi-host launcher for distributed training.

Reference: ``tools/launch.py:?`` → dmlc tracker (``3rdparty/dmlc-core/
tracker/dmlc_tracker/{local,ssh,...}.py``) spawning scheduler + servers +
workers with ``DMLC_ROLE``/``DMLC_PS_ROOT_URI`` env (SURVEY §2.3 D11).

TPU-native redesign: there are no scheduler/server roles — every host runs
the SAME script and ``jax.distributed.initialize`` (driven by
``mxnet_tpu.parallel.initialize``) forms the process group over the
coordinator address; collectives ride ICI/DCN, not ZMQ.  This launcher
keeps the reference's CLI shape (``launch.py -n N python train.py``) for
script compatibility:

- ``--launcher local`` forks N processes on this machine with
  ``MXT_COORDINATOR``/``MXT_NUM_PROCESSES``/``MXT_PROCESS_ID`` set —
  the loopback test topology (the reference's ``--launcher local`` analog,
  used by the distributed tests, SURVEY §4);
- ``--launcher ssh`` SPAWNS one ssh per rank (round-robin over the
  hostfile), same as the reference's dmlc ssh tracker — with the env
  contract exported on the remote shell and the per-job secret delivered
  over ssh's stdin so it never appears in argv, logs, or shell history.
  ``--dry-run`` restores emit-only mode (one command per line, secret
  referenced as ``${MXT_PS_SECRET:?...}`` for an external runner);
  ``MXT_SSH`` overrides the ssh binary (pluggable spawner — the loopback
  test substitutes a local stub, and GKE/xpk-style runners can slot in a
  pod exec).

Every launch mints one ``MXT_PS_SECRET`` shared across ranks: the
dist_async parameter server HMAC-signs its frames with it (see
``mxnet_tpu/kvstore/dist_async.py``).
"""
from __future__ import annotations

import argparse
import os
import random
import secrets
import shlex
import signal
import subprocess
import sys
import time

#: exit status of a graceful preemption drain — mirrors
#: ``mxnet_tpu.gluon.trainer.PREEMPTED_EXIT_CODE`` (BSD EX_TEMPFAIL; the
#: launcher stays stdlib-only, so the value is duplicated, pinned by
#: tests/test_fault_injection.py).  A rank exiting with it was NOT a
#: crash: it finished its step and wrote a drain checkpoint, so the
#: relaunch consumes the (larger) preemption budget, not max_restarts.
PREEMPTED_EXIT = 75

# current group + drain flag, visible to the SIGTERM forwarder: when the
# LAUNCHER is preempted it must pass the drain signal down and then exit
# with the preemption status itself instead of relaunching
_live_procs = []
_draining = False


def _forward_drain(_signum=None, _frame=None):
    global _draining
    _draining = True
    for p in list(_live_procs):
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass


def install_drain_forwarder():
    """SIGTERM on the launcher → SIGTERM every rank (graceful drain),
    then exit with the group's status once the ranks finish draining."""
    signal.signal(signal.SIGTERM, _forward_drain)


def _backoff_delay(restart_idx, base, cap, _rand=random.random):
    """Exponential backoff with full-range jitter: restart ``i`` sleeps
    uniform(0.5, 1.0) × min(cap, base·2^i) seconds, so a preemption storm
    across many jobs doesn't synchronize their relaunches (the fixed
    instant-restart loop hammered the coordinator port while the old
    group's socket was still in TIME_WAIT)."""
    if base <= 0:
        return 0.0
    return min(cap, base * (2 ** restart_idx)) * (0.5 + _rand() / 2)


def _spawn_group(n, cmd, coordinator, ps_secret, attempt, reason=None,
                 restarts=None):
    procs = []
    try:
        for rank in range(n):
            env = dict(os.environ)
            env.update({
                "MXT_COORDINATOR": coordinator,
                "MXT_NUM_PROCESSES": str(n),
                "MXT_PROCESS_ID": str(rank),
                "MXT_PS_SECRET": ps_secret,
                "MXT_LAUNCH_ATTEMPT": str(attempt),
                # loopback test topology runs every process on CPU
                "JAX_PLATFORMS": env.get("MXT_LAUNCH_PLATFORM", "cpu"),
            })
            if reason is not None:
                # why the previous group ended — ranks surface it as
                # launcher.restart.<reason> telemetry (parallel.initialize)
                env["MXT_RESTART_REASON"] = reason
            if restarts:
                env["MXT_RESTART_CRASHES"] = str(restarts.get("crash", 0))
                env["MXT_RESTART_PREEMPTIONS"] = \
                    str(restarts.get("preempted", 0))
            procs.append(subprocess.Popen(cmd, env=env))
    except OSError:
        # partial group (EMFILE/EAGAIN mid-spawn): reap what spawned or
        # the orphans wait at the coordinator forever
        _reap(procs)
        raise
    return procs


def _reap(procs, grace=10.0):
    """SIGTERM the group, then SIGKILL stragglers after ``grace``.
    A rank blocked inside a collective may never run its SIGTERM
    handler — the hard kill is not optional."""
    import time

    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.time() + grace
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.1)
        if p.poll() is None:
            try:
                p.kill()
                p.wait()
            except OSError:
                pass


def _wait_group(procs, poll_s=0.2):
    """Wait for all ranks; on the FIRST nonzero exit, reap the rest and
    return that rc.  Failure detection is what the reference's tracker
    gave for free (a dead dmlc worker tears down the job): without it, a
    surviving rank blocks forever inside its next collective waiting for
    the dead peer, and the job wedges instead of failing."""
    import time

    while True:
        live = 0
        for p in procs:
            rc = p.poll()
            if rc is None:
                live += 1
            elif rc != 0:
                _reap(procs)
                return rc
        if live == 0:
            return 0
        time.sleep(poll_s)


def launch_local(n, cmd, coordinator="127.0.0.1:12721", max_restarts=0,
                 max_preemptions=64, backoff_base=1.0, backoff_cap=30.0,
                 on_spawn=None, stats=None):
    """Fork n local ranks and babysit them.

    On any rank's nonzero exit the whole group is reaped (failure
    detection), then relaunched — ranks are expected to resume from
    their latest checkpoint (mxnet_tpu.checkpoint.resume), which
    tests/test_fault_injection.py proves reconverges to the
    uninterrupted run.  The exit status picks the budget: a graceful
    drain (``PREEMPTED_EXIT``) consumes ``max_preemptions``, anything
    else consumes ``max_restarts`` — preemptions are routine and should
    not burn the crash budget.  Relaunches back off exponentially with
    jitter (``backoff_base``/``backoff_cap``); a SIGTERM on the launcher
    itself drains the ranks (install_drain_forwarder) and returns
    without relaunching.

    ``on_spawn(procs)`` is called after every (re)spawn — the chaos
    harness's injection point (tools/chaos.py); ``stats`` (a dict)
    accumulates per-reason restart counts for the caller."""
    global _draining
    ps_secret = os.environ.get("MXT_PS_SECRET") or secrets.token_hex(16)
    restarts = {"crash": 0, "preempted": 0}
    if stats is not None:
        stats["restarts"] = restarts
    reason = None
    while True:
        procs = _spawn_group(n, cmd, coordinator, ps_secret,
                             attempt=restarts["crash"] +
                             restarts["preempted"],
                             reason=reason, restarts=restarts)
        _live_procs[:] = procs
        if _draining:
            _forward_drain()  # SIGTERM raced the spawn: drain this group
        if on_spawn is not None:
            on_spawn(procs)
        rc = _wait_group(procs)
        _live_procs[:] = []
        if rc == 0:
            return 0
        if _draining:
            return rc  # the launcher itself was preempted: no relaunch
        reason = "preempted" if rc == PREEMPTED_EXIT else "crash"
        budget = max_preemptions if reason == "preempted" else max_restarts
        if restarts[reason] >= budget:
            print(f"launch.py: group failed (rc={rc}, {reason}); "
                  f"{reason} budget exhausted ({restarts[reason]}/{budget})",
                  file=sys.stderr)
            return rc
        restarts[reason] += 1
        delay = _backoff_delay(restarts[reason] - 1, backoff_base,
                               backoff_cap)
        print(f"launch.py: group failed (rc={rc}, {reason}); "
              f"restart {restarts[reason]}/{budget} "
              f"after {delay:.2f}s backoff", file=sys.stderr)
        if delay:
            time.sleep(delay)


def emit_ssh(hosts, n, cmd, coordinator):
    # The secret is NOT embedded (emitted lines land in logs / shell
    # history / remote argv): the single-quoted ${...:?} expands on the
    # REMOTE shell, so the runner must export MXT_PS_SECRET on each host
    # out-of-band, and the command fails loudly if it is missing.
    lines = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        envs = (f"MXT_COORDINATOR={coordinator} MXT_NUM_PROCESSES={n} "
                f"MXT_PROCESS_ID={rank} "
                'MXT_PS_SECRET="${MXT_PS_SECRET:?export a shared '
                'MXT_PS_SECRET on each host}"')
        lines.append(f"ssh {host} '{envs} {' '.join(cmd)}'")
    return lines


def launch_ssh(hosts, n, cmd, coordinator):
    """Spawn one ssh per rank and wait (the dmlc ssh tracker analog).

    The per-job secret is piped to each remote's STDIN (``read -r`` on
    the far side), keeping it out of ssh argv — the round-2 security
    stance — while still making the launch one command end to end.
    ``MXT_SSH`` swaps the transport (e.g. a test stub or a pod exec)."""
    ssh = shlex.split(os.environ.get("MXT_SSH", "ssh"))
    ps_secret = os.environ.get("MXT_PS_SECRET") or secrets.token_hex(16)
    procs = []
    for rank in range(n):
        host = hosts[rank % len(hosts)]
        exports = (f"export MXT_PS_SECRET; "
                   f"export MXT_COORDINATOR={shlex.quote(coordinator)}; "
                   f"export MXT_NUM_PROCESSES={n}; "
                   f"export MXT_PROCESS_ID={rank}; ")
        remote = ("read -r MXT_PS_SECRET; " + exports +
                  "exec " + " ".join(shlex.quote(c) for c in cmd))
        p = subprocess.Popen(ssh + [host, remote],
                             stdin=subprocess.PIPE)
        try:
            p.stdin.write((ps_secret + "\n").encode())
            p.stdin.flush()
            p.stdin.close()
        except OSError:
            pass  # fast-failing ssh: its wait() status reports the rank
        procs.append(p)
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--launcher", default="local",
                   choices=["local", "ssh"])
    p.add_argument("-H", "--hostfile", default=None)
    p.add_argument("--coordinator", default="127.0.0.1:12721")
    p.add_argument("--dry-run", action="store_true",
                   help="ssh launcher: print the per-host commands "
                        "instead of spawning")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="local launcher: relaunch the whole group up to "
                        "this many times after a rank CRASH (ranks "
                        "resume from their latest checkpoint)")
    p.add_argument("--max-preemptions", type=int, default=64,
                   help="local launcher: separate relaunch budget for "
                        "graceful preemption drains (rank exit code "
                        f"{PREEMPTED_EXIT})")
    p.add_argument("--backoff-base", type=float, default=1.0,
                   help="local launcher: first-relaunch backoff seconds "
                        "(doubles per consecutive restart, jittered; "
                        "0 disables)")
    p.add_argument("--backoff-cap", type=float, default=30.0,
                   help="local launcher: max backoff seconds")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    if args.launcher == "local":
        if args.dry_run:
            p.error("--dry-run only applies to --launcher ssh")
        install_drain_forwarder()
        sys.exit(launch_local(args.num_workers, args.command,
                              args.coordinator,
                              max_restarts=args.max_restarts,
                              max_preemptions=args.max_preemptions,
                              backoff_base=args.backoff_base,
                              backoff_cap=args.backoff_cap))
    if args.max_restarts:
        p.error("--max-restarts only applies to --launcher local")
    hosts = ["localhost"]
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [l.strip() for l in f if l.strip()]
    if args.dry_run:
        for line in emit_ssh(hosts, args.num_workers, args.command,
                             args.coordinator):
            print(line)
        return
    sys.exit(launch_ssh(hosts, args.num_workers, args.command,
                        args.coordinator))


if __name__ == "__main__":
    main()
