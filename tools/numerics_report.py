"""Render numerics telemetry into per-layer timelines, or replay a
captured divergence with per-op NaN bisection.

The numerics tier (``mxnet_tpu.telemetry.numerics``) attaches a
``"numerics"`` block to step records at each stride boundary: per-path
tensor stats (l2 / maxabs / mean / nan / inf), ``first_nan`` provenance
and an aggregate ``grad_norm``.  This tool turns those blocks — from
telemetry JSONL streams or fleet flight-recorder dumps — back into the
training-dynamics picture:

    # per-layer l2-norm timeline ('!' marks nan/inf overflow cells)
    python tools/numerics_report.py out/rank*.jsonl

    # Perfetto counter tracks, one per stat path
    python tools/numerics_report.py out/rank0.jsonl --format chrome \
        --out numerics.json

    # replay a flagged step eagerly and name the first poisoned op
    python tools/numerics_report.py --replay dumps/capture-1920

Replay rebuilds the net from the capture's ``builder``
(``"module:function"`` + kwargs), restores params through the
checkpointer, feeds the snapshotted inputs eagerly under
``numerics.bisect()``, and prints the first op whose inputs were clean
but whose outputs went nan/inf.  The functions (`numerics_rows`,
`heatmap_text`, `chrome_counters`, `replay`) are importable for tests.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fleet_report import load_records  # noqa: E402


def numerics_rows(records):
    """``[(step, rank, path, stats), ...]`` flattened from every step
    record carrying a ``"numerics"`` block, in (step, rank) order."""
    rows = []
    for rec in records:
        num = rec.get("numerics")
        if not isinstance(num, dict):
            continue
        step = rec.get("step")
        rank = rec.get("rank") or 0
        for path, st in (num.get("tensors") or {}).items():
            rows.append((step, rank, path, st))
    return rows


def _columns(rows):
    steps = sorted({s for s, _, _, _ in rows if s is not None})
    paths = sorted({p for _, _, p, _ in rows})
    return steps, paths


def heatmap_text(records, metric="l2"):
    """Path x step text heatmap of ``metric`` over the numerics blocks.
    Cells carrying any nan/inf are flagged ``!``; the summary names the
    earliest overflow (step, path, layer) and any watchdog/first_nan
    provenance found in the stream."""
    rows = numerics_rows(records)
    lines = []
    if not rows:
        lines.append("no numerics blocks (was the numerics tier "
                     "enabled, and did a stride boundary pass?)")
        return "\n".join(lines)
    steps, paths = _columns(rows)
    cell = {(s, p): st for s, _, p, st in rows}
    lines.append("numerics heatmap: %s (! = nan/inf in cell)" % metric)
    lines.append("step" + " " * 28 + "".join("%12d" % s for s in steps))
    for p in paths:
        cells = []
        for s in steps:
            st = cell.get((s, p))
            if st is None:
                cells.append("%12s" % "-")
                continue
            bad = (st.get("nan") or 0) + (st.get("inf") or 0)
            cells.append("%11.3g%s" % (float(st.get(metric) or 0.0),
                                       "!" if bad else " "))
        lines.append("%-32s" % p[:32] + "".join(cells))
    lines.append("")
    overflow = sorted((s, p, st) for s, _, p, st in rows
                      if (st.get("nan") or 0) + (st.get("inf") or 0))
    if overflow:
        s, p, st = overflow[0]
        from mxnet_tpu.telemetry.numerics import layer_of
        lines.append("first overflow: step %s path %s (layer %d, "
                     "nan=%s inf=%s)" % (s, p, layer_of(p),
                                         st.get("nan"), st.get("inf")))
    else:
        lines.append("overflow: none")
    # surface first_nan provenance + nan_tensor anomalies when present
    for rec in records:
        fn = (rec.get("numerics") or {}).get("first_nan") \
            if isinstance(rec.get("numerics"), dict) else None
        if fn:
            lines.append("  step %-6s rank %-3s first_nan %s (layer %s)"
                         % (rec.get("step"), rec.get("rank") or 0,
                            fn.get("path"), fn.get("layer")))
        if rec.get("record") == "anomaly" \
                and rec.get("kind") in ("nan_tensor",
                                        "grad_norm_explosion"):
            lines.append("  step %-6s rank %-3s anomaly %s %s"
                         % (rec.get("step"), rec.get("rank") or 0,
                            rec.get("kind"),
                            {k: rec[k] for k in ("path", "layer",
                                                 "grad_norm")
                             if rec.get(k) is not None}))
    return "\n".join(lines)


def chrome_counters(records):
    """chrome://tracing / Perfetto JSON: one counter ("C") track per
    stat path with ``l2`` and ``overflow`` series — the offline twin of
    the live ``profiler.record_counter_event`` mirror.  Timestamps are
    wall-clock relative to the earliest record (step index as a
    fallback timebase when records carry no wall time)."""
    walls = [rec.get("wall_time") for rec in records
             if isinstance(rec, dict) and rec.get("wall_time") is not None]
    t0 = min(walls) if walls else 0.0
    events = []
    for rec in records:
        num = rec.get("numerics")
        if not isinstance(num, dict):
            continue
        rank = rec.get("rank") or 0
        wall = rec.get("wall_time")
        ts = ((float(wall) - t0) * 1e6 if wall is not None
              else float(rec.get("step") or 0) * 1e3)
        for path, st in (num.get("tensors") or {}).items():
            events.append({
                "ph": "C", "cat": "numerics",
                "name": "numerics/" + path,
                "pid": rank, "tid": 0, "ts": ts,
                "args": {"l2": float(st.get("l2") or 0.0),
                         "overflow": float((st.get("nan") or 0)
                                           + (st.get("inf") or 0))}})
        if num.get("grad_norm") is not None:
            events.append({
                "ph": "C", "cat": "numerics", "name": "numerics/grad_norm",
                "pid": rank, "tid": 0, "ts": ts,
                "args": {"grad_norm": float(num["grad_norm"])}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _build_net(meta):
    builder = meta.get("builder")
    if not builder or ":" not in builder:
        raise SystemExit(
            "capture has no usable builder (%r); re-capture with "
            "builder='module:function'" % (builder,))
    mod_name, fn_name = builder.split(":", 1)
    fn = getattr(importlib.import_module(mod_name), fn_name)
    return fn(**(meta.get("builder_kwargs") or {}))


def replay(capture_dir, max_journal=12):
    """Re-run a captured step eagerly under the per-op NaN bisection
    hook.  Returns ``(lines, result)`` — report text plus the raw
    ``BisectResult`` — so tests can assert on ``result.first``."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint as _ckpt
    from mxnet_tpu.telemetry import numerics

    meta, inputs = numerics.load_capture(capture_dir)
    net = _build_net(meta)
    if hasattr(net, "hybridize"):
        net.hybridize(False)  # eager replay — per-op dispatch, no jit
    try:
        net.initialize()  # deferred; checkpoint set_data supplies shapes
    except Exception:
        pass
    step, extra = _ckpt.resume(capture_dir, net)
    lines = ["replaying %s: step %s (%s), %d input(s), params from "
             "checkpoint step %s"
             % (capture_dir, meta.get("step"), meta.get("reason"),
                len(inputs), step)]
    if extra.get("numerics_capture"):
        lines.append("  capture reason: %s"
                     % extra["numerics_capture"].get("reason"))
    if meta.get("rng_key") is not None:
        from mxnet_tpu import random as mx_random
        import jax

        mx_random._STATE.key = jax.numpy.asarray(
            np.asarray(meta["rng_key"], dtype=np.uint32))
    args = [mx.nd.array(a) for a in inputs]
    with numerics.bisect() as res:
        out = net(*args)
    bad_out = any((np.isnan(np.asarray(getattr(o, "_data", o))).any()
                   or np.isinf(np.asarray(getattr(o, "_data", o))).any())
                  for o in (out if isinstance(out, (tuple, list))
                            else (out,))
                  if np.asarray(getattr(o, "_data", o)).dtype.kind == "f")
    if res.first is not None:
        i = res.first["index"]
        lines.append("first failing op: %s (dispatch #%d of %d)"
                     % (res.first["op"], i, len(res.ops)))
        lo = max(0, i - max_journal // 2)
        lines.append("op journal around the poisoned op:")
        for j, op in enumerate(res.ops[lo:lo + max_journal], start=lo):
            mark = " <-- first poisoned" if j == i else ""
            lines.append("  #%-4d %-28s inputs_bad=%-5s outputs_bad=%s%s"
                         % (j, op["op"], op["inputs_bad"],
                            op["outputs_bad"], mark))
    elif bad_out:
        lines.append("outputs are nan/inf but no clean->poisoned op "
                     "transition was seen (inputs or params already "
                     "poisoned at capture time)")
    else:
        lines.append("replay is clean: %d ops dispatched, no nan/inf "
                     "anywhere (divergence did not reproduce eagerly — "
                     "suspect non-determinism or compiled-only numerics)"
                     % len(res.ops))
    return lines, res


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render numerics telemetry (per-layer norm/overflow "
        "timelines) from JSONL streams / flight dumps, or replay a "
        "captured divergence with per-op NaN bisection")
    ap.add_argument("paths", nargs="*", metavar="path",
                    help="telemetry JSONL files, globs, or fleet "
                    "flight-recorder dumps")
    ap.add_argument("--metric", default="l2",
                    choices=("l2", "maxabs", "mean"),
                    help="stat for the heatmap cells (default: l2)")
    ap.add_argument("--format", choices=("text", "chrome"),
                    default="text")
    ap.add_argument("--out", default=None,
                    help="write here instead of stdout")
    ap.add_argument("--replay", default=None, metavar="CAPTURE_DIR",
                    help="replay a numerics.capture_step() snapshot "
                    "eagerly and name the first poisoned op")
    args = ap.parse_args(argv)

    if args.replay:
        lines, res = replay(args.replay)
        print("\n".join(lines))
        return 0 if res.first is None else 2
    if not args.paths:
        ap.error("give JSONL/dump paths, or --replay CAPTURE_DIR")
    records = load_records(args.paths)
    if not records:
        print("no records found", file=sys.stderr)
        return 1
    sink = open(args.out, "w", encoding="utf-8") if args.out \
        else sys.stdout
    try:
        if args.format == "chrome":
            json.dump(chrome_counters(records), sink, indent=1)
            sink.write("\n")
        else:
            sink.write(heatmap_text(records, metric=args.metric) + "\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
