"""Perf-regression gate over the committed round artifacts.

The repo commits a ``FAMILY_rNN.json`` artifact per bench round;
``benchmark/ledger.py`` normalizes them and
``benchmark/PERF_BASELINE.json`` pins, per family, the reference
headline value (direction + noise tolerance) and the acceptance flags
the round won.  This CLI is the enforcement end:

    # gate a fresh artifact against the committed baseline
    python tools/perf_gate.py --check SERVING_LATENCY_r20.json

    # re-verify every committed artifact still clears the manifest
    python tools/perf_gate.py --check-all

    # the r1 -> r19 trajectory, one line per family
    python tools/perf_gate.py --trend

    # regenerate the manifest after a reviewed perf change
    python tools/perf_gate.py --update-baseline

``--check`` exits 1 on any regression: a headline metric moved beyond
the family's tolerance in the bad direction (min-of-repeats when the
artifact carries ``value_all``), or an acceptance flag the baseline
held true is now false/missing.  New families and new flags pass —
the gate protects what earlier rounds won, it does not veto new work.

``tests/test_bench_smoke.py`` runs ``--check`` on a toy baseline and
asserts an injected 2x latency regression fails; CI-style use is
``--check NEW.json`` right after a bench run, before committing the
artifact.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from benchmark import ledger  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "benchmark", "PERF_BASELINE.json")


def _fmt_problem(p):
    if p["kind"] == "metric":
        arrow = "above" if p["direction"] == "lower" else "below"
        return ("REGRESSION %s: %s %s -> %s (%+.1f%%, %s baseline "
                "beyond %.0f%% tolerance)"
                % (p["family"], p["metric"], p["baseline"], p["new"],
                   100 * p["delta_frac"], arrow, 100 * p["tolerance"]))
    return ("REGRESSION %s: acceptance flag %r was true at baseline, "
            "now %s" % (p["family"], p["flag"], p["new"]))


def cmd_check(paths, baseline_path, root):
    base = ledger.load_baseline(baseline_path)
    failures = []
    for path in paths:
        row = ledger.normalize(path)
        probs = ledger.check(row, base)
        status = "FAIL" if probs else "ok"
        print("%-4s %s (family %s, round r%02d)"
              % (status, os.path.basename(path), row["family"],
                 row["round"]))
        for p in probs:
            print("  " + _fmt_problem(p))
        failures.extend(probs)
    if failures:
        print("perf_gate: %d regression(s)" % len(failures))
        return 1
    print("perf_gate: clean")
    return 0


def cmd_check_all(baseline_path, root):
    rows = ledger.scan(root)
    if not rows:
        print("no round artifacts under %s" % root, file=sys.stderr)
        return 1
    # only the baseline round of each family is gate-relevant: older
    # rounds are history the trend view covers, not current claims
    base = ledger.load_baseline(baseline_path)
    latest = {}
    for r in rows:
        cur = latest.get(r["family"])
        if cur is None or r["round"] > cur["round"]:
            latest[r["family"]] = r
    paths = [os.path.join(root, r["path"])
             for _, r in sorted(latest.items())]
    return cmd_check(paths, baseline_path, root)


def cmd_trend(root, as_json=False):
    rows = ledger.scan(root)
    entries = ledger.trend(rows)
    if as_json:
        json.dump(entries, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 0
    print("%-22s %-38s %-6s %s" % ("family", "metric", "dir",
                                   "rounds (round:value)"))
    for e in entries:
        pts = " ".join(
            "r%02d:%s" % (rnd, ("%g" % v) if v is not None else "-")
            for rnd, v in e["rounds"])
        mark = ""
        if "improved" in e:
            mark = " [%s %+.1f%%]" % (
                "improved" if e["improved"] else "regressed",
                100 * e["delta_frac"])
        print("%-22s %-38s %-6s %s%s"
              % (e["family"], e["metric"] or "-", e["direction"],
                 pts, mark))
    return 0


def cmd_update_baseline(baseline_path, root):
    rows = ledger.scan(root)
    if not rows:
        print("no round artifacts under %s" % root, file=sys.stderr)
        return 1
    manifest = ledger.build_baseline(rows)
    with open(baseline_path, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote %s (%d families from %d artifacts)"
          % (baseline_path, len(manifest["families"]), len(rows)))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="noise-aware perf-regression gate over the "
        "committed FAMILY_rNN.json bench artifacts")
    ap.add_argument("--check", nargs="+", metavar="ARTIFACT.json",
                    help="gate these artifacts against the baseline "
                    "manifest (exit 1 on regression)")
    ap.add_argument("--check-all", action="store_true",
                    help="gate every family's latest committed "
                    "artifact (the manifest must be clean vs itself)")
    ap.add_argument("--trend", action="store_true",
                    help="print the per-family round-over-round "
                    "trajectory")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate the manifest from the committed "
                    "artifacts (review the diff like a lockfile)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="manifest path (default: "
                    "benchmark/PERF_BASELINE.json)")
    ap.add_argument("--root", default=REPO,
                    help="directory holding the *_rNN.json artifacts "
                    "(default: the repo root)")
    ap.add_argument("--json", action="store_true",
                    help="with --trend: emit JSON instead of the "
                    "table")
    args = ap.parse_args(argv)

    if args.update_baseline:
        return cmd_update_baseline(args.baseline, args.root)
    if args.trend:
        return cmd_trend(args.root, as_json=args.json)
    if args.check_all:
        return cmd_check_all(args.baseline, args.root)
    if args.check:
        return cmd_check(args.check, args.baseline, args.root)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
