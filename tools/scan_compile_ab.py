#!/usr/bin/env python
"""scan_layers compile-time A/B on the REAL XLA:TPU backend (offline
topology client): the claim is layer-count-INDEPENDENT compile time —
`lax.scan` over the stacked decoder compiles ONE layer body regardless
of depth, while the python layer loop recompiles every layer.

Four compiles of the same small llama geometry (hidden 1024, 8 heads,
seq 1024, batch 2, bf16, sdpa attention — attention kernel choice is
irrelevant to the scaling story): {8, 24} layers x {loop, scan}.
Records wall-clock lower+compile seconds and the HLO size.  Measured
signature (r5): the TPU compiler dedups identical per-layer fusions,
so at this small geometry compile-TIME growth is the same for both
(~1.7x for 3x layers; run-to-run noise swamps any difference) —
scan's offline-provable win is CODE SIZE (optimized HLO ~2.8x smaller
at L24) plus the near-zero lower/trace cost (0.1 s vs ~1 s at L24).
The decisive scan wins remain the r4 ones: per-layer buffer dedup
(memory) and trace cost at real depths.
(estimated_cycles is NOT comparable across the two — a scanned body's
fusions are counted once, not per iteration — so this artifact
intentionally reports compile time and code size only.)

Writes one JSON blob to stdout (and argv[1] if given).  Single-process
(libtpu lockfile).
"""
import json
import sys
import time


def main():
    import os

    os.environ.pop("JAX_PLATFORMS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _tpu_topology import assert_tpu_hlo, topology_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.block import _CachedGraph
    from mxnet_tpu.models import llama

    mesh = topology_mesh("v5e:1x1")
    repl = NamedSharding(mesh, P())
    out = {"topology": "v5e:1x1 (offline libtpu AOT client)",
           "geometry": "hidden 1024, 8 heads, seq 1024, batch 2, bf16",
           "cases": {}}

    def build(layers, scan):
        mx.random.seed(0)
        net = llama.LlamaForCausalLM(llama.LlamaConfig(
            hidden_size=1024, intermediate_size=2816, num_layers=layers,
            num_heads=8, num_kv_heads=8, vocab_size=8192,
            max_seq_len=1024, attn_mode="sdpa", scan_layers=scan))
        net.initialize(mx.init.Zero())
        net(nd.ones((1, 8), dtype="int32"))
        net.cast("bfloat16")
        params = list(net.collect_params().values())
        graph = _CachedGraph(net, params, training=False)

        def fwd(p_raws, ids):
            outs, _, _ = graph._pure(p_raws, (ids,),
                                     jax.random.PRNGKey(0))
            return outs[0]

        abs_p = tuple(
            jax.ShapeDtypeStruct(p.shape, p.data()._data.dtype,
                                 sharding=repl) for p in params)
        ids = jax.ShapeDtypeStruct((2, 1024), jnp.int32, sharding=repl)
        return fwd, abs_p, ids

    for layers in (8, 24):
        for scan in (False, True):
            name = f"L{layers}_{'scan' if scan else 'loop'}"
            fwd, abs_p, ids = build(layers, scan)
            t0 = time.time()
            lowered = jax.jit(fwd).lower(abs_p, ids)
            t1 = time.time()
            comp = lowered.compile()
            t2 = time.time()
            hlo = comp.as_text()
            assert_tpu_hlo(hlo, what=name)
            out["cases"][name] = {
                "lower_sec": round(t1 - t0, 1),
                "compile_sec": round(t2 - t1, 1),
                "total_sec": round(t2 - t0, 1),
                "hlo_chars": len(hlo),
            }
            print(f"{name}: {out['cases'][name]}", file=sys.stderr)

    c = out["cases"]
    # compile_sec only: the ratio quoted as compile-time scaling must
    # not smuggle in the loop's tracing time (scan's near-zero lower
    # cost is reported separately, per case)
    out["loop_compile_ratio_24_vs_8"] = round(
        c["L24_loop"]["compile_sec"] / c["L8_loop"]["compile_sec"], 2)
    out["scan_compile_ratio_24_vs_8"] = round(
        c["L24_scan"]["compile_sec"] / c["L8_scan"]["compile_sec"], 2)
    out["hlo_size_loop_vs_scan_at_24"] = round(
        c["L24_loop"]["hlo_chars"] / c["L24_scan"]["hlo_chars"], 2)
    out["finding"] = (
        "XLA:TPU dedups identical per-layer fusions, so loop compile "
        "time grows sublinearly at this geometry; scan's offline-"
        "provable wins are lower tracing cost and ~linear-in-L smaller "
        "optimized HLO — the decisive wins (per-layer buffer dedup, "
        "trace cost at real depths) are the r4 CPU-proven ones")

    blob = json.dumps(out, indent=1)
    print(blob)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            f.write(blob + "\n")


if __name__ == "__main__":
    main()
