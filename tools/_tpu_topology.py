"""Shared offline-TPU-topology compile helpers.

One copy of the hazard-prone setup used by scale_proof.py,
int8_topology_probe.py, and pallas_topology_check.py: building a mesh
over an OFFLINE libtpu topology client and compiling with TPU
provenance ASSERTED.  The hazard: avals without shardings over the
topology's devices silently compile against the process's default CPU
backend and the "TPU evidence" is CPU HLO (this bug shipped once —
PERF_NOTES round 5).  Single-process: libtpu holds
/tmp/libtpu_lockfile.
"""
import re

def _host_bounds(topology_name):
    """chips_per_host_bounds for a v5e ``AxB`` shape: the 2x4 host tray
    where it divides, clamped down for sub-tray single-chip layouts
    (the API rejects bounds that don't divide the topology)."""
    shape = topology_name.split(":", 1)[1]
    a, b = (int(d) for d in shape.split("x")[:2])
    return (2 if a % 2 == 0 else 1, 4 if b % 4 == 0 else 1, 1)


def topology_mesh(topology_name="v5e:1x1", mesh_shape=None):
    """Mesh over an offline TPU topology.  ``mesh_shape``: dict like
    {"dp": 4, "tp": 8} (device count must match the topology) or None
    for a 1-axis mesh over all devices."""
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name,
        chips_per_host_bounds=_host_bounds(topology_name), num_slices=1)
    if mesh_shape is None:
        return Mesh(np.array(topo.devices), ("x",))
    dims = tuple(mesh_shape.values())
    n = int(np.prod(dims))
    assert n == len(topo.devices), (mesh_shape, len(topo.devices))
    return Mesh(np.array(topo.devices).reshape(dims),
                tuple(mesh_shape.keys()))


def assert_tpu_hlo(hlo, what=""):
    """TPU provenance: tiled layouts (``{...:T(8,128)...}``) exist only
    in XLA:TPU HLO.  A compile that silently targeted the CPU backend
    fails here instead of shipping CPU numbers as TPU evidence."""
    assert ":T(" in hlo, \
        f"{what}: no TPU tiling in optimized HLO — compiled for CPU?"


def estimated_cycles_sum(hlo, required=False):
    """Sum XLA:TPU's per-fusion ``estimated_cycles`` backend-config
    entries.  ``required=True`` raises when the HLO carries none — a
    serialization-format drift would otherwise silently zero every
    prediction built on this number (it is load-bearing for
    PREDICTED_THROUGHPUT / CYCLES_AB artifacts)."""
    cycles = [int(c) for c in
              re.findall(r'"estimated_cycles":"(\d+)"', hlo)]
    if required and not cycles:
        raise AssertionError(
            "no estimated_cycles in TPU HLO — backend_config "
            "serialization changed?")
    return sum(cycles), len(cycles)


def count_mosaic_calls(hlo):
    """Mosaic kernels appear as custom-calls with the
    ``tpu_custom_call`` target — a bare 'custom-call' substring count
    would also match sharding/annotation custom-calls and every USE of
    an instruction named %custom-call.N."""
    return len(re.findall(r'custom_call_target="tpu_custom_call"', hlo))


def compile_tpu_checked(fn, avals, mesh, what=""):
    """jit-compile ``fn`` on replicated shardings over ``mesh``'s
    topology devices; returns (compiled, hlo) with TPU provenance
    asserted."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    shaped = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=repl)
              for a in avals]
    comp = jax.jit(fn).lower(*shaped).compile()
    hlo = comp.as_text()
    assert_tpu_hlo(hlo, what)
    return comp, hlo
