"""Composed multi-host topology harness — ONE definition of the
"2 processes x N local virtual devices" loopback (GSPMD batch sharding
inside each process, dist_tpu_sync's cross-process gradient allreduce
outside, one stock ``gluon.Trainer`` step), shared by
``__graft_entry__.dryrun_multichip`` phase 5 and
``tests/test_dist_loopback.py`` so the topology and launch contract
cannot drift between the two (they briefly did in r4).

Reference composition style: the nightly dist tests always ran the full
scheduler+server+worker stack in one script
(tests/nightly/dist_sync_kvstore.py:?).
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Per-rank worker: {local} virtual CPU devices, GSPMD dp over the LOCAL
# mesh, disjoint per-rank rows of a shared global batch, {steps}
# momentum-SGD steps at global batch size — then dump weight+bias.
_WORKER = """
import os
import sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count={local}"
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel

parallel.initialize()
rank, n = jax.process_index(), jax.process_count()
assert n == 2, n
assert len(jax.local_devices()) == {local}, jax.local_devices()
assert len(jax.devices()) == 2 * {local}, jax.devices()

mesh = parallel.make_mesh({{"dp": {local}}}, devices=jax.local_devices())
with parallel.mesh_scope(mesh):
    mx.random.seed({seed})
    net = gluon.nn.Dense(3, use_bias=True)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 5)))
    parallel.replicate_block_params(net)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {{"learning_rate": 0.1, "momentum": 0.9}},
                            kvstore="dist_tpu_sync")
    rows = 2 * {local}                       # per-rank rows
    full = np.random.RandomState(0).randn(2 * rows, 5).astype(np.float32)
    x = parallel.shard_batch(nd.array(
        full[rank * rows:(rank + 1) * rows]))
    for _ in range({steps}):
        with autograd.record():
            loss = (net(x) ** 2).sum()       # sum-loss: step() rescales
        loss.backward()
        trainer.step(2 * rows)               # GLOBAL batch size
assert trainer._kvstore.num_workers == n
np.save(os.environ["OUT_FILE"] + str(rank) + ".npy",
        np.concatenate([net.weight.data().asnumpy().ravel(),
                        net.bias.data().asnumpy().ravel()]))
"""


def global_batch(n_local):
    return 4 * n_local


def run_composed(n_local, steps=4, seed=42, timeout=300):
    """Launch the 2-process composed topology; returns the two ranks'
    flattened (weight, bias) arrays.  Raises on nonzero exit."""
    import numpy as np

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "composed_worker.py")
        with open(script, "w") as f:
            f.write(_WORKER.format(repo=REPO, local=n_local, seed=seed,
                                   steps=steps))
        out = os.path.join(td, "params")
        env = dict(os.environ)
        env["OUT_FILE"] = out
        env["MXT_LAUNCH_PLATFORM"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "launch.py"),
             "-n", "2", "--coordinator", f"127.0.0.1:{port}",
             sys.executable, script], env=env, start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            raise
        if rc != 0:
            raise RuntimeError(f"composed multi-host workers rc={rc}")
        return [np.load(out + f"{i}.npy") for i in range(2)]


def oracle_single_process(n_local, steps=4, seed=42):
    """The single-process GSPMD oracle over the same global batch on a
    2*n_local-device dp mesh (call from a process that HAS the devices,
    e.g. under tests/conftest's virtual mesh)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd, parallel

    mesh = parallel.make_mesh({"dp": 2 * n_local})
    with parallel.mesh_scope(mesh):
        mx.random.seed(seed)
        net = gluon.nn.Dense(3, use_bias=True)
        net.initialize(mx.init.Xavier())
        net(nd.ones((1, 5)))
        parallel.replicate_block_params(net)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                kvstore="dist_tpu_sync")
        gb = global_batch(n_local)
        x = parallel.shard_batch(nd.array(
            np.random.RandomState(0).randn(gb, 5).astype(np.float32)))
        for _ in range(steps):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            trainer.step(gb)
        return np.concatenate([net.weight.data().asnumpy().ravel(),
                               net.bias.data().asnumpy().ravel()])
