"""MFU audit: ground every README MFU claim in XLA's OWN per-step FLOP
count instead of hand arithmetic (VERDICT r3 item 1).

For each benched workload this composes ONE pure train-step function out
of the exact framework pieces the bench executes — the hybridized net's
``_CachedGraph._pure`` forward, the bench's loss math, and the
optimizer's ``_step`` update — then asks the compiler what it costs:

    jax.jit(step).lower(abstract_args).compile().cost_analysis()

The resulting ``flops`` is XLA's count over the optimized HLO for one
full fwd+bwd+update step (matmuls, convs, attention, the full-vocab
softmax-CE, the optimizer elementwise traffic — everything; remat
recompute included when the workload trains with remat).  MFU derived
from it carries the compiler's receipt, not a spreadsheet's.

Reference posture: MXNet published measured throughput only
(docs/faq/perf.md:?); derived metrics like MFU need exactly this kind of
receipt.

Usage (each workload isolated in its own process — AMP is global state):

    python tools/mfu_audit.py resnet50          # one workload, JSON line
    python tools/mfu_audit.py bert_base
    python tools/mfu_audit.py llama1b
    python tools/mfu_audit.py all               # subprocess per workload,
                                                # writes MFU_AUDIT_r04.json

Runtime-registry mode: a run with ``MXNET_TELEMETRY=1`` (or
``telemetry.costs.enable()``) already holds every compiled artifact's
``cost_analysis()``; ``telemetry.costs.dump("COSTS.json")`` writes it and

    python tools/mfu_audit.py --from-registry COSTS.json

audits from the runtime's own numbers — no re-lowering, and the flops
are those of the artifacts that actually executed.  A missing/empty/
unreadable dump falls back to the lowering path above.

Throughput inputs default to the round-3 driver artifacts; override with
e.g. ``THROUGHPUT=5151.48`` (samples/sec) per run.  ``AUDIT_PLATFORM=cpu``
lowers on the CPU backend (identical dominant FLOPs; transcendental
counting may differ marginally — the JSON records which backend priced
the step).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# TPU v5e bf16 peak (MXU): the number every README MFU row divides by.
PEAK_BF16_TFLOPS = 197.0

# round-3 driver-captured throughputs (BENCH_r03.json) + the README's
# measured llama rate — the wall-clock side of the MFU fractions under
# audit.  Override per-run with THROUGHPUT.
DEFAULT_THROUGHPUT = {
    "resnet50": 5151.48,   # images/sec/chip, driver best-of-3
    "bert_base": 2304.3,   # samples/sec/chip, driver best-of-3
    "llama1b": 10900.0 / 2048.0,  # sequences/sec (10.9k tok/s, seq 2048)
}

# the hand counts the README used until this audit (GFLOP per sample)
HAND_GFLOP = {
    "resnet50": 24.6,      # 3 x fwd 8.2 (fwd = 4.1 GMAC)
    "bert_base": 84.0,     # 6 N_nonemb s + 3x MLM head
    "llama1b": None,       # filled from 6N at runtime
}


#: MXU flops per TensorCore cycle on v5e (4 MXUs x 128x128 MACs x 2):
#: XLA:TPU's per-fusion ``estimated_cycles`` measures in this clock
#: domain — large-matmul probes resolve ~120k flops/cycle against this
#: 131,072 ceiling (92%), which pins both the calibration and the
#: implied ~1.5 GHz clock (197e12 / 131072).
V5E_MXU_FLOPS_PER_CYCLE = 131072
V5E_CLOCK_HZ = PEAK_BF16_TFLOPS * 1e12 / V5E_MXU_FLOPS_PER_CYCLE


def _setup_platform():
    """AUDIT_PLATFORM: ``cpu`` (default) prices FLOPs/bytes on the CPU
    lowering; ``tpu_topology`` compiles against the OFFLINE libtpu
    v5e:1x1 topology client — real XLA:TPU fusions, with the
    per-fusion ``estimated_cycles`` summed into a predicted step time
    (serial-fusion model: DMA/compute overlap ignored, so the
    prediction is a floor on speed and measured throughput should land
    at or above it)."""
    plat = os.environ.get("AUDIT_PLATFORM", "cpu")
    if plat in ("cpu", "tpu_topology"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    if plat == "tpu_topology":
        # the prediction must price the kernels the real chip runs:
        # route the pallas flash path (the process backend being cpu
        # would otherwise silently swap in the chunked fallback)
        os.environ.setdefault("MXT_FORCE_PALLAS_FLASH", "1")
    return plat


def _topology_mesh():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _tpu_topology import topology_mesh

    return topology_mesh("v5e:1x1")


def _compose_step(net, loss_raw, opt, batch_for_rescale, key,
                  remat=False):
    """One pure (params, opt_states, inputs..., labels) -> loss step
    from the framework's own pieces; returns (jitted_fn, abstract_args).

    ``loss_raw(outs_raws, label_raw) -> scalar`` replicates the bench's
    loss math on raw arrays; the optimizer update reuses
    ``Optimizer._step`` verbatim (rescale_grad is set on ``opt`` exactly
    as ``gluon.Trainer.step(batch_size)`` would)."""
    import jax

    from mxnet_tpu.gluon.block import _CachedGraph

    params = list(net.collect_params().values())
    graph = _CachedGraph(net, params, training=True, remat=remat)
    diff_idx = [i for i, p in enumerate(params) if p.grad_req != "null"]
    opt.rescale_grad = 1.0 / batch_for_rescale
    # optimizer state per diff param, exactly as Trainer would create it
    states = [opt.create_state_multi_precision(i, params[i].data())
              for i in diff_idx]

    from mxnet_tpu.optimizer import _flatten_state

    flat_states = [tuple(s._data for s in _flatten_state(st))
                   for st in states]

    def step(p_raws, st_raws, in_raws, label_raw):
        def loss_of(diff_raws):
            full = list(p_raws)
            for j, i in enumerate(diff_idx):
                full[i] = diff_raws[j]
            outs, auxs, _stats = graph._pure(full, in_raws, key)
            return loss_raw(outs, label_raw), auxs

        fn = jax.checkpoint(loss_of) if remat else loss_of
        (loss, auxs), grads = jax.value_and_grad(fn, has_aux=True)(
            [p_raws[i] for i in diff_idx])
        new_ws, new_sts = [], []
        for j, i in enumerate(diff_idx):
            w, g = p_raws[i], grads[j]
            lr = opt._get_lr(i)
            wd = opt._get_wd(i)
            nw, nst = opt._step(w, g, st_raws[j], lr, wd, 1)
            new_ws.append(nw)
            new_sts.append(nst)
        return loss, new_ws, new_sts, auxs

    abstract = (
        [jax.ShapeDtypeStruct(p.shape, p.data()._data.dtype)
         for p in params],
        [tuple(jax.ShapeDtypeStruct(s.shape, s.dtype) for s in fs)
         for fs in flat_states],
    )
    return jax.jit(step), abstract


def _cost(jfn, abstract_params, abstract_states, in_structs, label_struct):
    import jax

    args = (abstract_params, abstract_states, in_structs, label_struct)
    plat = os.environ.get("AUDIT_PLATFORM", "cpu")
    if plat == "tpu_topology":
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(_topology_mesh(), P())
        args = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=repl), args)
    lowered = jfn.lower(*args)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):  # older jax returns per-device list
        ca = ca[0]
    out = {
        "flops": float(ca.get("flops", float("nan"))),
        "bytes_accessed": float(ca.get("bytes accessed",
                                       ca.get("bytes_accessed",
                                              float("nan")))),
    }
    if plat == "tpu_topology":
        from _tpu_topology import assert_tpu_hlo, estimated_cycles_sum

        hlo = compiled.as_text()
        assert_tpu_hlo(hlo, "mfu_audit")
        total, n = estimated_cycles_sum(hlo, required=True)
        out["tpu_estimated_cycles_sum"] = total
        out["tpu_estimated_fusions"] = n
    return out


def _emit(workload, per_step, batch, cost, hand_gflop, note=""):
    import jax

    thr = float(os.environ.get("THROUGHPUT",
                               DEFAULT_THROUGHPUT[workload]))
    xla_gflop_sample = cost["flops"] / batch / 1e9
    achieved_tflops = thr * xla_gflop_sample / 1e3
    mfu = achieved_tflops / PEAK_BF16_TFLOPS
    rec = {
        "workload": workload,
        "per_step": per_step,
        "batch": batch,
        # default_backend() reports the PROCESS backend (cpu even when
        # the jit target is the topology client) — record the actual
        # pricing backend
        "lowering_platform": (
            "xla:tpu (offline v5e:1x1 topology client)"
            if os.environ.get("AUDIT_PLATFORM") == "tpu_topology"
            else jax.default_backend()),
        "xla_flops_per_step": cost["flops"],
        "xla_bytes_accessed_per_step": cost["bytes_accessed"],
        "xla_gflop_per_sample": round(xla_gflop_sample, 3),
        "hand_gflop_per_sample": hand_gflop,
        "hand_vs_xla": (round(hand_gflop / xla_gflop_sample, 4)
                        if hand_gflop else None),
        "measured_throughput_per_sec": thr,
        "achieved_tflops": round(achieved_tflops, 2),
        "peak_bf16_tflops": PEAK_BF16_TFLOPS,
        "mfu": round(mfu, 4),
        "note": note,
    }
    if cost.get("tpu_estimated_cycles_sum"):
        step_s = cost["tpu_estimated_cycles_sum"] / V5E_CLOCK_HZ
        rec["tpu_estimated_cycles_sum"] = cost["tpu_estimated_cycles_sum"]
        rec["tpu_estimated_fusions"] = cost["tpu_estimated_fusions"]
        rec["predicted_step_ms"] = round(step_s * 1e3, 2)
        rec["predicted_throughput_per_sec"] = round(batch / step_s, 1)
        rec["predicted_mfu"] = round(
            cost["flops"] / step_s / 1e12 / PEAK_BF16_TFLOPS, 4)
        rec["prediction_model"] = (
            "sum of XLA:TPU per-fusion estimated_cycles / "
            f"{V5E_CLOCK_HZ/1e9:.2f} GHz; serial-fusion, no DMA "
            "overlap, and mosaic custom-calls (pallas kernels) carry "
            "NO estimate so their time is uncounted — a floor on "
            "speed, measured should land at or above "
            "predicted_throughput")
    print(json.dumps(rec))
    return rec


def audit_resnet50():
    """bench.py default leg: resnet50_v1, batch 64, 224^2, AMP bf16,
    SGD momentum 0.9, SoftmaxCE mean loss, Trainer.step(batch)."""
    _setup_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon, nd, optimizer

    batch = int(os.environ.get("BATCH", "64"))
    mx.random.seed(0)
    net = gluon.model_zoo.vision.get_model("resnet50_v1", classes=1000)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 3, 32, 32)))  # resolve deferred shapes
    amp.init(target_dtype="bfloat16")

    def loss_raw(outs, label):
        logits = outs[0].astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, label[:, None], axis=-1)
        return ce.mean()

    opt = optimizer.SGD(learning_rate=0.1, momentum=0.9)
    key = jax.random.PRNGKey(0)
    jfn, (ap, ast) = _compose_step(net, loss_raw, opt, batch, key)
    x = jax.ShapeDtypeStruct((batch, 3, 224, 224), jnp.float32)
    y = jax.ShapeDtypeStruct((batch,), jnp.int32)
    cost = _cost(jfn, ap, ast, [x], y)
    return _emit("resnet50", "fwd+bwd+sgd_mom update", batch, cost,
                 HAND_GFLOP["resnet50"],
                 note="AMP bf16 active during trace, as in bench.py")


def audit_bert_base():
    """bench.py BERT leg: bert_base, batch 64, seq 128, AMP bf16, Adam,
    full-vocab MLM CE over every position."""
    _setup_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import amp, nd, optimizer
    from mxnet_tpu.models import bert

    batch = int(os.environ.get("BATCH", "64"))
    seq = int(os.environ.get("SEQ", "128"))
    vocab = 30522
    mx.random.seed(0)
    net = bert.bert_base(vocab_size=vocab)
    net.initialize(mx.init.Xavier())
    ids = nd.ones((1, 8), dtype="int32")
    net(ids, nd.zeros((1, 8), dtype="int32"))  # resolve deferred shapes
    amp.init(target_dtype="bfloat16")

    def loss_raw(outs, label):
        # the SAME fused CE the bench's _MLMLoss dispatches
        # (nn_ops.softmax_cross_entropy): f32 internal math, no f32
        # materialization of the (rows, vocab) logits
        from mxnet_tpu.ops.nn_ops import _softmax_ce_sum

        # no flatten: (b, s, vocab) direct — the reshape forced a
        # layout copy of the logits (bytes_breakdown r5)
        return _softmax_ce_sum(outs[-1],
                               label.astype(jnp.int32)) / (batch * seq)

    opt = optimizer.Adam(learning_rate=1e-4)
    key = jax.random.PRNGKey(0)
    jfn, (ap, ast) = _compose_step(net, loss_raw, opt, 1, key)
    x = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    seg = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    y = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    cost = _cost(jfn, ap, ast, [x, seg], y)
    return _emit("bert_base", "fwd+bwd+adam update", batch, cost,
                 HAND_GFLOP["bert_base"],
                 note="AMP bf16 active during trace, as in bench.py; "
                      "loss counted over all positions x full vocab")


def audit_llama1b():
    """examples/train_llama_1b.py: h2304 18L GQA 18/6, bf16 params,
    remat, flash attention, SGD momentum, token CE."""
    _setup_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer
    from mxnet_tpu.models import llama

    batch = int(os.environ.get("BATCH", "4"))
    seq = int(os.environ.get("SEQ", "2048"))
    layers = int(os.environ.get("LAYERS", "18"))
    vocab = 32000
    mx.random.seed(0)
    net = llama.LlamaForCausalLM(llama.LlamaConfig(
        hidden_size=2304, intermediate_size=6144, num_layers=layers,
        num_heads=18, num_kv_heads=6, vocab_size=vocab,
        max_seq_len=seq, attn_mode="flash"))
    net.initialize(mx.init.Zero())  # values don't matter for pricing
    net(nd.ones((1, 8), dtype="int32"))
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values())
    net.cast("bfloat16")

    def loss_raw(outs, label):
        logits = outs[0].astype(jnp.float32).reshape((-1, vocab))
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, label.reshape((-1,))[:, None],
                                  axis=-1)
        return ce.sum() / (batch * seq)

    opt = optimizer.SGD(learning_rate=1e-3, momentum=0.9)
    key = jax.random.PRNGKey(0)
    jfn, (ap, ast) = _compose_step(net, loss_raw, opt, 1, key,
                                   remat=True)
    x = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    y = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    cost = _cost(jfn, ap, ast, [x], y)
    hand = 6 * n_params * seq / 1e9 * batch / batch  # 6N per token
    from mxnet_tpu.ops import flash_attention as _fa

    attn_path = ("pallas-flash" if _fa._on_tpu() and seq % 128 == 0
                 else "chunked-jnp")
    rec = _emit("llama1b", "fwd+bwd(remat)+sgd_mom update", batch, cost,
                round(hand, 1),
                note=f"{n_params/1e9:.2f}B params; hand = 6N/token "
                     "(remat recompute NOT in hand count, IS in "
                     f"XLA's); attention kernel priced: {attn_path}")
    return rec


WORKLOADS = {
    "resnet50": audit_resnet50,
    "bert_base": audit_bert_base,
    "llama1b": audit_llama1b,
}


# -- runtime-registry mode ---------------------------------------------------

def load_registry(path):
    """Parse a ``telemetry.costs.dump()`` JSON file; None when the file
    is missing, unreadable or holds no analyzed entries (the caller then
    falls back to the lowering path)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or not payload.get("entries"):
        return None
    return payload


def registry_report(payload, throughput=None, step_time_s=None):
    """Audit record from a runtime cost-registry dump: per-kind flops /
    bytes totals (execution-weighted and per-execution), MFU against the
    dump's peak when a measured ``throughput`` (steps/sec) or
    ``step_time_s`` is supplied.

    ``flops_per_step`` sums ONE execution of every train-step-resident
    kind (cachedop fwd/bwd, fused updates, bulk segments) — the same
    "one full step" the lowering path prices; ``total_flops`` weights by
    recorded execution counts (the whole run's compute)."""
    per_kind = {}
    for e in payload.get("entries", []):
        k = per_kind.setdefault(e["kind"], {
            "artifacts": 0, "executions": 0, "flops_per_execution": 0.0,
            "bytes_per_execution": 0.0, "total_flops": 0.0,
            "total_bytes_accessed": 0.0, "errors": 0})
        k["artifacts"] += 1
        k["executions"] += e.get("executions", 0)
        k["flops_per_execution"] += e.get("flops", 0.0) or 0.0
        k["bytes_per_execution"] += e.get("bytes_accessed", 0.0) or 0.0
        k["total_flops"] += (e.get("flops", 0.0) or 0.0) * \
            e.get("executions", 0)
        k["total_bytes_accessed"] += \
            (e.get("bytes_accessed", 0.0) or 0.0) * e.get("executions", 0)
        if e.get("error"):
            k["errors"] += 1
    flops_per_step = sum(k["flops_per_execution"] for k in
                         per_kind.values())
    rec = {
        "source": "runtime cost registry",
        "device_kind": payload.get("device_kind"),
        "peak_flops": payload.get("peak_flops"),
        "per_kind": per_kind,
        "flops_per_step": flops_per_step,
        "bytes_accessed_per_step": sum(
            k["bytes_per_execution"] for k in per_kind.values()),
        "total_flops": sum(k["total_flops"] for k in per_kind.values()),
        "total_bytes_accessed": sum(
            k["total_bytes_accessed"] for k in per_kind.values()),
    }
    peak = payload.get("peak_flops")
    if step_time_s is None and throughput:
        step_time_s = 1.0 / float(throughput)
    if peak and step_time_s:
        rec["step_time_s"] = step_time_s
        rec["achieved_flops_per_sec"] = flops_per_step / step_time_s
        rec["mfu"] = round(flops_per_step / step_time_s / peak, 4)
    return rec


def _main_from_registry(path):
    payload = load_registry(path)
    if payload is None:
        print(f"registry dump {path!r} missing or empty; falling back "
              "to the lowering path", file=sys.stderr)
        return False
    thr = os.environ.get("THROUGHPUT")
    step_s = os.environ.get("STEP_TIME_S")
    rec = registry_report(payload,
                          throughput=float(thr) if thr else None,
                          step_time_s=float(step_s) if step_s else None)
    print(json.dumps(rec, indent=1))
    return True


def main():
    argv = list(sys.argv[1:])
    if "--from-registry" in argv:
        i = argv.index("--from-registry")
        path = argv[i + 1] if i + 1 < len(argv) else "COSTS.json"
        if _main_from_registry(path):
            return
        del argv[i:i + 2]  # fallback: audit by lowering
    which = argv[0] if argv else "all"
    if which != "all":
        WORKLOADS[which]()
        return
    out = {"peak_bf16_tflops": PEAK_BF16_TFLOPS, "workloads": []}
    for name in WORKLOADS:
        env = dict(os.environ)
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            name], capture_output=True, text=True,
                           env=env)
        lines = [ln for ln in r.stdout.splitlines()
                 if ln.startswith("{")]
        if r.returncode != 0 or not lines:
            out["workloads"].append({"workload": name, "error":
                                     r.stderr[-2000:]})
            print(f"{name}: FAILED", file=sys.stderr)
            continue
        out["workloads"].append(json.loads(lines[-1]))
    default = ("PREDICTED_THROUGHPUT_r05.json"
               if os.environ.get("AUDIT_PLATFORM") == "tpu_topology"
               else "MFU_AUDIT_r04.json")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), os.environ.get("AUDIT_OUT", default))
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
