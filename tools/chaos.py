#!/usr/bin/env python
"""Chaos harness: kill random ranks mid-run under the local launcher.

Preemption on TPU pods is not a unit test — it is a SIGTERM (graceful
drain window) or a straight SIGKILL (spot reclaim, OOM-killer, kernel
panic) landing on an arbitrary worker at an arbitrary moment.  This
harness reproduces exactly that against ``tools/launch.py``'s local
loopback topology: it arms a background "monkey" on every (re)spawned
group which, after a seeded random delay, signals a seeded random rank.
The launcher's babysitting loop (reap → backoff → relaunch) and the
ranks' resume path (``mxnet_tpu.checkpoint.resume``) are then expected
to carry the job to completion as if nothing happened.

The schedule is DETERMINISTIC given ``--seed``: delays, victim ranks
and the SIGTERM/SIGKILL choice all come from one ``random.Random``, so
a chaos failure reproduces with the same command line.

Usage (the ``--`` separates harness flags from the training command):

    python tools/chaos.py -n 2 --kills 3 --mix mixed --seed 7 \
        --max-restarts 8 -- python train.py --epochs 2

Exit status is the group's final status (0 = the run survived the
chaos); a JSON summary of every injection and the launcher's restart
counts goes to stdout (or ``--summary FILE``).

Stdlib-only, like the launcher it drives (never imports mxnet_tpu/jax:
the ranks own the accelerator runtime, the harness only owns signals).
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import launch  # noqa: E402  (sibling module; stdlib-only)

_SIGNALS = {"term": (signal.SIGTERM,),
            "kill": (signal.SIGKILL,),
            "mixed": (signal.SIGTERM, signal.SIGKILL)}


class ChaosMonkey:
    """Injects up to ``kills`` signals into live groups, one per spawn.

    ``arm(procs)`` plugs into ``launch.launch_local(on_spawn=...)``:
    each call cancels the previous timer (that group is already dead)
    and starts a new one against the fresh group.  One injection per
    group maximum — the launcher must observe the failure and relaunch
    before the monkey strikes again, which is exactly the recovery
    cadence of real preemption."""

    def __init__(self, kills, mix="mixed", min_delay=1.0, max_delay=4.0,
                 seed=0):
        self.budget = kills
        self.signals = _SIGNALS[mix]
        self.min_delay = min_delay
        self.max_delay = max_delay
        self.rng = random.Random(seed)
        self.injections = []
        self._timer = None
        self._lock = threading.Lock()
        self._t0 = time.time()

    def arm(self, procs):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            if self.budget <= 0:
                return
            delay = self.rng.uniform(self.min_delay, self.max_delay)
            victim = self.rng.randrange(len(procs))
            sig = self.signals[self.rng.randrange(len(self.signals))]
            self._timer = threading.Timer(
                delay, self._strike, (procs, victim, sig))
            self._timer.daemon = True
            self._timer.start()

    def _strike(self, procs, victim, sig):
        with self._lock:
            if self.budget <= 0:
                return
            p = procs[victim]
            if p.poll() is not None:
                return  # group already dying on its own; keep the budget
            try:
                p.send_signal(sig)
            except OSError:
                return
            self.budget -= 1
            self.injections.append({
                "t": round(time.time() - self._t0, 3),
                "rank": victim,
                "pid": p.pid,
                "signal": signal.Signals(sig).name,
            })
            print(f"chaos.py: sent {signal.Signals(sig).name} to rank "
                  f"{victim} (pid {p.pid})", file=sys.stderr)

    def disarm(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None


def run_chaos(n, cmd, kills=2, mix="mixed", min_delay=1.0, max_delay=4.0,
              seed=0, coordinator="127.0.0.1:12721", max_restarts=8,
              max_preemptions=64, backoff_base=0.2, backoff_cap=5.0,
              fleet_dump=None):
    """Run ``cmd`` across ``n`` loopback ranks with chaos injection.

    Returns ``(rc, summary_dict)``.  The backoff default is shorter
    than the launcher's production default — chaos runs live in test
    lanes where wall-clock matters and the coordinator port is local.

    ``fleet_dump`` (a path template; ``{rank}`` expands per rank) turns
    on the training flight recorder in every rank via the
    ``MXNET_FLEET``/``MXNET_FLEET_DUMP`` env contract (the launcher
    copies the harness env into each rank), and the summary gains
    ``fleet_dumps``/``fleet_dumps_complete``: whether every KILLED rank
    left a readable flight-recorder dump behind — the forensics the
    chaos lane exists to prove out."""
    if fleet_dump is not None:
        os.environ["MXNET_FLEET"] = "1"
        os.environ["MXNET_FLEET_DUMP"] = fleet_dump
    monkey = ChaosMonkey(kills, mix=mix, min_delay=min_delay,
                         max_delay=max_delay, seed=seed)
    stats = {}
    try:
        rc = launch.launch_local(
            n, cmd, coordinator=coordinator, max_restarts=max_restarts,
            max_preemptions=max_preemptions, backoff_base=backoff_base,
            backoff_cap=backoff_cap, on_spawn=monkey.arm, stats=stats)
    finally:
        monkey.disarm()
    summary = {
        "rc": rc,
        "survived": rc == 0,
        "injections": monkey.injections,
        "kills_remaining": monkey.budget,
        "restarts": stats.get("restarts", {}),
        "seed": seed,
        "mix": mix,
        "num_workers": n,
    }
    if fleet_dump is not None:
        dumps = {}
        for inj in monkey.injections:
            rank = inj["rank"]
            path = fleet_dump.replace("{rank}", str(rank))
            ok = False
            try:
                with open(path, "r") as f:
                    ok = json.load(f).get("record") == "flight_recorder"
            except (OSError, json.JSONDecodeError):
                ok = False
            dumps[str(rank)] = path if ok else None
        summary["fleet_dumps"] = dumps
        summary["fleet_dumps_complete"] = \
            bool(dumps) and all(v is not None for v in dumps.values())
    return rc, summary


def main(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("--kills", type=int, default=2,
                   help="total signals to inject (one per group spawn)")
    p.add_argument("--mix", default="mixed",
                   choices=sorted(_SIGNALS),
                   help="term = graceful drains only, kill = hard kills "
                        "only, mixed = coin-flip per injection")
    p.add_argument("--min-delay", type=float, default=1.0,
                   help="earliest injection after a (re)spawn, seconds")
    p.add_argument("--max-delay", type=float, default=4.0)
    p.add_argument("--seed", type=int, default=0,
                   help="chaos schedule seed (delays, victims, signals)")
    p.add_argument("--coordinator", default="127.0.0.1:12721")
    p.add_argument("--max-restarts", type=int, default=8)
    p.add_argument("--max-preemptions", type=int, default=64)
    p.add_argument("--backoff-base", type=float, default=0.2)
    p.add_argument("--backoff-cap", type=float, default=5.0)
    p.add_argument("--summary", default=None,
                   help="write the JSON summary here instead of stdout")
    p.add_argument("--fleet-dump", default=None, metavar="TEMPLATE",
                   help="enable the training flight recorder in every "
                        "rank (MXNET_FLEET=1) with this dump path "
                        "template ({rank} expands per rank); the "
                        "summary then asserts a dump exists for every "
                        "killed rank")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no command given (separate it with --)")
    rc, summary = run_chaos(
        args.num_workers, cmd, kills=args.kills, mix=args.mix,
        min_delay=args.min_delay, max_delay=args.max_delay,
        seed=args.seed, coordinator=args.coordinator,
        max_restarts=args.max_restarts,
        max_preemptions=args.max_preemptions,
        backoff_base=args.backoff_base, backoff_cap=args.backoff_cap,
        fleet_dump=args.fleet_dump)
    text = json.dumps(summary, indent=2)
    if args.summary:
        with open(args.summary, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    sys.exit(rc)


if __name__ == "__main__":
    main()
