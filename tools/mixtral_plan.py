#!/usr/bin/env python
"""Mixtral-8x7B pre-dispatch fit plan: reject the dp2 mesh BEFORE paying
the 168s compile.

Round 5 learned the hard way that Mixtral on dp2xep8xtp4 does not fit a
v5e chip: the overflow only surfaced AFTER a 64-chip offline lowering
(MIXTRAL_DP2_OVERFLOW_r05.json, 16.09 GiB on the 15.75 GiB
compiler-enforced budget).  This tool shows the r10 planner reaching the
same verdict pre-compile, two ways:

1. **artifact lane (load-bearing)** — ``planner.plan_from_artifact``
   over the committed r05 lowerings: XLA's own per-device memory
   analysis, read back in microseconds.  dp2xep8xtp4 is rejected and
   dp1xep8xtp8 accepted with the exact bytes the TPU toolchain printed.
2. **analytic lane (directional)** — ``planner.plan_model`` over the
   real parameter shapes (``lowering.shell_params`` — no array is ever
   materialized), sharded by the SAME mixtral partition-rule table the
   Trainer places with, sgd-f32-momentum state multipliers, and the
   committed lowering's measured XLA temp as the activation hint.  Both
   meshes must agree with the artifact verdict (the byte totals differ
   by construction: the analytic lane prices grads as live buffers
   where XLA folds them into temps).

The recommendation is the r5 fix, now machine-named: mesh change to
dp1xep8xtp8 (64-way expert sharding, same 64 chips, SP_BATCH=2 holds
the global batch), confirmed by MIXTRAL_LOWER_TPU_r05.json.
``planner.prescribe`` additionally prices the same-mesh levers (host
offload of the 5.8 GiB momentum; halved batch) — analytic-only,
unconfirmed by a lowering.

Run: ``python tools/mixtral_plan.py [out.json]``
(pure host math: no mesh, no jax compile, no TPU topology client).
Artifact: MIXTRAL_PLAN_r10.json (override MXT_MIXTRAL_PLAN_OUT).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REPO = os.path.join(os.path.dirname(__file__), "..")
_ARTIFACTS = {
    "dp2xep8xtp4": "MIXTRAL_DP2_OVERFLOW_r05.json",
    "dp1xep8xtp8": "MIXTRAL_LOWER_TPU_r05.json",
}
_MESHES = {
    "dp2xep8xtp4": {"dp": 2, "ep": 8, "tp": 4},
    "dp1xep8xtp8": {"dp": 1, "ep": 8, "tp": 8},
}


class _AbstractMesh:
    """Axis sizes without devices — the planner and the partition-rule
    engine only ever read ``mesh.shape``."""

    def __init__(self, shape):
        self.shape = dict(shape)


def main():
    from mxnet_tpu.memory import lowering, planner
    from mxnet_tpu.memory.planner import plan_from_artifact, plan_model
    from mxnet_tpu.models import llama
    from mxnet_tpu.parallel import partition as pt

    t0 = time.time()
    budget = int(lowering.TPU_BUDGET_GIB * 2 ** 30)

    # the committed r05 lowerings: XLA's per-device memory analysis
    committed = {}
    for mesh_name, fname in _ARTIFACTS.items():
        with open(os.path.join(_REPO, fname)) as f:
            committed[mesh_name] = (fname, json.load(f))

    # real parameter shapes, zero bytes materialized
    net = llama.mixtral_8x7b(attn_mode="flash")
    _, shapes, _, n_params = lowering.shell_params(net)
    for fname, art in committed.values():
        assert n_params == art["n_params"], \
            (f"shape audit: shell_params counts {n_params} params, "
             f"{fname} lowered {art['n_params']}")
    params = {n: (s, "bfloat16") for n, s in shapes.items()}
    rules = pt.PartitionRules.for_family("mixtral")

    lanes = {}
    for mesh_name, mesh_axes in _MESHES.items():
        fname, art = committed[mesh_name]
        art_plan = plan_from_artifact(os.path.join(_REPO, fname))

        # global ids+labels bytes at the artifact's global batch
        gb, seq = art["global_batch_x_seq"]
        batch_bytes = 2 * gb * seq * 4
        # the committed lowering ran per-layer remat; back out the
        # tier-"none" figure the activation_hint API scales back down
        temp_b = art["xla_memory_analysis_per_device"]["temp_size_in_bytes"]
        hint_none = int(temp_b / 0.15)
        ana_plan = plan_model(
            params, mesh=_AbstractMesh(mesh_axes), rules=rules,
            optimizer="sgd", batch_bytes=batch_bytes, remat="layer",
            activation_hint=hint_none, budget=budget)

        lanes[mesh_name] = {
            "mesh": mesh_axes,
            "artifact": fname,
            "per_chip_batch": art["per_chip_batch"],
            "artifact_plan": art_plan.as_dict(),
            "analytic_plan": ana_plan.as_dict(),
            "verdicts_agree": art_plan.fits == ana_plan.fits,
        }

    # same-mesh levers for the failing config, priced analytically
    # (plan_model left _last_plan at the dp1 lane — re-plan dp2 so the
    # prescription targets the failure)
    fname2, art2 = committed["dp2xep8xtp4"]
    temp2 = art2["xla_memory_analysis_per_device"]["temp_size_in_bytes"]
    gb2, seq2 = art2["global_batch_x_seq"]
    failing = plan_model(
        params, mesh=_AbstractMesh(_MESHES["dp2xep8xtp4"]), rules=rules,
        optimizer="sgd", batch_bytes=2 * gb2 * seq2 * 4, remat="layer",
        activation_hint=int(temp2 / 0.15), budget=budget)
    rx = planner.prescribe(failing)

    dp1 = lanes["dp1xep8xtp8"]["artifact_plan"]
    recommendation = {
        "change": "mesh dp1xep8xtp8 (64-way expert sharding, same 64 "
                  "chips, SP_BATCH=2 holds the global batch)",
        "predicted_peak_bytes": dp1["predicted_peak_bytes"],
        "predicted_peak_gib": dp1["predicted_peak_gib"],
        "headroom_bytes": dp1["headroom_bytes"],
        "fits": dp1["fits"],
        "confirmed_by": "MIXTRAL_LOWER_TPU_r05.json",
    }

    dp2a, dp1a = (lanes["dp2xep8xtp4"]["artifact_plan"],
                  lanes["dp1xep8xtp8"]["artifact_plan"])
    acceptance = {
        # the artifact lane reproduces the committed TPU numbers exactly
        "dp2_rejected_pre_compile": not dp2a["fits"],
        "dp2_peak_matches_artifact": dp2a["predicted_peak_bytes"]
            == art2["fit_verdict"][
                "resident_bytes_per_device_args_plus_temp"],
        "dp1_fits": dp1a["fits"],
        "dp1_peak_matches_artifact": dp1a["predicted_peak_bytes"]
            == committed["dp1xep8xtp8"][1]["fit_verdict"][
                "resident_bytes_per_device_args_plus_temp"],
        "budget_is_compiler_enforced_15_75_gib":
            dp2a["budget_bytes"] == budget
            and dp1a["budget_bytes"] == budget,
        "analytic_agrees_both_meshes": all(
            ln["verdicts_agree"] for ln in lanes.values()),
        "recommendation_confirmed_by_lowering":
            recommendation["fits"]
            and committed["dp1xep8xtp8"][1]["fit_verdict"][
                "fits_hbm_compiler_enforced"],
        "param_count_audited": True,  # the asserts above
    }

    record = {
        "metric": "mixtral_dp2_predicted_peak_gib",
        "value": dp2a["predicted_peak_gib"],
        "unit": "GiB per device, planner verdict vs 15.75 GiB budget",
        "n_params": n_params,
        "budget_bytes": budget,
        "lanes": lanes,
        "recommendation": recommendation,
        "same_mesh_levers_analytic": rx["candidates"] if rx else None,
        "acceptance": acceptance,
        "wall_sec": round(time.time() - t0, 2),
    }
    line = json.dumps(record, indent=1, default=str)
    print(line)
    out_path = os.environ.get(
        "MXT_MIXTRAL_PLAN_OUT",
        sys.argv[1] if len(sys.argv) > 1
        else os.path.join(_REPO, "MIXTRAL_PLAN_r10.json"))
    with open(out_path, "w") as f:
        f.write(line + "\n")
    if not all(acceptance.values()):
        raise SystemExit(f"acceptance failed: "
                         f"{ {k: v for k, v in acceptance.items() if not v} }")


if __name__ == "__main__":
    main()
