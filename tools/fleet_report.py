"""Merge per-rank telemetry streams into one fleet report.

The launcher gives each rank its own JSONL file; the flight recorder
(``mxnet_tpu.telemetry.fleet``) dumps each rank's last-N ring as a
single JSON document on drain/halt/exit.  This tool joins either (or a
mix) back into the pod-scale picture:

    # rank x step heatmap + straggler/anomaly summary, all ranks
    python tools/fleet_report.py out/rank*.jsonl

    # the same from flight dumps left behind by a chaos kill
    python tools/fleet_report.py dumps/fd.rank0.json dumps/fd.rank1.json

    # one Perfetto timeline, one track per rank
    python tools/fleet_report.py out/rank*.jsonl --format chrome \
        --out fleet.json

Inputs may be telemetry JSONL streams (``record`` mixes of
``step``-shaped records, ``fleet`` views and ``anomaly`` events) or
fleet flight-recorder dumps (``{"record": "flight_recorder", "kind":
"fleet", "records": [...]}``); streams merge by ``(step, rank)`` via
``telemetry.read_jsonl``.  The functions (`load_records`,
`heatmap_text`, `chrome_timeline`) are importable for tests.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.telemetry import fleet as _fleet  # noqa: E402
from mxnet_tpu.telemetry.sinks import read_jsonl  # noqa: E402


def _is_flight_dump(path):
    try:
        with open(path, "r", encoding="utf-8") as f:
            head = f.read(1)
            if head != "{":
                return None
            f.seek(0)
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(doc, dict) and doc.get("record") == "flight_recorder":
        return doc
    return None


def load_records(paths):
    """Every record from ``paths`` (JSONL streams, globs, or fleet
    flight dumps), merged and sorted by ``(step, rank)``.  Dump-borne
    records inherit the dump's ``rank`` when they lack their own."""
    out = []
    jsonl_paths = []
    for p in paths:
        doc = _is_flight_dump(p)
        if doc is not None:
            r = doc.get("rank")
            for rec in doc.get("records", []):
                if isinstance(rec, dict):
                    rec.setdefault("rank", r)
                    out.append(rec)
        else:
            jsonl_paths.append(p)
    if jsonl_paths:
        out.extend(read_jsonl(jsonl_paths if len(jsonl_paths) > 1
                              else jsonl_paths[0]))
    out.sort(key=lambda rec: (rec.get("step") or 0, rec.get("rank") or 0)
             if isinstance(rec, dict) else (0, 0))
    return [rec for rec in out if isinstance(rec, dict)]


def _partition(records):
    steps, fleet_views, anomalies = [], [], []
    for rec in records:
        kind = rec.get("record")
        if kind == "fleet":
            fleet_views.append(rec)
        elif kind == "anomaly":
            anomalies.append(rec)
        elif "step_ms" in rec and "step" in rec:
            steps.append(rec)
    return steps, fleet_views, anomalies


def heatmap_text(records, metric="compute_ms", threshold=None):
    """Rank x step text heatmap of ``metric`` over the fleet views,
    plus a summary NAMING straggler ranks and anomaly windows.

    Each fleet-view step is a column; each rank a row; cells carry the
    per-rank value with a ``*`` straggler flag (value above
    ``threshold`` x the column median, default the watchdog's skew
    threshold)."""
    if threshold is None:
        threshold = _fleet.SKEW_THRESHOLD
    steps, views, anomalies = _partition(records)
    lines = []
    # one view record per exchange step suffices (all ranks see the
    # same gathered matrix; rank 0's copy wins)
    by_step = {}
    for v in views:
        by_step.setdefault(v.get("step"), v)
    cols = sorted(s for s in by_step if s is not None)
    flagged_by_rank = {}
    if cols:
        world = max(len(by_step[s].get(metric) or []) for s in cols)
        lines.append("fleet heatmap: %s (* = > %.2fx column median)"
                     % (metric, threshold))
        lines.append("step    " + "".join("%12d" % s for s in cols))
        for r in range(world):
            cells = []
            for s in cols:
                vals = by_step[s].get(metric) or []
                if r >= len(vals):
                    cells.append("%12s" % "-")
                    continue
                flag = r in _fleet.detect_skew(vals, threshold)
                if flag:
                    flagged_by_rank[r] = flagged_by_rank.get(r, 0) + 1
                cells.append("%11.1f%s" % (float(vals[r]),
                                           "*" if flag else " "))
            lines.append("rank %-3d" % r + "".join(cells))
    else:
        lines.append("no fleet-view records (was the fleet layer "
                     "enabled, and did a stride boundary pass?)")
    lines.append("")
    util = {}
    for s in cols:
        for r, v in enumerate(by_step[s].get("duty_cycle") or []):
            util.setdefault(r, []).append(float(v))
    # all-zero columns come from pre-r20 peers that never packed the
    # 7th float — "unknown", not "idle"
    if any(any(vs) for vs in util.values()):
        lines.append("utilization (mean duty cycle): " + ", ".join(
            "rank %d %.1f%%" % (r, 100.0 * sum(vs) / len(vs))
            for r, vs in sorted(util.items())))
    if flagged_by_rank:
        worst = sorted(flagged_by_rank.items(),
                       key=lambda kv: -kv[1])
        lines.append("stragglers (by %s skew): " % metric + ", ".join(
            "rank %d (%d/%d windows)" % (r, n, len(cols))
            for r, n in worst))
    else:
        lines.append("stragglers: none")
    if anomalies:
        lines.append("anomalies:")
        for a in anomalies:
            who = a.get("culprit", a.get("rank"))
            detail = {k: v for k, v in a.items()
                      if k not in ("record", "kind", "step", "rank",
                                   "world_size", "wall_time", "culprit")}
            lines.append("  step %-6s %-20s rank %-3s %s"
                         % (a.get("step"), a.get("kind"), who, detail))
    else:
        lines.append("anomalies: none")
    lines.append("records: %d step, %d fleet view, %d anomaly"
                 % (len(steps), len(views), len(anomalies)))
    return "\n".join(lines)


def chrome_timeline(records):
    """chrome://tracing / Perfetto JSON: one track (pid) per rank, one
    complete ("X") event per step record, instant ("i") events for
    anomalies.  Timestamps are wall-clock relative to the earliest
    record so multi-rank streams line up on one timebase."""
    steps, _views, anomalies = _partition(records)
    walls = [rec.get("wall_time") for rec in steps + anomalies
             if rec.get("wall_time") is not None]
    t0 = min(walls) if walls else 0.0
    events = []
    seen_ranks = set()

    def track(rank):
        if rank not in seen_ranks:
            seen_ranks.add(rank)
            events.append({"ph": "M", "pid": rank, "tid": 0,
                           "name": "process_name",
                           "args": {"name": "rank %s" % rank}})

    for rec in steps:
        rank = rec.get("rank") or 0
        track(rank)
        dur_ms = float(rec.get("step_ms") or 0.0)
        ts = (float(rec.get("wall_time") or t0) - t0) * 1e6
        args = {"step": rec.get("step")}
        for k in ("examples_per_sec", "peak_live_bytes", "host_sync",
                  "compile_count", "allreduce_bytes"):
            if rec.get(k) is not None:
                args[k] = rec[k]
        wait = (rec.get("counters") or {}).get("trainer.allreduce_wait_ms")
        if wait is not None:
            args["allreduce_wait_ms"] = wait
        events.append({"ph": "X", "cat": "fleet",
                       "name": "step %s" % rec.get("step"),
                       "pid": rank, "tid": 1, "ts": ts,
                       "dur": dur_ms * 1e3, "args": args})
    for a in anomalies:
        rank = a.get("rank") or 0
        track(rank)
        ts = (float(a.get("wall_time") or t0) - t0) * 1e6
        events.append({"ph": "i", "cat": "fleet", "s": "p",
                       "name": "anomaly:%s" % a.get("kind"),
                       "pid": rank, "tid": 1, "ts": ts,
                       "args": {k: v for k, v in a.items()
                                if k not in ("record", "wall_time")}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge per-rank telemetry JSONL streams / fleet "
        "flight dumps into a rank x step heatmap or a Perfetto "
        "timeline with one track per rank")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="per-rank JSONL files, globs, or fleet "
                    "flight-recorder dumps")
    ap.add_argument("--metric", default="compute_ms",
                    help="fleet-view column for the heatmap "
                    "(default: compute_ms)")
    ap.add_argument("--threshold", default=None, type=float,
                    help="straggler flag threshold (x column median; "
                    "default: the watchdog's)")
    ap.add_argument("--format", choices=("text", "chrome"),
                    default="text")
    ap.add_argument("--out", default=None,
                    help="write here instead of stdout")
    args = ap.parse_args(argv)

    records = load_records(args.paths)
    if not records:
        print("no records found", file=sys.stderr)
        return 1
    sink = open(args.out, "w", encoding="utf-8") if args.out \
        else sys.stdout
    try:
        if args.format == "chrome":
            json.dump(chrome_timeline(records), sink, indent=1)
            sink.write("\n")
        else:
            sink.write(heatmap_text(records, metric=args.metric,
                                    threshold=args.threshold) + "\n")
    finally:
        if sink is not sys.stdout:
            sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
