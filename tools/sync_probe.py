"""Tunnel sync-semantics probe: does block_until_ready wait for device
COMPLETION or return at dispatch?  Dispatches a known-FLOP scanned
matmul chain and times three sync methods against the chain's physical
minimum time at peak (r4: the dispatch-return behavior inflated the r3
BERT window into >100% of bf16 peak, MFU_AUDIT_r04.json).  Also
reports the device kind and the achievable matmul TFLOP/s."""
import time, sys
import numpy as np
t0=time.time()
import jax, jax.numpy as jnp
from jax import lax
print(f"import {time.time()-t0:.1f}s", flush=True)
t0=time.time()
print("devices:", jax.devices(), f"{time.time()-t0:.1f}s", flush=True)
N = 4096
x = jnp.asarray(np.random.randn(N, N), dtype=jnp.bfloat16)
print("array placed", flush=True)
CHAIN = 500
@jax.jit
def chain(x):
    def body(y, _):
        y = y @ x
        y = y / jnp.sqrt(jnp.float32(N)).astype(jnp.bfloat16)
        return y, ()
    y, _ = lax.scan(body, x, None, length=CHAIN)
    return y
t0=time.time()
y = chain(x)
print(f"dispatch1 {time.time()-t0:.1f}s", flush=True)
t0=time.time()
y.block_until_ready()
print(f"block1(compile+run) {time.time()-t0:.1f}s", flush=True)
t0=time.time()
s = np.asarray(y[0,0])
print(f"fetch1 {time.time()-t0:.3f}s", flush=True)
flops = 2*N**3*CHAIN
print(f"chain {flops/1e12:.1f} TF -> min {flops/197e12:.3f}s at peak", flush=True)
for trial in range(3):
    t0=time.time(); y = chain(x); t1=time.time()
    y.block_until_ready(); t2=time.time()
    jax.block_until_ready(jnp.zeros(())); t3=time.time()
    s = np.asarray(y[0,0]); t4=time.time()
    print(f"trial{trial}: dispatch={t1-t0:.3f} block=+{t2-t1:.3f} zeros=+{t3-t2:.3f} fetch=+{t4-t3:.3f} total={t4-t0:.3f}", flush=True)
