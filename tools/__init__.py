# tools/ is a namespace for repo tooling.  This file exists so
# ``python -m tools.lint`` resolves from the repo root; the standalone
# scripts in this directory (im2rec.py, launch.py, ...) are unaffected.
