#!/usr/bin/env python
"""im2rec: pack an image folder / list file into RecordIO shards.

Reference: ``tools/im2rec.py:?`` (+ C++ ``im2rec.cc`` [med]) — reads a
``.lst`` file (``index\\tlabel[\\tlabel...]\\tpath``) or generates one from a
directory tree, encodes images (resize/quality/center-crop) and writes
``prefix.rec`` (+ ``prefix.idx``) shards readable by ``ImageRecordIter``
(SURVEY §2.5).

TPU notes: output is byte-compatible with the reference RecordIO format
(dmlc recordio magic + IRHeader), so .rec files pack once and feed either
framework.  Encoding uses PIL when available; raw-ndarray packing
(``--pack-label`` style float payloads) needs no image library at all.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu import recordio  # noqa: E402

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive=False):
    """Yield (relpath, label) pairs; labels are per-subdirectory indices
    (reference behavior for --recursive)."""
    if recursive:
        cat = {}
        for path, _dirs, files in sorted(os.walk(root)):
            for f in sorted(files):
                if f.lower().endswith(_EXTS):
                    d = os.path.relpath(path, root)
                    if d not in cat:
                        cat[d] = len(cat)
                    yield os.path.join(os.path.relpath(path, root), f), \
                        cat[d]
    else:
        for i, f in enumerate(sorted(os.listdir(root))):
            if f.lower().endswith(_EXTS):
                yield f, 0


def make_list(args):
    """Write prefix.lst (reference --list mode)."""
    items = list(list_images(args.root, args.recursive))
    if args.shuffle:
        random.seed(100)
        random.shuffle(items)
    with open(args.prefix + ".lst", "w") as f:
        for i, (path, label) in enumerate(items):
            f.write(f"{i}\t{float(label)}\t{path}\n")
    return args.prefix + ".lst"


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def _encode_image(path, args):
    from PIL import Image
    import io as _io

    img = Image.open(path).convert("RGB")
    if args.resize:
        w, h = img.size
        short = min(w, h)
        scale = args.resize / short
        img = img.resize((max(1, int(w * scale)), max(1, int(h * scale))))
    if args.center_crop:
        w, h = img.size
        s = min(w, h)
        left, top = (w - s) // 2, (h - s) // 2
        img = img.crop((left, top, left + s, top + s))
    buf = _io.BytesIO()
    img.save(buf, format="JPEG" if args.encoding == ".jpg" else "PNG",
             quality=args.quality)
    return buf.getvalue()


def im2rec(args):
    lst = args.prefix + ".lst"
    if not os.path.exists(lst):
        lst = make_list(args)
    rec_path = args.prefix + ".rec"
    idx_path = args.prefix + ".idx"
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    n = 0
    for idx, labels, relpath in read_list(lst):
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        full = os.path.join(args.root, relpath)
        payload = _encode_image(full, args)
        writer.write_idx(idx, recordio.pack(header, payload))
        n += 1
    writer.close()
    print(f"wrote {n} records to {rec_path}")
    return rec_path, idx_path


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix (prefix.rec/.idx/.lst)")
    p.add_argument("root", help="image root directory")
    p.add_argument("--list", action="store_true",
                   help="only generate the .lst file")
    p.add_argument("--recursive", action="store_true")
    p.add_argument("--shuffle", action="store_true", default=True)
    p.add_argument("--no-shuffle", dest="shuffle", action="store_false")
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    args = p.parse_args(argv)
    if args.list:
        print(make_list(args))
    else:
        im2rec(args)


if __name__ == "__main__":
    main()
