#!/usr/bin/env python
"""Pallas fused 1x1-conv+BN+ReLU probe — the PERF_NOTES ceiling
question (VERDICT r3 item 9): ResNet-50's non-conv time is
bandwidth-bound elementwise/norm traffic between convs; can a
hand-fused Pallas kernel beat XLA's conv+BN+ReLU fusion?

The probe fuses the bottleneck block's 1x1 conv (half its FLOPs; as a
matmul it is exactly MXU-shaped) with the folded BN affine and the ReLU
in ONE Pallas kernel: out = relu(scale_n * (x @ w) + bias_n), written
bf16, scores tiled in VMEM.  The XLA baseline is the framework's own
Convolution+BatchNorm(inference)+relu chain — what bench.py's ResNet
actually runs per block.

Both paths are timed from the SAME NCHW logical input with the
scan-slope harness (benchmark/opperf.py — dispatch-return-proof), so
the Pallas path pays its NCHW<->NHWC transposes honestly.

Run on chip:  python tools/pallas_conv_probe.py          (prints JSON)
CPU numerics: BENCH_PLATFORM=cpu ... --check  (pallas interpret mode)
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def fused_matmul_affine_relu(x, w, scale, bias, block_m=512,
                             block_n=256, block_k=256, interpret=False):
    """relu(scale * (x @ w) + bias) as one Pallas kernel.

    x (M, K) bf16, w (K, N) bf16, scale/bias (N,) f32 -> (M, N) bf16.
    f32 accumulation in VMEM scratch across the K sweep; the affine +
    relu epilogue runs on the accumulator before the single bf16 store
    — the HBM round trip XLA's separate BN/ReLU kernels would pay is
    gone (that's the whole experiment)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = w.shape
    bm, bn, bk = (min(block_m, m), min(block_n, n), min(block_k, k))
    assert m % bm == 0 and n % bn == 0 and k % bk == 0, (m, n, k)
    nk = k // bk

    def kernel(x_ref, w_ref, s_ref, b_ref, o_ref, acc_ref, *, nk):
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += jnp.dot(
            x_ref[...].astype(jnp.bfloat16),
            w_ref[...].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32)

        @pl.when(kj == nk - 1)
        def _epilogue():
            y = acc_ref[...] * s_ref[...][0] + b_ref[...][0]
            o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)

    grid = (m // bm, n // bn, nk)
    return pl.pallas_call(
        functools.partial(kernel, nk=nk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, w, scale.reshape(1, -1), bias.reshape(1, -1))


def _paths(B, C, H, W, interpret=False):
    """(xla_fn, pallas_fn, inputs) for the SAME NCHW bottleneck stage."""
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    mx.random.seed(0)
    bf16 = "bfloat16"
    x = mx.random.uniform(shape=(B, C, H, W)).astype(bf16)
    w = mx.random.uniform(shape=(C, C, 1, 1)).astype(bf16)
    gamma = mx.random.uniform(shape=(C,)) + 0.5
    beta = mx.random.uniform(shape=(C,)) - 0.5
    mean = mx.random.uniform(shape=(C,)) * 0.1
    var = mx.random.uniform(shape=(C,)) + 0.9

    def xla_fn(x, w, gamma, beta, mean, var):
        y = nd.Convolution(x, w, kernel=(1, 1), num_filter=C,
                           no_bias=True)
        y = nd.BatchNorm(y, gamma, beta, mean, var,
                         use_global_stats=True)[0]
        return nd.relu(y)

    # BN folded to per-channel affine on the conv output
    def pallas_fn(x, w, gamma, beta, mean, var):
        from mxnet_tpu.ndarray import NDArray
        from mxnet_tpu.ops.registry import apply_op

        def f(xr, wr, g, b, mu, v):
            scale = (g / jnp.sqrt(v + 1e-5)).astype(jnp.float32)
            bias = (b - mu * scale).astype(jnp.float32)
            xm = xr.transpose(0, 2, 3, 1).reshape(-1, C)
            wm = wr.reshape(C, C).T
            ym = fused_matmul_affine_relu(xm, wm, scale, bias,
                                          interpret=interpret)
            return ym.reshape(xr.shape[0], xr.shape[2], xr.shape[3],
                              C).transpose(0, 3, 1, 2)

        return apply_op(f, x, w, gamma, beta, mean, var,
                        name="pallas_conv_bn_relu")

    return xla_fn, pallas_fn, [x, w, gamma, beta, mean, var]


def main():
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    import jax

    check = "--check" in sys.argv
    interpret = jax.default_backend() != "tpu" and \
        "axon" not in str(jax.devices()[0]).lower()

    B, C, H, W = ((4, 256, 16, 16) if check else (64, 256, 56, 56))
    xla_fn, pallas_fn, inputs = _paths(B, C, H, W, interpret=interpret)

    ref = xla_fn(*inputs).asnumpy().astype(np.float32)
    got = pallas_fn(*inputs).asnumpy().astype(np.float32)
    rms = float(np.sqrt(np.mean(ref.astype(np.float64) ** 2)))
    err = float(np.max(np.abs(ref - got)))
    # bf16 epilogue rounding: one ulp of the activation scale
    assert err <= max(0.02 * rms, 0.05), (err, rms)
    if check:
        print(json.dumps({"probe": "pallas_conv_bn_relu",
                          "numerics": "ok", "max_abs_err": err,
                          "interpret": interpret}))
        return

    from benchmark.opperf import _measure

    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    inner = int(os.environ.get("BENCH_OPPERF_INNER", "30"))
    flops = 2 * B * C * C * H * W
    t_xla = _measure(xla_fn, inputs, inner, repeats)
    t_pal = _measure(pallas_fn, inputs, inner, repeats)
    print(json.dumps({
        "probe": "pallas fused 1x1conv+BN+relu vs XLA chain "
                 "(PERF_NOTES ceiling question)",
        "shape": [B, C, H, W],
        "xla_usec_per_call": round(t_xla * 1e6, 2),
        "pallas_usec_per_call": round(t_pal * 1e6, 2),
        "xla_tflops": round(flops / t_xla / 1e12, 2),
        "pallas_tflops": round(flops / t_pal / 1e12, 2),
        "pallas_speedup": round(t_xla / t_pal, 3),
        "verdict": ("pallas wins — productionize in r5"
                    if t_pal < t_xla * 0.97 else
                    "no win — XLA's fusion already at the ceiling "
                    "(negative result, closes the question)"),
    }))


if __name__ == "__main__":
    main()
