"""Human, JSON, and SARIF reporters for mxlint."""
from __future__ import annotations

import json
from collections import Counter

from .rules import RULES


def render_human(new, waived, stale, out):
    for v in new:
        out.write(f"{v.path}:{v.line}:{v.col + 1}: "
                  f"{v.rule} [{v.severity}] {v.message}"
                  f"  (in {v.context})\n")
        if v.source:
            out.write(f"    {v.source}\n")
    by_rule = Counter(v.rule for v in new)
    if new:
        out.write("\n")
        for rule in sorted(by_rule):
            desc = RULES.get(rule, "tool error")
            out.write(f"  {rule}: {by_rule[rule]:>3}  {desc}\n")
        out.write(f"\nmxlint: {len(new)} new violation"
                  f"{'s' if len(new) != 1 else ''}"
                  f" ({len(waived)} waived by baseline)\n")
    else:
        out.write(f"mxlint: clean ({len(waived)} waived by baseline)\n")
    if stale:
        out.write(f"note: {len(stale)} baseline waiver"
                  f"{'s' if len(stale) != 1 else ''} no longer match — "
                  "debt was fixed; run --update-baseline to prune.\n")


def render_json(new, waived, stale, out, cache_stats=None):
    payload = {
        "new": [v.to_dict() for v in new],
        "waived": [v.to_dict() for v in waived],
        "stale_waivers": list(stale),
        "summary": {
            "new": len(new),
            "waived": len(waived),
            "stale": len(stale),
            "by_rule": dict(Counter(v.rule for v in new)),
        },
    }
    if cache_stats is not None:
        payload["summary"]["cache"] = dict(cache_stats)
    json.dump(payload, out, indent=2)
    out.write("\n")


def render_sarif(new, waived, stale, out):
    """SARIF 2.1.0 for CI code-scanning annotation.  New violations
    become results; baseline-waived ones are included with
    ``baselineState: "unchanged"`` so scanners can show waived debt
    without failing the run."""
    rules = [{
        "id": rid,
        "shortDescription": {"text": desc},
        "helpUri": "docs/lint.md",
    } for rid, desc in sorted(RULES.items())]

    def result(v, baseline_state=None):
        r = {
            "ruleId": v.rule,
            "level": "error" if v.severity == "error" else "warning",
            "message": {"text": f"{v.message}  (in {v.context})"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": v.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(v.line, 1),
                               "startColumn": v.col + 1},
                },
            }],
            "partialFingerprints": {"mxlint/v1": v.fingerprint()},
        }
        if baseline_state is not None:
            r["baselineState"] = baseline_state
        return r

    payload = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "mxlint",
                "informationUri": "docs/lint.md",
                "rules": rules,
            }},
            "results": [result(v) for v in new] +
                       [result(v, "unchanged") for v in waived],
        }],
    }
    json.dump(payload, out, indent=2)
    out.write("\n")
