"""Traced-region ("hot path") inference.

The analyzer must know which function bodies execute *inside a jax
trace*: a host sync there stalls (or breaks) the whole fused program,
while the same call in eager glue code is merely a normal blocking
fetch.  Tracing in this codebase enters through a small set of doors:

  * ``hybrid_forward`` bodies (CachedOp traces them — gluon/block.py),
  * functions handed to ``jax.jit`` / ``jax.vjp`` / ``jax.grad`` /
    ``jax.value_and_grad`` / ``jax.checkpoint`` / ``lax.scan`` /
    ``lax.cond`` / ``lax.while_loop`` / ``lax.fori_loop`` ...,
  * functions decorated with those transforms,
  * pure bodies handed to ``apply_op`` (ops/registry.py — every op's
    inner function runs under trace whenever the op is jitted or vjp'd),
  * anything those functions call *within the same module* (one-module
    call-graph closure: cross-module reachability is the registry's and
    the runtime's problem, and chasing it statically would drown the
    report in speculative paths).

Lexical nesting inherits hotness: a ``def body(...)`` inside a traced
``k_steps`` is itself traced.
"""
from __future__ import annotations

import ast

from .core import dotted_name, last_name

#: function-def names that are traced by construction
HOT_DEF_NAMES = {"hybrid_forward"}

#: last component of a dotted callable that *enters* a trace when handed
#: a function (jax.jit, lax.scan, registry.apply_op, ...)
TRACE_ENTRY_NAMES = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "vjp", "jvp",
    "checkpoint", "remat", "custom_vjp", "custom_jvp",
    "scan", "cond", "while_loop", "fori_loop", "switch", "associative_scan",
    "apply_op",
}

#: decorators that make the decorated def a traced region
HOT_DECORATOR_NAMES = TRACE_ENTRY_NAMES - {"apply_op"}

#: observability/recording callees that never run inside a trace: the
#: telemetry/profiler fast path reads host clocks by design, and CachedOp's
#: ``_trace_guard`` keeps instrumentation out of traced replays — so a call
#: to one of these must not propagate hotness into a same-module recording
#: helper (whose ``time.perf_counter`` would then false-positive as T4)
RECORDING_SAFE_CALLEES = {
    "span", "count", "gauge", "mark", "step_begin", "step_end",
    "record_op_event", "record_span_event", "record_counter_event",
    "current_scope_prefix",
    # memwatch/costs observability hooks (PR 5): shape×itemsize ledger
    # arithmetic and registry bookkeeping — never a device sync, and
    # guarded by one-boolean flags outside traces
    "track", "donated", "adopt", "step_mark", "annotate_oom", "note",
    # request tracing + SLO accounting (r12, telemetry.tracing /
    # serving.metrics): retroactive span appends from perf_counter
    # stamps and rolling goodput counters — host-side by contract
    "start_trace", "finish", "incident", "add_span", "observe",
    # fleet observability hooks (r13, telemetry.fleet): rank stamping,
    # ring appends and watchdog arithmetic behind one-boolean flags;
    # the stride allgather is isolated in _fleet_exchange
    # (MATERIALIZE_DEFS) and never rides these entry points' fast path
    "on_step_record", "observe_step", "observe_fleet",
    # numerics tier taps (r17, telemetry.numerics): pure jnp stat math
    # emitted as trace side outputs — no host transfer on any tap path;
    # the single stride-gated sync is numerics._materialize
    # (MATERIALIZE_DEFS), and record_compiled only queues device scalars
    "tap", "tap_stacked", "stats_of", "record_compiled",
    "record_stacked", "step_summary",
    # capacity accounting hooks (r20, telemetry.capacity): retroactive
    # interval-ledger / EWMA appends from stamps the serving lanes
    # already take — one boolean disabled, float ops under one lock
    # enabled, never a clock read of their own beyond the stamps
    # handed in, never a device touch
    "note_arrival", "note_completion", "note_tick", "note_spec",
    "note_kv", "lane_busy",
}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_trace_entry(func_expr) -> bool:
    """Is ``func_expr`` (the .func of a Call) a trace-entering callable?"""
    if isinstance(func_expr, ast.Call):
        # partial(jax.jit, ...)(f) / functools.partial(jax.jit, ...)
        if last_name(func_expr.func) == "partial" and func_expr.args:
            return _is_trace_entry(func_expr.args[0])
        return False
    name = last_name(func_expr)
    if name not in TRACE_ENTRY_NAMES:
        return False
    dotted = dotted_name(func_expr)
    if "." not in dotted:
        return True  # from jax import jit; from ..ops.registry import apply_op
    head = dotted.split(".", 1)[0]
    return head in ("jax", "lax", "jnp", "registry", "functools", "self") or \
        "jax" in dotted or "lax" in dotted or name == "apply_op"


class FunctionIndex:
    """Per-module index: every function/lambda node, its qualname, its
    parent chain, and the set of nodes whose bodies are traced."""

    def __init__(self, tree: ast.AST):
        self.tree = tree
        self.parents = {}          # id(node) -> parent node
        self.func_qualnames = {}   # id(func node) -> qualname
        self.by_name = {}          # bare name -> [func nodes]
        self._index()
        self.hot = self._infer_hot()

    # -- construction --------------------------------------------------------
    def _index(self):
        stack = [(self.tree, None, "")]
        while stack:
            node, parent, prefix = stack.pop()
            if parent is not None:
                self.parents[id(node)] = parent
            if isinstance(node, _FUNC_NODES):
                name = getattr(node, "name", "<lambda>")
                qual = f"{prefix}.{name}" if prefix else name
                self.func_qualnames[id(node)] = qual
                self.by_name.setdefault(name, []).append(node)
                child_prefix = qual
            elif isinstance(node, ast.ClassDef):
                child_prefix = f"{prefix}.{node.name}" if prefix \
                    else node.name
            else:
                child_prefix = prefix
            for child in ast.iter_child_nodes(node):
                stack.append((child, node, child_prefix))

    # -- hot inference -------------------------------------------------------
    def _decorator_hot(self, node) -> bool:
        for deco in getattr(node, "decorator_list", ()):
            target = deco.func if isinstance(deco, ast.Call) else deco
            if isinstance(target, ast.Call):  # @partial(jax.jit, ...)
                if _is_trace_entry(target):
                    return True
                continue
            if last_name(target) in HOT_DECORATOR_NAMES and \
                    ("jax" in dotted_name(target) or
                     "." not in dotted_name(target)):
                return True
        return False

    def _infer_hot(self):
        hot = set()
        # 1. roots by name / decorator
        for name, nodes in self.by_name.items():
            for node in nodes:
                if name in HOT_DEF_NAMES or self._decorator_hot(node):
                    hot.add(id(node))
        # 2. roots by being handed to a trace entry
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call) or \
                    not _is_trace_entry(call.func):
                continue
            candidates = list(call.args) + [kw.value for kw in call.keywords]
            for arg in candidates:
                if isinstance(arg, ast.Lambda):
                    hot.add(id(arg))
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    for fn in self.by_name.get(last_name(arg), ()):
                        hot.add(id(fn))
        # 3. same-module call-graph closure
        node_by_id = {id(n): n for nodes in self.by_name.values()
                      for n in nodes}
        changed = True
        while changed:
            changed = False
            for fid in list(hot):
                node = node_by_id.get(fid)
                if node is None:
                    continue
                for callee in self._called_names(node):
                    for fn in self.by_name.get(callee, ()):
                        if id(fn) not in hot:
                            hot.add(id(fn))
                            changed = True
        return hot

    def _called_names(self, func_node):
        """Bare names of same-module callables invoked from ``func_node``
        (``foo(...)``, ``self.foo(...)``, ``cls.foo(...)``)."""
        out = set()
        for call in ast.walk(func_node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if isinstance(f, ast.Name):
                if f.id not in RECORDING_SAFE_CALLEES:
                    out.add(f.id)
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls"):
                if f.attr not in RECORDING_SAFE_CALLEES:
                    out.add(f.attr)
        return out

    # -- queries -------------------------------------------------------------
    def enclosing_function(self, node):
        cur = self.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                return cur
            cur = self.parents.get(id(cur))
        return None

    def qualname_of(self, node) -> str:
        fn = node if isinstance(node, _FUNC_NODES) \
            else self.enclosing_function(node)
        if fn is None:
            return "<module>"
        return self.func_qualnames.get(id(fn), "<module>")

    def in_traced_region(self, node) -> bool:
        """True if any lexically-enclosing function is hot."""
        cur = node if isinstance(node, _FUNC_NODES) \
            else self.enclosing_function(node)
        while cur is not None:
            if id(cur) in self.hot:
                return True
            cur = self.enclosing_function(cur)
        return False


# ---------------------------------------------------------------------------
# Taint: which local names in a traced function derive from traced values
# ---------------------------------------------------------------------------

#: attribute reads that yield static (python-level) values even on traced
#: arrays — branching on these is fine
SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "name", "stype", "context",
              "itemsize"}

#: calls whose result is a static python value regardless of arguments
SAFE_CALLS = {"len", "isinstance", "issubclass", "type", "getattr",
              "hasattr", "callable", "str", "repr", "id", "issubdtype",
              "dtype", "format"}


def function_taint(func_node) -> set:
    """Names in ``func_node`` that (conservatively) hold traced values:
    parameters without defaults (minus self/cls/F) plus anything assigned
    from an expression involving a tainted name.  Config-style parameters
    (those *with* defaults) are presumed static — branching on ``axis`` or
    ``normalization`` retraces at most, it cannot fail inside the trace."""
    args = func_node.args
    tainted = set()
    positional = list(args.posonlyargs) + list(args.args)
    n_defaults = len(args.defaults)
    no_default = positional[:len(positional) - n_defaults] if n_defaults \
        else positional
    for a in no_default:
        if a.arg not in ("self", "cls", "F"):
            tainted.add(a.arg)
    if args.vararg is not None:
        tainted.add(args.vararg.arg)

    # forward pass over the body in statement order
    for node in ast.walk(func_node):
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.NamedExpr):
            targets, value = [node.target], node.value
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            targets, value = [node.target], node.iter
        if value is None:
            continue
        names = _target_names(targets)
        if expr_tainted(value, tainted):
            tainted.update(names)
    return tainted


def _target_names(targets):
    out = []
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.append(n.id)
    return out


def expr_tainted(expr, tainted: set) -> bool:
    """Does ``expr`` depend on a tainted name in a way that would force a
    concrete value out of a tracer?"""
    if isinstance(expr, ast.Constant):
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in SAFE_ATTRS:
            return False
        return expr_tainted(expr.value, tainted)
    if isinstance(expr, ast.Call):
        if last_name(expr.func) in SAFE_CALLS:
            return False
        parts = [expr.func] + list(expr.args) + \
            [kw.value for kw in expr.keywords]
        return any(expr_tainted(p, tainted) for p in parts)
    if isinstance(expr, ast.Compare):
        # ``x is None`` / ``mode == "valid"``: identity checks and
        # comparisons against string constants are config dispatch
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False
        operands = [expr.left] + list(expr.comparators)
        if any(isinstance(o, ast.Constant) and isinstance(o.value, str)
               for o in operands):
            return False
        return any(expr_tainted(o, tainted) for o in operands)
    if isinstance(expr, ast.Subscript):
        return expr_tainted(expr.value, tainted) or \
            expr_tainted(expr.slice, tainted)
    return any(expr_tainted(child, tainted)
               for child in ast.iter_child_nodes(expr))
