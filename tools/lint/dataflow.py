"""Def-use / alias dataflow for buffer-donation rules (T6/T7).

``jax.jit(fn, donate_argnums=...)`` invalidates the donated input
buffers at dispatch: any later read of a donated array surfaces as
XLA's cryptic "Array has been deleted", usually far from the call that
donated it.  These rules catch the two static shapes of that bug:

T6  use-after-donation: a binding passed at a donated position of a
    donating call is *read* in a later statement before being rebound.
    Rebinding (``w = step(w, g)``) clears the poison; so does ``del``.
T7  donation aliasing: the same array — or a view/member of the same
    parent container — reaches one call at both a donated and another
    position, or the donated callee *closes over* the array it is
    handed for donation.  XLA donates the underlying buffer, so the
    "other" reference dies with it.

Donating callables are resolved per module:

  * direct bindings        ``fn = jax.jit(f, donate_argnums=(0,))``
  * attribute bindings     ``self._step = jax.jit(self._impl, ...)``
  * factory functions      ``def _build(...): return jax.jit(k_steps,
    donate_argnums=(0, 1, 2, 3))`` — call sites of ``_build`` produce
    donating bindings; factories may also thread the argnums through a
    parameter (``def _jitted(self, key, fn, donate=()): ...
    jax.jit(fn, donate_argnums=donate)``), resolved from each call
    site's ``donate=`` argument
  * inline calls           ``jax.jit(f, donate_argnums=(0,))(x)``

The per-function scan is statement-ordered and branch-aware: ``if``
arms are scanned independently and merged (a name stays poisoned
unless *every* arm rebinds it); loop bodies are scanned twice so a
donation at the bottom of an iteration poisons a read at the top of
the next; ``except`` handlers inherit the poison of the guarded body
(the donating dispatch may have happened before the raise).

Reads that occur as arguments to the runtime donation sanitizer
(``_san.donate(...)`` / ``sanitizer.*``, see mxnet_tpu/sanitizer.py)
or the memwatch ledger (``_mw.donated(...)``, see
mxnet_tpu/telemetry/memwatch.py) are exempt: handing the just-donated
handles to the poison registry / releasing them from the live-buffer
ledger are the legitimate post-donation uses — both read only ``id()``
and shape metadata, never the device buffer.

Known precision limits (documented in docs/lint.md): attribute-rooted
bindings are tracked by attribute name only; ``donate_argnames`` is
not resolved; container concatenation (``w_raws + m_raws``) does not
propagate alias roots (array ``+`` allocates, tuple ``+`` shares —
statically indistinguishable, so we choose the quiet side).
"""
from __future__ import annotations

import ast

from .core import Violation, SEVERITY_ERROR, dotted_name, last_name

#: dotted heads naming the runtime donation sanitizer and the memwatch
#: ledger: reads inside these calls are the poison-registry handoff /
#: ledger release of just-donated handles, not buffer uses
SANITIZER_HEADS = {"_san", "sanitizer", "_mw", "memwatch"}

#: callables that enter a donating trace when given donate_argnums
_JIT_NAMES = {"jit", "pjit"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: alias-path index meaning "the whole container, or an unknown part"
_WHOLE = "*"


class Donating:
    """A resolved donating callable: which call-arg positions are
    donated, and enough about the wrapped function for messages and
    the closure-capture check."""

    __slots__ = ("argnums", "param_names", "label", "callee", "line")

    def __init__(self, argnums, param_names, label, callee, line):
        self.argnums = argnums          # donated *call-arg* positions
        self.param_names = param_names  # pos -> wrapped-fn param name
        self.label = label              # for messages
        self.callee = callee            # wrapped func ast node or None
        self.line = line                # jit(...) line


def _const_argnums(expr):
    """(0,) / [0, 2] / 0 as a tuple of ints, else None."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return (expr.value,)
    if isinstance(expr, (ast.Tuple, ast.List)):
        vals = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                vals.append(e.value)
            else:
                return None
        return tuple(vals)
    return None


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_donating_jit(call) -> bool:
    if not isinstance(call, ast.Call):
        return False
    if last_name(call.func) not in _JIT_NAMES:
        return False
    return _kw(call, "donate_argnums") is not None


def _positional_params(fn_node, skip_self):
    args = fn_node.args
    names = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    if skip_self and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def _param_default(fn_node, name):
    """Default expression for parameter ``name``, or None."""
    args = fn_node.args
    positional = list(args.posonlyargs) + list(args.args)
    n_def = len(args.defaults)
    for a, d in zip(positional[len(positional) - n_def:], args.defaults):
        if a.arg == name:
            return d
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if a.arg == name and d is not None:
            return d
    return None


class _Resolver:
    """Module-wide table of donating bindings."""

    def __init__(self, src, index):
        self.src = src
        self.index = index
        self.local = {}      # (id(scope) | None, name) -> Donating
        self.attrs = {}      # attribute name -> Donating
        self.inline = {}     # id(outer Call) -> Donating
        self.factories = {}  # id(func def) -> factory spec dict
        self._collect_jits()
        self._collect_factory_calls()

    @property
    def any(self):
        return bool(self.local or self.attrs or self.inline)

    # -- collection ----------------------------------------------------------
    def _collect_jits(self):
        for call in ast.walk(self.src.tree):
            if not _is_donating_jit(call):
                continue
            argnums_expr = _kw(call, "donate_argnums")
            wrapped = call.args[0] if call.args else None
            enclosing = self.index.enclosing_function(call)
            argnums = _const_argnums(argnums_expr)

            if argnums is None and isinstance(argnums_expr, ast.Name) and \
                    enclosing is not None and not \
                    isinstance(enclosing, ast.Lambda) and \
                    argnums_expr.id in _positional_params(enclosing, False):
                # factory threading argnums through a parameter
                # (optimizer._jitted): resolved per call site
                fn_param = wrapped.id if isinstance(wrapped, ast.Name) and \
                    wrapped.id in _positional_params(enclosing, False) \
                    else None
                self.factories[id(enclosing)] = {
                    "func": enclosing,
                    "argnums": ("param", argnums_expr.id),
                    "fn_param": fn_param,
                    "fixed_target": None if fn_param else
                    self._resolve_target(wrapped),
                    "line": call.lineno,
                }
                continue
            if argnums is None:
                continue  # computed argnums: not statically resolvable

            target, param_names, bound = self._resolve_target(wrapped)
            don = Donating(argnums, self._donated_param_names(
                argnums, param_names), self._label(wrapped), target,
                call.lineno)
            self._bind(call, enclosing, don)

    def _bind(self, call, enclosing, don):
        parent = self.index.parents.get(id(call))
        if isinstance(parent, ast.Call) and parent.func is call:
            self.inline[id(parent)] = don
        elif isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    self.local[(self._scope_key(enclosing), t.id)] = don
                elif isinstance(t, ast.Attribute):
                    self.attrs[t.attr] = don
        elif isinstance(parent, ast.Return) and enclosing is not None and \
                not isinstance(enclosing, ast.Lambda):
            self.factories[id(enclosing)] = {
                "func": enclosing,
                "argnums": don.argnums,
                "fn_param": None,
                "fixed_target": None,
                "line": don.line,
                "donating": don,
            }

    def _collect_factory_calls(self):
        if not self.factories:
            return
        factory_by_name = {}
        for spec in self.factories.values():
            factory_by_name[spec["func"].name] = spec
        for call in ast.walk(self.src.tree):
            if not isinstance(call, ast.Call):
                continue
            fname = last_name(call.func)
            spec = factory_by_name.get(fname)
            if spec is None:
                continue
            cands = self.index.by_name.get(fname, ())
            if not any(c is spec["func"] for c in cands):
                continue
            don = self._resolve_factory_call(call, spec)
            if don is None:
                continue
            enclosing = self.index.enclosing_function(call)
            self._bind(call, enclosing, don)

    def _resolve_factory_call(self, call, spec):
        factory = spec["func"]
        bound_call = isinstance(call.func, ast.Attribute)
        params = _positional_params(factory, bound_call)

        def arg_for(pname):
            kw = _kw(call, pname)
            if kw is not None:
                return kw
            try:
                pos = params.index(pname)
            except ValueError:
                return None
            if pos < len(call.args):
                return call.args[pos]
            return _param_default(factory, pname)

        argnums = spec["argnums"]
        if isinstance(argnums, tuple) and argnums and \
                argnums[0] == "param":
            argnums = _const_argnums(arg_for(argnums[1]))
            if argnums is None:
                return None
        if spec.get("donating") is not None:
            base = spec["donating"]
            return Donating(base.argnums, base.param_names,
                            f"{factory.name}(...)", base.callee, call.lineno)
        if spec["fn_param"] is not None:
            wrapped = arg_for(spec["fn_param"])
            target, param_names, _ = self._resolve_target(wrapped)
            return Donating(argnums, self._donated_param_names(
                argnums, param_names), f"{factory.name}(...)", target,
                call.lineno)
        target, param_names, _ = spec["fixed_target"] or (None, None, False)
        return Donating(argnums, self._donated_param_names(
            argnums, param_names or []), f"{factory.name}(...)", target,
            call.lineno)

    # -- helpers -------------------------------------------------------------
    def _resolve_target(self, wrapped):
        """-> (func ast node or None, positional param names, bound?)"""
        if isinstance(wrapped, ast.Lambda):
            return wrapped, _positional_params(wrapped, False), False
        if isinstance(wrapped, ast.Attribute):
            cands = self.index.by_name.get(wrapped.attr, ())
            if len(cands) == 1 and not isinstance(cands[0], ast.Lambda):
                # jit(self.meth): jax sees the *bound* signature
                return cands[0], _positional_params(cands[0], True), True
            return None, [], True
        if isinstance(wrapped, ast.Name):
            cands = self.index.by_name.get(wrapped.id, ())
            if len(cands) == 1:
                return cands[0], _positional_params(cands[0], False), False
        return None, [], False

    @staticmethod
    def _donated_param_names(argnums, param_names):
        out = {}
        for n in argnums:
            if 0 <= n < len(param_names):
                out[n] = param_names[n]
        return out

    @staticmethod
    def _label(wrapped):
        name = dotted_name(wrapped) or (
            "<lambda>" if isinstance(wrapped, ast.Lambda) else "<fn>")
        return f"jit({name})"

    def _scope_key(self, enclosing):
        return id(enclosing) if enclosing is not None else None

    # -- lookup --------------------------------------------------------------
    def lookup(self, call, enclosing):
        """Donating spec for ``call`` (an ast.Call), or None."""
        don = self.inline.get(id(call))
        if don is not None:
            return don
        f = call.func
        if isinstance(f, ast.Name):
            scope = enclosing
            while True:
                don = self.local.get((self._scope_key(scope), f.id))
                if don is not None:
                    return don
                if scope is None:
                    return None
                scope = self.index.enclosing_function(scope)
        if isinstance(f, ast.Attribute):
            return self.attrs.get(f.attr)
        return None


# ---------------------------------------------------------------------------
# Alias roots: (root, index) access paths per function, flow-insensitive
# ---------------------------------------------------------------------------

class _Aliases:
    """name -> set of (root, index) pairs.  ``index`` is a constant
    subscript/unpack position, or ``_WHOLE`` for the whole container
    (or an unknown part of it).  Two paths can alias iff the roots
    match and the indices are compatible (equal, or either whole)."""

    def __init__(self, fn_node):
        self.assigns = {}
        self._memo = {}
        if fn_node is not None:
            self._collect(fn_node)

    def _collect(self, fn_node):
        body = fn_node.body if not isinstance(fn_node, ast.Lambda) else []
        stack = list(body) if isinstance(body, list) else [body]
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_NODES):
                continue  # different scope
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._record(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._record(node.target, node.value)
            stack.extend(ast.iter_child_nodes(node))

    def _record(self, target, value):
        if isinstance(target, ast.Name):
            self.assigns.setdefault(target.id, set()).update(
                self.expr_paths(value))
        elif isinstance(target, (ast.Tuple, ast.List)):
            # a, b = state: positional const indices keep distinct
            # elements of one parent from aliasing each other
            for i, elt in enumerate(target.elts):
                if not isinstance(elt, ast.Name):
                    continue
                paths = set()
                for root, idx in self.expr_paths(value):
                    paths.add((root, i) if idx == _WHOLE else (root, _WHOLE))
                self.assigns.setdefault(elt.id, set()).update(paths)

    def expr_paths(self, expr):
        """Alias paths a *view-forming* expression shares with existing
        bindings; fresh allocations (math, .copy(), most calls) return
        no paths."""
        if isinstance(expr, ast.Name):
            return {(expr.id, _WHOLE)}
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                return {(dotted_name(expr), _WHOLE)}
            return self.expr_paths(base)
        if isinstance(expr, ast.Subscript):
            base_paths = self.expr_paths(expr.value)
            if isinstance(expr.slice, ast.Constant) and \
                    isinstance(expr.slice.value, int):
                return {(root, expr.slice.value) if idx == _WHOLE
                        else (root, _WHOLE) for root, idx in base_paths}
            return {(root, _WHOLE) for root, idx in base_paths}
        if isinstance(expr, ast.Starred):
            return self.expr_paths(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = set()
            for e in expr.elts:
                out |= {(root, _WHOLE) for root, _ in self.expr_paths(e)}
            return out
        if isinstance(expr, ast.IfExp):
            return self.expr_paths(expr.body) | self.expr_paths(expr.orelse)
        if isinstance(expr, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self._comp_paths(expr)
        if isinstance(expr, ast.Call):
            if last_name(expr.func) in ("tuple", "list") and \
                    len(expr.args) == 1:
                inner = expr.args[0]
                if isinstance(inner, (ast.GeneratorExp, ast.ListComp)):
                    return self._comp_paths(inner)
                if isinstance(inner, ast.Name):
                    return {(inner.id, _WHOLE)}
            return set()
        return set()

    def _comp_paths(self, comp):
        if len(comp.generators) != 1:
            return set()
        it = comp.generators[0].iter
        if isinstance(it, (ast.Name, ast.Attribute)):
            return {(root, _WHOLE) for root, _ in self.expr_paths(it)}
        return set()

    def roots_of(self, expr):
        """Transitive alias paths of a call argument."""
        out = set()
        stack = list(self.expr_paths(expr))
        while stack:
            root, idx = stack.pop()
            if (root, idx) in out:
                continue
            out.add((root, idx))
            for proot, pidx in self.assigns.get(root, ()):
                # composing through another binding loses the index
                stack.append((proot, pidx if idx == _WHOLE else _WHOLE))
        return out


def _paths_overlap(a, b):
    for root1, i1 in a:
        for root2, i2 in b:
            if root1 == root2 and (i1 == _WHOLE or i2 == _WHOLE or i1 == i2):
                return True
    return False


def _free_names(fn_node):
    """Names ``fn_node`` reads but does not bind — closure captures."""
    bound = set(_positional_params(fn_node, False))
    args = fn_node.args
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    bound.update(a.arg for a in args.kwonlyargs)
    reads = set()
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    reads.add(n.id)
                else:
                    bound.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(n.name)
            elif isinstance(n, ast.comprehension):
                for t in ast.walk(n.target):
                    if isinstance(t, ast.Name):
                        bound.add(t.id)
    return reads - bound


# ---------------------------------------------------------------------------
# Statement-ordered scan
# ---------------------------------------------------------------------------

class _Donation:
    __slots__ = ("label", "line", "pos", "param")

    def __init__(self, label, line, pos, param):
        self.label = label
        self.line = line
        self.pos = pos
        self.param = param


class _Scanner:
    def __init__(self, src, index, resolver, enabled):
        self.src = src
        self.index = index
        self.resolver = resolver
        self.enabled = enabled
        self.violations = []
        self._seen = set()

    def _on(self, rule):
        return self.enabled is None or rule in self.enabled

    def run(self):
        funcs = [n for nodes in self.index.by_name.values() for n in nodes
                 if not isinstance(n, ast.Lambda)]
        self._scan_block(self.src.tree.body, {}, None)
        for fn in funcs:
            self._scan_block(fn.body, {}, fn)
        return self.violations

    # -- emit ----------------------------------------------------------------
    def _emit(self, rule, node, message):
        line = getattr(node, "lineno", 0)
        key = (rule, line, getattr(node, "col_offset", 0), message)
        if key in self._seen:
            return
        self._seen.add(key)
        if self.src.is_suppressed(rule, line):
            return
        self.violations.append(Violation(
            rule=rule, severity=SEVERITY_ERROR, path=self.src.path,
            line=line, col=getattr(node, "col_offset", 0),
            context=self.index.qualname_of(node), message=message,
            source=self.src.line_text(line)))

    # -- block / branch scanning --------------------------------------------
    def _scan_block(self, stmts, state, scope):
        for stmt in stmts:
            self._scan_stmt(stmt, state, scope)

    def _scan_stmt(self, stmt, state, scope):
        if isinstance(stmt, ast.If):
            self._check_expr(stmt.test, state, scope)
            s_true, s_false = dict(state), dict(state)
            self._scan_block(stmt.body, s_true, scope)
            self._scan_block(stmt.orelse, s_false, scope)
            self._merge_into(state, s_true, s_false)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter, state, scope)
            self._scan_loop(stmt.body, state, scope,
                            clear=_store_names([stmt.target]))
            self._scan_block(stmt.orelse, state, scope)
        elif isinstance(stmt, ast.While):
            self._check_expr(stmt.test, state, scope)
            self._scan_loop(stmt.body, state, scope, clear=())
            self._scan_block(stmt.orelse, state, scope)
        elif isinstance(stmt, ast.Try):
            s_body = dict(state)
            self._scan_block(stmt.body, s_body, scope)
            outs = [s_body]
            for handler in stmt.handlers:
                # the donating dispatch may precede the raise: handlers
                # inherit the body's poison
                s_h = dict(state)
                self._merge_into(s_h, s_h, s_body)
                self._scan_block(handler.body, s_h, scope)
                outs.append(s_h)
            s_else = dict(s_body)
            self._scan_block(stmt.orelse, s_else, scope)
            outs.append(s_else)
            self._merge_into(state, *outs)
            self._scan_block(stmt.finalbody, state, scope)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            cleared = []
            for item in stmt.items:
                self._check_expr(item.context_expr, state, scope)
                if item.optional_vars is not None:
                    cleared.extend(_store_names([item.optional_vars]))
            for name in cleared:
                state.pop(name, None)
            self._scan_block(stmt.body, state, scope)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in stmt.decorator_list:
                self._check_expr(deco, state, scope)
            # body is a separate scope, scanned on its own
        elif isinstance(stmt, ast.ClassDef):
            self._scan_block(stmt.body, state, scope)
        else:
            self._scan_simple(stmt, state, scope)

    def _scan_loop(self, body, state, scope, clear):
        # two passes: the second sees the first's out-state, so a
        # donation at the bottom of an iteration flags an un-rebound
        # read at the top of the next (loop-carried use-after-donation)
        s1 = dict(state)
        for name in clear:
            s1.pop(name, None)
        self._scan_block(body, s1, scope)
        s2 = dict(state)
        self._merge_into(s2, s2, s1)
        for name in clear:
            s2.pop(name, None)
        self._scan_block(body, s2, scope)
        self._merge_into(state, state, s2)

    @staticmethod
    def _merge_into(state, *branches):
        merged = {}
        for b in branches:
            merged.update(b)
        state.clear()
        state.update(merged)

    # -- simple statements ---------------------------------------------------
    def _scan_simple(self, stmt, state, scope):
        if isinstance(stmt, ast.AugAssign):
            # ``w += 1`` reads w before rebinding it
            for name in _store_names([stmt.target]):
                self._check_read_name(name, stmt.target, state)
        self._check_expr(stmt, state, scope)
        for call, don in self._donating_calls(stmt, scope):
            if self._on("T7"):
                self._check_t7(call, don, scope)
            if self._on("T6"):
                self._mark_donated(call, don, state)
        for name in _assigned_names(stmt):
            state.pop(name, None)

    def _donating_calls(self, stmt, scope):
        out = []
        for node in _walk_executed(stmt):
            if isinstance(node, ast.Call):
                don = self.resolver.lookup(node, scope)
                if don is not None:
                    out.append((node, don))
        return out

    def _mark_donated(self, call, don, state):
        for pos in don.argnums:
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            if isinstance(arg, ast.Name):
                state[arg.id] = _Donation(don.label, call.lineno, pos,
                                          don.param_names.get(pos))

    # -- T6: reads of poisoned names ----------------------------------------
    def _check_expr(self, node, state, scope):
        if not self._on("T6") or not state:
            return
        for n in _walk_executed(node, skip_sanitizer=True):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                self._check_read_name(n.id, n, state)

    def _check_read_name(self, name, node, state):
        d = state.get(name)
        if d is None:
            return
        param = f" (param `{d.param}`)" if d.param else ""
        self._emit(
            "T6", node,
            f"`{name}` is read after being donated to {d.label} at line "
            f"{d.line} (donate_argnums position {d.pos}{param}) — the "
            "buffer was invalidated at dispatch; rebind it from the "
            "call's results or .copy() before the donating call")

    # -- T7: aliasing at the donating call site -----------------------------
    def _check_t7(self, call, don, scope):
        aliases = _Aliases(scope)
        n = len(call.args)
        paths = [aliases.roots_of(a) for a in call.args]
        names = [a.id if isinstance(a, ast.Name) else None
                 for a in call.args]
        donated = [p for p in don.argnums if p < n]
        for p in donated:
            for q in range(n):
                if q == p or (q in donated and q < p):
                    continue
                kind = "donated" if q in donated else "non-donated"
                if names[p] is not None and names[p] == names[q]:
                    self._emit(
                        "T7", call,
                        f"`{names[p]}` is passed to {don.label} at donated "
                        f"position {p} and {kind} position {q} — XLA "
                        "donates the underlying buffer, leaving the other "
                        "reference dangling")
                elif paths[p] and paths[q] and \
                        _paths_overlap(paths[p], paths[q]):
                    self._emit(
                        "T7", call,
                        f"argument at donated position {p} and {kind} "
                        f"position {q} of {don.label} are views/members of "
                        "the same parent — donating one invalidates the "
                        "buffer the other still references")
        # closure capture: the callee reads the very array it donates
        callee = don.callee
        if callee is None:
            return
        callee_scope = self.index.enclosing_function(callee)
        if callee_scope is not scope and callee_scope is not None:
            return  # different scopes: same name != same object
        free = _free_names(callee)
        for p in donated:
            nm = names[p]
            if nm is not None and nm in free:
                self._emit(
                    "T7", call,
                    f"`{nm}` is donated at position {p} of {don.label} but "
                    "also captured by the jitted function's closure — the "
                    "closed-over reference dies with the donated buffer "
                    "(pass it as an argument instead)")


# ---------------------------------------------------------------------------
# AST walking helpers
# ---------------------------------------------------------------------------

def _walk_executed(node, skip_sanitizer=False):
    """Walk ``node`` skipping nested function bodies (they execute
    later, under their own scan) and, optionally, arguments of
    sanitizer calls (``_san.donate(...)``)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if n is not node and isinstance(n, _FUNC_NODES):
            # decorators/defaults still execute here
            for deco in getattr(n, "decorator_list", ()):
                stack.append(deco)
            stack.extend(getattr(n.args, "defaults", ()))
            stack.extend(d for d in getattr(n.args, "kw_defaults", ())
                         if d is not None)
            continue
        if skip_sanitizer and isinstance(n, ast.Call):
            head = dotted_name(n.func).split(".", 1)[0]
            if head in SANITIZER_HEADS:
                continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _store_names(targets):
    out = []
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.append(n.id)
    return out


def _assigned_names(stmt):
    """Names rebound (or deleted) by a simple statement."""
    out = []
    if isinstance(stmt, ast.Assign):
        out.extend(_store_names(stmt.targets))
    elif isinstance(stmt, ast.AnnAssign):
        out.extend(_store_names([stmt.target]))
    elif isinstance(stmt, ast.AugAssign):
        out.extend(_store_names([stmt.target]))
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                out.append(t.id)
    for n in ast.walk(stmt):
        if isinstance(n, ast.NamedExpr) and isinstance(n.target, ast.Name):
            out.append(n.target.id)
    return out


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def check_donation(src, index, enabled=None):
    """Run T6/T7 over one parsed file.  ``enabled`` limits which of the
    two families report (None = both)."""
    resolver = _Resolver(src, index)
    if not resolver.any:
        return []
    return _Scanner(src, index, resolver, enabled).run()
