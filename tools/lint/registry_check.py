"""Runtime half of the T3 rule.

elemwise.py / output_ops.py register ops from tables inside loops, so a
static scan cannot see those names.  This module imports the real
registry (cpu backend, import-only — no device work) and checks the
invariants the static pass cannot:

  * no registration ever overwrote another (duplicate names/aliases),
  * every public op is callable,
  * every public op carries a docstring.
"""
from __future__ import annotations

import os

from .core import Violation, SEVERITY_ERROR, SEVERITY_WARNING

REGISTRY_PATH = "mxnet_tpu/ops/registry.py"


def run_registry_check():
    """Import mxnet_tpu and validate the live registry.  Returns a list
    of Violations (empty when healthy).  Import failures surface as a
    single E0 violation rather than crashing the linter."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import mxnet_tpu  # noqa: F401  (imports populate the registry)
        from mxnet_tpu.ops import registry
    except Exception as e:  # pragma: no cover - environment-dependent
        return [Violation(
            rule="E0", severity=SEVERITY_ERROR, path=REGISTRY_PATH,
            line=0, col=0, context="<import>",
            message=f"could not import mxnet_tpu for the runtime "
                    f"registry check: {e}")]

    violations = []

    def emit(message, severity=SEVERITY_ERROR, context="<registry>"):
        violations.append(Violation(
            rule="T3", severity=severity, path=REGISTRY_PATH, line=0,
            col=0, context=context, message=message))

    for name, prev, new in registry.duplicate_registrations():
        emit(f"op name {name!r} registered twice (by {prev!r} then "
             f"{new!r}) — the later registration shadows the earlier",
             context=name)

    for name in registry.list_ops():
        fn = registry.get_op(name)
        if not callable(fn):
            emit(f"registry entry {name!r} is not callable", context=name)
            continue
        meta = registry.op_meta(name)
        canonical = meta.get("canonical", name)
        if name != canonical:
            continue  # docstring lives on the canonical registration
        if name.startswith("_"):
            continue  # private/internal helper ops
        if not (getattr(fn, "__doc__", None) or "").strip():
            emit(f"op {name!r} has no docstring", severity=SEVERITY_WARNING,
                 context=name)
    return violations
