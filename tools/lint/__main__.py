"""CLI: ``python -m tools.lint [paths...]``.

Exit codes: 0 clean (all violations waived by baseline), 1 new
violations (or tool errors), 2 usage error.
"""
from __future__ import annotations

import argparse
import os
import sys

from .analyzer import analyze_paths
from .baseline import load_baseline, save_baseline, apply_baseline
from .cache import AnalysisCache, analyzer_salt
from .registry_check import run_registry_check
from .report import render_human, render_json, render_sarif
from .rules import RULES

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".cache.json")


def _changed_paths(ref, roots, ap):
    """``--changed`` file set: .py files ``git diff --name-only REF``
    reports plus untracked ones, restricted to ``roots`` and still
    present on disk.  Returns repo-root-relative paths (the same spelling
    directory discovery produces, so cache keys and baseline
    fingerprints match a full run's)."""
    import subprocess

    def _git(*cmd):
        try:
            proc = subprocess.run(
                ["git", *cmd], cwd=REPO_ROOT, capture_output=True,
                text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            ap.error(f"--changed needs git: {e}")
        if proc.returncode != 0:
            ap.error(f"git {' '.join(cmd)} failed: "
                     f"{proc.stderr.strip() or proc.returncode}")
        return [ln for ln in proc.stdout.split("\0") if ln]

    names = set(_git("diff", "--name-only", "-z", ref, "--"))
    names.update(_git("ls-files", "--others", "--exclude-standard", "-z"))
    prefixes = []
    for root in roots:
        rel = os.path.relpath(
            os.path.abspath(os.path.join(REPO_ROOT, root)), REPO_ROOT)
        prefixes.append(rel.rstrip(os.sep))
    out = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        if not any(name == p or name.startswith(p + "/")
                   for p in prefixes):
            continue
        if os.path.isfile(os.path.join(REPO_ROOT, name)):
            out.append(name)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="mxlint: trace-safety & op-registry static analyzer. "
                    "Rules: " + "; ".join(f"{k}: {v}"
                                          for k, v in sorted(RULES.items())))
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze "
                         "(default: mxnet_tpu)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default=None, dest="format",
                    help="report format (default: human); sarif emits "
                         "SARIF 2.1.0 for CI code-scanning annotation")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="alias for --format json")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline (waiver) file "
                         "(default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every violation")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate the baseline from the current "
                         "violations and exit 0")
    ap.add_argument("--rules", default=None, metavar="T1,T2,...",
                    help="comma-separated rule families to run "
                         "(default: all)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="analyze only .py files changed vs git REF "
                         "(default HEAD) plus untracked ones, restricted "
                         "to the given paths; reuses the content-hash "
                         "cache.  Cross-file checks (T3/T11 finalization) "
                         "see only the changed set")
    ap.add_argument("--no-registry", action="store_true",
                    help="skip the runtime registry check (T3's dynamic "
                         "half; needs an importable mxnet_tpu)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the per-file analysis cache "
                         "(tools/lint/.cache.json, content-hash keyed)")
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = {r.strip().upper() for r in args.rules.split(",")
                 if r.strip()}
        unknown = rules - set(RULES)
        if unknown:
            ap.error(f"unknown rule(s): {sorted(unknown)}; "
                     f"known: {sorted(RULES)}")

    paths = args.paths or ["mxnet_tpu"]
    if args.changed is not None:
        paths = _changed_paths(args.changed, paths, ap)
        if not paths:
            print("mxlint: no changed .py files under the requested "
                  "paths; nothing to analyze")
            return 0
    cache = None
    if not args.no_cache:
        cache = AnalysisCache(DEFAULT_CACHE, analyzer_salt(rules))
    try:
        violations = analyze_paths(paths, REPO_ROOT, rules=rules,
                                   cache=cache)
    except FileNotFoundError as e:
        ap.error(f"no such path: {e}")
    if cache is not None:
        cache.save()

    if not args.no_registry and (rules is None or "T3" in rules):
        violations.extend(run_registry_check())

    if args.update_baseline:
        if args.changed is not None:
            ap.error("--update-baseline needs the full tree; a --changed "
                     "run would drop every out-of-set waiver")
        save_baseline(args.baseline, violations)
        rel = os.path.relpath(args.baseline, REPO_ROOT)
        print(f"mxlint: baseline rewritten with {len(violations)} "
              f"waived violation(s) -> {rel}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, waived, stale = apply_baseline(violations, baseline)
    if args.changed is not None:
        # a partial file set cannot see most waived violations, so every
        # out-of-set waiver would be misreported as fixed debt
        stale = []

    fmt = args.format or ("json" if args.as_json else "human")
    out = sys.stdout
    if fmt == "json":
        render_json(new, waived, stale, out,
                    cache_stats=cache.stats() if cache is not None
                    else None)
    elif fmt == "sarif":
        render_sarif(new, waived, stale, out)
    else:
        render_human(new, waived, stale, out)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
