"""mxlint core data model: violations, fingerprints, inline suppressions.

A violation's *fingerprint* deliberately excludes line/column numbers so
that unrelated edits (imports added above, reflowed docstrings) do not
churn the checked-in baseline: it hashes the rule id, the repo-relative
path, the enclosing context (function qualname or op name) and the
stripped source line text.  Two identical statements in one function
share a fingerprint; the baseline stores a count per fingerprint so a
*third* copy still gates.
"""
from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field, asdict

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

#: ``# mxlint: disable=T1,T4`` / ``# mxlint: allow=all`` on the violating
#: line (or the line above, for statements that wrap) suppresses matching
#: rules.  ``allow`` and ``disable`` are synonyms; ids are case-insensitive.
_SUPPRESS_RE = re.compile(
    r"#\s*mxlint:\s*(?:disable|allow)\s*[=:]\s*([A-Za-z0-9_,\s*]+)")


@dataclass
class Violation:
    rule: str                 # "T1".."T5" (or "E0" for tool errors)
    severity: str             # "error" | "warning"
    path: str                 # repo-relative posix path
    line: int
    col: int
    context: str              # enclosing function qualname / op name
    message: str
    source: str = ""          # stripped source line (fingerprint material)

    def fingerprint(self) -> str:
        key = "|".join((self.rule, self.path, self.context,
                        self.source or self.message))
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d


@dataclass
class FileSource:
    """Parsed file + the bits every rule needs."""
    path: str                 # repo-relative posix path
    abspath: str
    text: str
    tree: ast.AST
    lines: list = field(default_factory=list)
    suppressions: dict = field(default_factory=dict)  # line -> set(rule ids)

    @classmethod
    def parse(cls, abspath, relpath):
        with open(abspath, "r", encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=relpath)
        lines = text.splitlines()
        return cls(path=relpath, abspath=abspath, text=text, tree=tree,
                   lines=lines, suppressions=_collect_suppressions(lines))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def is_suppressed(self, rule: str, lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            rules = self.suppressions.get(ln)
            if rules and ("*" in rules or "ALL" in rules or
                          rule.upper() in rules):
                return True
        return False


def _collect_suppressions(lines):
    out = {}
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if m:
            ids = {tok.strip().upper() for tok in m.group(1).split(",")
                   if tok.strip()}
            out[i] = ids
    return out


def dotted_name(node) -> str:
    """Best-effort dotted name of an expression: ``jax.lax.scan`` ->
    "jax.lax.scan"; returns "" for anything unresolvable."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def last_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""
