"""Compile-discipline tier: retrace hazards and signature budgets.

The whole runtime is built on the compile-once invariant: every hot
path (CachedOp forward/backward, bulked engine segments, FusedTrainStep,
the trainer's fused update, serving's bucket grid) traces+compiles once
per signature and replays forever.  A retrace regression is invisible
until a benchmark happens to assert a compile count — these families
stop the hazard from being *written*:

T13 retrace-hazard — code shapes that silently multiply signatures:
    a. a python scalar produced by ``float()``/``int()`` in an enclosing
       scope and captured by a traced closure instead of being lifted to
       a runtime argument or keyed into the compile signature (the PR 4
       float-lift rule, now enforced);
    b. ``if``/``while`` on ``.shape``/``.dtype``/``.size``/``.item()``
       inside a ``hybrid_forward`` — legal (shapes are static under
       trace) but every distinct value compiles a fresh program;
    c. compile-cache keys built from f-strings / ``.format()`` / ``%``
       formatting — float formatting folds distinct values into
       unstable text and hides what actually diverged;
    d. ``tuple(kwargs.items())`` (unsorted) feeding a compile key —
       dict insertion order differs per call site, so identical
       configurations produce distinct signatures.

T14 compile-site discipline — fresh callables and unbounded entries:
    a. ``jax.jit`` / ``checkpoint_wrap`` / ``CachedOp`` / ``Predictor``
       construction (or ``.hybridize()``) inside a loop — one fresh
       callable per iteration is a guaranteed cache miss per iteration
       (exempt inside ``__init__`` / ``_build*`` / ``warm*`` bodies,
       where a bounded one-time grid build is the sanctioned pattern);
    b. ``jax.jit(f)(args)`` — constructing and immediately invoking a
       jit discards the compiled callable, so every call re-traces;
    c. a public serving entry point that dispatches a jit-bound
       callable on caller-shaped input in a module with no
       ``BucketPolicy`` in sight: an unbounded signature space.

T15 signature-budget declaration — modules that own a compile site must
    declare ``__compile_signatures__`` (a dict mapping costs-registry
    kinds to an expected-signature budget: an int or a short formula
    string) or carry an inline ``# mxlint: signatures=...`` annotation.
    The declared kinds are cross-checked against the kinds the module
    actually registers via ``costs.note(...)`` so signature growth shows
    up as a reviewed diff to the budget, not silent drift.

Like the concurrency tier, everything here is per-file; there is no
cross-file finalization pass, so results cache cleanly per content hash.
"""
from __future__ import annotations

import ast
import re

from .core import (Violation, SEVERITY_ERROR, SEVERITY_WARNING,
                   dotted_name, last_name)

#: assignment targets that mark a value as a compile-cache key
_SIG_NAME_RE = re.compile(r"(?:^|_)(?:sig|key|signature)s?$")

#: inline alternative to ``__compile_signatures__`` for one-site helpers
_INLINE_BUDGET_RE = re.compile(r"#\s*mxlint:\s*signatures\s*[=:]")

#: branch-test attributes that are static under trace but key the compile
_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}

#: callables whose construction IS a compile site
_JIT_LAST = {"jit"}
_WRAP_LAST = {"checkpoint_wrap"}
_CTOR_NAMES = {"CachedOp", "Predictor"}

#: enclosing-def names where building a bounded grid of callables in a
#: loop is the sanctioned one-time pattern (serving's warm grid, module
#: construction, ``Block.hybridize``'s recursive descent over children);
#: everything else pays one compile per loop iteration
_LOOP_EXEMPT_PREFIXES = ("__init__", "_build", "build_", "warm", "_warm",
                         "hybridize")

_LOOP_NODES = (ast.For, ast.While, ast.AsyncFor)
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_jit_ctor(call) -> bool:
    """Is ``call`` the construction of a compiled callable?"""
    name = last_name(call.func)
    if name in _JIT_LAST:
        dotted = dotted_name(call.func)
        head = dotted.split(".", 1)[0]
        return head in ("jax", "jit") or "jax" in dotted
    if name in _WRAP_LAST:
        return True
    if name in _CTOR_NAMES and isinstance(call.func, (ast.Name,
                                                      ast.Attribute)):
        return True
    return False


def _is_costs_note(call) -> bool:
    if last_name(call.func) != "note":
        return False
    dotted = dotted_name(call.func)
    head = dotted.split(".", 1)[0]
    return head in ("costs", "_costs") or ".costs." in dotted


def _sig_assign_targets(node):
    """Names assigned by ``node`` that look like compile-key bindings."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) and \
            getattr(node, "value", None) is not None:
        targets = [node.target]
    out = []
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and _SIG_NAME_RE.search(n.id):
                out.append(n.id)
    return out


def _formatted_string_nodes(expr):
    """JoinedStr / ``"...".format(...)`` / ``"..." % ...`` inside expr."""
    out = []
    for n in ast.walk(expr):
        if isinstance(n, ast.JoinedStr) and any(
                isinstance(v, ast.FormattedValue) for v in n.values):
            out.append(n)
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "format":
            out.append(n)
        elif isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod) and \
                isinstance(n.left, (ast.Constant, ast.JoinedStr)) and \
                isinstance(getattr(n.left, "value", None), str):
            out.append(n)
    return out


def _assigned_names(func_node) -> set:
    """Local names bound by plain assignment in ``func_node`` (its own
    body only — nested defs are separate scopes)."""
    out = set()
    for node in _walk_own(func_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
            t = node.target
            if isinstance(t, ast.Name):
                out.add(t.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for n in ast.walk(node.optional_vars):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def _walk_own(func_node):
    """Walk ``func_node``'s body without descending into nested defs."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _param_names(func_node) -> set:
    args = func_node.args
    names = {a.arg for a in (list(args.posonlyargs) + list(args.args) +
                             list(args.kwonlyargs))}
    if args.vararg is not None:
        names.add(args.vararg.arg)
    if args.kwarg is not None:
        names.add(args.kwarg.arg)
    return names


def check_compile_discipline(src, index, enabled=None):
    """Per-file T13/T14/T15 sweep.  Returns a list of Violations."""
    violations = []

    def on(rule):
        return enabled is None or rule in enabled

    def emit(rule, severity, node, message):
        line = getattr(node, "lineno", 0)
        if src.is_suppressed(rule, line):
            return
        violations.append(Violation(
            rule=rule, severity=severity, path=src.path, line=line,
            col=getattr(node, "col_offset", 0),
            context=index.qualname_of(node), message=message,
            source=src.line_text(line)))

    if on("T13"):
        _check_t13_scalar_capture(src, index, emit)
        _check_t13_shape_branches(src, index, emit)
        _check_t13_formatted_keys(src, index, emit)
        _check_t13_dict_order_keys(src, index, emit)
    if on("T14"):
        _check_t14(src, index, emit)
    if on("T15"):
        _check_t15(src, index, emit)
    return violations


# --- T13a: python scalars baked into traced closures ------------------------

def _is_engine_lifted(index, fn):
    """A callable handed DIRECTLY to ``apply_op`` dispatches through the
    engine, whose ``_fun_key`` lifts top-level float closure cells to
    runtime scalar arguments (values stay out of the segment key) — the
    baked-scalar hazard T13a targets does not apply to float cells
    there.  Int cells are NOT lifted (they are structural more often
    than not), so the caller still reports those."""
    parent = index.parents.get(id(fn))
    if not isinstance(parent, ast.Call) or fn not in parent.args:
        return False
    callee = parent.func
    name = callee.id if isinstance(callee, ast.Name) else (
        callee.attr if isinstance(callee, ast.Attribute) else None)
    return name == "apply_op"


def _check_t13_scalar_capture(src, index, emit):
    for nodes in index.by_name.values():
        for fn in nodes:
            if id(fn) not in index.hot:
                continue
            parent = index.enclosing_function(fn)
            if parent is None or isinstance(parent, ast.Lambda):
                continue
            params = _param_names(fn)
            own = _assigned_names(fn)
            # scalar conversions bound in the enclosing scope
            scalar_defs = {}       # name -> assignment node
            keyed = set()          # names that also reach a sig/key tuple
            for node in _walk_own(parent):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Name) and \
                        node.value.func.id in ("float", "int") and \
                        node.value.args and \
                        not isinstance(node.value.args[0], ast.Constant):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            scalar_defs[t.id] = node
                if _sig_assign_targets(node):
                    for n in ast.walk(node.value if hasattr(node, "value")
                                      and node.value is not None else node):
                        if isinstance(n, ast.Name):
                            keyed.add(n.id)
            if not scalar_defs:
                continue
            for node in _walk_own(fn):
                if not (isinstance(node, ast.Name) and
                        isinstance(node.ctx, ast.Load)):
                    continue
                v = node.id
                if v in params or v in own or v in keyed or \
                        v not in scalar_defs:
                    continue
                if scalar_defs[v].value.func.id == "float" and \
                        _is_engine_lifted(index, fn):
                    keyed.add(v)
                    continue
                emit("T13", SEVERITY_ERROR, node,
                     f"python scalar '{v}' ({ast.unparse(scalar_defs[v].value)[:40]}) "
                     f"is captured by traced '{getattr(fn, 'name', '<lambda>')}' "
                     "and baked in at trace time — lift it to a runtime "
                     "argument (weak-typed scalar) or key the compile "
                     "cache on it")
                keyed.add(v)  # one report per captured name


# --- T13b: shape/dtype/item branches in hybridized forwards -----------------

def _in_hybrid_forward(index, node) -> bool:
    cur = index.enclosing_function(node)
    while cur is not None:
        if getattr(cur, "name", None) == "hybrid_forward":
            return True
        cur = index.enclosing_function(cur)
    return False


def _branch_hazard(test):
    """(kind, detail) if the branch test reads shape/dtype/.item()."""
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr in _SHAPE_ATTRS:
            return n.attr, ast.unparse(n)[:40]
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in ("item", "asscalar"):
            return n.func.attr + "()", ast.unparse(n)[:40]
    return None


def _check_t13_shape_branches(src, index, emit):
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if not _in_hybrid_forward(index, node):
            continue
        hazard = _branch_hazard(node.test)
        if hazard is None:
            continue
        what, detail = hazard
        kw = "while" if isinstance(node, ast.While) else "if"
        emit("T13", SEVERITY_WARNING, node,
             f"{kw} on {what} ({detail}) inside hybrid_forward: every "
             "distinct value traces a fresh program — hoist the check to "
             "construction time or bucket the input upstream")


# --- T13c: formatted strings feeding compile keys ---------------------------

def _check_t13_formatted_keys(src, index, emit):
    for node in ast.walk(src.tree):
        value = None
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                getattr(node, "value", None) is not None and \
                _sig_assign_targets(node):
            value = node.value
        elif isinstance(node, ast.Call) and _is_costs_note(node) and \
                len(node.args) >= 2:
            value = node.args[1]
        if value is None:
            continue
        for fmt in _formatted_string_nodes(value):
            emit("T13", SEVERITY_WARNING, fmt,
                 "compile key built from a formatted string — float "
                 "formatting folds distinct values into unstable text and "
                 "the retrace differ cannot name what changed; key on the "
                 "raw component tuple instead")


# --- T13d: dict-iteration order feeding compile keys ------------------------

def _check_t13_dict_order_keys(src, index, emit):
    for node in ast.walk(src.tree):
        if not (isinstance(node, (ast.Assign, ast.AnnAssign)) and
                getattr(node, "value", None) is not None and
                _sig_assign_targets(node)):
            continue
        fn = index.enclosing_function(node)
        kwarg = None
        if fn is not None and not isinstance(fn, ast.Lambda) and \
                fn.args.kwarg is not None:
            kwarg = fn.args.kwarg.arg
        for call in ast.walk(node.value):
            if not (isinstance(call, ast.Call) and
                    isinstance(call.func, ast.Name) and
                    call.func.id == "tuple" and len(call.args) == 1):
                continue
            inner = call.args[0]
            if not (isinstance(inner, ast.Call) and
                    isinstance(inner.func, ast.Attribute) and
                    inner.func.attr in ("items", "keys", "values")):
                continue
            base = inner.func.value
            base_name = base.id if isinstance(base, ast.Name) else ""
            if kwarg is not None and base_name == kwarg or \
                    base_name in ("kwargs", "kw", "attrs"):
                emit("T13", SEVERITY_WARNING, call,
                     f"tuple({base_name}.{inner.func.attr}()) feeds a "
                     "compile key in dict insertion order — identical "
                     "configurations from different call sites produce "
                     "distinct signatures; sort the items first")


# --- T14: compile-site construction discipline ------------------------------

def _enclosing_loop(index, node):
    cur = index.parents.get(id(node))
    while cur is not None:
        if isinstance(cur, _LOOP_NODES + _COMP_NODES):
            return cur
        if isinstance(cur, _FUNC_NODES):
            return None  # a def inside the loop is a fresh scope: the
            # construct runs when the def runs, not per loop iteration
        cur = index.parents.get(id(cur))
    return None


def _loop_exempt(index, node) -> bool:
    fn = index.enclosing_function(node)
    name = getattr(fn, "name", "") if fn is not None else ""
    return any(name.startswith(p) for p in _LOOP_EXEMPT_PREFIXES)


def _check_t14(src, index, emit):
    jit_attrs = set()   # self-attribute names bound to jitted callables
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_jit_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    jit_attrs.add(t.attr)

    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        # T14b: jax.jit(f)(args) — construct-and-discard
        if isinstance(node.func, ast.Call) and _is_jit_ctor(node.func):
            emit("T14", SEVERITY_ERROR, node,
                 "jit constructed and immediately invoked — the compile "
                 "cache keys on callable identity, so every call here "
                 "re-traces; construct once, store it, and reuse")
            continue
        # T14a: construction inside a loop
        is_ctor = _is_jit_ctor(node) or (
            isinstance(node.func, ast.Attribute) and
            node.func.attr == "hybridize")
        if is_ctor and _enclosing_loop(index, node) is not None and \
                not _loop_exempt(index, node):
            what = last_name(node.func)
            emit("T14", SEVERITY_ERROR, node,
                 f"{what}(...) constructed inside a loop — a fresh "
                 "callable per iteration is a guaranteed compile miss "
                 "per iteration; hoist the construction out of the loop "
                 "(one-time grid builds belong in __init__/_build*/warm*)")

    # T14c: unbounded serving entry points
    if "serving" not in src.path or not jit_attrs:
        return
    if "BucketPolicy" in src.text or "bucket_for" in src.text:
        return
    seen_defs = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name.startswith("_") or id(node) in seen_defs:
            continue
        params = _param_names(node) - {"self", "cls"}
        if not params:
            continue
        for call in _walk_own(node):
            if not (isinstance(call, ast.Call) and
                    isinstance(call.func, ast.Attribute) and
                    isinstance(call.func.value, ast.Name) and
                    call.func.value.id == "self" and
                    call.func.attr in jit_attrs):
                continue
            feeds = any(isinstance(n, ast.Name) and n.id in params
                        for a in call.args for n in ast.walk(a))
            if not feeds:
                continue
            seen_defs.add(id(node))
            emit("T14", SEVERITY_WARNING, node,
                 f"public entry '{node.name}' dispatches jitted "
                 f"'self.{call.func.attr}' on caller-shaped input and no "
                 "BucketPolicy bounds the signature space in this module "
                 "— pad/bucket upstream or waive with the enforcing "
                 "policy named")
            break


# --- T15: signature-budget declaration --------------------------------------

def _module_budget(src):
    """The module-level ``__compile_signatures__`` dict node, or None."""
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and \
                        t.id == "__compile_signatures__":
                    return node
    return None


def _owns_compile_site(src):
    """(owner_node, registered_kinds) — the first stored-jit/ctor node
    proving this module owns a compile site, plus every string-literal
    kind the module registers with ``costs.note``."""
    owner = None
    kinds = set()
    # only *stored* jits count as owned sites; jit(f)(x) is T14's
    # problem and a bare expression statement owns nothing
    stored = set()
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.Return)) and \
                getattr(node, "value", None) is not None:
            stored.add(id(node.value))
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_costs_note(node):
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                kinds.add(node.args[0].value)
            if owner is None:
                owner = node
        elif _is_jit_ctor(node) and id(node) in stored and owner is None:
            owner = node
    return owner, kinds


def _check_t15(src, index, emit):
    owner, kinds = _owns_compile_site(src)
    budget = _module_budget(src)
    if owner is None and budget is None:
        return
    has_inline = bool(_INLINE_BUDGET_RE.search(src.text))
    if owner is not None and budget is None and not has_inline:
        emit("T15", SEVERITY_ERROR, owner,
             "module owns a compile site but declares no "
             "__compile_signatures__ budget — declare a dict mapping "
             "each costs-registry kind to its expected signature count "
             "(int) or growth formula (str) so signature growth is a "
             "reviewed diff")
        return
    if budget is None:
        return
    if not isinstance(budget.value, ast.Dict):
        emit("T15", SEVERITY_ERROR, budget,
             "__compile_signatures__ must be a dict literal of "
             "{registry kind: budget}")
        return
    declared = {}
    for k, v in zip(budget.value.keys, budget.value.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            emit("T15", SEVERITY_ERROR, k or budget,
                 "__compile_signatures__ keys must be string literals "
                 "naming costs-registry kinds")
            continue
        declared[k.value] = v
        ok_value = isinstance(v, ast.Constant) and (
            (isinstance(v.value, int) and not isinstance(v.value, bool)
             and v.value > 0) or
            (isinstance(v.value, str) and v.value.strip()))
        if not ok_value:
            emit("T15", SEVERITY_ERROR, v,
                 f"budget for kind '{k.value}' must be a positive int or "
                 "a non-empty formula string")
    for kind in sorted(kinds - set(declared)):
        emit("T15", SEVERITY_ERROR, budget,
             f"registry kind '{kind}' is registered in this module but "
             "missing from __compile_signatures__ — add it with its "
             "expected signature budget")
    if kinds:
        for kind in sorted(set(declared) - kinds):
            emit("T15", SEVERITY_WARNING, budget,
                 f"__compile_signatures__ declares kind '{kind}' that "
                 "this module never registers with costs.note — stale "
                 "entry or typo")
