"""mxlint: trace-safety and op-registry static analyzer for mxnet_tpu.

Run as ``python -m tools.lint [paths...]`` from the repo root.  See
docs/lint.md for the rule families (T1..T5) and the baseline workflow.
"""
from .core import Violation, SEVERITY_ERROR, SEVERITY_WARNING  # noqa: F401
from .rules import RULES  # noqa: F401
from .analyzer import analyze_paths  # noqa: F401
from .baseline import load_baseline, save_baseline, apply_baseline  # noqa: F401
