"""Baseline (waiver) gate.

``baseline.json`` maps violation fingerprints to grandfathered counts.
A run fails only when some fingerprint's *current* count exceeds its
baseline count — pre-existing debt is waived, new debt is not, and
fixing an old violation can never break the gate.  Fingerprints omit
line numbers (see core.Violation.fingerprint) so unrelated edits do not
churn this file.
"""
from __future__ import annotations

import json
import os
from collections import Counter

BASELINE_VERSION = 1


def load_baseline(path):
    """Return {fingerprint: count}; empty dict if the file is absent."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    waivers = data.get("waivers", data) if isinstance(data, dict) else {}
    out = {}
    for fp, entry in waivers.items():
        if isinstance(entry, dict):
            out[fp] = int(entry.get("count", 1))
        else:
            out[fp] = int(entry)
    return out


def save_baseline(path, violations):
    """Write a fresh baseline from the current violation set, keeping a
    human-auditable sample (rule/path/context/message) per fingerprint.
    A waiver's ``why`` line — the written justification the concurrency
    tier requires for every grandfathered T10–T12 finding — survives
    regeneration as long as the fingerprint still occurs."""
    old_why = {}
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                old = json.load(f)
            for fp, entry in old.get("waivers", {}).items():
                if isinstance(entry, dict) and entry.get("why"):
                    old_why[fp] = entry["why"]
        except (ValueError, OSError):
            pass
    grouped = {}
    for v in violations:
        fp = v.fingerprint()
        entry = grouped.setdefault(fp, {
            "count": 0, "rule": v.rule, "path": v.path,
            "context": v.context, "message": v.message})
        entry["count"] += 1
        if fp in old_why:
            entry["why"] = old_why[fp]
    payload = {
        "version": BASELINE_VERSION,
        "note": ("Grandfathered mxlint violations. Regenerate with "
                 "`python -m tools.lint --update-baseline`; fix debt by "
                 "deleting entries and fixing the code. Each waiver may "
                 "carry a `why` justification (required for T10-T12); "
                 "`why` lines survive regeneration."),
        "waivers": {fp: grouped[fp] for fp in sorted(grouped)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_baseline(violations, baseline):
    """Split ``violations`` into (new, waived, stale_fingerprints).

    Per fingerprint, the first ``baseline[fp]`` occurrences are waived
    and the rest are new.  ``stale`` lists baseline fingerprints that no
    longer occur at all — fixed debt whose waivers can be deleted.
    """
    budget = dict(baseline)
    new, waived = [], []
    seen = Counter()
    for v in violations:
        fp = v.fingerprint()
        seen[fp] += 1
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            waived.append(v)
        else:
            new.append(v)
    stale = sorted(fp for fp in baseline if seen[fp] == 0)
    return new, waived, stale
