"""mxlint driver: walk files, run per-file rules, finalize cross-file
T3 checks, and hand the result to the baseline gate."""
from __future__ import annotations

import ast
import os

from .core import Violation, FileSource, SEVERITY_ERROR
from .rules import FileChecker, check_registrations

#: directories never worth analyzing
_SKIP_DIRS = {".git", "__pycache__", "build", "dist", ".eggs",
              "node_modules", ".pytest_cache"}


def iter_py_files(paths, root):
    """Yield (abspath, relpath) for every .py file under ``paths``
    (files or directories), relpaths posix-style against ``root``."""
    seen = set()
    for p in paths:
        ap = os.path.abspath(p if os.path.isabs(p)
                             else os.path.join(root, p))
        if os.path.isfile(ap):
            cands = [ap]
        elif os.path.isdir(ap):
            cands = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in _SKIP_DIRS]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        cands.append(os.path.join(dirpath, fn))
        else:
            raise FileNotFoundError(p)
        for c in cands:
            if c in seen:
                continue
            seen.add(c)
            rel = os.path.relpath(c, root).replace(os.sep, "/")
            yield c, rel


def analyze_paths(paths, root, rules=None):
    """Run the analyzer over ``paths``.  Returns a sorted violation list.

    ``rules`` is an optional iterable of rule ids ("T1".."T5") limiting
    which families run; None means all.
    """
    enabled = set(rules) if rules is not None else None
    violations = []
    all_regs = []
    sources = []
    for abspath, relpath in iter_py_files(paths, root):
        try:
            src = FileSource.parse(abspath, relpath)
        except (SyntaxError, UnicodeDecodeError) as e:
            violations.append(Violation(
                rule="E0", severity=SEVERITY_ERROR, path=relpath,
                line=getattr(e, "lineno", 0) or 0, col=0,
                context="<parse>", message=f"unparseable file: {e}"))
            continue
        checker = FileChecker(src, enabled=enabled)
        violations.extend(checker.run())
        all_regs.extend(checker.registrations)
        sources.append(src)
    if enabled is None or "T3" in enabled:
        violations.extend(check_registrations(all_regs, sources))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations
