"""mxlint driver: walk files, run per-file rules, finalize cross-file
T3/T11 checks, and hand the result to the baseline gate."""
from __future__ import annotations

import os

from .core import Violation, FileSource, SEVERITY_ERROR
from .concurrency import check_lock_order
from .rules import FileChecker, check_registrations

#: directories never worth analyzing
_SKIP_DIRS = {".git", "__pycache__", "build", "dist", ".eggs",
              "node_modules", ".pytest_cache"}


def iter_py_files(paths, root):
    """Yield (abspath, relpath) for every .py file under ``paths``
    (files or directories), relpaths posix-style against ``root``."""
    seen = set()
    for p in paths:
        ap = os.path.abspath(p if os.path.isabs(p)
                             else os.path.join(root, p))
        if os.path.isfile(ap):
            cands = [ap]
        elif os.path.isdir(ap):
            cands = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in _SKIP_DIRS]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        cands.append(os.path.join(dirpath, fn))
        else:
            raise FileNotFoundError(p)
        for c in cands:
            if c in seen:
                continue
            seen.add(c)
            rel = os.path.relpath(c, root).replace(os.sep, "/")
            yield c, rel


def analyze_paths(paths, root, rules=None, cache=None):
    """Run the analyzer over ``paths``.  Returns a sorted violation list.

    ``rules`` is an optional iterable of rule ids ("T1".."T12") limiting
    which families run; None means all.  ``cache`` is an optional
    ``cache.AnalysisCache``: per-file results are reused when the file's
    content hash matches, while the cross-file passes (T3 registration
    consistency, the T11 lock-order graph) always rebuild from the
    cached facts.
    """
    enabled = set(rules) if rules is not None else None
    violations = []
    all_reg_facts = []
    all_lock_facts = []
    for abspath, relpath in iter_py_files(paths, root):
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError) as e:
            violations.append(Violation(
                rule="E0", severity=SEVERITY_ERROR, path=relpath,
                line=0, col=0, context="<parse>",
                message=f"unreadable file: {e}"))
            continue
        if cache is not None:
            from .cache import content_digest
            digest = content_digest(text)
            hit = cache.get(relpath, digest)
            if hit is not None:
                file_violations, reg_facts, lock_facts = hit
                violations.extend(file_violations)
                all_reg_facts.extend(reg_facts)
                all_lock_facts.append(lock_facts)
                continue
        try:
            src = FileSource.parse(abspath, relpath)
        except (SyntaxError, UnicodeDecodeError) as e:
            violations.append(Violation(
                rule="E0", severity=SEVERITY_ERROR, path=relpath,
                line=getattr(e, "lineno", 0) or 0, col=0,
                context="<parse>", message=f"unparseable file: {e}"))
            continue
        checker = FileChecker(src, enabled=enabled)
        file_violations = checker.run()
        violations.extend(file_violations)
        all_reg_facts.extend(checker.reg_facts)
        all_lock_facts.append(checker.lock_facts)
        if cache is not None:
            cache.put(relpath, digest, file_violations,
                      checker.reg_facts, checker.lock_facts)
    if enabled is None or "T3" in enabled:
        violations.extend(check_registrations(all_reg_facts))
    if enabled is None or "T11" in enabled:
        violations.extend(check_lock_order(all_lock_facts))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations
