"""Concurrency-safety rule families (T10–T12).

The runtime is genuinely multithreaded — the async engine worker, the
prefill/decode serving lanes, the data-plane prefetch thread, the async
checkpoint writer, metrics HTTP servers, the fleet watchdog — and they
share mutable state.  These families prove the locking discipline in
review, the same way T6/T7 prove the donation contract:

T10 (guard consistency)
    Per module, infer the shared-mutable-state map: ``self`` attributes
    and module globals that are *written* outside ``__init__`` and are
    accessed at least once under a lock.  Any *other* access to the same
    state that happens bare (no lock held lexically) is flagged — the
    ``RequestQueue.rejected``-style bug where writers hold the lock and
    one reader forgot.  Functions whose name carries a ``_locked``
    suffix are exempt by convention (the caller holds the lock), as are
    ``__init__``/``__new__``/``__repr__`` (construction and debug
    rendering are single-threaded by contract).

T11 (deadlock + blocking-under-lock)
    Build the static lock-acquisition-order graph across the whole
    package — an edge A→B for every site that acquires B while holding
    A (lexical ``with`` nesting and ``.acquire()`` under a held
    ``with``).  A cycle in the cross-file graph is an error: two
    threads taking the locks in opposite orders deadlock.  Additionally
    flag unbounded blocking calls made while a lock is held:
    ``queue.get()``/``put()`` without a timeout, ``ticket.result()``,
    ``Condition.wait()`` (on a *different* object than the held lock —
    ``self._cond.wait()`` inside ``with self._cond:`` is the
    condition-variable protocol and exempt), and ``thread.join()``.

T12 (thread lifecycle)
    ``threading.Thread`` sites must follow the package discipline:
    *named* (``name="mxt-..."`` — ps/the flight recorder/the straggler
    watchdog attribute threads by name), either ``daemon=True`` or
    joined somewhere on a shutdown path, and their target loop must
    capture exceptions for re-raise at a materialization point (the
    contract ``engine._AsyncExecutor``, the serving lanes and
    ``data/prefetch.py`` honor) instead of dying silently.

Runtime twin: ``MXNET_SANITIZE_LOCKS=1`` (``mxnet_tpu/sanitizer.py``)
wraps the package locks to record the *actual* acquisition order and
held-while-blocking events, and powers the deterministic interleaving
harness in ``tools/race.py``.  Lock identities here — ``module.NAME``
for globals, ``module.Class.attr`` for instance locks — match the
names passed to ``sanitizer.wrap_lock`` so the static and runtime
graphs can be unioned and cross-checked.  See docs/concurrency.md.
"""
from __future__ import annotations

import ast
import re

from .core import (Violation, SEVERITY_ERROR, SEVERITY_WARNING, last_name)

#: threading factories whose result is a lock-like guard
LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}

#: the sanitizer's instrumentation wrapper — ``wrap_lock(Lock(), name)``
#: is still a lock declaration
LOCK_WRAPPERS = {"wrap_lock"}

#: attribute/global names that read as locks even without a visible
#: declaration (locks handed across objects, e.g. ``q._cond``)
_LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|mutex|cond)$", re.IGNORECASE)

#: container methods that mutate their receiver (a store for T10)
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "add", "remove", "discard", "pop", "popleft", "popitem",
             "clear", "update", "setdefault", "sort", "reverse"}

#: receiver names that look like a queue for the blocking get/put check
_QUEUEISH_RE = re.compile(r"(?:^|_)(?:q|queue)$", re.IGNORECASE)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

#: functions whose accesses never count for T10: construction and debug
#: rendering are single-threaded, ``*_locked`` helpers run with the
#: caller's lock held by contract
_EXEMPT_FUNC_RE = re.compile(
    r"(?:^__init__$|^__new__$|^__del__$|^__repr__$|_locked$|_locked_)")


def module_of(path: str) -> str:
    """Last dotted-module component of a repo-relative path:
    ``mxnet_tpu/serving/lanes.py`` -> ``lanes`` (``__init__.py`` ->
    its package directory name).  Lock identities are scoped by this
    component so importers (``engine._SEG_LOCK``) and the defining file
    agree on the name."""
    parts = path.replace("\\", "/").split("/")
    leaf = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if leaf == "__init__" and len(parts) > 1:
        return parts[-2]
    return leaf


def _is_lock_value(value) -> bool:
    """Is this assignment RHS a lock construction?  Handles bare
    ``threading.Lock()`` and the sanitizer wrapper
    ``_san.wrap_lock(threading.Lock(), "name")``."""
    if not isinstance(value, ast.Call):
        return False
    name = last_name(value.func)
    if name in LOCK_FACTORIES:
        return True
    if name in LOCK_WRAPPERS and value.args:
        return _is_lock_value(value.args[0]) or \
            isinstance(value.args[0], (ast.Name, ast.Attribute))
    return False


class _Access:
    """One load/store of a shared-state candidate."""

    __slots__ = ("state", "node", "store", "locks", "func")

    def __init__(self, state, node, store, locks, func):
        self.state = state      # state id, e.g. "DecodeLane._seqs"
        self.node = node
        self.store = store
        self.locks = locks      # frozenset of lock ids held lexically
        self.func = func        # enclosing function node


class ModuleConcurrency:
    """Per-file concurrency model: declared locks, thread entry points,
    shared-state accesses with the lexically-held lock set, and the
    lock-acquisition facts the cross-file T11 graph is built from."""

    def __init__(self, src, index):
        self.src = src
        self.index = index
        self.mod = module_of(src.path)
        self.module_locks = {}   # global name -> lock id
        self.class_locks = {}    # class name -> {attr -> lock id}
        self.thread_targets = set()   # id(func) run on a thread
        self.threaded = False    # module spawns/uses any thread at all
        self.accesses = []       # [_Access]
        self.acquire_edges = []  # [(src_id, dst_id, node)]
        self.blocking = []       # [(held lock id, desc, node)]
        self.thread_sites = []   # [Thread(...) call nodes]
        self._class_of_func = {}  # id(func) -> enclosing class name or ""
        self._globals_cache = None
        self._collect_locks()
        self._map_classes()
        self._collect_thread_sites()
        self._scan_functions()

    # -- declarations --------------------------------------------------------
    def _collect_locks(self):
        for node in ast.walk(self.src.tree):
            if not isinstance(node, ast.Assign) or \
                    not _is_lock_value(node.value):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.module_locks[t.id] = f"{self.mod}.{t.id}"
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    cls = self._enclosing_class(node)
                    if cls:
                        self.class_locks.setdefault(cls, {})[t.attr] = \
                            f"{self.mod}.{cls}.{t.attr}"

    def _enclosing_class(self, node):
        cur = self.index.parents.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = self.index.parents.get(id(cur))
        return ""

    def _map_classes(self):
        for nodes in self.index.by_name.values():
            for fn in nodes:
                if isinstance(fn, _FUNC_NODES):
                    self._class_of_func[id(fn)] = self._enclosing_class(fn)

    # -- thread entry points -------------------------------------------------
    def _collect_thread_sites(self):
        handler_classes = set()
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.ClassDef):
                for base in node.bases:
                    if "Handler" in last_name(base):
                        handler_classes.add(node.name)
        entries = set()
        for node in ast.walk(self.src.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = last_name(node.func)
            target = None
            if fname in ("Thread", "Timer"):
                self.thread_sites.append(node)
                self.threaded = True
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                if target is None and fname == "Timer" and \
                        len(node.args) > 1:
                    target = node.args[1]
            elif fname in ("submit", "add_done_callback") and node.args:
                # executor callbacks run on pool threads
                self.threaded = True
                target = node.args[0]
            if isinstance(target, (ast.Name, ast.Attribute)):
                for fn in self.index.by_name.get(last_name(target), ()):
                    if isinstance(fn, _FUNC_NODES):
                        entries.add(id(fn))
        # every method of an HTTP handler class runs on a server thread
        for nodes in self.index.by_name.values():
            for fn in nodes:
                if self._class_of_func.get(id(fn)) in handler_classes:
                    entries.add(id(fn))
                    self.threaded = True
        # same-module closure: anything a thread entry calls is on-thread
        node_by_id = {id(n): n for nodes in self.index.by_name.values()
                      for n in nodes if isinstance(n, _FUNC_NODES)}
        work = list(entries)
        while work:
            fn = node_by_id.get(work.pop())
            if fn is None:
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                callee = None
                if isinstance(f, ast.Name):
                    callee = f.id
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ("self", "cls"):
                    callee = f.attr
                if callee:
                    for g in self.index.by_name.get(callee, ()):
                        if id(g) not in entries and \
                                isinstance(g, _FUNC_NODES):
                            entries.add(id(g))
                            work.append(id(g))
        self.thread_targets = entries

    # -- lock identity -------------------------------------------------------
    def lock_id(self, expr, func):
        """Resolve a ``with``-subject / ``.acquire()`` receiver to a
        lock id, or None when it is not lock-like.  Unknown-owner locks
        (``q._cond`` reached through another object) resolve to a
        ``?``-scoped id: real for held-set purposes, excluded from the
        cross-file order graph."""
        if isinstance(expr, ast.Call):
            # ``with self._lock:`` vs ``with Lock():`` — a direct
            # construction guards nothing shared
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return self.module_locks[expr.id]
            if _LOCKISH_RE.search(expr.id):
                return f"{self.mod}.?{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                cls = self._class_of_func.get(id(func), "") if func \
                    is not None else ""
                attrs = self.class_locks.get(cls, {})
                if expr.attr in attrs:
                    return attrs[expr.attr]
                if _LOCKISH_RE.search(expr.attr):
                    return f"{self.mod}.{cls}.{expr.attr}"
                return None
            if isinstance(base, ast.Name):
                # module global through an import alias:
                # ``engine._SEG_LOCK`` — scope by the alias's last
                # component, which matches the defining module's own id
                if _LOCKISH_RE.search(expr.attr):
                    return f"{base.id}.{expr.attr}"
                return None
            if _LOCKISH_RE.search(expr.attr):
                return f"{self.mod}.?.{expr.attr}"
        return None

    # -- the walk ------------------------------------------------------------
    def _scan_functions(self):
        for nodes in self.index.by_name.values():
            for fn in nodes:
                if isinstance(fn, _FUNC_NODES) and \
                        self.index.enclosing_function(fn) is None:
                    for stmt in fn.body:
                        self._walk_stmt(stmt, fn, ())

    def _walk_stmt(self, stmt, func, held):
        """Statement walk tracking the lexically-held lock stack.  Each
        expression is recorded exactly once, at its owning statement."""
        if isinstance(stmt, _FUNC_NODES):
            for b in stmt.body:
                self._walk_stmt(b, stmt, ())
            return
        if isinstance(stmt, ast.ClassDef):
            for b in stmt.body:
                self._walk_stmt(b, func, held)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                self._record_exprs(item.context_expr, func, held)
                lid = self.lock_id(item.context_expr, func)
                if lid:
                    for h in new_held:
                        if h != lid:
                            self.acquire_edges.append((h, lid, stmt))
                    new_held = new_held + (lid,)
            for b in stmt.body:
                self._walk_stmt(b, func, new_held)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._record_exprs(child, func, held)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child, func, held)
            elif isinstance(child, ast.excepthandler):
                for b in child.body:
                    self._walk_stmt(b, func, held)
            elif type(child).__name__ == "match_case":
                for b in child.body:
                    self._walk_stmt(b, func, held)

    def _record_exprs(self, expr, func, held):
        """Record accesses / acquire-calls / blocking calls in one
        expression tree (lambda bodies run later — skipped)."""
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Call):
                self._record_call(n, func, held)
            self._record_access(n, func, held)
            for c in ast.iter_child_nodes(n):
                stack.append(c)

    def _record_call(self, call, func, held):
        f = call.func
        if not isinstance(f, ast.Attribute):
            return
        meth = f.attr
        recv = last_name(f.value) or \
            (f.value.attr if isinstance(f.value, ast.Attribute) else "")
        if meth == "acquire":
            lid = self.lock_id(f.value, func)
            if lid:
                for h in held:
                    if h != lid:
                        self.acquire_edges.append((h, lid, call))
            return
        if not held:
            return
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        nonblocking = any(
            kw.arg == "block" and isinstance(kw.value, ast.Constant)
            and kw.value.value is False for kw in call.keywords)
        if meth in ("get", "put") and not has_timeout and not nonblocking \
                and _QUEUEISH_RE.search(recv or ""):
            self.blocking.append(
                (held[-1], f"{recv}.{meth}() with no timeout", call))
        elif meth == "result" and not call.args and not has_timeout:
            self.blocking.append(
                (held[-1], f"{recv}.result() with no timeout", call))
        elif meth in ("wait", "wait_for") and not has_timeout:
            bounded = meth == "wait" and call.args  # wait(t) positional
            lid = self.lock_id(f.value, func)
            if not bounded and (lid is None or lid not in held):
                self.blocking.append(
                    (held[-1], f"{recv}.{meth}() with no timeout", call))
        elif meth == "join" and not call.args and not has_timeout and \
                "thread" in (recv or "").lower():
            self.blocking.append(
                (held[-1], f"{recv}.join() with no timeout", call))

    def _record_access(self, n, func, held):
        state = None
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id == "self":
            cls = self._class_of_func.get(id(func), "") if func \
                is not None else ""
            if not cls or self._is_lock_name(cls, n.attr):
                return
            state = f"{cls}.{n.attr}"
        elif isinstance(n, ast.Name) and n.id in self._globals() and \
                n.id not in self.module_locks:
            state = f"{self.mod}.{n.id}"
        if state is None:
            return
        store = isinstance(n.ctx, (ast.Store, ast.Del))
        parent = self.index.parents.get(id(n))
        if isinstance(parent, ast.Subscript) and \
                isinstance(parent.ctx, (ast.Store, ast.Del)):
            store = True
        if isinstance(parent, ast.Attribute) and parent.attr in _MUTATORS:
            store = True
        if isinstance(parent, ast.AugAssign) and parent.target is n:
            store = True
        self.accesses.append(_Access(state, n, store, frozenset(held),
                                     func))

    def _is_lock_name(self, cls, attr) -> bool:
        return attr in self.class_locks.get(cls, {}) or \
            bool(_LOCKISH_RE.search(attr))

    def _globals(self):
        """Module-scope names assigned to non-def/class values — the
        candidates for shared module-level state."""
        if self._globals_cache is None:
            out = set()
            for stmt in self.src.tree.body:
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, ast.AnnAssign):
                    targets = [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            self._globals_cache = out
        return self._globals_cache


# ---------------------------------------------------------------------------
# Per-file checks
# ---------------------------------------------------------------------------

def check_concurrency(src, index, enabled=None):
    """Run T10 / T11's per-file half / T12 over one file.  Returns
    ``(violations, lock_facts)`` where ``lock_facts`` is the
    serializable per-file contribution to the cross-file T11 graph."""
    model = ModuleConcurrency(src, index)
    violations = []

    def on(rule):
        return enabled is None or rule in enabled

    def emit(rule, severity, node, message):
        line = getattr(node, "lineno", 0)
        if src.is_suppressed(rule, line):
            return
        violations.append(Violation(
            rule=rule, severity=severity, path=src.path, line=line,
            col=getattr(node, "col_offset", 0),
            context=index.qualname_of(node), message=message,
            source=src.line_text(line)))

    if on("T10"):
        _check_guards(model, emit)
    if on("T11"):
        for held, desc, node in model.blocking:
            emit("T11", SEVERITY_WARNING, node,
                 f"unbounded blocking call ({desc}) while holding "
                 f"`{held}` — a stalled peer turns this into a "
                 "deadlock; add a timeout or move the wait outside "
                 "the lock")
    if on("T12"):
        _check_lifecycle(model, src, index, emit)

    lock_facts = {
        "path": src.path,
        "edges": [{
            "src": a, "dst": b,
            "line": getattr(node, "lineno", 0),
            "col": getattr(node, "col_offset", 0),
            "context": index.qualname_of(node),
            "source": src.line_text(getattr(node, "lineno", 0)),
            "suppressed": src.is_suppressed(
                "T11", getattr(node, "lineno", 0)),
        } for a, b, node in model.acquire_edges],
    }
    return violations, lock_facts


def _check_guards(model, emit):
    if not model.threaded:
        return  # nothing in this module runs off the main thread
    by_state = {}
    for a in model.accesses:
        by_state.setdefault(a.state, []).append(a)
    for state, accs in sorted(by_state.items()):
        relevant = [a for a in accs if a.func is None or
                    not _EXEMPT_FUNC_RE.search(
                        getattr(a.func, "name", "") or "")]
        locked = [a for a in relevant if a.locks]
        bare = [a for a in relevant if not a.locks]
        if not locked or not bare:
            continue
        if not any(a.store for a in relevant):
            continue  # read-only after construction: lock incidental
        guards = sorted({lid for a in locked for lid in a.locks})
        for a in bare:
            kind = "written" if a.store else "read"
            emit("T10",
                 SEVERITY_ERROR if a.store else SEVERITY_WARNING,
                 a.node,
                 f"`{state}` is {kind} without a lock here but guarded "
                 f"by {', '.join(f'`{g}`' for g in guards)} elsewhere "
                 f"({len(locked)} locked access"
                 f"{'es' if len(locked) != 1 else ''}) — take the lock "
                 "or waiver with a why")


def _check_lifecycle(model, src, index, emit):
    for call in model.thread_sites:
        kwargs = {kw.arg: kw.value for kw in call.keywords}
        is_timer = last_name(call.func) == "Timer"
        bound = _bound_name(call, index)
        if "name" not in kwargs and not is_timer:
            emit("T12", SEVERITY_WARNING, call,
                 "unnamed thread — pass name=\"mxt-...\" so the flight "
                 "recorder / straggler watchdog / ps can attribute it")
        daemon = kwargs.get("daemon")
        is_daemon = isinstance(daemon, ast.Constant) and \
            daemon.value is True
        if not is_daemon and bound is not None:
            is_daemon = _daemon_assigned(call, bound, index)
        if not is_daemon:
            joined = bound is not None and _is_joined(src.tree, bound)
            if not joined:
                emit("T12", SEVERITY_ERROR, call,
                     "non-daemon thread with no join on any shutdown "
                     "path — it leaks past interpreter exit; pass "
                     "daemon=True or join it in a close()/stop() path")
        target = kwargs.get("target")
        if target is None and is_timer and len(call.args) > 1:
            target = call.args[1]
        if isinstance(target, (ast.Name, ast.Attribute)):
            for fn in index.by_name.get(last_name(target), ()):
                if not isinstance(fn, _FUNC_NODES):
                    continue
                if _has_loop(fn) and not _captures_errors(fn, index):
                    emit("T12", SEVERITY_WARNING, call,
                         f"worker `{fn.name}` loops with no exception "
                         "capture — an error kills the thread silently; "
                         "capture it and re-raise at a materialization "
                         "point (the engine/_prefetch/lane contract)")


def _bound_name(call, index):
    """The name/attr a Thread construction is assigned to, or None."""
    parent = index.parents.get(id(call))
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Attribute):
                return t.attr
    return None


def _daemon_assigned(call, bound, index):
    """``t.daemon = True`` (or ``t.setDaemon(True)``) in the same
    function as the construction."""
    fn = index.enclosing_function(call)
    scope = fn if fn is not None else index.tree
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and last_name(t.value) == bound and \
                        isinstance(n.value, ast.Constant) and \
                        n.value.value is True:
                    return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "setDaemon" and \
                last_name(n.func.value) == bound:
            return True
    return False


def _is_joined(tree, bound):
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "join" and \
                last_name(n.func.value) == bound:
            return True
    return False


def _has_loop(fn):
    return any(isinstance(n, (ast.While, ast.For)) for n in ast.walk(fn))


def _captures_errors(fn, index, _depth=0):
    """The worker (or any same-module function it calls, one hop) has a
    try/except — the captured-for-re-raise contract."""
    if any(isinstance(n, ast.Try) for n in ast.walk(fn)):
        return True
    if _depth >= 1:
        return False
    for call in ast.walk(fn):
        if not isinstance(call, ast.Call):
            continue
        f = call.func
        callee = None
        if isinstance(f, ast.Name):
            callee = f.id
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id in ("self", "cls"):
            callee = f.attr
        for g in index.by_name.get(callee or "", ()):
            if isinstance(g, _FUNC_NODES) and \
                    _captures_errors(g, index, _depth + 1):
                return True
    return False


# ---------------------------------------------------------------------------
# Cross-file T11 finalization: the package-wide lock-order graph
# ---------------------------------------------------------------------------

def build_lock_graph(all_lock_facts):
    """Merge per-file facts into ``{(src, dst): [edge dict, ...]}``,
    dropping unknown-owner (``?``-scoped) locks — they have no stable
    cross-file identity."""
    graph = {}
    for facts in all_lock_facts:
        for e in facts.get("edges", ()):
            if "?" in e["src"] or "?" in e["dst"]:
                continue
            graph.setdefault((e["src"], e["dst"]),
                             []).append(dict(e, path=facts["path"]))
    return graph


def check_lock_order(all_lock_facts):
    """Error on every cycle in the package-wide acquisition-order
    graph.  One violation per cycle, attributed to the cycle's
    lexicographically-first edge site; a cycle is waived only when
    EVERY participating edge site carries an inline T11 suppression."""
    graph = build_lock_graph(all_lock_facts)
    adj = {}
    for (a, b) in graph:
        adj.setdefault(a, set()).add(b)
    violations = []
    for cyc in _find_cycles(adj):
        edges = []
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            edges.extend(graph.get((a, b), ()))
        if not edges or all(e["suppressed"] for e in edges):
            continue
        site = min(edges, key=lambda e: (e["path"], e["line"]))
        chain = " -> ".join(cyc + (cyc[0],))
        others = "; ".join(
            f"{e['src']}->{e['dst']} at {e['path']}:{e['line']}"
            for e in sorted(edges, key=lambda e: (e["path"], e["line"])))
        violations.append(Violation(
            rule="T11", severity=SEVERITY_ERROR, path=site["path"],
            line=site["line"], col=site["col"], context=site["context"],
            message=f"lock-order cycle: {chain} — two threads taking "
                    f"these in opposite orders deadlock ({others})",
            source=site["source"]))
    return violations


def _find_cycles(adj):
    """Elementary cycles, deduped by node set, each returned as a tuple
    rotated to start at its smallest node.  DFS with an explicit stack —
    fine for lock graphs (tens of nodes)."""
    cycles = {}
    for start in sorted(adj):
        stack = [(start, (start,))]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in cycles:
                        i = path.index(min(path))
                        cycles[key] = path[i:] + path[:i]
                elif nxt not in path and len(path) < 16:
                    stack.append((nxt, path + (nxt,)))
    return sorted(cycles.values())
