"""mxlint rule families.

T1  host-sync calls (``asnumpy``/``.item()``/``np.asarray``/
    ``jax.device_get``/``block_until_ready``/``float()``...) — errors
    inside traced regions, warnings for unambiguous syncs anywhere else.
T2  python ``if``/``while``/``assert`` on traced values inside traced
    regions (the trace either fails to concretize or silently bakes one
    branch into every execution).
T3  op-registry consistency: registrations must be unique, documented,
    and ops whose pure body is non-differentiable must carry an explicit
    ``no_grad=True`` marker (mxnet_tpu/ops/registry.py) instead of
    silently producing garbage cotangents.
T4  nondeterminism inside traced regions: host ``time.*`` or
    ``random``/``np.random`` calls get baked in as trace-time constants —
    every execution replays the same "random" numbers.
T5  in-place numpy mutation of jax-backed buffers (``x.asnumpy()[i] = v``
    mutates a host copy — or a read-only view — never device memory).
T6  use-after-donation: a binding passed at a donated position of a
    ``jax.jit(..., donate_argnums=...)`` call is read after the call
    before being rebound (tools/lint/dataflow.py).
T7  donation aliasing: the same array — or a view/member of the same
    parent — reaches a donating call at both a donated and another
    position, or is captured by the donated callee's closure.
T8  partition-rule sanity: literal rule tables handed to
    ``PartitionRules`` / ``Trainer(partition_rules=...)`` /
    ``place_params`` with a pattern that cannot compile, a rule
    statically unreachable (after a catch-all, or a duplicate pattern
    under first-match-wins), or model-axis specs with no terminal
    catch-all — unmatched parameters then silently replicate, which on
    a mesh with a model axis is a memory regression that trains fine.
T9  memory-policy bypass: hand-rolled ``jax.checkpoint``/``jax.remat``
    in MODEL code (under ``models/`` or a file defining a
    ``hybrid_forward`` block) sidesteps the auto-remat tier ladder —
    use ``memory.policy.checkpoint_wrap`` / ``hybridize(remat=...)``;
    and planner calls (``plan_model``/``auto_tier``/...) as bare
    statements discard the fit verdict they exist to produce.
"""
from __future__ import annotations

import ast
import re

from .core import (Violation, SEVERITY_ERROR, SEVERITY_WARNING, dotted_name,
                   last_name)
from .compile_discipline import check_compile_discipline
from .concurrency import check_concurrency
from .dataflow import check_donation
from .hotpath import FunctionIndex, function_taint, expr_tainted

RULES = {
    "T1": "host-sync call reachable from a traced hot path",
    "T2": "python control flow on a traced value",
    "T3": "op-registry inconsistency (docstring / duplicate / grad path)",
    "T4": "host nondeterminism inside a traced region",
    "T5": "in-place numpy mutation of a jax-backed buffer",
    "T6": "use of a buffer after it was donated to a jitted call",
    "T7": "aliased array reaches a donating call (donation aliasing)",
    "T8": "partition-rule sanity (dead rule / silent replicate)",
    "T9": "memory-policy bypass (hand-rolled remat / dropped verdict)",
    "T10": "shared state accessed bare where it is lock-guarded elsewhere",
    "T11": "lock-order cycle / unbounded blocking call under a held lock",
    "T12": "thread lifecycle (unnamed / unjoined non-daemon / silent worker)",
    "T13": "retrace hazard (baked scalar / shape branch / unstable key)",
    "T14": "compile-site discipline (fresh callable / unbounded entry)",
    "T15": "signature budget (__compile_signatures__) missing or stale",
}

#: families whose cross-file halves the analyzer finalizes after the
#: per-file sweep
_CONCURRENCY_RULES = frozenset({"T10", "T11", "T12"})

#: compile-discipline tier (tools/lint/compile_discipline.py) — fully
#: per-file, so per-content-hash caching holds with no cross-file facts
_COMPILE_RULES = frozenset({"T13", "T14", "T15"})

# --- T1 ---------------------------------------------------------------------

#: method-style syncs: ``x.asnumpy()``, ``x.item()``, ...  With the
#: async engine tier (PR 7) ``wait_to_read`` may block on the worker
#: thread's completion event rather than the device — still a host
#: sync.  ``result`` covers ticket-style waits (async checkpoint
#: tickets, executor futures): joining one inside a traced region
#: serializes the trace on host progress.  It is deliberately NOT in
#: SYNC_METHODS_ANYWHERE — ``ticket.result()`` in eager glue
#: (checkpoint.py drain paths) is the intended usage.
SYNC_METHODS = {"asnumpy", "asscalar", "item", "tolist",
                "block_until_ready", "wait_to_read", "wait_to_write",
                "result"}

#: syncs unambiguous enough to warn about even in eager glue code
SYNC_METHODS_ANYWHERE = {"asnumpy", "asscalar", "item",
                         "block_until_ready"}

#: designated result-materialization defs: a function carrying one of
#: these names IS the module's sanctioned batch-boundary sync point
#: (the serving scheduler's ``_materialize`` — one device->host wait
#: per dispatched batch, at demux; see docs/serving.md).  Sync methods
#: inside such a def skip the eager T1 warning — the same shape as the
#: PR 7 ``ticket.result()`` treatment (intentional eager waits stay
#: legal) but scoped by enclosing-def name instead of method name.
#: Inside a TRACED region the error still fires: naming a hot function
#: ``_materialize`` buys nothing.  ``_lane_materialize`` is the
#: disaggregated serving lanes' twin (serving/lanes.py): the decode
#: drain and the prefill→decode handoff sync there, and nowhere else.
#: ``_fleet_exchange`` (telemetry/fleet.py, r13) is the stride-gated
#: allgather of the packed step-stats vector: an intentional eager
#: collective+sync at the fleet-exchange boundary, never per-step and
#: never inside a trace — exempt the same way.
#: ``_prefetch`` (data/prefetch.py, r14) is the data plane's transfer
#: thread: it device_puts the NEXT batch and ``block_until_ready``s it
#: so the trainer inherits a landed array instead of a lazy copy — the
#: sync IS the prefetch, off the consumer thread by construction, never
#: in a trace.
MATERIALIZE_DEFS = {"_materialize", "_lane_materialize", "_fleet_exchange",
                    "_prefetch"}

#: function-style syncs, matched on dotted name
SYNC_FUNCS_ANYWHERE = {"jax.device_get"}
SYNC_FUNCS_TRACED = {"np.asarray", "numpy.asarray", "onp.asarray",
                     "_np.asarray", "np.array", "numpy.array",
                     "jax.device_get",
                     # engine.flush() executes the thread's pending bulk
                     # segment — a host-side sync site (docs/engine.md);
                     # inside a traced region it is at best a no-op and at
                     # worst hides a real sync the eager path would hit
                     "engine.flush", "_engine.flush",
                     "mxnet_tpu.engine.flush"}

#: builtins that force a tracer to a host scalar
SCALAR_BUILTINS = {"float", "int", "bool"}

#: dotted heads naming the observability layer: ``telemetry.count(...)`` /
#: ``prof.record_span_event(...)`` never sync and never run inside a trace
#: (spans enter the trace path only via _trace_guard-stripped replays), so
#: T1/T4 skip them outright
RECORDING_HEADS = {"telemetry", "profiler", "prof",
                   # memory/cost observability (telemetry.memwatch /
                   # telemetry.costs, conventionally imported as _mw /
                   # _costs): ledger and registry updates are host-side
                   # arithmetic behind one-boolean flags — never a sync
                   "memwatch", "costs", "_mw", "_costs",
                   # r12 request tracing + the serving metrics endpoint
                   # (telemetry.tracing / serving.metrics): span records
                   # are retroactive dict/list appends from perf_counter
                   # stamps the lanes already take, and the scrape
                   # renderer reads telemetry snapshots — host-side by
                   # contract, never a device sync
                   "tracing", "_tracing", "metrics",
                   # r13 fleet observability (telemetry.fleet, aliased
                   # _fleet_mod in telemetry/__init__; promtext is the
                   # shared scrape renderer): ring appends, watchdog
                   # arithmetic and text rendering — host-side; the one
                   # collective lives in _fleet_exchange (see
                   # MATERIALIZE_DEFS), stride-gated off the hot path
                   "fleet", "_fleet", "_fleet_mod", "promtext",
                   # r17 numerics tier (telemetry.numerics, conventionally
                   # imported as _numerics): taps are pure jnp stat math
                   # that rides the trace as side outputs — never
                   # jax.debug, never a host sync; the one materialize is
                   # stride-gated inside numerics._materialize
                   # (MATERIALIZE_DEFS) and the forensic replay half never
                   # runs in training code
                   "numerics", "_numerics",
                   # r18 recompile sanitizer (telemetry.retrace): observe
                   # hooks ride compile-miss branches only — structural
                   # bookkeeping behind one boolean, never a device sync,
                   # and replays never reach them
                   "retrace", "_retrace",
                   # r20 capacity accounting (telemetry.capacity, aliased
                   # _capacity_mod in telemetry/__init__): note hooks are
                   # retroactive interval/EWMA appends from perf_counter
                   # stamps the serving lanes already take — one boolean
                   # disabled, a few float ops under one lock enabled,
                   # never a device touch
                   "capacity", "_capacity", "_capacity_mod"}


def _is_recording_call(dotted: str) -> bool:
    return bool(dotted) and dotted.split(".", 1)[0] in RECORDING_HEADS


# --- T4 ---------------------------------------------------------------------

_TIME_LAST = {"time", "perf_counter", "monotonic", "process_time",
              "time_ns", "perf_counter_ns", "now", "utcnow", "today"}
_NP_RANDOM_PREFIXES = ("np.random.", "numpy.random.", "onp.random.",
                       "_np.random.")


def _is_nondet_call(dotted: str) -> bool:
    if not dotted:
        return False
    if dotted.startswith(_NP_RANDOM_PREFIXES):
        return True
    if dotted.startswith("random."):
        return True  # stdlib random (jax.random is keyed => deterministic)
    if dotted.startswith(("time.", "datetime.")) and \
            dotted.rsplit(".", 1)[-1] in _TIME_LAST:
        return True
    return False


# --- T3 ---------------------------------------------------------------------

#: jnp/lax calls whose output carries no useful cotangent: an op whose
#: pure body *returns* one of these needs an explicit no_grad marker
NONDIFF_CALLS = {"argmax", "argmin", "argsort", "sign", "floor", "ceil",
                 "round", "rint", "trunc", "searchsorted", "nonzero",
                 "logical_not", "logical_and", "logical_or", "logical_xor",
                 "isnan", "isinf", "isfinite", "equal", "not_equal",
                 "greater", "greater_equal", "less", "less_equal",
                 "one_hot", "bincount", "sort_key_val"}

#: wrappers transparent to differentiability: ``nondiff(...).astype(...)``
#: is still nondiff
_TRANSPARENT_WRAPPERS = {"astype", "reshape", "moveaxis", "swapaxes",
                         "transpose", "squeeze", "expand_dims", "ravel"}


class Registration:
    """One static ``@defop`` / ``_export`` site."""

    __slots__ = ("name", "aliases", "no_grad", "func_node", "path", "line",
                 "col", "dynamic")

    def __init__(self, name, aliases, no_grad, func_node, path, line, col,
                 dynamic=False):
        self.name = name
        self.aliases = aliases
        self.no_grad = no_grad
        self.func_node = func_node
        self.path = path
        self.line = line
        self.col = col
        self.dynamic = dynamic


def _const_str(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _const_str_tuple(node):
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [_const_str(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)
    return None


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def collect_registrations(src, index: FunctionIndex):
    """Find every static op-registration site in a file.

    Handles both exporter idioms in this codebase:
      * registry-style  ``_export(fn, name="x", aliases=(...), no_grad=True)``
        and the ``@defop("x", aliases=..., no_grad=...)`` decorator;
      * elemwise-style  ``_export("x", fn, aliases, no_grad=True)``
        (string first).
    Registrations whose name is computed (a loop variable) are recorded as
    ``dynamic`` and left to the runtime registry check.
    """
    regs = []
    decorator_calls = set()
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and \
                        last_name(deco.func) == "defop":
                    decorator_calls.add(id(deco))
                    name = None
                    if deco.args:
                        name = _const_str(deco.args[0])
                    kw_name = _kw(deco, "name")
                    if kw_name is not None:
                        name = _const_str(kw_name)
                    regs.append(_make_reg(name or node.name, deco, node,
                                          src.path))
                elif last_name(deco) == "defop":
                    regs.append(Registration(node.name, (), False, node,
                                             src.path, node.lineno,
                                             node.col_offset))
        if not isinstance(node, ast.Call) or id(node) in decorator_calls:
            continue
        if last_name(node.func) not in ("_export", "_export_fn", "defop"):
            continue
        if not node.args:
            continue
        first = node.args[0]
        if _const_str(first) is not None:
            # elemwise-style: _export(name, fn, aliases=...)
            fn_node = node.args[1] if len(node.args) > 1 else None
            regs.append(_make_reg(_const_str(first), node,
                                  _resolve_func(fn_node, index), src.path,
                                  alias_pos=2))
        elif isinstance(first, (ast.Name, ast.Lambda, ast.Attribute)):
            name_expr = _kw(node, "name")
            if name_expr is None and len(node.args) > 1:
                name_expr = node.args[1]
            if name_expr is not None:
                name = _const_str(name_expr)
                dynamic = name is None
            else:
                name = last_name(first) if not isinstance(first, ast.Lambda) \
                    else None
                dynamic = name is None
            regs.append(_make_reg(name, node,
                                  _resolve_func(first, index), src.path,
                                  dynamic=dynamic))
        else:
            # _export(_scalar_op(_name, _fn), name=_name): fully dynamic
            regs.append(Registration(None, (), False, None, src.path,
                                     node.lineno, node.col_offset,
                                     dynamic=True))
    return regs


def _make_reg(name, call, func_node, path, alias_pos=None, dynamic=False):
    aliases = ()
    alias_expr = _kw(call, "aliases")
    if alias_expr is None and alias_pos is not None and \
            len(call.args) > alias_pos:
        alias_expr = call.args[alias_pos]
    if alias_expr is not None:
        aliases = _const_str_tuple(alias_expr) or ()
    ng_expr = _kw(call, "no_grad")
    no_grad = isinstance(ng_expr, ast.Constant) and ng_expr.value is True
    return Registration(name, aliases, no_grad, func_node, path,
                        call.lineno, call.col_offset, dynamic=dynamic)


def _resolve_func(node, index: FunctionIndex):
    if isinstance(node, ast.Lambda):
        return node
    if isinstance(node, (ast.Name, ast.Attribute)):
        cands = index.by_name.get(last_name(node), ())
        if len(cands) == 1:
            return cands[0]
    return None


def _returns_nondiff(expr, func_node, _depth=0) -> bool:
    """Does ``expr`` (a return value) derive directly from a
    non-differentiable primitive?  Unwraps dtype/layout-transparent
    wrappers and follows one level of local assignment."""
    if _depth > 4 or expr is None:
        return False
    if isinstance(expr, ast.Compare):
        return True
    if isinstance(expr, ast.Call):
        name = last_name(expr.func)
        if name in NONDIFF_CALLS:
            return True
        if name in _TRANSPARENT_WRAPPERS and \
                isinstance(expr.func, ast.Attribute):
            return _returns_nondiff(expr.func.value, func_node, _depth + 1)
        return False
    if isinstance(expr, ast.Name):
        assigned = None
        for n in ast.walk(func_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in n.targets):
                assigned = n.value
        return _returns_nondiff(assigned, func_node, _depth + 1)
    if isinstance(expr, ast.Attribute):
        return _returns_nondiff(expr.value, func_node, _depth + 1)
    return False


def _pure_bodies(func_node, index: FunctionIndex):
    """Inner callables handed to ``apply_op`` inside an op wrapper — the
    functions that actually trace."""
    out = []
    for call in ast.walk(func_node):
        if isinstance(call, ast.Call) and \
                last_name(call.func) == "apply_op" and call.args:
            inner = call.args[0]
            if isinstance(inner, ast.Lambda):
                out.append(inner)
            elif isinstance(inner, ast.Name):
                resolved = _resolve_func(inner, index)
                if resolved is not None and resolved is not func_node:
                    out.append(resolved)
    return out


def _all_returns_nondiff(fn) -> bool:
    if isinstance(fn, ast.Lambda):
        return _returns_nondiff(fn.body, fn)
    returns = [n for n in ast.walk(fn) if isinstance(n, ast.Return)
               and n.value is not None]
    if not returns:
        return False
    return all(_returns_nondiff(r.value, fn) for r in returns)


# --- T8 ---------------------------------------------------------------------

#: regexes that match every parameter path — a rule after one of these
#: is dead under first-match-wins
_CATCH_ALL_PATTERNS = {"", ".*", ".+", "^.*", ".*$", "^.*$", "^.+$"}

#: spec axis names that shard the MODEL (vs the batch): a table using
#: these must say what happens to everything else
_MODEL_AXES = {"tp", "ep", "mp", "sp", "model", "expert", "tensor"}


def _literal_rule_table(node, src):
    """``node`` as a literal ((pattern, spec), ...) rule table, following
    one level of module-scope Name assignment.  Returns a list of
    (pattern_str_or_None, spec_elements_or_None, ast_node) entries, or
    None when the expression is not a literal table (dynamic tables are
    the engine's problem at runtime, not the linter's)."""
    if isinstance(node, ast.Name):
        assigned = None
        for stmt in src.tree.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == node.id
                    for t in stmt.targets):
                assigned = stmt.value
        node = assigned
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    entries = []
    for elt in node.elts:
        if not isinstance(elt, (ast.Tuple, ast.List)) or \
                len(elt.elts) != 2:
            return None  # not a (pattern, spec) table after all
        pat = _const_str(elt.elts[0])
        spec_node = elt.elts[1]
        spec = None
        if isinstance(spec_node, (ast.Tuple, ast.List)):
            vals = [e.value for e in spec_node.elts
                    if isinstance(e, ast.Constant)]
            if len(vals) == len(spec_node.elts):
                spec = vals
        entries.append((pat, spec, elt))
    return entries


# --- T9 ---------------------------------------------------------------------

#: direct remat primitives — the policy engine's ``checkpoint_wrap`` is
#: the ONE sanctioned call site for model code (memory/policy.py), so a
#: dotted call to any of these inside model code bypasses the tier ladder
_T9_CHECKPOINT_CALLS = {"jax.checkpoint", "jax.remat",
                        "jax.ad_checkpoint.checkpoint",
                        "ad_checkpoint.checkpoint"}

#: planner/policy entry points whose RETURN VALUE is the product: a fit
#: verdict, a prescription, or a selected tier.  Called as a bare
#: statement, the verdict is discarded and nothing gates on it.
_T9_PLANNER_FUNCS = {"plan_model", "auto_tier", "plan_from_artifact",
                     "select_tier", "prescribe"}

#: dotted heads that identify the planner (``planner.plan_model`` /
#: ``mem.auto_tier``); a bare imported name also counts
_T9_PLANNER_HEADS = {"planner", "policy", "memory", "mem", "_mem",
                     "_planner", "_policy", "_mem_planner", "_mem_policy",
                     "mxnet_tpu"}


def _t9_is_model_code(src) -> bool:
    """Model code = a file under a ``models`` package, or one defining a
    class with a ``hybrid_forward`` method (a gluon block)."""
    parts = src.path.replace("\\", "/").split("/")
    if "models" in parts:
        return True
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        item.name == "hybrid_forward":
                    return True
    return False


def _t9_stmt_calls(tree):
    """ids of Call nodes that ARE a whole expression statement — their
    value is discarded."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


# ---------------------------------------------------------------------------
# Per-file rule driver
# ---------------------------------------------------------------------------

class FileChecker:
    """Runs T1/T2/T4/T5 over one parsed file and collects T3
    registrations for the cross-file pass."""

    def __init__(self, src, enabled=None):
        self.src = src
        self.enabled = enabled
        self.index = FunctionIndex(src.tree)
        self.violations = []
        self.registrations = []
        self.reg_facts = []       # serializable T3 facts (cacheable)
        self.lock_facts = {"path": src.path, "edges": []}  # T11 facts
        self._taint_cache = {}

    def _on(self, rule):
        return self.enabled is None or rule in self.enabled

    def run(self):
        if self._on("T3"):
            self.registrations = collect_registrations(self.src, self.index)
            self.reg_facts = [registration_facts(r, self.src, self.index)
                              for r in self.registrations]
        if self.enabled is None or (self.enabled & _CONCURRENCY_RULES):
            conc, self.lock_facts = check_concurrency(
                self.src, self.index, enabled=self.enabled)
            self.violations.extend(conc)
        if self.enabled is None or (self.enabled & _COMPILE_RULES):
            self.violations.extend(check_compile_discipline(
                self.src, self.index, enabled=self.enabled))
        if self._on("T6") or self._on("T7"):
            self.violations.extend(check_donation(
                self.src, self.index, enabled=self.enabled))
        t5_taint = self._t5_taint() if self._on("T5") else {}
        t9_model = _t9_is_model_code(self.src) if self._on("T9") else False
        t9_stmts = _t9_stmt_calls(self.src.tree) if self._on("T9") \
            else frozenset()
        for node in ast.walk(self.src.tree):
            hot = self.index.in_traced_region(node)
            if isinstance(node, ast.Call):
                if self._on("T1"):
                    self._check_t1(node, hot)
                if self._on("T4") and hot:
                    self._check_t4(node)
                if self._on("T5"):
                    self._check_t5_mutator_call(node, t5_taint)
                if self._on("T8"):
                    self._check_t8(node)
                if self._on("T9"):
                    self._check_t9(node, t9_model, t9_stmts)
            elif isinstance(node, (ast.If, ast.While, ast.Assert)) and hot:
                if self._on("T2"):
                    self._check_t2(node)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                if self._on("T5"):
                    self._check_t5_store(node, t5_taint)
        return self.violations

    def _emit(self, rule, severity, node, message):
        line = getattr(node, "lineno", 0)
        if self.src.is_suppressed(rule, line):
            return
        self.violations.append(Violation(
            rule=rule, severity=severity, path=self.src.path, line=line,
            col=getattr(node, "col_offset", 0),
            context=self.index.qualname_of(node), message=message,
            source=self.src.line_text(line)))

    # -- T1 ------------------------------------------------------------------
    def _check_t1(self, call, hot):
        func = call.func
        dotted = dotted_name(func)
        if _is_recording_call(dotted):
            return
        if isinstance(func, ast.Attribute):
            meth = func.attr
            if hot and meth in SYNC_METHODS:
                self._emit("T1", SEVERITY_ERROR, call,
                           f".{meth}() forces a host sync inside a traced "
                           "hot path")
                return
            if not hot and meth in SYNC_METHODS_ANYWHERE:
                fn_node = self.index.enclosing_function(call)
                if fn_node is not None and \
                        getattr(fn_node, "name", None) in MATERIALIZE_DEFS:
                    return  # sanctioned batch-boundary sync point
                self._emit("T1", SEVERITY_WARNING, call,
                           f".{meth}() blocks on the dispatch queue; keep "
                           "it out of per-step loops or waiver it")
                return
        if hot and dotted in SYNC_FUNCS_TRACED:
            self._emit("T1", SEVERITY_ERROR, call,
                       f"{dotted}() on a traced value concretizes the "
                       "tracer (host sync) inside a hot path")
            return
        if not hot and dotted in SYNC_FUNCS_ANYWHERE:
            self._emit("T1", SEVERITY_WARNING, call,
                       f"{dotted}() is a blocking device->host transfer")
            return
        if hot and isinstance(func, ast.Name) and \
                func.id in SCALAR_BUILTINS and len(call.args) == 1 and \
                not isinstance(call.args[0], ast.Constant):
            fn_node = self.index.enclosing_function(call)
            taint = self._taint_for(fn_node)
            if fn_node is not None and expr_tainted(call.args[0], taint):
                self._emit("T1", SEVERITY_ERROR, call,
                           f"{func.id}() on a traced value forces a host "
                           "sync / concretization inside a hot path")

    # -- T2 ------------------------------------------------------------------
    def _taint_for(self, fn_node):
        if fn_node is None:
            return set()
        key = id(fn_node)
        if key not in self._taint_cache:
            if isinstance(fn_node, ast.Lambda):
                taint = {a.arg for a in fn_node.args.args}
            else:
                taint = function_taint(fn_node)
            self._taint_cache[key] = taint
        return self._taint_cache[key]

    def _check_t2(self, node, ):
        fn_node = self.index.enclosing_function(node)
        if fn_node is None:
            return
        taint = self._taint_for(fn_node)
        test = node.test
        if expr_tainted(test, taint):
            kind = {ast.If: "if", ast.While: "while",
                    ast.Assert: "assert"}[type(node)]
            self._emit("T2", SEVERITY_ERROR, node,
                       f"python `{kind}` on a traced value inside a traced "
                       "region — use lax.cond/jnp.where or hoist the check "
                       "out of the trace")

    # -- T4 ------------------------------------------------------------------
    def _check_t4(self, call):
        dotted = dotted_name(call.func)
        if _is_recording_call(dotted):
            return
        if _is_nondet_call(dotted):
            self._emit("T4", SEVERITY_ERROR, call,
                       f"{dotted}() inside a traced region is evaluated "
                       "once at trace time and baked in as a constant — "
                       "thread a jax PRNG key / pass timestamps as inputs")

    # -- T8 ------------------------------------------------------------------
    def _check_t8(self, call):
        """Static sanity on LITERAL partition-rule tables at the sites
        that consume them."""
        name = last_name(call.func)
        table_expr = None
        if name == "PartitionRules" and call.args:
            table_expr = call.args[0]
        elif name == "place_params" and len(call.args) > 1:
            table_expr = call.args[1]
        if table_expr is None:
            kw = _kw(call, "partition_rules") or _kw(call, "rules")
            table_expr = kw
        if table_expr is None:
            return
        entries = _literal_rule_table(table_expr, self.src)
        if not entries:
            return
        seen, dead_after = {}, None
        uses_model_axis = False
        for pat, spec, node in entries:
            if pat is None:
                continue  # computed pattern: runtime's problem
            try:
                re.compile(pat)
            except re.error as e:
                self._emit("T8", SEVERITY_ERROR, node,
                           f"partition rule pattern {pat!r} does not "
                           f"compile ({e}) — it can never match a "
                           "parameter")
                continue
            if dead_after is not None:
                self._emit("T8", SEVERITY_ERROR, node,
                           f"rule {pat!r} is unreachable: it follows the "
                           f"catch-all {dead_after!r} and first match "
                           "wins — reorder the table")
            elif pat in seen:
                self._emit("T8", SEVERITY_ERROR, node,
                           f"duplicate pattern {pat!r}: first match wins, "
                           "this rule never fires — merge or reorder")
            seen[pat] = node
            if pat.strip("$^") in ("", ".*", ".+") or \
                    pat in _CATCH_ALL_PATTERNS:
                dead_after = dead_after or pat
            if spec and any(a in _MODEL_AXES for a in spec
                            if isinstance(a, str)):
                uses_model_axis = True
        has_catch_all = dead_after is not None
        explicit_policy = _kw(call, "on_unmatched") is not None
        if uses_model_axis and not has_catch_all and not explicit_policy:
            self._emit("T8", SEVERITY_WARNING, call,
                       "rule table shards model axes but has no terminal "
                       "catch-all and no on_unmatched= policy: unmatched "
                       "parameters silently replicate over the mesh — add "
                       "an explicit ('.*', ()) fallback or "
                       "on_unmatched='error'")

    # -- T9 ------------------------------------------------------------------
    def _check_t9(self, call, model_code, stmt_calls):
        dotted = dotted_name(call.func)
        if model_code and dotted in _T9_CHECKPOINT_CALLS:
            self._emit("T9", SEVERITY_ERROR, call,
                       f"hand-rolled {dotted}() in model code bypasses "
                       "the remat policy engine — wrap with "
                       "memory.policy.checkpoint_wrap (or declare "
                       "hybridize(remat=...) / set_remat) so the "
                       "auto-tier ladder stays in control")
            return
        name = last_name(call.func)
        if name in _T9_PLANNER_FUNCS and id(call) in stmt_calls:
            head = dotted.split(".", 1)[0] if "." in dotted else ""
            if not head or head in _T9_PLANNER_HEADS:
                self._emit("T9", SEVERITY_WARNING, call,
                           f"{name}() called as a bare statement — the "
                           "returned plan/verdict is discarded; assign "
                           "it and gate on fits/headroom (or drop the "
                           "call)")

    # -- T5 ------------------------------------------------------------------
    def _t5_taint(self):
        """Names assigned from host views of device buffers."""
        taint = set()
        for node in ast.walk(self.src.tree):
            if not isinstance(node, ast.Assign):
                continue
            if _is_host_view(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        taint.add(t.id)
        return taint

    def _check_t5_store(self, node, taint):
        target = node.targets[0] if isinstance(node, ast.Assign) \
            else node.target
        root = _subscript_root(target)
        if root is None:
            return
        if isinstance(root, ast.Name) and root.id in taint:
            self._emit("T5", SEVERITY_ERROR, node,
                       f"in-place mutation of `{root.id}`, a host view of "
                       "a jax-backed buffer — the write never reaches "
                       "device memory (copy first, or build a new array)")
        elif _is_host_view(root):
            self._emit("T5", SEVERITY_ERROR, node,
                       "subscript-assign into a fresh host view of a "
                       "jax-backed buffer — the write is discarded")

    def _check_t5_mutator_call(self, call, taint):
        func = call.func
        if isinstance(func, ast.Attribute) and \
                func.attr in ("fill", "put", "itemset", "resize",
                              "setfield", "partition"):
            base = func.value
            if isinstance(base, ast.Name) and base.id in taint:
                self._emit("T5", SEVERITY_ERROR, call,
                           f"`.{func.attr}()` mutates `{base.id}`, a host "
                           "view of a jax-backed buffer")
            elif _is_host_view(base):
                self._emit("T5", SEVERITY_ERROR, call,
                           f"`.{func.attr}()` mutates a fresh host view "
                           "of a jax-backed buffer")
        if dotted_name(func) in ("np.copyto", "numpy.copyto") and call.args:
            dst = call.args[0]
            if (isinstance(dst, ast.Name) and dst.id in taint) or \
                    _is_host_view(dst):
                self._emit("T5", SEVERITY_ERROR, call,
                           "np.copyto into a host view of a jax-backed "
                           "buffer — the write never reaches the device")


def _subscript_root(target):
    """For ``a[i]`` / ``a[i][j]`` / ``a.flat[i]`` return the base
    expression ``a``; None if the target is a bare name/attribute."""
    if not isinstance(target, ast.Subscript):
        return None
    base = target.value
    while isinstance(base, ast.Subscript):
        base = base.value
    if isinstance(base, ast.Attribute) and base.attr == "flat":
        base = base.value
    return base


def _is_host_view(expr) -> bool:
    """``x.asnumpy()`` / ``jax.device_get(x)`` / ``np.asarray(x._data)``."""
    if not isinstance(expr, ast.Call):
        return False
    func = expr.func
    if isinstance(func, ast.Attribute) and func.attr == "asnumpy":
        return True
    dotted = dotted_name(func)
    if dotted == "jax.device_get":
        return True
    if dotted in ("np.asarray", "numpy.asarray", "onp.asarray") and \
            expr.args and isinstance(expr.args[0], ast.Attribute) and \
            expr.args[0].attr == "_data":
        return True
    return False


# ---------------------------------------------------------------------------
# Cross-file T3 finalization
# ---------------------------------------------------------------------------

def registration_facts(reg, src, index):
    """Reduce a Registration (which carries an AST node) to the
    serializable facts the cross-file pass needs.  Everything derived
    from the AST — docstrings, lambda-ness, the nondiff return scan —
    is computed here, per file, so cached files skip AST work
    entirely."""
    fn = reg.func_node
    is_lambda = isinstance(fn, ast.Lambda)
    has_doc = bool(ast.get_docstring(fn)) if fn is not None and \
        not is_lambda else False
    returns_nondiff = False
    if fn is not None and not is_lambda and not reg.no_grad:
        returns_nondiff = any(_all_returns_nondiff(body)
                              for body in _pure_bodies(fn, index))
    return {
        "name": reg.name,
        "aliases": list(reg.aliases),
        "no_grad": reg.no_grad,
        "dynamic": reg.dynamic,
        "path": reg.path,
        "line": reg.line,
        "col": reg.col,
        "has_func": fn is not None,
        "is_lambda": is_lambda,
        "has_doc": has_doc,
        "returns_nondiff": returns_nondiff,
        "suppressed": src.is_suppressed("T3", reg.line),
        "source": src.line_text(reg.line),
    }


def check_registrations(all_facts):
    """Duplicate / docstring / grad-path checks over every static
    registration fact collected in the run (see registration_facts)."""
    violations = []

    def emit(fact, message, severity=SEVERITY_ERROR, context=None):
        if fact["suppressed"]:
            return
        violations.append(Violation(
            rule="T3", severity=severity, path=fact["path"],
            line=fact["line"], col=fact["col"],
            context=context or (fact["name"] or "<dynamic>"),
            message=message, source=fact["source"]))

    seen = {}
    for fact in all_facts:
        if fact["dynamic"] or fact["name"] is None:
            continue
        for name in (fact["name"],) + tuple(fact["aliases"]):
            prev = seen.get(name)
            if prev is not None and (prev["path"], prev["line"]) != \
                    (fact["path"], fact["line"]):
                emit(fact, f"op name {name!r} already registered at "
                           f"{prev['path']}:{prev['line']} — duplicate "
                           "registration shadows the original",
                     context=name)
            else:
                seen[name] = fact
        if not fact["has_func"]:
            continue
        if not fact["name"].startswith("_"):
            if fact["is_lambda"]:
                emit(fact, f"op {fact['name']!r} is registered as a bare "
                           "lambda — give it a named, documented wrapper",
                     severity=SEVERITY_WARNING)
            elif not fact["has_doc"]:
                emit(fact, f"op {fact['name']!r} has no docstring",
                     severity=SEVERITY_WARNING)
        if fact["returns_nondiff"]:
            emit(fact, f"op {fact['name']!r} returns a "
                       "non-differentiable value but is not marked "
                       "no_grad=True — mark it (or wire a custom vjp) "
                       "so autograd skips the vjp trace instead of "
                       "emitting garbage cotangents")
    return violations
