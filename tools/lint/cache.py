"""Per-file analysis cache.

mxlint re-runs on every tier-1 invocation; as rule families grow
(T1→T12) the full-AST sweep is the dominant cost.  Per-file results
are pure functions of (file content, analyzer source, enabled rules),
so they cache under a content hash:

  * key: sha1 of the file's bytes;
  * salt: sha1 over every ``tools/lint/*.py`` source plus the sorted
    enabled-rule set — any analyzer edit or rule-selection change drops
    the whole cache (correct by construction, no fine-grained
    invalidation to get wrong);
  * value: the file's serialized violations plus the serializable
    cross-file facts (T3 registration facts, T11 lock-order edges).

The cross-file passes themselves (duplicate registrations, the
lock-order cycle scan) always re-run — they are cheap graph work over
the cached facts.  Hit/miss counts surface in ``--json`` as
``summary.cache``.
"""
from __future__ import annotations

import hashlib
import json
import os

CACHE_VERSION = 1

#: violation fields in serialization order (mirrors core.Violation)
_V_FIELDS = ("rule", "severity", "path", "line", "col", "context",
             "message", "source")


def analyzer_salt(enabled=None):
    """Hash of the analyzer's own sources + the enabled-rule set."""
    h = hashlib.sha1()
    lint_dir = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(os.listdir(lint_dir)):
        if not fn.endswith(".py"):
            continue
        h.update(fn.encode("utf-8"))
        with open(os.path.join(lint_dir, fn), "rb") as f:
            h.update(f.read())
    h.update(repr(sorted(enabled) if enabled is not None
                  else "all").encode("utf-8"))
    return h.hexdigest()


def content_digest(text: str) -> str:
    return hashlib.sha1(text.encode("utf-8")).hexdigest()


class AnalysisCache:
    """Content-hash-keyed store of per-file analysis results."""

    def __init__(self, path, salt):
        self.path = path
        self.salt = salt
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._files = {}
        if path and os.path.exists(path):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
                if data.get("version") == CACHE_VERSION and \
                        data.get("salt") == salt:
                    self._files = data.get("files", {})
            except (ValueError, OSError):
                pass  # corrupt/unreadable cache == cold cache

    def get(self, relpath, digest):
        """(violations, reg_facts, lock_facts) or None on miss."""
        entry = self._files.get(relpath)
        if entry is None or entry.get("digest") != digest:
            self.misses += 1
            return None
        self.hits += 1
        from .core import Violation
        violations = [Violation(**{k: d[k] for k in _V_FIELDS})
                      for d in entry["violations"]]
        return violations, entry["reg_facts"], entry["lock_facts"]

    def put(self, relpath, digest, violations, reg_facts, lock_facts):
        self._files[relpath] = {
            "digest": digest,
            "violations": [{k: getattr(v, k) for k in _V_FIELDS}
                           for v in violations],
            "reg_facts": reg_facts,
            "lock_facts": lock_facts,
        }
        self._dirty = True

    def save(self):
        if not self._dirty or not self.path:
            return
        payload = {"version": CACHE_VERSION, "salt": self.salt,
                   "files": self._files}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # read-only checkout: run uncached

    def stats(self):
        return {"hits": self.hits, "misses": self.misses}
