"""T11 fixture: lock-order cycle (A->B in one path, B->A in another)
plus unbounded blocking calls under a held lock."""
import queue
import threading

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()
_jobs_queue = queue.Queue()


def forward():
    with _LOCK_A:
        with _LOCK_B:                 # edge A->B
            return 1


def backward():
    with _LOCK_B:
        with _LOCK_A:                 # edge B->A: closes the T11 cycle
            return 2


def blocked_get():
    with _LOCK_A:
        return _jobs_queue.get()      # T11 warning: timeout-less get under lock


def blocked_put(item):
    with _LOCK_B:
        _jobs_queue.put(item)         # T11 warning: unbounded put under lock


def blocked_result(ticket):
    with _LOCK_A:
        return ticket.result()        # T11 warning: unbounded wait under lock


def bounded_get():
    with _LOCK_A:
        return _jobs_queue.get(timeout=1.0)   # ok: bounded

def nonblocking_put(item):
    with _LOCK_B:
        _jobs_queue.put(item, block=False)    # ok: non-blocking


def spawn():
    t = threading.Thread(target=forward, name="mxt-order")
    t.daemon = True
    t.start()
    t.join()
