"""T15 fixture: the inline-annotation form — a one-site helper whose
budget rides a comment instead of a module dict."""
import jax


def make_step(fn):
    # mxlint: signatures=1 (single static schema, rebuilt on reload only)
    return jax.jit(fn)
