"""T5 fixture: in-place numpy mutation of jax-backed buffers."""
import jax
import numpy as np


def clobber_weights(param, idx, val):
    host = param.asnumpy()
    host[idx] = val                   # T5 error: writes a host copy only
    host[idx] += 1                    # T5 error: augmented host mutation
    return host


def clobber_fresh_view(param):
    param.asnumpy()[0] = 0.0          # T5 error: write into fresh view
    jax.device_get(param)[1] = 1.0    # T5 error: same via device_get
    np.copyto(param.asnumpy(), 0.0)   # T5 error: copyto into host view
    return param


def fill_view(param):
    view = jax.device_get(param)
    view.fill(0.0)                    # T5 error: mutator on host view
    return view


def good_update(param, idx, val):
    fresh = np.array(param.asnumpy())  # explicit copy: mutation is fine
    fresh[idx] = val                   # ok: fresh is a real copy
    return fresh
