"""T6 fixture: use-after-donation.  Seeds true positives for every
donating-binding shape (local, attribute, factory, inline) plus
false-positive traps that must stay quiet."""
import jax


def _update(w, g):
    return w - 0.01 * g


# -- local binding -----------------------------------------------------------

def local_binding_read_after(w, g):
    step = jax.jit(_update, donate_argnums=(0,))
    new_w = step(w, g)
    total = w.sum()                   # T6 error: w was donated above
    return new_w, total


def local_binding_rebound(w, g):
    step = jax.jit(_update, donate_argnums=(0,))
    w = step(w, g)                    # rebinds w: poison cleared
    return w.sum()                    # ok


def read_before_call(w, g):
    step = jax.jit(_update, donate_argnums=(0,))
    norm = w.sum()                    # ok: read precedes the donation
    return step(w, g), norm


# -- loop-carried ------------------------------------------------------------

def loop_carried(w, grads):
    step = jax.jit(_update, donate_argnums=(0,))
    out = None
    for g in grads:
        out = step(w, g)              # T6 error: w donated by the
        #                               previous iteration, never rebound
    return out


def loop_rebound(w, grads):
    step = jax.jit(_update, donate_argnums=(0,))
    for g in grads:
        w = step(w, g)                # ok: rebound every iteration
    return w


# -- branch merge ------------------------------------------------------------

def branch_partial_rebind(w, g, flag):
    step = jax.jit(_update, donate_argnums=(0,))
    out = step(w, g)
    if flag:
        w = out                       # only one arm rebinds
    return w.sum()                    # T6 error: other arm left w dead


def branch_full_rebind(w, g, flag):
    step = jax.jit(_update, donate_argnums=(0,))
    out = step(w, g)
    if flag:
        w = out
    else:
        w = out * 1.0
    return w.sum()                    # ok: every arm rebinds w


# -- attribute binding -------------------------------------------------------

class Stepper:
    def __init__(self):
        self._step = jax.jit(self._impl, donate_argnums=(1,))

    def _impl(self, w, state, x):
        return w @ x, state + 1

    def run(self, w, state, x):
        out, new_state = self._step(w, state, x)
        stale = state + 0             # T6 error: state donated at pos 1
        return out, new_state, stale

    def run_clean(self, w, state, x):
        out, state = self._step(w, state, x)
        return out, state + 0         # ok: rebound in the same statement


# -- factory binding ---------------------------------------------------------

def _make_step():
    return jax.jit(_update, donate_argnums=(0,))


def factory_read_after(w, g):
    step = _make_step()
    new_w = step(w, g)
    return new_w, w * 2               # T6 error: w donated via factory


# -- inline call -------------------------------------------------------------

def inline_read_after(w, g):
    new_w = jax.jit(_update, donate_argnums=(0,))(w, g)
    return new_w + w                  # T6 error: inline donation


# -- sanitizer exemption -----------------------------------------------------

def sanitizer_handoff(w, g, _san):
    step = jax.jit(_update, donate_argnums=(0,))
    new_w = step(w, g)
    _san.donate((w,), "fixture site")  # ok: poison-registry handoff
    return new_w
