"""T6 fixture: fleet observability hooks in training hot paths.

The r13 fleet layer stamps rank/world onto step records
(``fleet.on_step_record``), runs watchdog arithmetic
(``observe_step``/``observe_fleet``) and appends to the flight-recorder
ring — all host-side behind one boolean.  The analyzer must (a) not
flag ``fleet.*`` calls in hot step code, (b) not let hotness leak into
a same-module hook helper through its bare-name call, (c) leave the
``_fleet_exchange`` def's intentional eager materialize unflagged
(MATERIALIZE_DEFS — the stride-gated allgather syncs there by design),
while (d) still flagging a real host sync in a jitted step body.
"""
import time

import jax
import numpy as np

from mxnet_tpu.telemetry import fleet


def on_step_record(record, t0):
    # same-module fleet hook: the perf_counter stamp and dict writes
    # are host-side by design — hotness must NOT leak in through the
    # bare-name call in traced_train_tick below
    record["hook_ms"] = (time.perf_counter() - t0) * 1e3
    record["rank"] = 0


def traced_train_tick(step_fn, batch, record, t0):
    out = step_fn(batch)
    if record is not None:
        on_step_record(record, t0)                    # ok: helper
        fleet.incident("watchdog_halt",               # ok: fleet.*
                       context={"step": record["step"]})
    return out


traced_train_tick_jit = jax.jit(traced_train_tick, static_argnums=0)


def _fleet_exchange(vec, gathered):
    # the stride-gated allgather boundary: one intentional eager
    # device->host materialize per exchange window, never per step —
    # MATERIALIZE_DEFS exempts the T1 eager warning here
    return gathered.asnumpy().reshape(-1, vec.size)


def bad_synced_tick(step_fn, batch, record):
    out = step_fn(batch)
    host = np.asarray(out)          # T1 error: sync in the hot step
    if record is not None:
        record["loss"] = host[0]
    return host


bad_synced_tick_jit = jax.jit(bad_synced_tick, static_argnums=0)
