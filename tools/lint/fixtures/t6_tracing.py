"""T6 fixture: request-tracing span recording in serving hot paths.

The r12 decode tick records retroactive spans (``trace.add``) and the
failure paths call ``tracing.incident`` — both host-side dict/list work
behind the ``trace is not None`` guard.  The analyzer must (a) not flag
``tracing.*`` calls in hot dispatch code, (b) not let hotness leak into
a same-module span helper (whose perf_counter stamp is the point),
while (c) still flagging a real host sync sitting next to the span
bookkeeping.
"""
import time

import jax
import numpy as np

from mxnet_tpu.telemetry import tracing


def add_span(trace, t0, step):
    # same-module recording helper: the perf_counter read (the span's
    # closing stamp) is host-side by design — hotness must NOT leak in
    # through the bare-name call in traced_decode_tick below
    trace.add("decode.step", t0, time.perf_counter(), step=step)


def traced_decode_tick(engine, active, trace, t0):
    out = engine.step(active)
    if trace is not None:
        add_span(trace, t0, engine.steps)                # ok: helper
        tracing.incident("replica_exception",            # ok: tracing.*
                         context={"step": engine.steps})
    return out


traced_decode_tick_jit = jax.jit(traced_decode_tick, static_argnums=0)


def bad_synced_tick(engine, active, trace):
    out = engine.step(active)
    host = np.asarray(out)             # T1 error: sync in the hot tick
    if trace is not None:
        trace.event("evict", value=host[0])
    return host


bad_synced_tick_jit = jax.jit(bad_synced_tick, static_argnums=0)
