# mxlint test fixtures: these files are PARSED by the analyzer in
# tests/test_lint.py, never imported/executed.  Each t*_ file seeds
# positive violations for one rule family; clean.py must stay clean.
