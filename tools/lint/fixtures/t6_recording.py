"""T6 fixture: recording/observability calls in hot paths.

Telemetry and profiler instrumentation is allowed in (host-side) hot
dispatch code — the recording fast path reads host clocks by design and
never executes inside a trace.  The analyzer must (a) not propagate
hotness into same-module recording helpers, and (b) not flag
``telemetry.*`` / ``prof.*`` calls themselves, while (c) still flagging
a direct wall-clock read in a traced body.
"""
import time

import jax

from mxnet_tpu import telemetry
from mxnet_tpu import profiler as prof

_PHASES = {}


def count(name, n=1):
    # same-module recording helper: the perf_counter read is the point —
    # hotness must NOT leak in through the bare-name call below
    _PHASES[name] = (_PHASES.get(name, 0.0) + n, time.perf_counter())


def instrumented_step(params, batch):
    count("step")                      # ok: recording helper, exempted
    telemetry.count("step_fusion.steps")   # ok: telemetry module call
    prof.record_op_event("step", 0.0)      # ok: profiler module call

    def loss_fn(p):
        return ((p * batch) ** 2).sum()

    return jax.value_and_grad(loss_fn)(params)


instrumented_step_jit = jax.jit(instrumented_step)


def bad_timed(params):
    stamp = time.perf_counter()       # T4 error: wall clock in trace
    return params * stamp


bad_timed_jit = jax.jit(bad_timed)
