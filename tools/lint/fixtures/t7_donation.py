"""T7 fixture: donation aliasing.  Same array (or views/members of the
same parent) reaching a donating call at donated + other positions,
and closure capture of a donated array.  Ends with clean shapes that
must not report."""
import jax


def _axpy(w, g):
    return w + g


def _combine(a, b, c):
    return a + b * c


# -- same name at two positions ----------------------------------------------

def same_name_donated_and_read(w):
    step = jax.jit(_axpy, donate_argnums=(0,))
    return step(w, w)                 # T7 error: w donated at 0, read at 1


def same_name_double_donation(a):
    both = jax.jit(_axpy, donate_argnums=(0, 1))
    return both(a, a)                 # T7 error: one buffer donated twice


# -- views / members of the same parent --------------------------------------

def view_aliases_parent(w):
    step = jax.jit(_axpy, donate_argnums=(0,))
    row = w[0]
    return step(w, row)               # T7 error: row is a view of w


def member_aliases_container(params):
    step = jax.jit(_axpy, donate_argnums=(0,))
    raws = tuple(p.data for p in params)
    first = params[0]
    return step(raws, first)          # T7 error: first is a member of the
    #                                   container raws was built from


def distinct_elements_ok(params):
    step = jax.jit(_axpy, donate_argnums=(0,))
    return step(params[0], params[1])  # ok: distinct constant indices


def fresh_math_ok(w):
    step = jax.jit(_axpy, donate_argnums=(0,))
    doubled = w * 2                   # fresh allocation, not a view
    return step(w, doubled)           # ok


def copy_ok(w):
    step = jax.jit(_axpy, donate_argnums=(0,))
    saved = w.copy()                  # explicit copy breaks aliasing
    return step(w, saved)             # ok


# -- closure capture ---------------------------------------------------------

def closure_captures_donated(w, g):
    def body(x):
        return x + w                  # closes over w ...

    step = jax.jit(body, donate_argnums=(0,))
    return step(w)                    # T7 error: ... and w is donated


def closure_clean(w, g):
    def body_clean(x):
        return x + g                  # closes over g, not the donated w

    step = jax.jit(body_clean, donate_argnums=(0,))
    return step(w)                    # ok


# -- three-arg mixed ---------------------------------------------------------

def unpack_aliases(state):
    step = jax.jit(_combine, donate_argnums=(0,))
    master, extra = state
    whole = state
    return step(whole, master, extra)  # T7 errors: master and extra are
    #                                    members of the donated whole
