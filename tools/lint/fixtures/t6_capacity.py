"""T6 fixture: capacity-accounting hooks in serving hot paths.

The r20 capacity layer turns stamps the lanes already take into
duty-cycle ledgers and λ/μ estimators (``capacity.note_tick`` /
``note_arrival`` / ``lane_busy`` ...) — host-side float ops behind one
boolean.  The analyzer must (a) not flag ``capacity.*`` calls in hot
decode-tick code, (b) not let hotness leak into a same-module hook
helper through its bare-name call, (c) keep tolerating the recording
heads alongside real work in a jitted body, while (d) still flagging
a genuine host sync in that same body.
"""
import time

import jax
import numpy as np

from mxnet_tpu.telemetry import capacity


def note_tick(index, active, capacity_slots, t0, t1):
    # same-module capacity hook: retroactive interval append from the
    # stamps the lane already took (falling back to its own clock read,
    # like the real hook) — hotness must NOT leak in through the
    # bare-name call in traced_decode_tick below
    t_end = time.perf_counter() if t1 is None else t1
    _ = (t_end - t0, active / capacity_slots, index)


def traced_decode_tick(step_fn, batch, index, t0, t1):
    out = step_fn(batch)
    note_tick(index, 4, 8, t0, t1)                  # ok: helper
    capacity.note_tick(index, 4, 8, t0, t1)         # ok: capacity.*
    capacity.note_kv(index, 60, 100)                # ok: capacity.*
    capacity.lane_busy(index, "prefill", t0, t1)    # ok: capacity.*
    return out


traced_decode_tick_jit = jax.jit(traced_decode_tick, static_argnums=0)


def bad_synced_tick(step_fn, batch, record):
    out = step_fn(batch)
    host = np.asarray(out)          # T1 error: sync in the hot step
    if record is not None:
        record["loss"] = host[0]
    return host


bad_synced_tick_jit = jax.jit(bad_synced_tick, static_argnums=0)
