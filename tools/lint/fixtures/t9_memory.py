"""T9 fixture: memory-policy bypass in model code.

Positives: hand-rolled ``jax.checkpoint``/``jax.remat`` inside a
hybrid block (model code — the policy engine's ``checkpoint_wrap`` is
the one sanctioned site), and planner calls whose verdict is discarded.
Negatives: ``checkpoint_wrap``-routed remat and planner calls whose
plan is assigned and gated on.
"""
import jax

from mxnet_tpu.memory import planner, policy
from mxnet_tpu.memory.planner import plan_model
from mxnet_tpu.memory.policy import auto_tier, checkpoint_wrap


class HandRolledBlock:
    """A gluon-shaped block that bypasses the tier ladder."""

    def hybrid_forward(self, F, x):
        inner = jax.checkpoint(self._layer)          # T9: bypasses policy
        return inner(x)

    def remat_forward(self, x):
        return jax.remat(self._layer)(x)             # T9: bypasses policy

    def _layer(self, x):
        return x * 2.0


class PolicyRoutedBlock:
    """The sanctioned shape: remat goes through the policy engine."""

    def hybrid_forward(self, F, x):
        wrapped = checkpoint_wrap(self._layer, "layer")  # clean
        return wrapped(x)

    def _layer(self, x):
        return x * 2.0


def dropped_verdicts(net, mesh):
    planner.plan_model(net, mesh=mesh)               # T9: verdict unused
    plan_model(net, mesh=mesh)                       # T9: verdict unused
    auto_tier(net, mesh=mesh)                        # T9: tier unused


def gated_verdicts(net, mesh):
    plan = planner.plan_model(net, mesh=mesh)        # clean: assigned
    if not plan.fits:
        raise MemoryError(plan.top_buffers)
    tier, _ = auto_tier(net, mesh=mesh)              # clean: consumed
    return tier
