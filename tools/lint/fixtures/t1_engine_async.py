"""T1 fixture: async-engine materialization points are sync sites.

With the async engine tier (``MXNET_ENGINE_ASYNC``) a size-flushed
segment runs on the worker thread; ``wait_to_read`` blocks the caller
on the worker's completion event and ticket-style ``.result()`` waits
join background work.  Both are host syncs: harmless as eager glue,
T1 findings inside a traced region.
"""
import jax

from mxnet_tpu import engine


def eager_drain(a, b):
    c = a + b
    c.wait_to_read()                  # fine: eager glue, explicit barrier
    engine.flush()                    # fine: drains the async queue too
    return c


def eager_ticket_join(ticket, x):
    y = x * 2
    ticket.result()                   # fine: joining a background save
    return y


def bad_jitted_wait(params, batch):
    loss = params * batch
    loss.wait_to_read()               # T1 error: worker-event wait in trace
    return loss


def bad_jitted_ticket(params, ticket):
    out = params + 1
    ticket.result()                   # T1 error: future join inside a trace
    return out


bad_jitted_wait_jit = jax.jit(bad_jitted_wait)
bad_jitted_ticket_jit = jax.jit(bad_jitted_ticket)
