"""T1 fixture: the data plane's prefetch-thread materialize site.

``DevicePrefetcher._prefetch`` (data/prefetch.py) lands each batch on
device and waits for the transfer on the background thread — the sync
IS the prefetch.  A def named ``_prefetch`` (MATERIALIZE_DEFS) gets the
scoped eager exemption; the same sync elsewhere in loader glue still
warns, and inside a traced region it stays an error regardless.
"""
import jax


def _prefetch(batches, put):
    out = []
    for b in batches:
        dev = put(b)
        dev.block_until_ready()       # fine: THE transfer-thread wait
        out.append(dev)
    return out


def loader_loop(batches, put, q):
    for dev in _prefetch(batches, put):   # fine: sanctioned helper call
        q.put(dev)


def leaky_wait(dev):
    return dev.block_until_ready()    # T1 warning: sync outside the
                                      # designated prefetch def


def bad_traced_prefetch(w, x):
    y = w * x
    return y.block_until_ready()      # T1 error: sync inside a trace


def _hot_prefetch(arrays):
    # the exemption covers EAGER warnings only: a traced sync is an
    # error no matter how prefetch-ish the def's name is
    first = arrays[0]
    return first.asnumpy()            # T1 error: traced sync


bad_traced_jit = jax.jit(bad_traced_prefetch)
hot_prefetch_jit = jax.jit(_hot_prefetch)
