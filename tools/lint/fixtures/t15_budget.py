"""T15 fixture: a declared budget with a missing kind, a stale kind and
an invalid budget value."""
import jax

from mxnet_tpu.telemetry import costs as _costs

__compile_signatures__ = {
    "fused_step": "1 per (batch schema x mesh x numerics mode)",  # ok
    "stale_kind": 2,              # T15 warning: never registered here
    "bad_budget": 0,              # T15 error: must be positive / formula
}


class Runner:
    def __init__(self, fn):
        self._fn = jax.jit(fn)

    def run(self, batch):
        out = self._fn(batch)
        _costs.note("fused_step", ("k",), self._fn, (batch,))
        _costs.note("bad_budget", ("k",), self._fn, (batch,))
        # T15 error: registered below but missing from the declaration
        _costs.note("unbudgeted", ("k",), self._fn, (batch,))
        return out
