"""Negative fixture: idiomatic hot-path code the analyzer must NOT flag."""
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.registry import apply_op, make_exporter

_export = make_exporter(__import__(__name__))


class GoodBlock:
    def __init__(self, flatten=False):
        self._flatten = flatten       # rank handling fixed at build time

    def hybrid_forward(self, F, x, act="relu"):
        if act == "relu":             # config dispatch on a default param
            return jnp.maximum(x, 0)
        if self._flatten:             # construction-time config, static
            return x
        return jnp.tanh(x)


def train_step(params, batch, key):
    noise = jax.random.normal(key, batch.shape)

    def loss_fn(p):
        return jnp.mean((p * batch - noise) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, grads


# mxlint: signatures=1 (single static train step, rebuilt on reload only)
train_step_jit = jax.jit(train_step)


def clean_scale(a, scale=2.0):
    """Scale every element (differentiable, documented)."""
    return apply_op(lambda x: x * scale, a, name="clean_scale")


_export(clean_scale, name="clean_scale")


def clean_floor(a):
    """Elementwise floor (explicitly non-differentiable)."""
    return apply_op(lambda x: jnp.floor(x), a, name="clean_floor")


_export(clean_floor, name="clean_floor", no_grad=True)


def host_logging(metrics):
    # eager host code between steps: plain attribute access, no syncs
    return {k: v for k, v in metrics.items()}
