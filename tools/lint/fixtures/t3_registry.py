"""T3 fixture: registry inconsistencies at static registration sites."""
import jax.numpy as jnp

from mxnet_tpu.ops.registry import apply_op, defop, make_exporter

_export = make_exporter(__import__(__name__))


def fix_argmax(a, axis=None):
    """Index of the maximum (non-differentiable)."""
    return apply_op(lambda x: jnp.argmax(x, axis=axis), a, name="fix_argmax")


_export(fix_argmax, name="fix_argmax")  # T3: nondiff but no no_grad marker


def fix_undocumented(a):
    return apply_op(lambda x: x * 2, a, name="fix_undocumented")


_export(fix_undocumented, name="fix_undocumented")  # T3: no docstring


def fix_dup(a):
    """First registration."""
    return a


def fix_dup2(a):
    """Second registration stealing the same name."""
    return a


_export(fix_dup, name="fix_dup")
_export(fix_dup2, name="fix_dup")       # T3: duplicate name


@defop("fix_sign", no_grad=True)
def fix_sign(a):
    """Sign of each element (marked no_grad: clean)."""
    return apply_op(lambda x: jnp.sign(x), a, name="fix_sign")
