"""T13 fixture: retrace hazards — baked scalars, shape branches in
hybridized forwards, formatted / dict-ordered compile keys."""
# mxlint: signatures=1 per helper (keeps T15 out of this T13 fixture)
import jax


# -- a. python scalar captured by a traced closure ---------------------------

def make_scaled_step(optzr):
    scale = float(optzr.rescale_grad)

    def step(x):
        return x * scale              # T13 error: baked at trace time

    return jax.jit(step)


def make_keyed_step(optzr, cache):
    scale = float(optzr.rescale_grad)
    sig = ("step", scale)             # ok: the bake is keyed — a new
    fn = cache.get(sig)               # scale compiles a new entry instead
    if fn is None:                    # of silently retracing the old one

        def step(x):
            return x * scale

        fn = jax.jit(step)
        cache[sig] = fn
    return fn


def make_lifted_step():
    def step(x, scale):               # ok: scale is a runtime argument
        return x * scale

    return jax.jit(step)


# -- b. shape/dtype branches inside hybrid_forward ---------------------------

class PadBlock:
    def __init__(self, multiple, pad):
        self._multiple = multiple
        self._pad = pad

    def hybrid_forward(self, F, x):
        if x.shape[1] % self._multiple:   # T13 warning: per-shape retrace
            x = F.pad(x, ((0, 0), (0, 1)))
        while x.ndim > 2:                 # T13 warning: per-rank retrace
            x = F.squeeze(x, axis=0)
        if self._pad:                     # ok: config dispatch, static
            x = x + 1
        return x


# -- c. formatted strings feeding compile keys -------------------------------

def formatted_key(lr, wd):
    sig = f"lr={lr:.3f}/wd={wd}"      # T13 warning: float -> text key
    return sig


def tuple_key(lr, wd):
    sig = ("sgd", lr, wd)             # ok: raw component tuple
    return sig


# -- d. dict-iteration order feeding compile keys ----------------------------

def attr_key(**kwargs):
    key = tuple(kwargs.items())       # T13 warning: insertion-ordered
    return key


def attr_key_sorted(**kwargs):
    key = tuple(sorted(kwargs.items()))   # ok: canonical order
    return key


# -- e. engine-lifted float cells (apply_op dispatch) ------------------------

def scalar_op_lifted(apply_op, fn, data, scalar):
    s = float(scalar)
    # ok: handed straight to apply_op — the engine lifts float cells to
    # runtime scalar args, the value never joins the segment key
    return apply_op(lambda x: fn(x, s), data, name="op")


def scalar_op_int_capture(apply_op, fn, data, scalar):
    n = int(scalar)
    # T13 error: int cells are NOT lifted — keyed by value, one compile
    # per distinct constant
    return apply_op(lambda x: fn(x, n), data, name="op")
