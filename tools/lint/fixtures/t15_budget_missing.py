"""T15 fixture: module owns a compile site (stored jit) but declares no
signature budget at all."""
import jax


class Undeclared:
    def __init__(self, fn):
        self._fn = jax.jit(fn)    # T15 error: no __compile_signatures__

    def run(self, x):
        return self._fn(x)
