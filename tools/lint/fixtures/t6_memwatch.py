"""Fixture: memwatch/costs observability hooks in hot dispatch paths.

The PR-5 memory/cost hooks follow the telemetry discipline — one
module-global boolean, shape×itemsize arithmetic, never a device sync —
and sit directly in dispatch code (apply_op's deferred path, CachedOp
run, the trainer's fused update).  The analyzer must (a) not flag
``_mw.track``/``_mw.donated``/``_costs.note`` calls in host-side hot
code, (b) not propagate hotness into a same-module ledger helper, while
(c) still flagging a real host sync next to them.
"""
import time

import jax
import numpy as np

from mxnet_tpu.telemetry import costs as _costs
from mxnet_tpu.telemetry import memwatch as _mw

_LEDGER = {}


def track(raw, owner=None):
    # same-module ledger helper: the perf_counter read (entry age
    # stamping) is host-side by design — hotness must NOT leak in
    # through the bare-name call in dispatch() below
    _LEDGER[id(raw)] = (owner, time.perf_counter())


def dispatch(fn, w_raws, g_raws, key):
    if _mw._enabled:
        _mw.track(w_raws[0])               # ok: memwatch hook, exempted
        track(w_raws[0], owner="fixture")  # ok: recording helper
    if _costs._enabled:
        _costs.note("fixture", key, fn, (w_raws, g_raws))  # ok
    out = fn(w_raws, g_raws)
    if _mw._enabled:
        _mw.donated(w_raws)                # ok: donation release hook
    return out


dispatch_jit = jax.jit(dispatch, static_argnums=(0, 3))


def bad_synced_dispatch(fn, w_raws):
    if _mw._enabled:
        _mw.track(w_raws[0])
    host = np.asarray(w_raws[0])  # T1 error: sync in dispatch hot path
    return fn(w_raws), host


bad_synced_dispatch_jit = jax.jit(bad_synced_dispatch, static_argnums=0)
