"""T8 fixture: partition-rule tables with static hazards.

Never imported — analyzed as source only (the mxnet_tpu import below
resolves nothing at lint time)."""
from mxnet_tpu import gluon, parallel
from mxnet_tpu.parallel import PartitionRules, place_params

# T8 error: pattern cannot compile — the rule can never match
BROKEN = PartitionRules((
    (r"(q|k|v_weight$", ("tp", None)),
    (r".*", ()),
))

# T8 error: rules after the catch-all are dead under first-match-wins
SHADOWED = PartitionRules((
    (r".*", ()),
    (r"(^|[._])q_weight$", ("tp", None)),
))

# T8 error: duplicate pattern — the second copy never fires
DUPLICATE = PartitionRules((
    (r"(^|[._])q_weight$", ("tp", None)),
    (r"(^|[._])q_weight$", (None, "tp")),
    (r".*", ()),
))

# T8 warning: tp specs but no terminal catch-all and no on_unmatched=
# policy — every unmatched parameter silently replicates
SILENT_TABLE = (
    (r"(^|[._])(q|k|v)_weight$", ("tp", None)),
    (r"(^|[._])o_weight$", (None, "tp")),
)


def silent_replicate_trainer(net, mesh):
    return gluon.Trainer(net.collect_params(), "sgd",
                         partition_rules=SILENT_TABLE, mesh=mesh)


# ok: terminal catch-all makes the replicate fallback explicit
GOOD = PartitionRules((
    (r"(^|[._])(q|k|v)_weight$", ("tp", None)),
    (r".*", ()),
))


def good_explicit_policy(params, mesh):
    # ok: no catch-all, but the silent fallback is disabled outright
    return place_params(params, (
        (r"(^|[._])(q|k|v)_weight$", ("tp", None)),
    ), mesh=mesh, on_unmatched="error")
