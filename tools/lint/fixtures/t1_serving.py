"""T1 fixture: serving's designated materialization def vs stray syncs.

The serving scheduler materializes a whole dispatched batch at ONE
demux point — a def named ``_materialize`` (``MATERIALIZE_DEFS``).
Sync methods inside that def are sanctioned (no eager warning); the
same calls anywhere else in serving glue still warn, and inside a
traced region they are errors regardless of the def's name.
"""
import jax


def _materialize(arrays):
    out = []
    for a in arrays:
        out.append(a.asnumpy())       # fine: THE designated sync point
    return out


def scheduler_demux(outs, reqs):
    host = _materialize(outs)         # fine: sanctioned helper call
    for r, h in zip(reqs, host):
        r.future.set_result(h)


def leaky_sync(out):
    return out.asnumpy()              # T1 warning: sync outside the
                                      # designated materialization def


def bad_traced_materialize(w, x):
    y = w * x
    return y.asnumpy()                # T1 error: sync inside a trace


def _hot_materialize(arrays):
    # the exemption covers EAGER warnings only: any traced sync is an
    # error no matter how materialize-ish the def's name is
    first = arrays[0]
    return first.asnumpy()            # T1 error: traced sync


bad_traced_jit = jax.jit(bad_traced_materialize)
hot_materialize_jit = jax.jit(_hot_materialize)
