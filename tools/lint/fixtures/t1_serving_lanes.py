"""T1 fixture: the disaggregated lanes' materialization def.

serving/lanes.py syncs at ``_lane_materialize`` — the prefill→decode
handoff (first tokens) and the decode tick drain — mirroring the
scheduler's ``_materialize``.  Both names are in ``MATERIALIZE_DEFS``:
eager syncs inside them are sanctioned, syncs anywhere else in the
lanes still warn, and a traced sync is an error no matter the name.
"""
import jax


def _lane_materialize(arrays):
    out = []
    for a in arrays:
        out.append(a.asnumpy())       # fine: the lanes' designated sync
    return out


def decode_drain(engine, seqs):
    toks = _lane_materialize([engine.last_tokens])  # fine: helper call
    for slot, (req, tokens) in seqs.items():
        tokens.append(int(toks[0][slot]))


def leaky_lane_sync(toks):
    return toks.asnumpy()             # T1 warning: sync outside the
                                      # designated lane materialize def


def _hot_lane_materialize(pool):
    # the exemption is eager-only: a traced sync is an error even
    # inside a def named like the sanctioned one
    return pool.asnumpy()             # T1 error: traced sync


hot_lane_jit = jax.jit(_hot_lane_materialize)
