"""T2 fixture: python control flow on traced values in traced regions."""
import jax


class BadBlock:
    def hybrid_forward(self, F, x):
        if x > 0:                     # T2 error: branch on traced value
            return x
        return -x


def bad_loss(w, target):
    while w < target:                 # T2 error: while on traced value
        w = w * 2
    assert w > 0                      # T2 error: assert on traced value
    return w


bad_loss_jit = jax.jit(bad_loss)


class GoodBlock:
    def hybrid_forward(self, F, x, axis=0):
        if axis is None:              # ok: identity check on config param
            return x
        if len(x.shape) == 2:         # ok: static shape metadata
            return x
        return x
