"""T14 fixture: compile-site discipline — fresh callables per call or
per loop iteration (guaranteed cache misses)."""
# mxlint: signatures=1 per helper (keeps T15 out of this T14 fixture)
import jax


def per_call_jit(fn, x):
    return jax.jit(fn)(x)             # T14 error: construct-and-discard


def per_item_grid(fns, xs):
    out = []
    for f, x in zip(fns, xs):
        step = jax.jit(f)             # T14 error: fresh callable per
        out.append(step(x))           # iteration = compile miss per item
    return out


def _build_grid(fns):
    compiled = []
    for f in fns:
        compiled.append(jax.jit(f))   # ok: sanctioned one-time build def
    return compiled


class Stack:
    def __init__(self, blocks):
        self._blocks = blocks
        for b in blocks:
            b.hybridize()             # ok: __init__ builds the grid once

    def rewrap(self):
        for b in self._blocks:
            b.hybridize()             # T14 error: re-hybridize per call

    def warm_modes(self, modes):
        for m in modes:
            self._blocks[0].hybridize(remat=m)   # ok: warm* is exempt
