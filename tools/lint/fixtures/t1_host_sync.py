"""T1 fixture: host syncs inside traced regions (and one eager warning)."""
import jax
import numpy as np


class BadBlock:
    def hybrid_forward(self, F, x):
        host = x.asnumpy()            # T1 error: sync inside hybrid_forward
        return host.sum()


def bad_step(params, batch):
    loss = params * batch
    print(float(loss))                # T1 error: float() on traced value
    return loss


bad_step_jit = jax.jit(bad_step)


def bad_scan_body(carry, x):
    y = carry + x
    np.asarray(y)                     # T1 error: concretizes the tracer
    return y, y


def fused(xs):
    return jax.lax.scan(bad_scan_body, 0.0, xs)


def eager_glue(arr):
    return arr.asnumpy()              # T1 warning: blocking fetch, eager


def suppressed_sync(params):
    def inner(p):
        v = p.asnumpy()  # mxlint: disable=T1
        return v

    return jax.jit(inner)(params)
