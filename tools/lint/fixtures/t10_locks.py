"""T10 fixture: shared state accessed bare where it is lock-guarded
elsewhere in the same module (guard-consistency)."""
import threading


class Ledger:
    """Mixes locked and bare access to the same attribute."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._entries["seed"] = 0     # ok: __init__ is exempt

    def record(self, k, v):
        with self._lock:
            self._entries[k] = v      # locked write: establishes guard

    def total(self):
        with self._lock:
            return sum(self._entries.values())

    def drop(self, k):
        self._entries.pop(k, None)    # T10 error: bare write

    def peek(self, k):
        return self._entries.get(k)   # T10 warning: bare read

    def drain_locked(self):
        self._entries.clear()         # ok: _locked suffix = caller holds it

    def start(self):
        t = threading.Thread(target=self.record, args=("x", 1),
                             name="mxt-ledger")
        t.daemon = True
        t.start()
        t.join()


_CACHE = {}
_CACHE_LOCK = threading.Lock()


def cache_put(k, v):
    with _CACHE_LOCK:
        _CACHE[k] = v                 # locked write: establishes guard


def cache_del(k):
    del _CACHE[k]                     # T10 error: bare module-global write


def spawn():
    t = threading.Thread(target=cache_put, args=(1, 2), name="mxt-cache")
    t.daemon = True
    t.start()
    t.join()


class Unthreaded:
    """Same shape but the module would be clean without the Thread use
    above — kept here to pin that T10 only fires in threaded modules."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1
