"""T1 fixture: engine.flush() is a bulk-segment sync site in traced regions."""
import jax

from mxnet_tpu import engine


def eager_boundary(a, b):
    c = a + b
    engine.flush()                    # fine: eager glue, explicit boundary
    return c


def bad_jitted_step(params, batch):
    loss = params * batch
    engine.flush()                    # T1 error: sync site inside a trace
    return loss


bad_jitted_step_jit = jax.jit(bad_jitted_step)
