"""T12 fixture: thread lifecycle — unnamed threads, unjoined
non-daemon threads, worker loops with no exception capture."""
import threading


def tick():
    return 1


def spin():
    while True:                       # loop body for the silent-worker case
        tick()


def guarded_spin():
    try:
        while True:
            tick()
    except Exception:
        raise


def unnamed():
    t = threading.Thread(target=tick)     # T12 warning: no name=
    t.daemon = True
    t.start()
    t.join()


def unjoined():
    # T12 error: non-daemon, never joined anywhere in this module
    t2 = threading.Thread(target=tick, name="mxt-leak")
    t2.start()


def silent_worker():
    # T12 warning: worker loops forever with no exception capture
    t3 = threading.Thread(target=spin, name="mxt-spin", daemon=True)
    t3.start()


def good_worker():
    t4 = threading.Thread(target=guarded_spin, name="mxt-good",
                          daemon=True)   # ok: named, daemon, try/except
    t4.start()


def good_joined():
    t5 = threading.Thread(target=tick, name="mxt-join")
    t5.start()
    t5.join()                         # ok: named and joined
