"""T6 fixture: numerics stat taps in traced training hot paths.

The r17 numerics tier bakes per-tensor stat bundles (l2/maxabs/mean/
nan/inf) into the step compile as side outputs — pure jnp math, no
``jax.debug``, no host transfer on any tap path.  The analyzer must
(a) not flag ``numerics.*`` / ``_numerics.*`` taps inside jitted step
bodies, (b) not let hotness leak into a same-module tap helper through
its bare-name call, (c) leave the tier's ``_materialize`` def's
intentional stride-boundary device_get unflagged (MATERIALIZE_DEFS),
while (d) still flagging a real host sync smuggled into a traced
region next to a tap.
"""
import jax
import numpy as np

from mxnet_tpu.telemetry import numerics
from mxnet_tpu.telemetry import numerics as _numerics


def _tap_activations(name, x):
    # same-module tap helper: pure device-scalar stat math routed to the
    # active trace collector — hotness must NOT leak in through the
    # bare-name call in traced_step below
    _numerics.tap(name, x)
    return x


def traced_step(params, batch):
    h = batch @ params["w"]
    _tap_activations("hidden", h)                 # ok: helper
    numerics.tap("hidden_direct", h)              # ok: numerics.*
    st = _numerics.stats_of(h)                    # ok: pure jnp math
    _numerics.record_compiled(("hidden",), (st,))  # ok: queues scalars
    return h.sum()


traced_step_jit = jax.jit(traced_step)


def _materialize(entries):
    # the tier's ONE host sync: stride-gated fetch of every pending
    # device stat in a single transfer — MATERIALIZE_DEFS exempts the
    # T1 eager warning here
    return [e[1].asnumpy() for e in entries]


def bad_stat_tick(params, batch):
    h = batch @ params["w"]
    numerics.tap("hidden", h)
    host = np.asarray(h)            # T1 error: sync in the traced step
    return host.sum()


bad_stat_tick_jit = jax.jit(bad_stat_tick)
