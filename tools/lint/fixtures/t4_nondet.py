"""T4 fixture: host nondeterminism baked into traced regions."""
import random
import time

import jax
import numpy as np


def bad_dropout(x):
    mask = np.random.rand(*x.shape)   # T4 error: trace-time constant mask
    return x * (mask > 0.5)


bad_dropout_jit = jax.jit(bad_dropout)


class NoisyBlock:
    def hybrid_forward(self, F, x):
        jitter = random.random()      # T4 error: stdlib random in trace
        stamp = time.time()           # T4 error: wall clock in trace
        return x + jitter + stamp


def good_dropout(x, key):
    mask = jax.random.bernoulli(key, 0.5, x.shape)  # ok: keyed PRNG
    return x * mask


good_dropout_jit = jax.jit(good_dropout)


def eager_logger(msg):
    return f"{time.time()} {msg}"     # ok: host code, not traced
