"""T14c fixture (path carries ``serving``): a public entry point that
dispatches a jit-bound callable on caller-shaped input in a module where
nothing bounds the signature grid — an unbounded signature space."""
import jax

__compile_signatures__ = {}


class MiniEngine:
    def __init__(self, fn):
        self._step = jax.jit(fn)

    def generate(self, prompts):
        return self._step(prompts)    # T14 warning: caller-shaped input,
        # nothing bounds the (batch, len) grid in this module

    def _drain(self, prompts):
        return self._step(prompts)    # ok: private helper — the public
        # surface is where the budget is enforced
