"""Reconstruct and render per-request span trees from trace records.

The serving tracer (``mxnet_tpu.telemetry.tracing``) emits ONE record
per completed request — ``{"record": "trace", "trace_id": ..., "spans":
[...]}`` — into the telemetry JSONL stream, and keeps a bounded ring of
the most recent ones that the flight recorder dumps on incidents
(overload rejection, replica exception, OOM).  This tool joins both
sources back into something a human (or Perfetto) can read:

    # list every trace in a stream / flight dump
    python tools/trace_report.py telemetry.jsonl --list

    # one request's span tree, ASCII
    python tools/trace_report.py telemetry.jsonl --trace-id 3f2a-000007

    # ... selected by request id instead
    python tools/trace_report.py flight_record_1234.json --request-id 42

    # chrome://tracing / Perfetto JSON for every selected trace
    python tools/trace_report.py telemetry.jsonl --format chrome \
        --out trace.json

Input may be a telemetry JSONL stream (any mix of records; only
``record == "trace"`` lines are used) or a flight-recorder dump
(``{"record": "flight_recorder", "traces": [...]}``).  The functions
(`load_traces`, `build_tree`, `render_tree`, `chrome_trace`) are
importable for tests and notebooks.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_traces(path):
    """Every trace record in ``path`` — a telemetry JSONL stream or a
    flight-recorder dump — in file order."""
    with open(path, "r", encoding="utf-8") as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            # maybe a single JSON document (flight dump); a JSONL
            # stream of dicts also starts with "{" so fall back on
            # parse failure
            try:
                doc = json.load(f)
            except json.JSONDecodeError:
                f.seek(0)
            else:
                if doc.get("record") == "flight_recorder":
                    return list(doc.get("traces", []))
                return [doc] if doc.get("record") == "trace" else []
        out = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("record") == "trace":
                out.append(rec)
    return out


def select(traces, trace_id=None, request_id=None):
    """Filter by trace id and/or request id (None = keep all)."""
    out = traces
    if trace_id is not None:
        out = [t for t in out if t.get("trace_id") == trace_id]
    if request_id is not None:
        out = [t for t in out if t.get("request_id") == int(request_id)]
    return out


def build_tree(trace):
    """The span forest of one trace record: a list of root nodes, each
    ``{"span": <span dict>, "children": [...]}`` ordered by start
    time.  Orphans (parent id never emitted — a lane died mid-request)
    surface as extra roots rather than vanishing."""
    spans = trace.get("spans", [])
    nodes = {s["id"]: {"span": s, "children": []} for s in spans}
    roots = []
    for s in sorted(spans, key=lambda s: (s.get("ts", 0.0), s["id"])):
        parent = s.get("parent")
        node = nodes[s["id"]]
        if parent is not None and parent in nodes and parent != s["id"]:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)
    return roots


def _fmt_tags(span):
    tags = dict(span.get("tags") or {})
    thread = span.get("thread")
    if thread:
        tags["thread"] = thread
    if not tags:
        return ""
    body = ", ".join(f"{k}={tags[k]}" for k in sorted(tags))
    return f"  [{body}]"


def render_tree(trace, out=None):
    """ASCII span tree, times relative to the trace's t0."""
    lines = []
    t0 = trace.get("t0", 0.0)
    header = (f"trace {trace.get('trace_id')}  "
              f"request={trace.get('request_id')}  "
              f"status={trace.get('status')}  "
              f"total={trace.get('total_ms', 0.0):.3f}ms")
    if trace.get("tenant") is not None:
        header += f"  tenant={trace['tenant']}"
    lines.append(header)

    def walk(node, prefix, last):
        s = node["span"]
        rel_ms = (s.get("ts", t0) - t0) * 1e3
        stem = "" if prefix is None else prefix + ("`-- " if last
                                                   else "|-- ")
        lines.append(f"{stem}{s['name']}  +{rel_ms:.3f}ms "
                     f"({s.get('dur_ms', 0.0):.3f}ms){_fmt_tags(s)}")
        kids = node["children"]
        child_prefix = "" if prefix is None else \
            prefix + ("    " if last else "|   ")
        for i, kid in enumerate(kids):
            walk(kid, child_prefix, i == len(kids) - 1)

    for i, root in enumerate(build_tree(trace)):
        walk(root, None, i == len(build_tree(trace)) - 1)
    text = "\n".join(lines)
    if out is not None:
        out.write(text + "\n")
    return text


def chrome_trace(traces):
    """chrome://tracing / Perfetto "trace event" JSON for the selected
    traces: one pid per trace, one tid per originating thread, complete
    ("X") events with microsecond timestamps relative to each trace's
    t0."""
    events = []
    for pid, trace in enumerate(traces, start=1):
        t0 = trace.get("t0", 0.0)
        tids = {}
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"trace "
                                f"{trace.get('trace_id')} req "
                                f"{trace.get('request_id')}"}})
        for s in trace.get("spans", []):
            thread = s.get("thread") or "main"
            if thread not in tids:
                tids[thread] = len(tids) + 1
                events.append({"ph": "M", "pid": pid,
                               "tid": tids[thread],
                               "name": "thread_name",
                               "args": {"name": thread}})
            args = dict(s.get("tags") or {})
            args["trace_id"] = trace.get("trace_id")
            args["request_id"] = trace.get("request_id")
            events.append({
                "ph": "X", "cat": "trace", "name": s["name"],
                "pid": pid, "tid": tids[thread],
                "ts": (s.get("ts", t0) - t0) * 1e6,
                "dur": s.get("dur_ms", 0.0) * 1e3,
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render serving request traces from a telemetry "
        "JSONL stream or a flight-recorder dump")
    ap.add_argument("paths", nargs="+", metavar="path",
                    help="telemetry JSONL stream(s), a glob (with "
                    "--merge), or a flight dump JSON")
    ap.add_argument("--merge", action="store_true",
                    help="merge multiple per-rank JSONL streams by "
                    "(step, rank) via telemetry.read_jsonl before "
                    "selecting traces (implied by >1 path)")
    ap.add_argument("--trace-id", default=None,
                    help="render only this trace id")
    ap.add_argument("--request-id", default=None, type=int,
                    help="render only this request id")
    ap.add_argument("--list", action="store_true",
                    help="one summary line per trace, no tree")
    ap.add_argument("--format", choices=("tree", "chrome"),
                    default="tree")
    ap.add_argument("--out", default=None,
                    help="write here instead of stdout")
    args = ap.parse_args(argv)

    if args.merge or len(args.paths) > 1:
        # multi-stream mode rides the merged reader (glob-aware); the
        # single-path default stays dependency-free
        from mxnet_tpu.telemetry.sinks import read_jsonl

        merged = read_jsonl(args.paths if len(args.paths) > 1
                            else args.paths[0])
        traces = [r for r in merged if isinstance(r, dict) and
                  r.get("record") == "trace"]
    else:
        traces = load_traces(args.paths[0])
    traces = select(traces, trace_id=args.trace_id,
                    request_id=args.request_id)
    if not traces:
        print("no matching trace records", file=sys.stderr)
        return 1
    sink = open(args.out, "w", encoding="utf-8") if args.out \
        else sys.stdout
    try:
        if args.list:
            for t in traces:
                print(f"{t.get('trace_id')}  request="
                      f"{t.get('request_id')}  "
                      f"status={t.get('status')}  "
                      f"spans={len(t.get('spans', []))}  "
                      f"total={t.get('total_ms', 0.0):.3f}ms",
                      file=sink)
        elif args.format == "chrome":
            json.dump(chrome_trace(traces), sink, indent=1)
            sink.write("\n")
        else:
            for t in traces:
                render_tree(t, out=sink)
                print(file=sink)
    finally:
        if sink is not sys.stdout:
            sink.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
