"""Seeded deterministic-interleaving harness for concurrency tests.

``tools/lint`` rules T10-T12 prove lock discipline statically and the
runtime lock sanitizer (``mxnet_tpu.sanitizer``, ``MXNET_SANITIZE_LOCKS``)
observes the real acquisition order — this module closes the loop by
*driving* a chosen interleaving, so a racy handoff can be replayed
bit-identically from a seed instead of hoping the OS scheduler
cooperates.

Model (cooperative serialization):

* A :class:`Harness` owns a set of *managed* threads (``spawn``) and a
  single ``random.Random(seed)``.  At most ONE managed thread runs at a
  time; every other managed thread is parked inside :func:`point`.
* The scheduler loop (``run``) waits until every managed thread is
  parked or done, then grants one of the *ready* parked threads chosen
  by the seeded rng over their sorted names.  Same seed -> same grant
  sequence -> the recorded trace replays bit-identically.
* Lock boundaries park automatically: ``run`` installs the sanitizer's
  trace hook, so a managed thread acquiring a ``wrap_lock``-wrapped lock
  parks at ``lock:<name>`` first.  A thread parked on a lock owned by
  another managed thread is not ready — and if NO thread is ready while
  some are still parked, the harness raises :class:`DeadlockError` with
  the park labels and lock owners (a deadlock witnessed, not guessed).
* Foreign threads (a pool worker, an async writer) can join the managed
  set for a scoped region via ``with managed("writer"):`` — pair every
  adoption with an autonomous completion signal (an Event the driving
  thread waits on) so the managed-set composition at each grant decision
  stays schedule-independent.

Rules for test authors:

* A managed thread may make a *real* blocking call only if it unblocks
  autonomously (a foreign thread finishes the work) — never when the
  unblocking requires granting another managed thread; park instead,
  or wrap the call in ``with external("label"):`` — the thread leaves
  the scheduled set for the scope (the scheduler keeps granting others,
  and waits rather than declaring deadlock while an external call is in
  flight) and re-parks at ``external:<label>`` on exit.
* Put a ``point("label")`` between the steps whose interleavings you
  want explored; vary the seed to explore, pin the seed to regress.
* ``Harness(seed, park_locks=False)`` disables parking at sanitizer
  lock boundaries: use it when unmanaged threads take package locks on
  racy paths (their acquisition COUNT would leak into the trace);
  determinism then rests on explicit ``point()`` placement alone.

``python -m tools.race --report`` runs the built-in scenarios twice and
emits a JSON report asserting bit-identical replay (wired into
tests/test_bench_smoke.py).  Stdlib-only; the sanitizer import is lazy.
"""
from __future__ import annotations

import contextlib
import json
import random
import threading
import time

__all__ = ["Harness", "DeadlockError", "point", "managed", "external",
           "active"]

#: the currently-running harness (one at a time per process)
_ACTIVE = None


class DeadlockError(RuntimeError):
    """No managed thread is ready: every one is parked on a lock owned
    by another parked thread (or waiting forever)."""


def active():
    """The running :class:`Harness`, or None."""
    return _ACTIVE


def point(label):
    """Interleaving point: park the calling managed thread until the
    scheduler grants it.  No-op (one global read) outside a harness or
    on an unmanaged thread — safe to leave in production-adjacent test
    helpers."""
    h = _ACTIVE
    if h is not None:
        h.point(label)


@contextlib.contextmanager
def managed(name):
    """Adopt the calling *foreign* thread into the active harness for
    the scope, as ``name``; release it on exit.  No-op without an
    active harness."""
    h = _ACTIVE
    if h is None:
        yield
        return
    h._adopt(name)
    try:
        yield
    finally:
        h._resign(name)


@contextlib.contextmanager
def external(label):
    """Mark the calling managed thread as *externally blocked* for the
    scope: the scheduler treats it as settled but never grants it, so a
    real blocking call whose unblocking needs OTHER managed threads to
    be granted (a backpressured save, a join) can sit inside.  On exit
    the thread re-parks at ``external:<label>``.  No-op without an
    active harness or on an unmanaged thread."""
    h = _ACTIVE
    name = getattr(h._local, "name", None) if h is not None else None
    if name is None:
        yield
        return
    with h._cv:
        h._external[name] = label
        h._state[name] = "external"
        h._labels[name] = "external:" + label
        h._cv.notify_all()
    try:
        yield
    finally:
        with h._cv:
            h._external.pop(name, None)
        h.point("external:" + label)


class Harness:
    def __init__(self, seed=0, park_locks=True):
        self.seed = int(seed)
        self.park_locks = bool(park_locks)
        self.rng = random.Random(self.seed)
        #: the replay artifact: ("grant"|"acquired"|"released"|"done",
        #: thread name, label) — appended only by the single running
        #: thread / the scheduler, so identical grant sequences produce
        #: identical traces
        self.trace = []
        self._cv = threading.Condition()
        self._threads = {}          # name -> Thread (spawned only)
        self._state = {}            # name -> running|parked|done
        self._labels = {}           # name -> current park label
        self._grant = None
        self._external = {}         # name -> label while in external()
        self._owners = {}           # sanitizer lock name -> (name, depth)
        self._failures = {}         # name -> exception
        self._local = threading.local()

    # -- building -------------------------------------------------------------
    def spawn(self, name, fn, *args, **kwargs):
        """Register a managed thread; started by :meth:`run`."""
        if name in self._threads:
            raise ValueError(f"duplicate managed thread {name!r}")
        t = threading.Thread(target=self._main, name=f"mxt-race-{name}",
                             args=(name, fn, args, kwargs), daemon=True)
        self._threads[name] = t
        self._state[name] = "running"
        return self

    def _main(self, name, fn, args, kwargs):
        self._local.name = name
        self.point("start")
        try:
            fn(*args, **kwargs)
        except BaseException as e:   # re-raised from run()
            self._failures[name] = e
        finally:
            with self._cv:
                self._state[name] = "done"
                self.trace.append(("done", name, ""))
                self._cv.notify_all()

    # -- managed-thread side --------------------------------------------------
    def point(self, label):
        name = getattr(self._local, "name", None)
        if name is None:
            return
        with self._cv:
            self._state[name] = "parked"
            self._labels[name] = label
            self._cv.notify_all()
            while self._grant != name:
                self._cv.wait()
            self._grant = None
            # a lock park inside an external() scope resumes external
            self._state[name] = ("external" if name in self._external
                                 else "running")
            # the grant, not the park, is the trace event: parks can
            # race during startup, grants are scheduler-serialized
            self.trace.append(("grant", name, label))
            self._cv.notify_all()

    def _adopt(self, name):
        self._local.name = name
        with self._cv:
            if name in self._state and self._state[name] != "done":
                raise ValueError(f"managed name {name!r} already live")
            self._state[name] = "running"
            self._cv.notify_all()

    def _resign(self, name):
        self._local.name = None
        with self._cv:
            self._state.pop(name, None)
            self._labels.pop(name, None)
            self._external.pop(name, None)
            self._cv.notify_all()

    # -- sanitizer integration ------------------------------------------------
    def _hook(self, event, lockname):
        name = getattr(self._local, "name", None)
        if name is None:
            return                   # foreign thread: not scheduled
        if event == "acquire":
            self.point("lock:" + lockname)
        elif event == "acquired":
            with self._cv:
                owner, depth = self._owners.get(lockname, (name, 0))
                self._owners[lockname] = (name, depth + 1)
                self.trace.append(("acquired", name, lockname))
        elif event == "released":
            with self._cv:
                owner, depth = self._owners.get(lockname, (name, 1))
                if depth <= 1:
                    self._owners.pop(lockname, None)
                else:
                    self._owners[lockname] = (owner, depth - 1)
                self.trace.append(("released", name, lockname))

    # -- scheduler ------------------------------------------------------------
    def _settled(self):
        return all(s in ("parked", "done", "external")
                   for s in self._state.values())

    def _ready(self):
        out = []
        for name, state in sorted(self._state.items()):
            if state != "parked":
                continue
            label = self._labels.get(name, "")
            if label.startswith("lock:"):
                owner = self._owners.get(label[5:])
                if owner is not None and owner[0] != name:
                    continue         # lock held by another managed thread
            out.append(name)
        return out

    def _diagnose(self):
        parked = {n: self._labels.get(n, "?")
                  for n, s in sorted(self._state.items()) if s == "parked"}
        owners = {ln: o[0] for ln, o in sorted(self._owners.items())}
        return f"parked={parked} lock_owners={owners} seed={self.seed}"

    def run(self, timeout=60.0):
        """Start every spawned thread and drive the seeded schedule to
        completion.  Returns the trace; raises DeadlockError on a
        witnessed deadlock and re-raises the first managed-thread
        exception otherwise."""
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a race harness is already active")
        _ACTIVE = self
        prev_hook = prev_enabled = None
        san = None
        if self.park_locks:
            try:
                from mxnet_tpu import sanitizer as san   # noqa: F811
            except Exception:
                pass                 # stdlib-only mode: no lock parking
        deadline = time.monotonic() + timeout
        try:
            if san is not None:
                prev_enabled = san.locks_enabled()
                san.enable_locks()
                prev_hook = san.set_trace_hook(self._hook)
            for name in sorted(self._threads):
                self._threads[name].start()
            with self._cv:
                while True:
                    while not self._settled():
                        if not self._cv.wait(0.2) \
                                and time.monotonic() > deadline:
                            raise DeadlockError(
                                "harness timeout (a managed thread is "
                                "blocked outside a park point): "
                                + self._diagnose())
                    live = [n for n, s in self._state.items()
                            if s != "done"]
                    if not live:
                        break
                    ready = self._ready()
                    if not ready and any(
                            s == "external"
                            for s in self._state.values()):
                        # an external call is in flight: wait for it to
                        # return (and re-park) instead of declaring
                        # deadlock — its unblocking is autonomous once
                        # every grantable thread has run
                        if not self._cv.wait(0.2) \
                                and time.monotonic() > deadline:
                            raise DeadlockError(
                                "external call never returned: "
                                + self._diagnose())
                        continue
                    if not ready:
                        self.trace.append(
                            ("deadlock", "", ",".join(sorted(live))))
                        raise DeadlockError(
                            "all managed threads parked, none ready: "
                            + self._diagnose())
                    pick = ready[self.rng.randrange(len(ready))]
                    self._grant = pick
                    self._cv.notify_all()
                    while self._grant is not None:
                        if not self._cv.wait(0.2) \
                                and time.monotonic() > deadline:
                            raise DeadlockError(
                                "granted thread never resumed: "
                                + self._diagnose())
        finally:
            if san is not None:
                san.set_trace_hook(prev_hook)
                if not prev_enabled:
                    san.disable_locks()
            _ACTIVE = None
        for name in sorted(self._failures):
            raise self._failures[name]
        return self.trace


# ---------------------------------------------------------------------------
# Built-in scenarios (--report): the harness's own regression surface
# ---------------------------------------------------------------------------

def _scenario_points(seed):
    """Three workers interleaving three labelled steps each."""
    h = Harness(seed)
    log = []

    def worker(me):
        for step in ("load", "compute", "store"):
            h.point(step)
            log.append(f"{me}.{step}")

    for w in ("w1", "w2", "w3"):
        h.spawn(w, worker, w)
    trace = h.run()
    return trace, log


def _scenario_locks(seed):
    """Two threads taking two sanitizer-wrapped locks in a consistent
    order: schedules vary with the seed, the order graph stays acyclic."""
    from mxnet_tpu import sanitizer as san

    h = Harness(seed)
    a = san.wrap_lock(threading.Lock(), "race.demo.A")
    b = san.wrap_lock(threading.Lock(), "race.demo.B")
    shared = []

    def worker(me):
        with a:
            h.point("mid")
            with b:
                shared.append(me)

    h.spawn("t1", worker, "t1")
    h.spawn("t2", worker, "t2")
    trace = h.run()
    return trace, shared


def _scenario_deadlock(seed):
    """Opposite lock orders: returns True when the harness *witnessed*
    the deadlock for this seed (both threads parked on the other's
    lock), False when the schedule dodged it."""
    from mxnet_tpu import sanitizer as san

    h = Harness(seed)
    a = san.wrap_lock(threading.Lock(), "race.dl.A")
    b = san.wrap_lock(threading.Lock(), "race.dl.B")

    def fwd():
        with a:
            h.point("mid")
            with b:
                pass

    def bwd():
        with b:
            h.point("mid")
            with a:
                pass

    h.spawn("fwd", fwd)
    h.spawn("bwd", bwd)
    try:
        h.run(timeout=20.0)
        return False
    except DeadlockError:
        return True


def _trace_key(trace):
    return json.dumps(trace, separators=(",", ":"))


def _report(seed):
    from mxnet_tpu import sanitizer as san

    report = {"seed": seed, "scenarios": [], "ok": True}

    t1, log1 = _scenario_points(seed)
    t2, log2 = _scenario_points(seed)
    t3, _ = _scenario_points(seed + 1)
    report["scenarios"].append({
        "name": "points",
        "events": len(t1),
        "replay_identical": _trace_key(t1) == _trace_key(t2)
                            and log1 == log2,
        "seed_changes_schedule": _trace_key(t1) != _trace_key(t3),
    })

    san.reset_locks()
    l1, s1 = _scenario_locks(seed)
    l2, s2 = _scenario_locks(seed)
    report["scenarios"].append({
        "name": "locks",
        "events": len(l1),
        "replay_identical": _trace_key(l1) == _trace_key(l2)
                            and s1 == s2,
        "order_violations": san.lock_order_violations(),
    })

    san.reset_locks()
    witnessed = None
    for s in range(16):
        if _scenario_deadlock(s):
            witnessed = s
            break
    report["scenarios"].append({
        "name": "deadlock",
        "witnessed_at_seed": witnessed,
        "replay_identical": witnessed is not None
                            and _scenario_deadlock(witnessed),
        "runtime_cycle_detected":
            bool(san.lock_order_violations()) or witnessed is not None,
    })
    san.reset_locks()

    report["ok"] = all(sc.get("replay_identical") for sc in
                       report["scenarios"]) \
        and not report["scenarios"][1]["order_violations"]
    return report


def main(argv=None):
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m tools.race",
        description="seeded deterministic-interleaving harness; --report "
                    "runs the built-in scenarios twice and checks "
                    "bit-identical replay")
    ap.add_argument("--report", action="store_true",
                    help="emit the JSON self-check report")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if not args.report:
        ap.error("nothing to do (pass --report)")
    report = _report(args.seed)
    json.dump(report, sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
