"""Benchmark: BASELINE.md tracked metrics on one chip.

Default run measures BOTH tracked training metrics back to back —
ResNet-50 v1 images/sec/chip, then BERT-base (seq 128) samples/sec/chip
— and prints ONE JSON line.  Schema keeps ``metric``/``value`` as the
ResNet number (driver compatibility); the BERT number rides alongside as
``bert_base_samples_per_sec_per_chip``.

Measurement protocol (BASELINE.md): synthetic data, hybridized net under
``gluon.Trainer``, steady state after warmup (compile) steps, best of
``BENCH_REPEATS`` windows.  ``vs_baseline`` would be measured against
the reference's published number, which was unrecoverable (empty
reference mount — BASELINE.md); reported as ``null`` = no baseline
available (never 0.0, which would read as "exactly at baseline").

``BENCH_MODEL=bert_base`` runs ONLY the BERT workload (its own JSON
schema); ``BENCH_SKIP_BERT=1`` keeps the default run ResNet-only.
"""
from __future__ import annotations

import json
import os
import time


def main():
    # BENCH_PLATFORM=cpu forces the XLA CPU backend for local sanity runs
    # (the env-var route is pinned by the host sitecustomize; only the
    # pre-init config update wins)
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    else:
        # fail FAST and machine-readably when the accelerator backend is
        # down: in-process jax.devices() blocks for many minutes before
        # raising when the remote tunnel is dead (observed r4), and a
        # raw traceback leaves no JSON line for the driver to record
        import subprocess
        import sys

        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0])"],
                capture_output=True, text=True, timeout=240)
            ok = r.returncode == 0
            detail = (r.stdout or r.stderr).strip()[-200:]
        except subprocess.TimeoutExpired:
            ok, detail = False, "backend init timeout (240s)"
        if not ok:
            print(json.dumps({
                "metric": "resnet50_v1_train_images_per_sec_per_chip",
                "value": None,
                "unit": "images/sec/chip",
                "vs_baseline": None,
                "error": f"accelerator backend unavailable: {detail}",
            }))
            return

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    # 128 is the measured single-chip sweet spot for the ResNet leg
    # (r5 sweep: b64 2,261 / b128 2,513 / b256 2,398 img/s); the BERT
    # leg pins its own protocol batch below.  Disclosed in the JSON.
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    # ~2s of steady state: short runs are visibly jittery through the
    # remote-dispatch tunnel (r1 driver measured 13% below a local rerun
    # of the identical code; 100 steps brought repeat spread under ±4%)
    steps = int(os.environ.get("BENCH_STEPS", "100"))
    # BASELINE.md protocol: steady state = skip the first 20 steps
    warmup = int(os.environ.get("BENCH_WARMUP", "20"))
    image = int(os.environ.get("BENCH_IMAGE", "224"))
    model = os.environ.get("BENCH_MODEL", "resnet50_v1")
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")

    if model.startswith("bert"):
        # BERT's measured sweet spot is its protocol batch 64 (r5
        # sweep: b64 796 / b128 750 samp/s, b256 OOM — the workload is
        # HBM-bound, bigger batches don't help); an explicit
        # BENCH_BATCH still overrides for sweeps
        if "BENCH_BATCH" not in os.environ:
            batch = int(os.environ.get("BENCH_BERT_BATCH", "64"))
        ips, repeats, spe = _bench_bert(batch, steps, warmup, dtype,
                                        model)
        print(json.dumps({
            "metric": f"{model}_pretrain_samples_per_sec_per_chip",
            "value": round(ips, 2),
            "unit": "samples/sec/chip",
            "batch": batch,
            "aggregation": f"best_of_{repeats}_windows",
            "steps_per_execution": spe,
            "vs_baseline": None,
        }))
        return

    mx.random.seed(0)
    net = gluon.model_zoo.vision.get_model(model, classes=1000)
    net.initialize(mx.init.Xavier())
    # resolve deferred shapes on a tiny input: the resolve pass runs
    # imperatively (per-op dispatch), so keep it off the 224² hot path
    net(nd.ones((1, 3, 32, 32)))
    if dtype in ("bfloat16", "float16"):
        from mxnet_tpu import amp

        amp.init(target_dtype=dtype)
    # BENCH_REMAT=1: activation checkpointing (recompute fwd in bwd) —
    # trades FLOPs for activation memory.  Not needed at the default
    # b128 (the r5 sweep ran b128 AND b256 remat=0 on chip without
    # OOM; remat at b256 measured throughput-neutral)
    net.hybridize(static_alloc=True, static_shape=True,
                  remat=bool(int(os.environ.get("BENCH_REMAT", "0"))))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    # the reference protocol keeps the loss in the symbolic graph
    # (SoftmaxOutput); hybridizing the loss is the gluon equivalent and
    # removes ~5 eager dispatches per step (+11% measured)
    loss_fn.hybridize()

    x = mx.random.uniform(shape=(batch, 3, image, image))
    y = nd.array(np.random.randint(0, 1000, (batch,)))

    def eager_step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch)
        return loss

    step, spe = _maybe_fuse(
        eager_step, net, trainer,
        lambda n, xx, yy: loss_fn(n(xx), yy), (x, y), batch)

    last = None
    for _ in range((warmup + spe - 1) // spe):  # ceil: >= warmup steps
        last = step()
    if last is not None:
        _hard_sync(last)  # warmup fully done before any window starts

    ips, repeats = _best_window(step, batch * spe, max(1, steps // spe))
    record = {
        "metric": f"{model}_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "batch": batch,
        "aggregation": f"best_of_{repeats}_windows",
        # device-side step chaining (gluon.FusedTrainStep): K optimizer
        # steps per dispatch — chip throughput, not tunnel-dispatch rate
        "steps_per_execution": spe,
        # reference baseline unrecoverable (BASELINE.md): null = none
        "vs_baseline": None,
    }

    if not int(os.environ.get("BENCH_SKIP_DATA", "0")):
        # BASELINE protocol: "synthetic-data variant reported alongside
        # real-data to isolate input pipeline" — same net/trainer/loss,
        # but batches flow JPEG->decode->augment->HBM through
        # ImageRecordIter with thread prefetch (VERDICT r3 item 2)
        try:
            data_ips, data_note = _bench_resnet_recordio(
                net, trainer, loss_fn, batch, image,
                min(steps, int(os.environ.get("BENCH_DATA_STEPS", "20"))))
            record[f"{model}_recordio_images_per_sec_per_chip"] = \
                round(data_ips, 2)
            record[f"{model}_recordio_note"] = data_note
        except Exception as e:
            record["recordio_error"] = f"{type(e).__name__}: {e}"

    if not int(os.environ.get("BENCH_SKIP_BERT", "0")):
        # release the ResNet program + arrays before the BERT compile so
        # both workloads see the full HBM
        import gc

        del net, trainer, loss_fn, x, y, step
        gc.collect()
        try:
            # the tracked BERT metric is pinned to the BASELINE protocol
            # batch (64) regardless of BENCH_BATCH overrides aimed at
            # the ResNet leg (e.g. BENCH_BATCH=256)
            bert_batch = int(os.environ.get("BENCH_BERT_BATCH", "64"))
            bert_ips, _, bert_spe = _bench_bert(bert_batch, steps,
                                                warmup, dtype,
                                                "bert_base")
            record["bert_base_samples_per_sec_per_chip"] = \
                round(bert_ips, 2)
            record["bert_base_unit"] = "samples/sec/chip"
            record["bert_base_batch"] = bert_batch
            record["bert_base_steps_per_execution"] = bert_spe
        except Exception as e:  # keep the measured ResNet number
            record["bert_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(record))


def _bench_resnet_recordio(net, trainer, loss_fn, batch, image, steps):
    """Real-data leg: the SAME hybridized net + trainer step, fed from a
    synthetic-JPEG RecordIO file through ImageRecordIter (thread decode
    + prefetch, device-side normalize).  Returns (img/s, bottleneck
    note): on a many-core TPU-VM host the pipeline sustains the chip
    (benchmark/input_pipeline.py measures decode scaling); on a 1-core
    dev host the leg is decode-bound and says so instead of lying."""
    import os
    import tempfile
    import time

    from mxnet_tpu import autograd
    from mxnet_tpu.io import ImageRecordIter

    repeats = int(os.environ.get("BENCH_REPEATS", "3"))
    # size the file so EVERY window (plus warm step) fits in one epoch:
    # a mid-window it.reset() tears down and respawns the prefetch
    # thread, charging ~seconds of stall to "real-data throughput"
    n_imgs = (steps * repeats + 2) * batch
    rec = os.path.join(tempfile.gettempdir(),
                       f"mxt_bench_{image}_{n_imgs}.rec")
    if not os.path.exists(rec):
        import sys

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from benchmark.input_pipeline import make_recfile

        make_recfile(rec[:-4], n_imgs, image)
    threads = max(2, (os.cpu_count() or 2))
    it = ImageRecordIter(path_imgrec=rec,
                         data_shape=(3, image, image),
                         batch_size=batch, rand_mirror=True,
                         preprocess_threads=threads,
                         prefetch_buffer=4)

    def next_batch():
        try:
            return next(it)
        except StopIteration:  # only across reruns of the leg
            it.reset()
            return next(it)

    def step():
        b = next_batch()
        with autograd.record():
            loss = loss_fn(net(b.data[0]), b.label[0])
        loss.backward()
        trainer.step(batch)
        return loss

    _hard_sync(step())  # compile with the real-data shapes
    ips, _ = _best_window(step, batch, steps, repeats=repeats)

    # attribute the bottleneck: pure-pipeline throughput with the model
    # out of the loop (fresh epoch, no device work)
    it.reset()
    t0 = time.time()
    n = 0
    for b in it:
        n += b.data[0].shape[0]
    pipe_ips = n / (time.time() - t0)
    note = (f"input-pipeline-bound: decode sustains ~{pipe_ips:.0f} "
            f"img/s on {os.cpu_count()} host core(s); scales with "
            "cores (benchmark/input_pipeline.py)"
            if pipe_ips < ips * 1.5 else
            f"pipeline headroom ok (decode ~{pipe_ips:.0f} img/s)")
    return ips, note


def _maybe_fuse(eager_step, net, trainer, forward_loss, batch_arrays,
                batch_size):
    """Wrap the training step in ``gluon.FusedTrainStep`` with
    ``BENCH_STEPS_PER_EXEC`` inner steps per dispatch (default 8) —
    the TPU step-chaining idiom that keeps the window measuring chip
    time instead of per-step tunnel round trips (the r5 sync probe
    measured ~20 ms/step of dispatch overhead, ~45% of the ResNet
    step).  Any failure falls back to the per-step loop so the bench
    never loses its number to the optimization."""
    from mxnet_tpu import gluon

    spe = int(os.environ.get("BENCH_STEPS_PER_EXEC", "8"))
    if spe <= 1:
        return eager_step, 1
    # FusedTrainStep's first call snapshots, hard-syncs and restores on
    # failure, so trace/compile/fit problems surface HERE with the
    # trainer state pristine for the eager fallback
    try:
        fstep = gluon.FusedTrainStep(
            net, trainer, forward_loss, steps_per_execution=spe,
            batch_size=batch_size)
        _hard_sync(fstep(*batch_arrays))  # validate before any window
        return (lambda: fstep(*batch_arrays)), spe
    except Exception as e:
        import sys

        print(f"step fusion unavailable ({type(e).__name__}: {e}); "
              "falling back to per-step dispatch", file=sys.stderr)
        return eager_step, 1


def _hard_sync(arr):
    """Force TRUE device completion, not dispatch-return: fetch the
    value to host.  Through the remote tunnel ``block_until_ready`` can
    return once work is enqueued (r3 opperf finding) — a window timed
    that way measures dispatch throughput, which the r4 MFU audit caught
    pricing BERT above 100% of peak.  A host fetch of the loss cannot
    complete until every queued program before it has executed (single
    in-order device stream), so the clock stops at real completion; its
    one-time ~110 ms RTT is amortized over the whole window."""
    return arr.asnumpy()


def _best_window(step, samples_per_call, calls, repeats=None):
    """Best of ``BENCH_REPEATS`` steady-state windows, each closed by a
    hard host-fetch sync (see :func:`_hard_sync`).  The remote dispatch
    tunnel shows transient congestion worth ±20% on identical code; the
    best window approximates uncontended chip throughput (the quantity
    BASELINE.md's protocol is after), while any single window measures
    the tunnel's mood.  ``step`` may be a per-step dispatch (1 batch per
    call) or a fused K-step execution (``samples_per_call`` = batch*K)."""
    import time

    repeats = repeats or int(os.environ.get("BENCH_REPEATS", "3"))
    best = 0.0
    for _ in range(repeats):
        tic = time.time()
        last = None
        for _ in range(calls):
            last = step()
        _hard_sync(last)
        wall = time.time() - tic
        best = max(best, samples_per_call * calls / wall)
    return best, repeats


def _bench_bert(batch, steps, warmup, dtype, model_name):
    """BERT-base MLM-style pretraining step (seq 128, BASELINE protocol).
    Returns (samples/sec, window repeats)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.models import bert

    seq = int(os.environ.get("BENCH_SEQ", "128"))
    vocab = 30522
    mx.random.seed(0)
    builder = getattr(bert, model_name)  # unknown names must fail loudly
    net = builder(vocab_size=vocab)
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, vocab, (batch, seq)), dtype="int32")
    seg = nd.zeros((batch, seq), dtype="int32")
    labels = nd.array(rng.randint(0, vocab, (batch, seq)), dtype="int32")
    net(ids, seg)  # resolve deferred shapes
    if dtype in ("bfloat16", "float16"):
        from mxnet_tpu import amp

        amp.init(target_dtype=dtype)
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-4})

    # loss-in-graph (same protocol as the ResNet leg, +11% there): the
    # MLM cross-entropy compiles with its own CachedOp instead of three
    # eager dispatches per step — host dispatch is the scarce resource
    # through the tunnel
    class _MLMLoss(gluon.HybridBlock):
        def hybrid_forward(self, F, mlm, lab):
            # NO reshape to (b*s, vocab): the CE op reduces over the
            # last axis of any leading shape, and flattening forced a
            # 1.5 GB layout copy of the logits (PERF_NOTES r5 cont. 6)
            return F.softmax_cross_entropy(mlm, lab) / (batch * seq)

    loss_fn = _MLMLoss()
    loss_fn.hybridize()

    def eager_step():
        with autograd.record():
            # outputs: (seq, pooled, nsp_logits, mlm_logits)
            outs = net(ids, seg)
            loss = loss_fn(outs[-1], labels)
        loss.backward()
        trainer.step(1)
        return loss

    step, spe = _maybe_fuse(
        eager_step, net, trainer,
        lambda n, i, s, l: loss_fn(n(i, s)[-1], l), (ids, seg, labels), 1)

    last = None
    for _ in range((warmup + spe - 1) // spe):  # ceil: >= warmup steps
        last = step()
    if last is not None:
        _hard_sync(last)  # warmup fully done before any window starts
    ips, repeats = _best_window(step, batch * spe, max(1, steps // spe))
    return ips, repeats, spe


if __name__ == "__main__":
    main()
