"""Sparse op algebra (VERDICT r4 #7): stype-dispatching elemwise
binary/unary families, cast_storage across every stype pair, and the
chained sparse workflow staying sparse end-to-end.

Reference model: the FComputeEx kernels + storage fallback of
src/operator/tensor/elemwise_binary_op_basic.cc:? /
elemwise_unary_op_basic.cc:? / cast_storage-inl.h:?, and their tests in
tests/python/unittest/test_sparse_operator.py:?.  Oracle everywhere: the
same op on the densified operands.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse as sp

R = onp.random.RandomState(7)


def _sparse_np(shape, density=0.4, seed_off=0):
    rs = onp.random.RandomState(11 + seed_off)
    x = onp.round(rs.randn(*shape), 2).astype(onp.float32)
    x[rs.rand(*shape) > density] = 0.0
    return x


def _rsp(x):
    return nd.array(x).tostype("row_sparse")


def _csr(x):
    return nd.array(x).tostype("csr")


A = _sparse_np((6, 5))
B = _sparse_np((6, 5), seed_off=1)
D = onp.round(R.randn(6, 5), 2).astype(onp.float32) + 3.0  # dense, nonzero


# --- binary: sparse kernels keep the stype ----------------------------------

@pytest.mark.parametrize("mk,stype", [(_rsp, "row_sparse"), (_csr, "csr")])
@pytest.mark.parametrize("opname,npop", [
    ("add", onp.add), ("subtract", onp.subtract), ("multiply", onp.multiply),
])
def test_binary_sparse_sparse(mk, stype, opname, npop):
    out = getattr(sp, opname)(mk(A), mk(B))
    assert out.stype == stype, f"{opname} fell back to {out.stype}"
    onp.testing.assert_allclose(out.asnumpy(), npop(A, B), rtol=1e-6)


@pytest.mark.parametrize("mk,stype", [(_rsp, "row_sparse"), (_csr, "csr")])
def test_binary_sparse_dense_mul_div(mk, stype):
    s = mk(A)
    out = s * nd.array(D)
    assert out.stype == stype
    onp.testing.assert_allclose(out.asnumpy(), A * D, rtol=1e-6)
    out = nd.array(D) * s  # reflected: dense.__mul__ dispatches too
    assert out.stype == stype
    onp.testing.assert_allclose(out.asnumpy(), A * D, rtol=1e-6)
    out = s / nd.array(D)
    assert out.stype == stype
    onp.testing.assert_allclose(out.asnumpy(), A / D, rtol=1e-6)


@pytest.mark.parametrize("mk,stype", [(_rsp, "row_sparse"), (_csr, "csr")])
def test_binary_sparse_scalar(mk, stype):
    s = mk(A)
    for out, want in ((s * 2.5, A * 2.5), (s / 2.0, A / 2.0),
                      (3.0 * s, A * 3.0), (-s, -A)):
        assert out.stype == stype
        onp.testing.assert_allclose(out.asnumpy(), want, rtol=1e-6)


def test_binary_storage_fallback_densifies():
    """Ops without a sparse kernel produce DENSE output with dense
    semantics (reference FallBackCompute)."""
    out = _rsp(A) + nd.array(D)          # rsp + dense -> dense
    assert not isinstance(out, sp.BaseSparseNDArray)
    onp.testing.assert_allclose(out.asnumpy(), A + D, rtol=1e-6)
    out = _csr(A) + 1.0                  # nonzero scalar shifts the zeros
    assert not isinstance(out, sp.BaseSparseNDArray)
    onp.testing.assert_allclose(out.asnumpy(), A + 1.0, rtol=1e-6)
    out = sp.divide(_rsp(A), _rsp(B))    # rsp/rsp has no sparse kernel
    assert not isinstance(out, sp.BaseSparseNDArray)


def test_binary_union_actually_merges():
    """Disjoint row sets must union, not overwrite."""
    a = sp.row_sparse_array((onp.ones((2, 3), onp.float32),
                             onp.array([0, 2])), shape=(5, 3))
    b = sp.row_sparse_array((onp.full((2, 3), 2.0, onp.float32),
                             onp.array([2, 4])), shape=(5, 3))
    out = a + b
    assert out.stype == "row_sparse"
    assert out.indices.asnumpy().tolist() == [0, 2, 4]
    want = onp.zeros((5, 3), onp.float32)
    want[0], want[2], want[4] = 1.0, 3.0, 2.0
    onp.testing.assert_allclose(out.asnumpy(), want)


def test_binary_intersection_drops_single_sided_rows():
    a = sp.row_sparse_array((onp.ones((2, 3), onp.float32),
                             onp.array([0, 2])), shape=(5, 3))
    b = sp.row_sparse_array((onp.full((2, 3), 2.0, onp.float32),
                             onp.array([2, 4])), shape=(5, 3))
    out = a * b
    assert out.stype == "row_sparse"
    assert out.indices.asnumpy().tolist() == [2]
    onp.testing.assert_allclose(out.asnumpy(),
                                (a.asnumpy() * b.asnumpy()))


# --- unary ------------------------------------------------------------------

@pytest.mark.parametrize("mk,stype", [(_rsp, "row_sparse"), (_csr, "csr")])
@pytest.mark.parametrize("opname,npop", [
    ("abs", onp.abs), ("sign", onp.sign), ("square", onp.square),
    ("sqrt", lambda x: onp.sqrt(onp.abs(x))),
    ("relu", lambda x: onp.maximum(x, 0)),
    ("negative", onp.negative), ("tanh", onp.tanh),
    ("expm1", onp.expm1), ("log1p", lambda x: onp.log1p(onp.abs(x))),
])
def test_unary_zero_preserving_keeps_structure(mk, stype, opname, npop):
    x = onp.abs(A) if opname in ("sqrt", "log1p") else A
    out = getattr(nd, opname)(mk(x))
    assert out.stype == stype, f"{opname} densified"
    onp.testing.assert_allclose(out.asnumpy(), npop(x), rtol=1e-5,
                                atol=1e-6)


def test_unary_non_zero_preserving_densifies():
    out = nd.exp(_rsp(A))  # exp(0)=1: dense by definition
    assert not isinstance(out, sp.BaseSparseNDArray)
    onp.testing.assert_allclose(out.asnumpy(), onp.exp(A), rtol=1e-5)


# --- cast_storage -----------------------------------------------------------

def test_cast_storage_all_pairs():
    dense = nd.array(A)
    for src in ("default", "row_sparse", "csr"):
        x = nd.cast_storage(dense, src) if src != "default" else dense
        for dst in ("default", "row_sparse", "csr"):
            y = nd.cast_storage(x, dst)
            want_stype = dst if dst != "default" else None
            if want_stype:
                assert y.stype == want_stype, (src, dst)
            onp.testing.assert_allclose(
                y.asnumpy() if hasattr(y, "asnumpy") else y, A, rtol=0,
                atol=0)


def test_cast_storage_csr_pattern():
    c = nd.cast_storage(nd.array(A), "csr")
    scipy_rows, scipy_cols = onp.nonzero(A)
    assert c.indices.asnumpy().tolist() == scipy_cols.tolist()
    indptr = onp.concatenate(
        [[0], onp.cumsum(onp.bincount(scipy_rows, minlength=A.shape[0]))])
    assert c.indptr.asnumpy().tolist() == indptr.tolist()


# --- the chained user script (VERDICT done-criterion) -----------------------

def test_sparse_chain_never_densifies():
    """elemwise -> cast_storage -> elemwise -> dot, sparse at every
    intermediate step (the reference's sparse user workflow: feature
    scaling + storage conversion + a sparse-dense matmul)."""
    X = _sparse_np((8, 6), density=0.3)
    W = onp.round(R.randn(6, 4), 2).astype(onp.float32)

    rsp = nd.array(X).tostype("row_sparse")
    scaled = rsp * 0.5                     # rsp kernel
    assert scaled.stype == "row_sparse"
    sq = nd.square(scaled)                 # structure-preserving unary
    assert sq.stype == "row_sparse"
    csr = nd.cast_storage(sq, "csr")       # rsp -> csr, no dense hop
    assert csr.stype == "csr"
    damped = csr * nd.array(onp.full((8, 6), 0.9, onp.float32))
    assert damped.stype == "csr"           # csr×dense kernel
    out = nd.dot(damped, nd.array(W))      # BCOO sparse matmul path
    want = ((X * 0.5) ** 2 * 0.9) @ W
    onp.testing.assert_allclose(out.asnumpy(), want, rtol=2e-5,
                                atol=1e-5)


def test_csr_roundtrip_via_rsp():
    c = _csr(A)
    r = c.tostype("row_sparse")
    assert r.stype == "row_sparse"
    onp.testing.assert_allclose(r.asnumpy(), A)
    back = r.tostype("csr")
    assert back.stype == "csr"
    onp.testing.assert_allclose(back.asnumpy(), A)


def test_fallback_keeps_dense_autograd_tape():
    """A dense operand inside autograd.record() must keep its gradient
    when a sparse array joins the expression via the storage fallback
    (the densified sparse side is a constant)."""
    from mxnet_tpu import autograd

    x = nd.array(D)
    x.attach_grad()
    s = _rsp(A)
    with autograd.record():
        z = (x * 3.0) + s          # fallback: rsp+dense -> dense
        loss = (z * z).sum()
    loss.backward()
    want = 2.0 * (3.0 * D + A) * 3.0
    onp.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


def test_out_kwarg_with_sparse_raises():
    o = nd.zeros((6, 5))
    with pytest.raises(mx.MXNetError):
        nd.square(_rsp(A), out=o)
    with pytest.raises(mx.MXNetError):
        nd.multiply(_rsp(A), _rsp(B), out=o)


def test_cast_storage_3d_rsp_to_csr_raises():
    r = sp.row_sparse_array((onp.ones((2, 2, 2), onp.float32),
                             onp.array([0, 2])), shape=(4, 2, 2))
    with pytest.raises(mx.MXNetError):
        r.tostype("csr")


def test_stored_entry_kernel_defers_to_tape_when_recording():
    """Inside autograd.record(), a dense operand's gradient must flow
    even for multiply/divide (the stored-entry kernels would sever the
    tape, so dispatch takes the dense fallback while recording)."""
    from mxnet_tpu import autograd

    x = nd.array(D)
    x.attach_grad()
    s = _csr(A)
    with autograd.record():
        z = x * s                       # recording: falls back to dense
        loss = (z * z).sum() + (x * x).sum()
    loss.backward()
    want = 2.0 * (D * A) * A + 2.0 * D
    onp.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)
    # outside record(): the sparse kernel engages again
    out = x * s
    assert out.stype == "csr"


def test_divide_semantics_match_inside_record():
    """sparse/dense divide must produce the SAME values inside and
    outside autograd.record(): implicit zeros stay zero on both paths
    (the tape fallback masks, never 0/0-NaNs), and the dense operand's
    gradient flows."""
    from mxnet_tpu import autograd

    dz = D.copy()
    dz[0, 0] = 0.0  # a zero denominator at an UNSTORED coordinate
    Az = A.copy()
    Az[0, 0] = 0.0
    s = _csr(Az)
    outside = (s / nd.array(dz)).asnumpy()
    x = nd.array(dz)
    x.attach_grad()
    with autograd.record():
        z = s / x
        loss = z.sum()
    loss.backward()
    inside = z.asnumpy()
    assert onp.isfinite(inside).all(), "NaN leaked on the tape path"
    onp.testing.assert_allclose(inside, outside, rtol=1e-6)
    g = x.grad.asnumpy()
    assert onp.isfinite(g).all()
    assert g[0, 0] == 0.0  # unstored coord contributes no gradient
    mask = Az != 0
    onp.testing.assert_allclose(g[mask], -Az[mask] / dz[mask] ** 2,
                                rtol=1e-5)


def test_sparse_dot_dense_operand_gradient_flows():
    """Autograd through sparse dot (reference example/sparse/
    linear_classification workflow): the DENSE operand's gradient must
    flow through the BCOO matmul; the sparse side is a constant."""
    from mxnet_tpu import autograd

    X = _sparse_np((12, 6), density=0.4)
    w = nd.zeros((6, 1))
    w.attach_grad()
    csr = nd.array(X).tostype("csr")
    with autograd.record():
        out = nd.dot(csr, w)
        loss = (out * out).sum() + out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    want = X.T @ (2 * (X @ onp.zeros((6, 1))) + 1)
    onp.testing.assert_allclose(g, want, rtol=1e-5)
    assert onp.abs(g).sum() > 0


def test_sparse_linear_classification_example_smoke(monkeypatch):
    """The user-facing example trains end to end on a tiny config."""
    import runpy

    for k, v in (("N", "1500"), ("D", "256"), ("STEPS", "45"),
                 ("BATCH", "128"), ("LR", "5.0")):
        monkeypatch.setenv(k, v)
    runpy.run_path(os.path.join(
        os.path.dirname(__file__), "..", "examples",
        "sparse_linear_classification.py"), run_name="__main__")
