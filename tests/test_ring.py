"""Sequence/context + pipeline parallelism tests on the 8-device CPU mesh.

Ring attention and Ulysses must be EXACT vs dense single-device attention
(same math, different schedule), forward and backward; the GPipe pipeline
must match sequential stage application.  Reference has none of this
(SURVEY D7/D8 — new capability).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel


def _dense_attention(q, k, v, causal=False):
    """NumPy reference: softmax(QK^T/sqrt(h))V on (B, T, N, H)."""
    b, t, n, h = q.shape
    logits = np.einsum("btnh,bsnh->bnts", q, k) / np.sqrt(h)
    if causal:
        keep = np.tril(np.ones((t, t), bool))
        logits = np.where(keep[None, None], logits, -1e30)
    logits = logits - logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bnts,bsnh->btnh", p, v)


@pytest.fixture
def sp_mesh():
    m = parallel.make_mesh({"sp": 8})
    with parallel.mesh_scope(m):
        yield m


@pytest.fixture
def pp_mesh():
    m = parallel.make_mesh({"pp": 4}, devices=None)
    with parallel.mesh_scope(m):
        yield m


def _qkv(b=2, t=32, n=4, h=8, seed=0):
    r = np.random.RandomState(seed)
    return (r.randn(b, t, n, h).astype(np.float32),
            r.randn(b, t, n, h).astype(np.float32),
            r.randn(b, t, n, h).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(sp_mesh, causal):
    q, k, v = _qkv()
    out = parallel.ring_attention(nd.array(q), nd.array(k), nd.array(v),
                                  causal=causal)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(sp_mesh, causal):
    q, k, v = _qkv(n=8)  # heads must divide sp=8
    out = parallel.ulysses_attention(nd.array(q), nd.array(k), nd.array(v),
                                     causal=causal)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=2e-5, atol=2e-5)


def test_ring_attention_backward_matches_dense(sp_mesh):
    """Gradients through the ring schedule == gradients through one-device
    attention (checks ppermute/scan transpose)."""
    qn, kn, vn = _qkv(t=16)

    def run(attn_fn):
        q, k, v = nd.array(qn), nd.array(kn), nd.array(vn)
        for a in (q, k, v):
            a.attach_grad()
        with autograd.record():
            out = attn_fn(q, k, v)
            loss = (out * out).sum()
        loss.backward()
        return [a.grad.asnumpy() for a in (q, k, v)]

    from mxnet_tpu.ops import attention as att
    ring = run(lambda q, k, v: parallel.ring_attention(q, k, v, causal=True))
    dense = run(lambda q, k, v: att.dot_product_attention(q, k, v,
                                                          causal=True))
    for g_r, g_d in zip(ring, dense):
        np.testing.assert_allclose(g_r, g_d, rtol=1e-4, atol=1e-4)


def test_ring_attention_seq_not_divisible(sp_mesh):
    q, k, v = _qkv(t=30)
    with pytest.raises(mx.MXNetError):
        parallel.ring_attention(nd.array(q), nd.array(k), nd.array(v))


def test_pipeline_matches_sequential(pp_mesh):
    """4-stage tanh-Dense pipeline over 6 microbatches == running the four
    stages back to back."""
    s, d, m, b = 4, 8, 6, 3
    r = np.random.RandomState(1)
    w = r.randn(s, d, d).astype(np.float32) * 0.3
    bias = r.randn(s, d).astype(np.float32) * 0.1
    xs = r.randn(m, b, d).astype(np.float32)

    def stage_fn(p, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ p["w"] + p["b"])

    out = parallel.pipeline_apply(
        stage_fn, {"w": nd.array(w), "b": nd.array(bias)}, nd.array(xs))

    ref = xs.copy()
    for i in range(s):
        ref = np.tanh(ref @ w[i] + bias[i])
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-5)


def test_pipeline_backward(pp_mesh):
    """Pipeline gradients == sequential gradients (scan transpose drives the
    reverse schedule)."""
    s, d, m, b = 4, 4, 5, 2
    r = np.random.RandomState(2)
    w_np = (r.randn(s, d, d) * 0.3).astype(np.float32)
    xs_np = r.randn(m, b, d).astype(np.float32)

    def stage_fn(p, x):
        import jax.numpy as jnp

        return jnp.tanh(x @ p)

    w = nd.array(w_np)
    w.attach_grad()
    with autograd.record():
        out = parallel.pipeline_apply(stage_fn, w, nd.array(xs_np))
        loss = (out * out).sum()
    loss.backward()
    g_pipe = w.grad.asnumpy()

    # sequential reference via jax
    import jax
    import jax.numpy as jnp

    def seq_loss(wr):
        y = jnp.asarray(xs_np)
        for i in range(s):
            y = jnp.tanh(y @ wr[i])
        return (y * y).sum()

    g_ref = np.asarray(jax.grad(seq_loss)(jnp.asarray(w_np)))
    np.testing.assert_allclose(g_pipe, g_ref, rtol=1e-4, atol=1e-4)


def test_pipeline_bad_stack_dim(pp_mesh):
    with pytest.raises(mx.MXNetError):
        parallel.pipeline_apply(lambda p, x: x, nd.ones((3, 2, 2)),
                                nd.ones((2, 2, 2)))


@pytest.mark.parametrize("tied", [False, True], ids=["untied", "tied"])
def test_pipeline_llama_matches_plain(pp_mesh, tied):
    """D7 on a REAL model: the same LlamaForCausalLM Blocks staged over
    pp=4 must reproduce the unpipelined loss AND every parameter
    gradient, and drive a gluon Trainer step (VERDICT r2: pipeline
    parallelism had only run on toy tanh stages).  The tied case pins
    the GPipe head to the embedding matrix — ADVICE r3: the pipelined
    forward must not fall back to the dead lm_head Dense."""
    from mxnet_tpu.models import llama

    mx.random.seed(4)
    net = llama.llama_tiny(num_layers=4, attn_mode="sdpa",
                           tie_embeddings=tied)
    net.initialize()
    r = np.random.RandomState(0)
    ids = nd.array(r.randint(0, 256, (4, 16)), dtype="int32")
    labels = nd.array(r.randint(0, 256, (4, 16)), dtype="int32")

    def loss_of(logits):
        return nd.softmax_cross_entropy(
            logits.reshape((-1, 256)), labels.reshape((-1,))).mean()

    with autograd.record():
        plain = loss_of(net(ids))
    plain.backward()
    g_plain = {k: p.grad().asnumpy()
               for k, p in net._collect_params_with_prefix().items()
               if p.grad_req != "null"}
    plain_val = float(plain.asscalar())

    for p in net.collect_params().values():
        if p.grad_req != "null":
            p.zero_grad()
    with autograd.record():
        piped = loss_of(llama.llama_pipeline_forward(
            net, ids, n_microbatches=2))
    piped.backward()
    np.testing.assert_allclose(float(piped.asscalar()), plain_val,
                               rtol=1e-5, atol=1e-6)
    g_piped = {k: p.grad().asnumpy()
               for k, p in net._collect_params_with_prefix().items()
               if p.grad_req != "null"}
    assert g_plain.keys() == g_piped.keys()
    for k in g_plain:
        np.testing.assert_allclose(g_piped[k], g_plain[k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)

    # Trainer integration: a pipelined step updates finite params
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    with autograd.record():
        loss = loss_of(llama.llama_pipeline_forward(
            net, ids, n_microbatches=2))
    loss.backward()
    trainer.step(4)
    for k, p in net._collect_params_with_prefix().items():
        assert np.isfinite(p.data().asnumpy()).all(), k


def test_pipeline_1f1b_matches_reference(pp_mesh):
    """1F1B fused train step == plain sequential forward/backward: loss
    and the per-stage parameter gradients must match an independent
    jax.grad reference (and GPipe's pipeline_apply path)."""
    import jax
    import jax.numpy as jnp

    s, d, m, b = 4, 8, 6, 3
    r = np.random.RandomState(7)
    w = r.randn(s, d, d).astype(np.float32) * 0.4
    xs = r.randn(m, b, d).astype(np.float32)
    labels = r.randn(m, b, d).astype(np.float32)

    def stage_fn(p, x):
        return jnp.tanh(x @ p)

    def loss_fn(out, lab, tail):
        return jnp.sum(((out * tail[0]) - lab) ** 2)

    scale = np.float32(1.3)
    loss, grads, tgrads, dxs = parallel.pipeline_train_1f1b(
        stage_fn, loss_fn, nd.array(w), nd.array(xs), nd.array(labels),
        tail_params=(nd.array(scale.reshape(1)),))

    # independent reference: sequential stages, jax autodiff
    def ref_loss(wstack, xsa, tl):
        total = 0.0
        for i in range(m):
            h = xsa[i]
            for si in range(s):
                h = stage_fn(wstack[si], h)
            total = total + loss_fn(h, jnp.asarray(labels[i]), tl)
        return total

    ref_val, (ref_grad, ref_dxs, ref_tg) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(jnp.asarray(w), jnp.asarray(xs),
                                     (jnp.asarray(scale.reshape(1)),))
    np.testing.assert_allclose(float(loss.asscalar()), float(ref_val),
                               rtol=1e-5)
    np.testing.assert_allclose(grads.asnumpy(), np.asarray(ref_grad),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(dxs.asnumpy(), np.asarray(ref_dxs),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(tgrads[0].asnumpy(),
                               np.asarray(ref_tg[0]),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_1f1b_llama_matches_plain(pp_mesh):
    """Full-model 1F1B: llama_pipeline_train_step's loss AND every
    parameter gradient (decoder stacks, embedding via input cotangent,
    norm/head via tail grads) must equal the plain unpipelined run, and
    a Trainer step must work off the deposited grads."""
    from mxnet_tpu.models import llama

    mx.random.seed(8)
    net = llama.llama_tiny(num_layers=4, attn_mode="sdpa")
    net.initialize()
    r = np.random.RandomState(1)
    ids = nd.array(r.randint(0, 256, (4, 16)), dtype="int32")
    labels = nd.array(r.randint(0, 256, (4, 16)), dtype="int32")

    with autograd.record():
        logits = net(ids)
        # softmax_cross_entropy returns the token SUM; the fused step
        # returns the token MEAN — match scales
        plain = nd.softmax_cross_entropy(
            logits.reshape((-1, 256)), labels.reshape((-1,))) / (4 * 16)
    plain.backward()
    g_plain = {k: p.grad().asnumpy()
               for k, p in net._collect_params_with_prefix().items()
               if p.grad_req != "null"}
    plain_val = float(plain.asscalar())

    for p in net.collect_params().values():
        if p.grad_req != "null":
            p.zero_grad()
    with autograd.record():
        piped = llama.llama_pipeline_train_step(net, ids, labels,
                                                n_microbatches=2)
    piped.backward()
    np.testing.assert_allclose(float(piped.asscalar()), plain_val,
                               rtol=1e-5, atol=1e-6)
    g_piped = {k: p.grad().asnumpy()
               for k, p in net._collect_params_with_prefix().items()
               if p.grad_req != "null"}
    assert g_plain.keys() == g_piped.keys()
    for k in g_plain:
        np.testing.assert_allclose(g_piped[k], g_plain[k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    with autograd.record():
        loss = llama.llama_pipeline_train_step(net, ids, labels,
                                               n_microbatches=2)
    loss.backward()
    trainer.step(4)
    for k, p in net._collect_params_with_prefix().items():
        assert np.isfinite(p.data().asnumpy()).all(), k


def test_pipeline_1f1b_tied_embeddings_and_program_cache(pp_mesh):
    """Tied-embedding models route the LM head through the embedding
    matrix (round-3 review: the fused step silently used the dead
    lm_head for tied configs), and repeated steps reuse ONE cached
    program instead of re-tracing the schedule."""
    from mxnet_tpu.models import llama
    from mxnet_tpu.parallel import pipeline as pl

    mx.random.seed(9)
    net = llama.llama_tiny(num_layers=4, attn_mode="sdpa",
                           tie_embeddings=True)
    net.initialize()
    r = np.random.RandomState(2)
    ids = nd.array(r.randint(0, 256, (4, 16)), dtype="int32")
    labels = nd.array(r.randint(0, 256, (4, 16)), dtype="int32")

    with autograd.record():
        logits = net(ids)
        plain = nd.softmax_cross_entropy(
            logits.reshape((-1, 256)), labels.reshape((-1,))) / (4 * 16)
    plain.backward()
    g_plain = {k: p.grad().asnumpy()
               for k, p in net._collect_params_with_prefix().items()
               if p.grad_req != "null"}
    plain_val = float(plain.asscalar())

    for p in net.collect_params().values():
        if p.grad_req != "null":
            p.zero_grad()
    n_prog0 = len(pl._1F1B_PROGRAMS)
    with autograd.record():
        piped = llama.llama_pipeline_train_step(net, ids, labels,
                                                n_microbatches=2)
    piped.backward()
    np.testing.assert_allclose(float(piped.asscalar()), plain_val,
                               rtol=1e-5, atol=1e-6)
    g_piped = {k: p.grad().asnumpy()
               for k, p in net._collect_params_with_prefix().items()
               if p.grad_req != "null"}
    for k in g_plain:
        np.testing.assert_allclose(g_piped[k], g_plain[k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)

    # second step, same shapes: the OWN program cache must not grow
    # (stage_fn/loss_fn identities are pinned on the net)
    n_prog1 = len(pl._1F1B_PROGRAMS)
    with autograd.record():
        loss2 = llama.llama_pipeline_train_step(net, ids, labels,
                                                n_microbatches=2)
    loss2.backward()
    assert len(pl._1F1B_PROGRAMS) == n_prog1
    assert n_prog1 == n_prog0 + 1
