"""Legacy Module API tests.

Modeled on the reference's tests/python/unittest/test_module.py:? — fit
convergence, score/predict, checkpointing, bucketing, input grads.
"""
import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym as S

logging.disable(logging.INFO)


def _blobs(n=128, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    x = np.concatenate([rng.randn(n, dim) + 1.2,
                        rng.randn(n, dim) - 1.2]).astype(np.float32)
    y = np.concatenate([np.zeros(n), np.ones(n)]).astype(np.float32)
    perm = rng.permutation(2 * n)
    return x[perm], y[perm]


def _mlp_sym(hidden=8, classes=2):
    data = S.Variable("data")
    net = S.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = S.Activation(net, act_type="relu")
    net = S.FullyConnected(net, num_hidden=classes, name="fc2")
    return S.SoftmaxOutput(net, S.Variable("softmax_label"), name="softmax")


def test_module_fit_and_score():
    x, y = _blobs()
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier())
    it.reset()
    name, acc = mod.score(it, "acc")[0]
    assert acc > 0.9, acc
    pred = mod.predict(it)
    assert pred.shape == (256, 2)


def test_module_checkpoint(tmp_path):
    x, y = _blobs(n=32)
    it = mx.io.NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=1, initializer=mx.init.Xavier())
    prefix = str(tmp_path / "mod")
    mod.save_checkpoint(prefix, 1)
    sym, args, aux = mx.serialization.load_checkpoint(prefix, 1)
    m2 = mx.mod.Module(sym, context=mx.cpu())
    m2.bind([("data", (16, 6))], [("softmax_label", (16,))],
            for_training=False)
    m2.set_params(args, aux)
    batch = mx.io.DataBatch(data=[mx.nd.array(x[:16])])
    mod.forward(batch, is_train=False)
    m2.forward(batch, is_train=False)
    np.testing.assert_allclose(m2.get_outputs()[0].asnumpy(),
                               mod.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_module_batchnorm_aux_updates():
    data = S.Variable("data")
    net = S.Convolution(data, num_filter=4, kernel=(3, 3), name="conv")
    net = S.BatchNorm(net, name="bn", momentum=0.5)
    net = S.Pooling(net, global_pool=True, pool_type="avg")
    net = S.Flatten(net)
    net = S.SoftmaxOutput(net, S.Variable("softmax_label"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind([("data", (8, 3, 8, 8))], [("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer()
    before = mod._exec.aux_dict["bn_moving_mean"].asnumpy().copy()
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(8, 3, 8, 8).astype(np.float32) + 3.0)],
        label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))])
    mod.forward_backward(batch)
    mod.update()
    after = mod._exec.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(before, after), "moving stats must update"


def test_module_input_grads():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params(mx.init.Xavier())
    batch = mx.io.DataBatch(
        data=[mx.nd.ones((4, 6))],
        label=[mx.nd.array(np.array([0, 1, 0, 1], np.float32))])
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0]
    assert g.shape == (4, 6)
    assert np.abs(g.asnumpy()).sum() > 0


def test_bucketing_module():
    # variable-length averaging task: same params across two buckets
    def sym_gen(seq_len):
        data = S.Variable("data")
        net = S.mean(data, axis=1, keepdims=False)
        net = S.FullyConnected(net, num_hidden=2, name="fc")
        net = S.SoftmaxOutput(net, S.Variable("softmax_label"),
                              name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    mod.bind([("data", (4, 8, 3))], [("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer()
    rng = np.random.RandomState(0)

    class _B:
        def __init__(self, key, t):
            self.bucket_key = key
            self.data = [mx.nd.array(rng.randn(4, t, 3).astype(np.float32))]
            self.label = [mx.nd.array(np.array([0, 1, 0, 1], np.float32))]
            self.provide_data = [("data", (4, t, 3))]
            self.provide_label = [("softmax_label", (4,))]

    mod.forward(_B(8, 8), is_train=True)
    mod.backward()
    mod.update()
    out8 = mod.get_outputs()[0]
    assert out8.shape == (4, 2)
    mod.forward(_B(4, 4), is_train=True)  # new bucket, shared params
    mod.backward()
    mod.update()
    assert mod.get_outputs()[0].shape == (4, 2)
    # params are shared by handle between buckets
    m8 = mod._buckets[8]._exec.arg_dict["fc_weight"]
    m4 = mod._buckets[4]._exec.arg_dict["fc_weight"]
    assert m8 is m4


def test_module_fixed_params():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu(),
                        fixed_param_names=["fc1_weight"])
    mod.bind([("data", (4, 6))], [("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer()
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    batch = mx.io.DataBatch(
        data=[mx.nd.ones((4, 6))],
        label=[mx.nd.array(np.array([0, 1, 0, 1], np.float32))])
    mod.forward_backward(batch)
    mod.update()
    np.testing.assert_array_equal(
        w_before, mod._exec.arg_dict["fc1_weight"].asnumpy())
