"""Test fixture: run the whole suite on a virtual 8-device CPU mesh.

This is the TPU build's analog of the reference's import-under-new-context
trick (tests/python/gpu/test_operator_gpu.py:? imports the unittest modules
with ctx=gpu): XLA's CPU backend is the "fake device" the reference never
had, and --xla_force_host_platform_device_count=8 gives every test a
multi-device mesh without hardware.  Must run before jax initialises a
backend; the axon sitecustomize pins JAX_PLATFORMS=axon so we override via
jax.config, which takes effect because no backend has been created yet at
conftest-import time.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# float64 needed by finite-difference gradient checks (CPU-only; the TPU
# bench path stays in x32/bf16)
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# Fast CI lane: the heavyweight model/system suites carry the 'slow'
# marker so `pytest -m "not slow"` is a <5-min core lane (ops, autograd,
# gluon fundamentals, data plane, serialization, kvstore), while the
# default full run keeps everything.  Module-level marking keeps the
# split in one place.
_SLOW_MODULES = {
    "test_llama", "test_model_zoo", "test_nlp_models",
    "test_detection_models", "test_operator_sweep", "test_quantization",
    "test_module", "test_moe", "test_ring", "test_parallel",
    "test_onnx", "test_dist_loopback", "test_nightly_large",
    "test_model", "test_rnn", "test_contrib_gluon", "test_fm",
    "test_contrib", "test_fault_injection",
}


# Heaviest tier: the model-family suites (big configs, many compiles).
# `pytest -m "not heavy"` is the mid lane — core + distributed-system
# suites in a ~15-min window — while cheap per-family smokes live in the
# fast lane (tests/test_model_smoke.py).
_HEAVY_MODULES = {
    "test_llama", "test_model_zoo", "test_nlp_models",
    "test_detection_models", "test_moe", "test_onnx", "test_model",
    "test_rnn", "test_quantization",
}


def pytest_collection_modifyitems(items):
    for item in items:
        if item.module.__name__ in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
        if item.module.__name__ in _HEAVY_MODULES:
            item.add_marker(pytest.mark.heavy)


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx

    mx.random.seed(42)
    yield
