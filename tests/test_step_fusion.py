"""FusedTrainStep: K device-side optimizer steps == K eager Trainer steps.

The fused program (gluon/step_fusion.py) must be a pure dispatch
optimization — same parameters, same optimizer state, same aux (BN
running stats), same per-step losses as the eager
record/backward/step loop it replaces (reference protocol:
python/mxnet/gluon/trainer.py:? Trainer.step per-batch semantics).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.base import MXNetError

K = 4
BATCH = 8


def _mlp(bn=False, dropout=0.0):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    if bn:
        net.add(gluon.nn.BatchNorm())
    if dropout:
        net.add(gluon.nn.Dropout(dropout))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 8)))  # resolve deferred shapes
    return net


def _data(k=K, seed=0):
    rng = np.random.RandomState(seed)
    xs = nd.array(rng.randn(k, BATCH, 8).astype(np.float32))
    ys = nd.array(rng.randint(0, 4, (k, BATCH)))
    return xs, ys


def _eager_steps(net, trainer, loss_fn, xs, ys):
    losses = []
    for i in range(xs.shape[0]):
        x, y = xs[i], ys[i]
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(BATCH)
        losses.append(float(loss.sum().asnumpy()))
    return losses


def _fused(net, trainer, loss_fn, k=K, batch_size=BATCH, stacked=True):
    return gluon.FusedTrainStep(
        net, trainer,
        lambda n, x, y: loss_fn(n(x), y),
        steps_per_execution=k, batch_size=batch_size,
        stacked_inputs=stacked)


def _params_of(net):
    # global auto-naming differs between net instances (dense0 vs dense2):
    # compare positionally, collect_params() preserves creation order
    return [(name, p.data().asnumpy().copy())
            for name, p in net.collect_params().items()]


def _assert_tree_close(a, b, rtol=2e-5, atol=2e-6):
    assert len(a) == len(b)
    for (name, va), (_, vb) in zip(a, b):
        np.testing.assert_allclose(va, vb, rtol=rtol, atol=atol,
                                   err_msg=name)


@pytest.mark.parametrize("optim,kw,hybridize", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, False),
    ("adam", {"learning_rate": 1e-3}, False),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}, True),
])
def test_fused_matches_eager(optim, kw, hybridize):
    mx.random.seed(7)
    net_a = _mlp()
    net_b = _mlp()
    if hybridize:
        # the bench shape: CachedOp jit inlines inside the fused program
        net_a.hybridize(static_alloc=True)
        net_b.hybridize(static_alloc=True)
    # identical init
    for (_, pa), (_, pb) in zip(net_a.collect_params().items(),
                                net_b.collect_params().items()):
        pb.set_data(pa.data().copy())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr_a = gluon.Trainer(net_a.collect_params(), optim, dict(kw))
    tr_b = gluon.Trainer(net_b.collect_params(), optim, dict(kw))
    xs, ys = _data()

    eager_losses = _eager_steps(net_a, tr_a, loss_fn, xs, ys)
    fused_losses = _fused(net_b, tr_b, loss_fn)(xs, ys).asnumpy()

    np.testing.assert_allclose(fused_losses, eager_losses,
                               rtol=2e-5, atol=2e-6)
    _assert_tree_close(_params_of(net_a), _params_of(net_b))
    # optimizer state advanced identically (momenta / m,v)
    import mxnet_tpu.optimizer as opt

    for sa_state, sb_state in zip(tr_a._states, tr_b._states):
        if sa_state is None:
            assert sb_state is None
            continue
        sa = opt._flatten_state(sa_state)
        sb = opt._flatten_state(sb_state)
        for ra, rb in zip(sa, sb):
            np.testing.assert_allclose(ra.asnumpy(), rb.asnumpy(),
                                       rtol=2e-5, atol=2e-6)
    # update counts advanced by K on both paths
    assert tr_b._optimizer._index_update_count == \
        tr_a._optimizer._index_update_count


def test_bn_aux_threads_through_scan():
    mx.random.seed(3)
    net_a = _mlp(bn=True)
    net_b = _mlp(bn=True)
    for (_, pa), (_, pb) in zip(net_a.collect_params().items(),
                                net_b.collect_params().items()):
        pb.set_data(pa.data().copy())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.05})
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.05})
    xs, ys = _data(seed=1)
    _eager_steps(net_a, tr_a, loss_fn, xs, ys)
    _fused(net_b, tr_b, loss_fn)(xs, ys)
    # running_mean/var are grad_req='null' aux params: the fused path
    # must advance them through the scan carry exactly K times
    _assert_tree_close(_params_of(net_a), _params_of(net_b),
                       rtol=5e-5, atol=5e-6)


def test_constant_batch_broadcasts():
    """A plain (batch, ...) input is reused by every inner step (the
    synthetic-bench shape); equivalent to stacking it K times."""
    mx.random.seed(5)
    net_a = _mlp()
    net_b = _mlp()
    for (_, pa), (_, pb) in zip(net_a.collect_params().items(),
                                net_b.collect_params().items()):
        pb.set_data(pa.data().copy())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    xs, ys = _data(seed=2)
    x0 = xs[0]
    y0 = ys[0]
    stacked_x = nd.array(np.repeat(x0.asnumpy()[None], K, axis=0))
    stacked_y = nd.array(np.repeat(y0.asnumpy()[None], K, axis=0))
    la = _fused(net_a, tr_a, loss_fn)(stacked_x, stacked_y).asnumpy()
    lb = _fused(net_b, tr_b, loss_fn, stacked=False)(x0, y0).asnumpy()
    np.testing.assert_allclose(la, lb, rtol=2e-5, atol=2e-6)
    _assert_tree_close(_params_of(net_a), _params_of(net_b))


def test_multi_precision_bf16():
    """bf16 weights + f32 masters: the fused path must update the master
    and write back a bf16 copy, matching the eager mp path."""
    mx.random.seed(11)
    net_a = _mlp()
    net_b = _mlp()
    for (_, pa), (_, pb) in zip(net_a.collect_params().items(),
                                net_b.collect_params().items()):
        pb.set_data(pa.data().copy())
    net_a.cast("bfloat16")
    net_b.cast("bfloat16")
    kw = {"learning_rate": 0.05, "momentum": 0.9,
          "multi_precision": True}
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd", dict(kw))
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd", dict(kw))
    rng = np.random.RandomState(4)
    xs = nd.array(rng.randn(K, BATCH, 8).astype(np.float32),
                  dtype="bfloat16")
    ys = nd.array(rng.randint(0, 4, (K, BATCH)))
    _eager_steps(net_a, tr_a, loss_fn, xs, ys)
    _fused(net_b, tr_b, loss_fn)(xs, ys)
    _assert_tree_close(_params_of(net_a), _params_of(net_b),
                       rtol=2e-2, atol=2e-3)  # bf16 storage


def test_dropout_fresh_key_per_inner_step():
    mx.random.seed(13)
    net = _mlp(dropout=0.5)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.0})  # lr 0: only masks vary
    xs, ys = _data(seed=6)
    x0, y0 = xs[0], ys[0]
    losses = _fused(net, tr, loss_fn, k=8, stacked=False)(x0, y0).asnumpy()
    # same data + frozen weights: loss differences can only come from
    # per-step dropout masks — a replayed mask would repeat values
    assert len(np.unique(np.round(losses, 6))) > 1


def test_first_call_failure_restores_state():
    """A failure during the validated first execution must leave params,
    optimizer state and update counts pristine for the eager fallback."""
    mx.random.seed(17)
    net = _mlp()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    xs, ys = _data(seed=9)
    before = _params_of(net)
    counts_before = dict(tr._optimizer._index_update_count)

    def bad_loss(n, x, y):
        raise ValueError("injected trace failure")

    fstep = gluon.FusedTrainStep(net, tr, bad_loss,
                                 steps_per_execution=K,
                                 batch_size=BATCH, stacked_inputs=True)
    with pytest.raises(ValueError):
        fstep(xs, ys)
    _assert_tree_close(before, _params_of(net), rtol=0, atol=0)
    assert dict(tr._optimizer._index_update_count) == counts_before
    # eager path still trains from the pristine state
    losses = _eager_steps(net, tr, loss_fn, xs, ys)
    assert losses[-1] < losses[0] * 1.5  # sane, finite


def test_update_on_kvstore_rejected():
    net = _mlp()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    tr._kv_initialized = True
    tr._update_on_kvstore = True
    with pytest.raises(MXNetError):
        gluon.FusedTrainStep(net, tr, lambda n, x, y: n(x),
                             steps_per_execution=2, batch_size=BATCH)
