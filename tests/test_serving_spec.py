"""r19 speed multipliers: speculative decoding + radix prefix cache.

Three layers of proof:

* **Ledger units** — the ``BlockAllocator`` refcount surface
  (alloc/share/release, free-at-zero, check invariants), the paged
  manager's ``advance_n``/``truncate`` rollback contract (blocks past
  the shrunk reservation return to the pool), and the radix trie
  (block-aligned matching, LRU leaf eviction, evict-while-shared
  keeping the block alive for the remaining holder).
* **Token-exactness** — the server with speculation on (same-net draft
  at several k, and a differently-initialized draft forcing
  mid-sequence rejections) and with the radix cache on must emit
  BIT-identical sequences to the offline ``generate()`` oracle: the
  speed multipliers may never change tokens.
* **Compile discipline** — a dp2 CPU-mesh run with both features on
  stays clean under the retrace sanitizer after one warm pass, and the
  target engine holds exactly one decode-path signature per mode
  (``("verify",)`` in spec mode — never ``("step",)``).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, serving, telemetry
from mxnet_tpu.serving import ServerConfig
from mxnet_tpu.serving.kv_cache import BlockAllocator, PagedKVCacheManager
from mxnet_tpu.serving.radix import RadixPrefixCache
from mxnet_tpu.telemetry.sinks import ListSink


# --- allocator refcounts -----------------------------------------------------

def test_allocator_share_release_refcounts():
    a = BlockAllocator(8, 4)
    blocks = a.alloc(2)
    assert [a.refcount(b) for b in blocks] == [1, 1]
    a.share([blocks[0]])
    assert a.refcount(blocks[0]) == 2
    assert a.shared_blocks == 1
    a.check()
    # first release drops to 1 holder — the block stays allocated
    a.release([blocks[0]])
    assert a.refcount(blocks[0]) == 1
    assert a.blocks_in_use == 2 and a.shared_blocks == 0
    # last release frees it
    a.release(blocks)
    assert a.blocks_in_use == 0 and a.free_blocks == 8
    assert a.peak_shared_blocks == 1
    a.check()


def test_allocator_share_free_block_rejected():
    a = BlockAllocator(4, 4)
    blocks = a.alloc(1)
    a.free(blocks)
    with pytest.raises(mx.MXNetError):
        a.share(blocks)                    # resurrecting a freed block
    with pytest.raises(mx.MXNetError):
        a.release(blocks)                  # double free still rejected


# --- truncate rollback -------------------------------------------------------

def test_paged_truncate_releases_tail_blocks():
    m = PagedKVCacheManager(num_slots=2, max_len=64, num_blocks=16,
                            block_size=4)
    slot, blocks = m.admit("r1", prompt_len=10, max_new_tokens=20)
    st = m.state(slot)
    st.pos = 10                            # prefill wrote the prompt
    assert st.reserved == 30 and len(blocks) == 8
    for _ in range(5):
        m.advance(slot)
    for _ in range(5):
        m.consume(slot)
    # 15 tokens remain owed; rolling back to pos 12 shrinks the
    # reservation to 12 + 15 = 27 tokens = 7 blocks: one block frees
    freed = m.truncate(slot, 12)
    assert len(freed) == 1
    assert st.pos == 12 and st.reserved == 27 and len(st.blocks) == 7
    assert m.allocator.free_blocks == 9
    m.check()
    with pytest.raises(mx.MXNetError):
        m.truncate(slot, 13)               # cannot truncate forward
    m.evict(slot)
    assert m.allocator.blocks_in_use == 0


def test_paged_advance_n_respects_reservation():
    m = PagedKVCacheManager(num_slots=1, max_len=32, num_blocks=8,
                            block_size=4)
    slot, _ = m.admit("r1", prompt_len=4, max_new_tokens=4)
    m.state(slot).pos = 4
    m.advance_n(slot, 4)                   # up to reserved is fine
    with pytest.raises(mx.MXNetError):
        m.advance_n(slot, 1)               # past the reservation raises


# --- radix trie --------------------------------------------------------------

def test_radix_insert_lookup_block_aligned():
    a = BlockAllocator(8, 4)
    rx = RadixPrefixCache(a, block_size=4, capacity_tokens=64)
    blocks = a.alloc(3)
    prompt = list(range(11))               # cap: 10 // 4 * 4 = 8 tokens
    rx.insert(prompt, blocks)
    assert rx.cached_tokens() == 8         # only FULL blocks cached
    assert a.refcount(blocks[0]) == 2      # cache holds its own ref
    assert a.refcount(blocks[2]) == 1      # partial tail block: not cached
    matched, shared = rx.lookup(prompt)
    assert matched == 8 and shared == blocks[:2]
    # a prompt that IS exactly the cached prefix must leave >= 1 novel
    # token: the match caps at (len - 1) // bs * bs
    assert rx.match_len(prompt[:8]) == 4
    # diverging second block: only the first matches
    other = prompt[:4] + [99] * 7
    assert rx.match_len(other) == 4
    rx.clear()
    assert a.refcount(blocks[0]) == 1
    a.free(blocks)
    a.check()


def test_radix_lru_eviction_and_evict_while_shared():
    a = BlockAllocator(8, 4)
    rx = RadixPrefixCache(a, block_size=4, capacity_tokens=8)
    b1 = a.alloc(2)
    rx.insert(list(range(9)), b1)          # 2 nodes = 8 tokens (at budget)
    a.release(b1)                          # prefiller done: cache sole holder
    # a "request" adopts the first cached block (evict-while-shared prey)
    a.share([b1[0]])
    b2 = a.alloc(2)
    rx.insert([50 + i for i in range(9)], b2)   # pushes over budget
    a.release(b2)
    assert rx.cached_tokens() == 8 and rx.evictions == 2
    # LRU leaves evicted: the first prompt's path went first, and the
    # shared block SURVIVES in the allocator for its remaining holder
    assert rx.match_len(list(range(9))) == 0
    assert a.refcount(b1[0]) == 1          # cache ref dropped, request's lives
    assert a.refcount(b1[1]) == 0          # unshared leaf fully freed
    a.release([b1[0]])
    rx.clear()
    a.check()
    assert a.blocks_in_use == 0


def test_radix_manager_check_covers_cache_refs():
    m = PagedKVCacheManager(num_slots=2, max_len=32, num_blocks=8,
                            block_size=4)
    rx = RadixPrefixCache(m.allocator, block_size=4, capacity_tokens=32)
    m.prefix_cache = rx
    prompt = list(range(9))
    slot, blocks = m.admit("r1", prompt_len=9, max_new_tokens=4)
    m.state(slot).pos = 9
    rx.insert(prompt, blocks)
    m.check()                              # slot + cache refs reconcile
    # a second request adopts the cached prefix
    matched, shared = rx.lookup(prompt)
    slot2, blocks2 = m.admit("r2", prompt_len=9, max_new_tokens=4,
                             shared_blocks=shared)
    assert blocks2[:2] == blocks[:2]
    assert m.allocator.refcount(blocks[0]) == 3  # 2 slots + cache
    assert m.stats()["shared_blocks"] == 2
    m.check()
    m.evict(slot)
    m.evict(slot2)
    m.check()
    rx.clear()
    assert m.allocator.blocks_in_use == 0


# --- end-to-end token exactness ----------------------------------------------

def _tiny():
    from mxnet_tpu.models.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    return net


@pytest.mark.parametrize("k", [1, 2, 3])
def test_speculative_token_exact_same_net_draft(k):
    """Same-net draft: every proposal matches, yet the output must be
    byte-identical to plain generate() — the acceptance rule emits only
    target argmaxes."""
    net = _tiny()
    rs = np.random.RandomState(0)
    p1 = rs.randint(1, 250, size=5)
    p2 = rs.randint(1, 250, size=9)
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=1 << 30,
                       draft_net=net, spec_k=k)
    srv = serving.GenerativeServer(net, cfg)
    with srv:
        r1 = srv.generate(p1, max_new_tokens=12)
        r2 = srv.generate(p2, max_new_tokens=7)
        stats = srv.stats()
    o1 = net.generate(nd.array(p1[None]), 12).asnumpy()[0]
    o2 = net.generate(nd.array(p2[None]), 7).asnumpy()[0]
    assert np.array_equal(r1, o1)
    assert np.array_equal(r2, o2)
    spec = stats["speculative"]
    assert spec["k"] == k and spec["draft_tokens"] > 0
    # same net -> every in-budget proposal accepted (the only slack is
    # the final round's budget clamp)
    assert spec["accept_rate"] >= 0.6
    sigs = stats["compiled_signatures"]
    assert sigs.count(("verify",)) == 1
    assert ("step",) not in sigs


def test_speculative_token_exact_rejecting_draft():
    """A differently-initialized draft disagrees mid-sequence; rejected
    suffixes roll back through truncate() and the output still matches
    the oracle exactly."""
    net = _tiny()
    draft = _tiny()                        # same arch, different weights
    rs = np.random.RandomState(3)
    prompts = [rs.randint(1, 250, size=n) for n in (5, 9, 12)]
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=1 << 30,
                       draft_net=draft, spec_k=3)
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    try:
        srv = serving.GenerativeServer(net, cfg)
        with srv:
            outs = [srv.generate(p, max_new_tokens=10) for p in prompts]
            stats = srv.stats()
            srv.replicas[0].mgr.check()
    finally:
        telemetry.disable()
        telemetry.reset()
    for p, r in zip(prompts, outs):
        o = net.generate(nd.array(p[None]), 10).asnumpy()[0]
        assert np.array_equal(r, o)
    spec = stats["speculative"]
    # a random draft over a 256 vocab rejects nearly always — the
    # machinery exercised here IS the rollback path
    assert spec["draft_tokens"] > spec["accepted_tokens"]
    assert stats["kv_cache"]["occupancy"] == 0
    # per-request records carry the speculation telemetry fields
    recs = [r for r in sink.records if r.get("record") == "serving.request"]
    assert recs and all(r["draft_tokens"] > 0 for r in recs)
    assert all("accept_rate" in r for r in recs)


def test_radix_prefix_cache_token_exact_and_shared():
    """Requests sharing a system prompt prefill only their novel
    suffix (prefix KV adopted by reference), with identical tokens."""
    net = _tiny()
    rs = np.random.RandomState(1)
    sys_prompt = rs.randint(1, 250, size=20)
    prompts = [np.concatenate([sys_prompt, rs.randint(1, 250, size=n)])
               for n in (4, 6, 3)]
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, block_size=8, summary_every=1 << 30,
                       radix_cache=True)
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    try:
        srv = serving.GenerativeServer(net, cfg)
        with srv:
            outs = [srv.generate(p, max_new_tokens=6) for p in prompts]
            stats = srv.stats()
            srv.replicas[0].mgr.check()
    finally:
        telemetry.disable()
        telemetry.reset()
    for p, r in zip(prompts, outs):
        o = net.generate(nd.array(p[None]), 6).asnumpy()[0]
        assert np.array_equal(r, o)
    rx = stats["radix_cache"]
    assert rx["hits"] >= 2                 # requests 2 and 3 reused
    assert rx["hit_tokens"] >= 2 * 16      # two full 8-token blocks each
    assert stats["kv_cache"]["peak_shared_blocks"] >= 2
    assert stats["kv_cache"]["occupancy"] == 0
    recs = [r for r in sink.records if r.get("record") == "serving.request"]
    hits = [r for r in recs if r.get("prefix_hit_tokens")]
    assert len(hits) >= 2
    assert all(r["prefill_saved_ms"] > 0 for r in hits)


def test_spec_and_radix_rejected_on_slots_mode():
    net = _tiny()
    with pytest.raises(mx.MXNetError):
        serving.GenerativeServer(
            net, ServerConfig(kv_mode="slots", radix_cache=True))
    with pytest.raises(mx.MXNetError):
        serving.GenerativeServer(
            net, ServerConfig(kv_mode="slots", draft_net=net))


def test_spec_requires_paged_engine_verify():
    from mxnet_tpu.serving.generative import LlamaServingEngine

    net = _tiny()
    eng = LlamaServingEngine(net, max_len=32, num_slots=2,
                             kv_mode="slots")
    with pytest.raises(mx.MXNetError):
        eng.verify(np.zeros((2, 2), np.int32))


# --- dp2 mesh, both features, retrace-clean ----------------------------------

def test_dp2_spec_radix_token_exact_sanitizer_clean():
    """Both multipliers on over a dp2 CPU mesh: token-exact on every
    replica, zero post-warmup retraces, one decode-path signature per
    engine, and the refcount invariants hold at drain."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.telemetry import retrace

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 CPU devices (conftest sets XLA_FLAGS)")
    net = _tiny()
    draft = _tiny()
    rs = np.random.RandomState(2)
    sys_prompt = rs.randint(1, 250, size=18)
    prompts = [np.concatenate([sys_prompt, rs.randint(1, 250, size=n)])
               for n in (4, 5, 6, 7)]
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "tp"))
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, block_size=8, summary_every=1 << 30,
                       draft_net=draft, spec_k=3, radix_cache=True)
    retrace.enable(mode="warn")
    try:
        srv = serving.GenerativeServer(net, cfg, mesh=mesh)
        with srv:
            warm = [srv.submit(p, max_new_tokens=8) for p in prompts]
            for f in warm:
                f.result(180)
            retrace.warm()
            futs = [srv.submit(p, max_new_tokens=8) for p in prompts]
            outs = [f.result(180) for f in futs]
            stats = srv.stats()
            for rep in srv.replicas:
                rep.mgr.check()
        violations = retrace.violations()
    finally:
        retrace.disable()
        retrace.reset()
    for p, r in zip(prompts, outs):
        o = net.generate(nd.array(p[None]), 8).asnumpy()[0]
        assert np.array_equal(r, o)
    assert violations == []
    assert stats["num_replicas"] == 2
    assert stats["radix_cache"]["hits"] > 0
    assert stats["speculative"]["draft_tokens"] > 0
    verified = 0
    for rep in srv.replicas:
        sigs = rep.engine.compiled_signatures()
        assert ("step",) not in sigs        # spec mode never compiles it
        verified += sigs.count(("verify",))
        assert sigs.count(("verify",)) <= 1
        draft_sigs = rep.draft.compiled_signatures()
        assert ("verify",) not in draft_sigs
        assert draft_sigs.count(("step",)) <= 1
        # at drain the only live blocks are the prefix cache's own
        assert rep.mgr.allocator.blocks_in_use == \
            len(rep.radix.block_refs())
    assert verified >= 1                    # at least one replica decoded
