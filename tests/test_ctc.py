"""CTC loss tests: brute-force path-enumeration oracle, finite-difference
gradients, gluon wiring, and an F.*-name existence sweep.

Reference test model: tests/python/unittest/test_operator.py test_ctc_loss
(known-value + grad checks against the C++ ctc_loss.cc implementation,
SURVEY §4); the oracle here enumerates every alignment path instead of
trusting any closed-form value.
"""
import itertools
import re
from pathlib import Path

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _collapse(path, blank):
    """CTC collapse: merge repeats, then drop blanks."""
    out = []
    prev = None
    for p in path:
        if p != prev:
            if p != blank:
                out.append(p)
            prev = p
    return tuple(out)


def _ctc_bruteforce(logits, labels, in_lens, lab_lens, blank):
    """-log sum_{paths collapsing to label} prod_t softmax(logits)[t, path_t]
    by enumerating all C^T paths (tiny T/C only)."""
    T, N, C = logits.shape
    e = np.exp(logits - logits.max(axis=2, keepdims=True))
    probs = e / e.sum(axis=2, keepdims=True)
    losses = []
    for n in range(N):
        tgt = tuple(labels[n][:lab_lens[n]])
        tl = in_lens[n]
        total = 0.0
        for path in itertools.product(range(C), repeat=tl):
            if _collapse(path, blank) == tgt:
                p = 1.0
                for t, c in enumerate(path):
                    p *= probs[t, n, c]
                total += p
        losses.append(-np.log(total) if total > 0 else np.inf)
    return np.array(losses)


@pytest.mark.parametrize("blank_label", ["first", "last"])
def test_ctc_loss_matches_bruteforce(blank_label):
    rs = np.random.RandomState(0)
    T, N, C = 4, 3, 3
    blank = 0 if blank_label == "first" else C - 1
    logits = rs.randn(T, N, C).astype(np.float64)
    # labels avoid the blank class; lengths vary per row
    classes = [c for c in range(C) if c != blank]
    lab_lens = np.array([2, 1, 2])
    L = 2
    labels = np.zeros((N, L), np.int32)
    pad = 0 if blank_label == "first" else -1
    labels[:] = pad
    for n in range(N):
        labels[n, :lab_lens[n]] = rs.choice(classes, lab_lens[n])
    in_lens = np.array([4, 3, 4])

    ref = _ctc_bruteforce(logits, labels, in_lens, lab_lens, blank)
    out = nd.ctc_loss(nd.array(logits, dtype="float64"),
                      nd.array(labels, dtype="int32"),
                      nd.array(in_lens, dtype="int32"),
                      nd.array(lab_lens, dtype="int32"),
                      blank_label=blank_label).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("blank_label", ["first", "last"])
def test_ctc_loss_padding_derived_lengths(blank_label):
    """Without label_lengths, lengths come from the first padding value
    (0 for blank_label='first', -1 for 'last') — reference
    LabelTensorToPackedVector semantics."""
    rs = np.random.RandomState(1)
    T, N, C = 4, 2, 3
    blank = 0 if blank_label == "first" else C - 1
    pad = 0 if blank_label == "first" else -1
    logits = rs.randn(T, N, C).astype(np.float64)
    classes = [c for c in range(C) if c != blank]
    labels = np.full((N, 3), pad, np.int32)
    labels[0, :2] = [classes[0], classes[1]]
    labels[1, :1] = [classes[1]]
    lab_lens = np.array([2, 1])
    ref = _ctc_bruteforce(logits, labels, np.array([T, T]), lab_lens, blank)
    out = nd.ctc_loss(nd.array(logits, dtype="float64"),
                      nd.array(labels, dtype="int32"),
                      blank_label=blank_label).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-8)


def test_ctc_loss_numeric_gradient():
    rs = np.random.RandomState(2)
    T, N, C = 5, 2, 4
    logits = rs.randn(T, N, C) * 0.5
    labels = nd.array([[1, 2], [3, 0]], dtype="int32")
    lab_lens = nd.array([2, 1], dtype="int32")

    def fn(d):
        return nd.ctc_loss(d, labels, label_lengths=lab_lens,
                           blank_label="first")

    check_numeric_gradient(fn, [logits], eps=1e-4, rtol=1e-3, atol=1e-5)


def test_ctc_loss_impossible_label_is_huge():
    """label longer than the input sequence: no valid alignment."""
    logits = nd.zeros((2, 1, 4))
    labels = nd.array([[1, 2, 3]], dtype="int32")
    out = nd.ctc_loss(logits, labels,
                      label_lengths=nd.array([3], dtype="int32"))
    assert float(out.asscalar()) > 1e20


def test_gluon_ctc_loss_layouts():
    """gluon CTCLoss: NTC (default) == TNC-transposed; blank is the LAST
    class; runs under autograd + hybridize."""
    rs = np.random.RandomState(3)
    T, N, C = 6, 2, 5
    pred_tnc = rs.randn(T, N, C).astype(np.float32)
    label = np.array([[0, 1, 2], [3, -1, -1]], np.float32)

    l_ntc = gluon.loss.CTCLoss(layout="NTC")
    l_tnc = gluon.loss.CTCLoss(layout="TNC")
    out_ntc = l_ntc(nd.array(pred_tnc.transpose(1, 0, 2)), nd.array(label))
    out_tnc = l_tnc(nd.array(pred_tnc), nd.array(label))
    np.testing.assert_allclose(out_ntc.asnumpy(), out_tnc.asnumpy(),
                               rtol=1e-5)
    # cross-check against the op with blank_label='last'
    direct = nd.ctc_loss(nd.array(pred_tnc),
                         nd.array(label, dtype="int32"),
                         blank_label="last").asnumpy()
    np.testing.assert_allclose(out_tnc.asnumpy(), direct, rtol=1e-5)
    # and it backpropagates
    p = nd.array(pred_tnc)
    p.attach_grad()
    with autograd.record():
        loss = l_tnc(p, nd.array(label)).sum()
    loss.backward()
    g = p.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_every_F_reference_resolves():
    """Walk every ``F.<name>`` reference in the gluon/model source and
    assert the op exists in BOTH the ndarray and symbol namespaces — the
    guard that would have caught the round-1 dangling F.ctc_loss."""
    import mxnet_tpu.ndarray as ndm
    import mxnet_tpu.symbol as sym

    root = Path(mx.__file__).parent
    pat = re.compile(r"\bF\.([A-Za-z_][A-Za-z0-9_]*)")
    skip = {"array"}  # F.array is creation, ndarray-only by design
    missing = []
    for py in root.rglob("*.py"):
        for name in pat.findall(py.read_text()):
            if name in skip:
                continue
            if not hasattr(ndm, name):
                missing.append(f"nd.{name} ({py.relative_to(root)})")
            if not hasattr(sym, name):
                missing.append(f"sym.{name} ({py.relative_to(root)})")
    assert not missing, f"dangling F.* references: {sorted(set(missing))}"


def test_symbolic_arange_and_ctc_bindings():
    """mx.sym.arange accepts positional start/stop and evaluates; symbolic
    ctc_loss with only label_lengths binds the length input correctly."""
    import mxnet_tpu.symbol as sym

    r = (sym.arange(2, 8, dtype="float32") * 1.0).eval()
    np.testing.assert_allclose(r[0].asnumpy() if isinstance(r, (list, tuple))
                               else r.asnumpy(), np.arange(2, 8, dtype="f"))

    rs = np.random.RandomState(4)
    T, N, C = 5, 2, 4
    logits = rs.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2], [3, 0]], np.int32)
    lens = np.array([2, 1], np.int32)
    d, l, ll = sym.Variable("d"), sym.Variable("l"), sym.Variable("ll")
    out = sym.ctc_loss(d, l, ll, use_data_lengths=False,
                       use_label_lengths=True, blank_label="first")
    got = out.eval(d=nd.array(logits), l=nd.array(labels, dtype="int32"),
                   ll=nd.array(lens, dtype="int32"))
    got = got[0] if isinstance(got, (list, tuple)) else got
    want = nd.ctc_loss(nd.array(logits), nd.array(labels, dtype="int32"),
                       label_lengths=nd.array(lens, dtype="int32"),
                       blank_label="first")
    np.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-5)


def test_ctc_loss_input_validation():
    data = nd.random.uniform(shape=(5, 2, 4))
    # blank='last' (C-1=3): a live label equal to the blank index raises
    with pytest.raises(mx.base.MXNetError):
        nd.ctc_loss(data, nd.array([[3, 1], [1, 2]]), blank_label="last")
    # blank='first': label_lengths exposing a 0 (blank) as live raises
    with pytest.raises(mx.base.MXNetError):
        nd.ctc_loss(data, nd.array([[0, 1], [1, 2]]),
                    label_lengths=nd.array([2, 2]), use_label_lengths=True)
    # data_lengths beyond T raises
    with pytest.raises(mx.base.MXNetError):
        nd.ctc_loss(data, nd.array([[1, 2], [1, 2]]),
                    data_lengths=nd.array([9, 3]), use_data_lengths=True)
