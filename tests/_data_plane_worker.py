"""Loopback training worker for the data-plane elastic suite.

NOT a test module — ``tests/test_data_plane.py`` launches this under
``tools/launch.py`` with the ``_preempt_worker.py`` env contract, plus:

  REC_DIR   directory of .rec/.idx shards every rank streams from

The loop is the r14 contract under test: the SAME elastic 2→1→2 resume
the preemption worker proves, but fed through the REAL streaming data
plane (ShardedRecordReader → StreamingLoader → DevicePrefetcher) over
record files instead of an in-memory array — sample order stays a pure
function of (seed, step), so per-step losses and final params must
match fixed-size oracles exactly.
"""
import os
import sys

sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.pop("XLA_FLAGS", None)
import jax

jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, data, gluon, nd, parallel
from mxnet_tpu import telemetry

parallel.initialize()
rank, world = jax.process_index(), jax.process_count()

mx.random.seed(42)
net = gluon.nn.Dense(3, use_bias=True)
net.initialize(mx.init.Xavier())
net(nd.ones((1, 5)))
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        kvstore="dist_tpu_sync")

ckpt_dir = os.environ["CKPT_DIR"]
total = int(os.environ["TOTAL_STEPS"])
loss_file = os.environ.get("LOSS_FILE")
BATCH = 8

start, _ = checkpoint.resume(ckpt_dir, net, trainer)
if start:
    print(f"rank {rank}: resumed from step {start} (world={world})",
          flush=True)

# the real data plane: resume = construct at the checkpointed step;
# there is no loader state to restore (docs/data.md)
reader = data.ShardedRecordReader(os.environ["REC_DIR"], batch_size=BATCH,
                                  seed=5)
loader = data.StreamingLoader(
    reader, transform=lambda b: np.frombuffer(b, dtype=np.float32),
    num_workers=2, prefetch_depth=2, start_step=start,
    num_steps=total - start)
trainer.attach_data_prefetcher(loader)

for step, x in zip(range(start, total), loader):
    telemetry.step_begin()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(BATCH)
    gloss = parallel.process_sum_hostvec(
        np.asarray([float(loss.asnumpy())], dtype=np.float64))[0]
    telemetry.step_end(examples=BATCH, loss=float(gloss),
                       global_step=step)
    if rank == 0:
        if loss_file:
            with open(loss_file, "a") as f:
                f.write(f"{step} {gloss:.9e}\n")
        checkpoint.save_checkpoint(ckpt_dir, step + 1, net, trainer)

loader.close()
np.save(os.environ["OUT_FILE"] + str(rank) + ".npy",
        np.concatenate([net.weight.data().asnumpy().ravel(),
                        net.bias.data().asnumpy().ravel()]))
print(f"rank {rank}: done at step {total}", flush=True)
