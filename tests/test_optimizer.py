"""Optimizer + Trainer + lr_scheduler tests (modeled on the reference's
tests/python/unittest/test_optimizer.py:? — update math vs numpy
references, multi-precision, trainer integration)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _nd(x):
    return nd.array(np.asarray(x, np.float32))


def test_sgd_matches_numpy():
    w = _nd([1.0, 2.0, 3.0])
    g = _nd([0.1, 0.2, 0.3])
    o = mx.optimizer.SGD(learning_rate=0.5, wd=0.01)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    expect = np.array([1, 2, 3]) - 0.5 * (
        np.array([0.1, 0.2, 0.3]) + 0.01 * np.array([1, 2, 3]))
    assert np.allclose(w.asnumpy(), expect, atol=1e-6)


def test_sgd_momentum():
    w = _nd([1.0])
    g = _nd([1.0])
    o = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    assert np.allclose(w.asnumpy(), [0.9])
    o.update(0, w, g, state)
    # mom = 0.9*(-0.1) - 0.1*1 = -0.19 → w = 0.9 - 0.19 = 0.71
    assert np.allclose(w.asnumpy(), [0.71], atol=1e-6)


def test_sgd_clip_gradient():
    w = _nd([0.0])
    g = _nd([100.0])
    o = mx.optimizer.SGD(learning_rate=1.0, clip_gradient=1.0)
    o.update(0, w, g, o.create_state(0, w))
    assert np.allclose(w.asnumpy(), [-1.0])


def test_sgd_multi_precision():
    w16 = nd.array(np.array([1.0, 2.0]), dtype=np.float16)
    g16 = nd.array(np.array([0.5, 0.5]), dtype=np.float16)
    o = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                         multi_precision=True)
    state = o.create_state_multi_precision(0, w16)
    master, _ = state
    assert master.dtype == np.float32
    o.update_multi_precision(0, w16, g16, state)
    assert w16.dtype == np.float16
    assert np.allclose(master.asnumpy(), [0.95, 1.95], atol=1e-3)


def test_adam_matches_numpy():
    w = _nd([1.0, -1.0])
    g = _nd([0.3, -0.7])
    o = mx.optimizer.Adam(learning_rate=0.01, beta1=0.9, beta2=0.999,
                          epsilon=1e-8)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    m = 0.1 * np.array([0.3, -0.7])
    v = 0.001 * np.array([0.3, -0.7]) ** 2
    lr_t = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expect = np.array([1.0, -1.0]) - lr_t * m / (np.sqrt(v) + 1e-8)
    assert np.allclose(w.asnumpy(), expect, atol=1e-6)


def test_adamw_decoupled_wd():
    w = _nd([1.0])
    g = _nd([0.0])
    o = mx.optimizer.AdamW(learning_rate=0.1, wd=0.1)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    # zero grad → update is pure decoupled decay: w -= lr*wd*w
    assert np.allclose(w.asnumpy(), [1.0 - 0.1 * 0.1 * 1.0], atol=1e-6)


def test_lamb_runs_and_descends():
    w = _nd(np.ones(10))
    o = mx.optimizer.LAMB(learning_rate=0.01)
    state = o.create_state(0, w)
    for _ in range(3):
        g = w * 2.0  # grad of sum(w^2)
        o.update(0, w, g, state)
    assert (w.asnumpy() < 1.0).all()


@pytest.mark.parametrize("name", ["rmsprop", "adagrad", "adadelta", "ftrl",
                                  "signum", "nag", "lars", "signsgd"])
def test_optimizers_descend_quadratic(name):
    o = mx.optimizer.create(name)
    w = _nd(np.linspace(-2, 2, 8))
    state = o.create_state_multi_precision(0, w)
    f0 = float((w * w).sum().asscalar())
    for _ in range(20):
        g = 2.0 * w
        o.update_multi_precision(0, w, g, state)
    f1 = float((w * w).sum().asscalar())
    assert f1 < f0, f"{name}: {f0} -> {f1}"


def test_optimizer_registry_and_create():
    o = mx.optimizer.create("sgd", learning_rate=0.25)
    assert isinstance(o, mx.optimizer.SGD)
    assert o.learning_rate == 0.25
    with pytest.raises(Exception):
        mx.optimizer.create("nope")


def test_lr_scheduler_factor():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25


def test_lr_scheduler_multifactor():
    s = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1,
                                             base_lr=1.0)
    assert s(2) == 1.0
    assert np.isclose(s(6), 0.1)
    assert np.isclose(s(16), 0.01)


def test_lr_scheduler_warmup_cosine():
    s = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0,
                                        final_lr=0.0, warmup_steps=10,
                                        warmup_begin_lr=0.0)
    assert s(0) == 0.0
    assert s(5) == 0.5
    assert np.isclose(s(10), 1.0, atol=1e-6)
    assert np.isclose(s(100), 0.0, atol=1e-6)
    mid = s(55)
    assert 0.4 < mid < 0.6


def test_optimizer_lr_scheduler_integration():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.1, base_lr=1.0)
    o = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = _nd([10.0])
    state = o.create_state(0, w)
    for _ in range(5):
        o.update(0, w, _nd([0.0]), state)
    assert o.learning_rate < 1.0


def test_trainer_converges_linear_regression():
    mx.random.seed(3)
    true_w = np.array([[2.0], [-3.4]])
    x = np.random.randn(64, 2).astype(np.float32)
    y = (x @ true_w + 4.2).astype(np.float32)

    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(60):
        with autograd.record():
            loss = loss_fn(net(nd.array(x)), nd.array(y))
        loss.backward()
        trainer.step(64)
    got_w = net.weight.data().asnumpy().ravel()
    got_b = net.bias.data().asnumpy().ravel()
    assert np.allclose(got_w, true_w.ravel(), atol=0.1)
    assert np.allclose(got_b, [4.2], atol=0.1)


def test_trainer_hybridized_training_step():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu", in_units=4),
                nn.Dense(1, in_units=8))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    x = mx.random.uniform(shape=(16, 4))
    y = x.sum(axis=1, keepdims=True)
    losses = []
    for _ in range(100):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(16)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.3


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = nd.ones((4, 2))
    with autograd.record():
        loss = gluon.loss.L2Loss()(net(x), nd.zeros((4, 2)))
    loss.backward()
    trainer.step(4)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    n_update = trainer.optimizer.num_update

    trainer2 = gluon.Trainer(net.collect_params(), "adam",
                             {"learning_rate": 0.1})
    trainer2.load_states(f)
    assert trainer2.optimizer.num_update == n_update


def test_trainer_kvstore_none():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    with autograd.record():
        loss = net(nd.ones((2, 2))).sum()
    loss.backward()
    w0 = net.weight.data().asnumpy().copy()
    trainer.step(2)
    assert not np.allclose(w0, net.weight.data().asnumpy())


def test_trainer_lr_mult():
    net = nn.Dense(1, in_units=1, use_bias=False)
    net.initialize(mx.init.One())
    net.weight.lr_mult = 0.0
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    with autograd.record():
        loss = net(nd.ones((1, 1))).sum()
    loss.backward()
    trainer.step(1)
    assert np.allclose(net.weight.data().asnumpy(), 1.0)


def test_kvstore_push_pull():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 1)
    kv.push(3, [nd.ones((2, 3)), nd.ones((2, 3)) * 2])
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 3)


def test_kvstore_updater():
    kv = mx.kv.create("device")
    kv.init("w", nd.ones((2,)))

    def updater(key, grad, weight):
        weight -= 0.1 * grad

    kv.set_updater(updater)
    kv.push("w", nd.ones((2,)))
    out = nd.zeros((2,))
    kv.pull("w", out=out)
    assert np.allclose(out.asnumpy(), 0.9)


def test_kvstore_row_sparse_pull():
    from mxnet_tpu.ndarray import sparse as sp

    kv = mx.kv.create("local")
    kv.init(0, nd.arange(0, 12).reshape((4, 3)))
    out = nd.zeros((4, 3))
    kv.row_sparse_pull(0, out=out, row_ids=nd.array([1, 3]))
    got = out.asnumpy()
    assert np.allclose(got[1], [3, 4, 5])
    assert np.allclose(got[0], 0)


def test_sparse_sgd_lazy_update():
    from mxnet_tpu.ndarray import sparse as sp

    w = nd.ones((4, 2))
    grad = sp.RowSparseNDArray(nd.ones((1, 2)), nd.array([2]), (4, 2))
    o = mx.optimizer.SGD(learning_rate=0.5)
    o.update(0, w, grad, o.create_state(0, w))
    got = w.asnumpy()
    assert np.allclose(got[2], 0.5 - 0.0)  # 1 - 0.5*1
    assert np.allclose(got[0], 1.0)
