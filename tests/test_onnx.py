"""ONNX export tests: structural round trip through the bundled
wire-format decoder (reference model: tests/python/unittest/onnx/ export
tests, SURVEY §2.4 onnx row)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.symbol as sym
from mxnet_tpu import nd
from mxnet_tpu.contrib.onnx import export_model
from mxnet_tpu.contrib.onnx import _proto as P


def _mlp():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.softmax(net, name="out")
    return net


def _params_for(net, data_shape):
    shapes, _, aux_shapes = net.infer_shape(data=data_shape)
    out = {n: nd.random.uniform(-1, 1, shape=s)
           for n, s in zip(net.list_arguments(), shapes) if n != "data"}
    for n, s in zip(net.list_auxiliary_states(), aux_shapes):
        out[n] = nd.ones(s) if n.endswith("var") else nd.zeros(s)
    return out


def _model_fields(path):
    with open(path, "rb") as f:
        model = P.parse(f.read())
    return model


def test_export_mlp_structure(tmp_path):
    net = _mlp()
    params = _params_for(net, (2, 8))
    path = str(tmp_path / "mlp.onnx")
    export_model(net, params, [(2, 8)], onnx_file_path=path)
    model = _model_fields(path)
    # ModelProto: ir_version(1), producer(2), graph(7), opset(8)
    assert P.fields(model, 1)[0] == 8
    assert P.fields(model, 2)[0] == b"mxnet_tpu"
    opset = P.parse(P.fields(model, 8)[0])
    assert P.fields(opset, 2)[0] == 13
    graph = P.parse(P.fields(model, 7)[0])
    node_bufs = P.fields(graph, 1)
    ops = []
    for nb in node_bufs:
        nproto = P.parse(nb)
        ops.append(P.fields(nproto, 4)[0].decode())
    # fc → Flatten+Gemm each; relu; softmax
    assert ops == ["Flatten", "Gemm", "Relu", "Flatten", "Gemm",
                   "Softmax"]
    # initializers carry the 4 param tensors with raw data
    inits = P.fields(graph, 5)
    assert len(inits) == 4
    t0 = P.parse(inits[0])
    name = P.fields(t0, 8)[0].decode()
    assert name in params
    raw = P.fields(t0, 9)[0]
    want = params[name].asnumpy()
    got = onp.frombuffer(raw, onp.float32).reshape(want.shape)
    onp.testing.assert_allclose(got, want, rtol=1e-6)
    # one graph input (data), one output
    assert len(P.fields(graph, 11)) == 1
    assert len(P.fields(graph, 12)) == 1
    vin = P.parse(P.fields(graph, 11)[0])
    assert P.fields(vin, 1)[0] == b"data"


def test_export_conv_net(tmp_path):
    data = sym.var("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          name="conv1")
    net = sym.BatchNorm(net, name="bn1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max",
                      name="pool1")
    net = sym.Pooling(net, global_pool=True, pool_type="avg", name="gap")
    net = sym.Flatten(net, name="flat")
    net = sym.FullyConnected(net, num_hidden=2, name="fc")
    params = _params_for(net, (1, 3, 8, 8))
    path = str(tmp_path / "conv.onnx")
    export_model(net, params, [(1, 3, 8, 8)], onnx_file_path=path)
    graph = P.parse(P.fields(_model_fields(path), 7)[0])
    ops = [P.fields(P.parse(nb), 4)[0].decode()
           for nb in P.fields(graph, 1)]
    assert ops[0] == "Conv"
    assert "BatchNormalization" in ops
    assert "MaxPool" in ops and "GlobalAveragePool" in ops
    # conv node carries kernel/pads/strides attrs
    conv_attrs = {}
    for ab in P.fields(P.parse(P.fields(graph, 1)[0]), 5):
        ap = P.parse(ab)
        conv_attrs[P.fields(ap, 1)[0].decode()] = ap
    assert {"kernel_shape", "strides", "pads",
            "group"} <= set(conv_attrs)


def test_export_rejects_unknown_op(tmp_path):
    data = sym.var("data")
    net = sym.topk(data, k=2, name="t")
    with pytest.raises(mx.MXNetError):
        export_model(net, {}, [(2, 8)],
                     onnx_file_path=str(tmp_path / "x.onnx"))


def _forward(net, params, x):
    """Bind + forward a symbol with given params (numpy in/out)."""
    shapes = {"data": x.shape}
    ex = net.simple_bind(grad_req="null", **shapes)
    ex.copy_params_from({**params, "data": nd.array(x)})
    return ex.forward()[0].asnumpy()


def test_import_roundtrip_mlp(tmp_path):
    """export → import → numerically identical forward."""
    from mxnet_tpu.contrib.onnx import import_model

    net = _mlp()
    params = _params_for(net, (2, 8))
    path = str(tmp_path / "mlp.onnx")
    export_model(net, params, [(2, 8)], onnx_file_path=path)

    sym2, args2, aux2 = import_model(path)
    x = onp.random.RandomState(0).randn(2, 8).astype(onp.float32)
    ref = _forward(net, params, x)
    got = _forward(sym2, {**args2, **aux2}, x)
    onp.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_import_roundtrip_conv_bn_pool(tmp_path):
    from mxnet_tpu.contrib.onnx import import_model

    data = sym.var("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          name="c1")
    net = sym.BatchNorm(net, fix_gamma=False, name="bn1")
    net = sym.Activation(net, act_type="relu", name="r1")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max",
                      name="p1")
    net = sym.Pooling(net, kernel=(1, 1), global_pool=True,
                      pool_type="avg", name="gp")
    net = sym.Flatten(net, name="fl")
    net = sym.FullyConnected(net, num_hidden=5, name="fc")
    params = _params_for(net, (2, 3, 8, 8))
    path = str(tmp_path / "cnn.onnx")
    export_model(net, params, [(2, 3, 8, 8)], onnx_file_path=path)

    sym2, args2, aux2 = import_model(path)
    x = onp.random.RandomState(1).randn(2, 3, 8, 8).astype(onp.float32)
    ref = _forward(net, params, x)
    got = _forward(sym2, {**args2, **aux2}, x)
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # moving stats land in aux, matching reference convention
    assert any("mean" in k for k in aux2), sorted(aux2)


def test_import_unknown_op_raises(tmp_path):
    from mxnet_tpu.contrib.onnx import import_model
    from mxnet_tpu.contrib.onnx.mx2onnx import _node, _value_info

    graph = P.fbytes(1, _node("NotARealOp", ["data"], ["y"], "n0"))
    graph += P.fbytes(11, _value_info("data", (1,)))
    graph += P.fbytes(12, _value_info("y", (1,)))
    model = P.fint(1, 8) + P.fbytes(7, graph)
    path = tmp_path / "bad.onnx"
    path.write_bytes(model)
    with pytest.raises(mx.MXNetError):
        import_model(str(path))


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2 ** 32, 2 ** 60):
        buf = P.fint(3, v)
        parsed = P.parse(buf)
        assert parsed == [(3, 0, v)]


def _encoder_layer():
    """A real transformer encoder layer built from the NLP subset ops
    (round 4): embedding -> self-attention (batch_dot QK^T, scaled
    softmax, batch_dot AV) -> residual + LayerNorm -> GELU FFN ->
    residual + LayerNorm -> vocab head."""
    H, HEADS = 16, 2
    ids = sym.var("data")
    h = sym.Embedding(ids, input_dim=32, output_dim=H, name="embed")

    q = sym.FullyConnected(h, num_hidden=H, flatten=False, no_bias=True,
                           name="q")
    k = sym.FullyConnected(h, num_hidden=H, flatten=False, no_bias=True,
                           name="k")
    v = sym.FullyConnected(h, num_hidden=H, flatten=False, no_bias=True,
                           name="v")

    def heads(t, tag):
        t = sym.Reshape(t, shape=(2, 6, HEADS, H // HEADS),
                        name=f"{tag}_split")
        return sym.transpose(t, axes=(0, 2, 1, 3), name=f"{tag}_bhtd")

    qh, kh = heads(q, "qh"), heads(k, "kh")
    vh = heads(v, "vh")
    kt = sym.transpose(kh, axes=(0, 1, 3, 2), name="kT")
    scores = sym.batch_dot(qh, kt, name="scores")
    scaled = sym.broadcast_div(
        scores, sym.sqrt(sym.var("scale"), name="sq"), name="scaled")
    att = sym.softmax(scaled, axis=-1, name="att")
    ctx = sym.batch_dot(att, vh, name="ctx")
    ctx = sym.transpose(ctx, axes=(0, 2, 1, 3), name="ctx_btHd")
    ctx = sym.Reshape(ctx, shape=(2, 6, H), name="ctx_merge")
    proj = sym.FullyConnected(ctx, num_hidden=H, flatten=False,
                              no_bias=True, name="proj")

    res1 = sym.broadcast_add(h, proj, name="res1")
    ln1 = sym.LayerNorm(res1, name="ln1")
    ffn1 = sym.FullyConnected(ln1, num_hidden=2 * H, flatten=False,
                              name="ffn1")
    gelu = sym.LeakyReLU(ffn1, act_type="gelu", name="gelu")
    ffn2 = sym.FullyConnected(gelu, num_hidden=H, flatten=False,
                              name="ffn2")
    res2 = sym.broadcast_add(ln1, ffn2, name="res2")
    out = sym.LayerNorm(res2, name="ln2")
    return sym.softmax(out, axis=-1, name="probs")


def test_export_import_transformer_encoder(tmp_path):
    """The NLP-subset round trip (VERDICT r3 weak 8): a transformer
    encoder layer — Embedding/attention batch_dots/LayerNorm/GELU —
    exports to opset-13 ONNX and re-imports numerically identical."""
    from mxnet_tpu.contrib.onnx import import_model

    net = _encoder_layer()
    H = 16
    shapes = {"embed_weight": (32, H),
              "q_weight": (H, H), "k_weight": (H, H),
              "v_weight": (H, H), "proj_weight": (H, H),
              "ln1_gamma": (H,), "ln1_beta": (H,),
              "ffn1_weight": (2 * H, H), "ffn1_bias": (2 * H,),
              "ffn2_weight": (H, 2 * H), "ffn2_bias": (H,),
              "ln2_gamma": (H,), "ln2_beta": (H,)}
    rs = onp.random.RandomState(0)
    params = {"scale": nd.array(onp.asarray([8.0], onp.float32))}
    for n, s in shapes.items():
        init = onp.ones(s) if n.endswith("gamma") else \
            (rs.randn(*s) * 0.3)
        params[n] = nd.array(init.astype(onp.float32))
    assert set(params) | {"data"} == set(net.list_arguments()), \
        sorted(net.list_arguments())
    path = str(tmp_path / "encoder.onnx")
    export_model(net, params, [(2, 6)], onnx_file_path=path)

    sym2, args2, aux2 = import_model(path)
    ids = rs.randint(0, 32, (2, 6)).astype(onp.float32)

    def fwd(s, p):
        # free variables (scale; imported Constant scalars like the
        # LayerNorm eps) have no inferable shape — hand them all in
        kw = {n: tuple(onp.asarray(a.asnumpy()).shape)
              for n, a in p.items()}
        ex = s.simple_bind(grad_req="null", data=(2, 6), **kw)
        ex.copy_params_from({**p, "data": nd.array(ids)})
        return ex.forward()[0].asnumpy()

    ref = fwd(net, params)
    got = fwd(sym2, {**args2, **aux2})
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_bert_onnx_export_roundtrip(tmp_path):
    """The NLP zoo exports (VERDICT r3 weak 8, closed): a trained gluon
    BERT -> symbol graph bound to the SAME parameters
    (models.bert.bert_to_symbol) -> ONNX -> re-import, with all four
    heads (sequence, pooled, NSP, MLM) numerically matching the gluon
    inference forward."""
    import mxnet_tpu as mx
    from mxnet_tpu.contrib.onnx import import_model
    from mxnet_tpu.models import bert

    mx.random.seed(0)
    net = bert.bert_tiny(vocab_size=50, dropout=0.0)
    net.initialize(mx.init.Xavier())
    rs = onp.random.RandomState(0)
    B, T = 2, 12
    ids = nd.array(rs.randint(0, 50, (B, T)), dtype="int32")
    seg = nd.array(rs.randint(0, 2, (B, T)), dtype="int32")
    ref = [o.asnumpy() for o in net(ids, seg)]

    path = str(tmp_path / "bert.onnx")
    bert.export_bert_onnx(net, path, batch=B, seq_len=T)

    sym2, args2, aux2 = import_model(path)
    p = {**args2, **aux2}
    kw = {n: tuple(onp.asarray(a.asnumpy()).shape) for n, a in p.items()}
    ex = sym2.simple_bind(grad_req="null", data0=(B, T), data1=(B, T),
                          **kw)
    ex.copy_params_from({**p, "data0": ids, "data1": seg})
    got = [o.asnumpy() for o in ex.forward()]
    assert len(got) == len(ref) == 4
    for g, r in zip(got, ref):
        onp.testing.assert_allclose(g, r, rtol=2e-4, atol=2e-5)


def test_export_scalar_op_dtype_tracking(tmp_path):
    """Non-float32 graphs with scalar arithmetic (ADVICE r4): constants
    are emitted in the tracked operand dtype, integer operands get the
    runtime's promote-to-f32 Cast (true division, never ONNX int
    truncation), and `where` follows its BRANCH dtype, not the
    condition's."""
    from mxnet_tpu.contrib.onnx import import_model

    # int32 / 2 == true division through export+import
    path = str(tmp_path / "i32div.onnx")
    export_model(sym.var("data") / 2.0, {}, [(1, 4)],
                 input_types=["int32"], onnx_file_path=path)
    s, a, _ = import_model(path)
    exe = s.bind(mx.cpu(), {"data": nd.array(
        onp.array([[5, 2, 7, 9]], dtype="int32"), dtype="int32"), **a})
    got = exe.forward()[0].asnumpy()
    assert got.ravel().tolist() == [2.5, 1.0, 3.5, 4.5], got

    # fractional scalar on an int operand exports via the Cast path
    path2 = str(tmp_path / "i32mul.onnx")
    export_model(sym.var("data") * 0.5, {}, [(1, 3)],
                 input_types=["int32"], onnx_file_path=path2)
    s2, a2, _ = import_model(path2)
    exe2 = s2.bind(mx.cpu(), {"data": nd.array(
        onp.array([[1, 3, 5]], dtype="int32"), dtype="int32"), **a2})
    assert exe2.forward()[0].asnumpy().ravel().tolist() == [0.5, 1.5, 2.5]

    # where(mask:int32, x:f32, y:f32) * 0.5 — branch dtype wins
    m, xx, yy = sym.var("mask"), sym.var("x"), sym.var("y")
    path3 = str(tmp_path / "where.onnx")
    export_model(sym.where(m, xx, yy) * 0.5, {}, [(2, 2)] * 3,
                 input_types=["int32", "float32", "float32"],
                 onnx_file_path=path3)
    s3, a3, _ = import_model(path3)
    exe3 = s3.bind(mx.cpu(), {
        "mask": nd.array(onp.array([[1, 0], [0, 1]], dtype="int32"),
                         dtype="int32"),
        "x": nd.array(onp.full((2, 2), 4.0, dtype="float32")),
        "y": nd.array(onp.full((2, 2), 8.0, dtype="float32")), **a3})
    assert exe3.forward()[0].asnumpy().ravel().tolist() == \
        [2.0, 4.0, 4.0, 2.0]


def test_import_clip_absent_bounds(tmp_path):
    """ONNX Clip with no min/max inputs is an identity: legitimate
    extreme float32 values (inside (3.4e38, f32max]) pass through
    unclipped (ADVICE r4)."""
    from mxnet_tpu.contrib.onnx import import_model
    from mxnet_tpu.contrib.onnx import mx2onnx as M

    nodes = [M._node("Relu", ["data"], ["r0"], "r0"),
             M._node("Clip", ["r0"], ["out"], "out")]
    graph = b"".join(P.fbytes(1, nb) for nb in nodes)
    graph += P.fstr(2, "clip_test")
    graph += P.fbytes(11, M._value_info("data", (1, 2), P.FLOAT))
    graph += P.fbytes(12, M._value_info("out", (1, 2), P.FLOAT))
    model = P.fint(1, M._IR_VERSION) + P.fstr(2, "t") + P.fstr(3, "0")
    model += P.fbytes(7, graph) + P.fbytes(8, P.fint(2, M._OPSET))
    path = str(tmp_path / "clip.onnx")
    with open(path, "wb") as f:
        f.write(model)
    s, a, _ = import_model(path)
    big = float(onp.float32(3.402e38))
    exe = s.bind(mx.cpu(), {"data": nd.array(
        onp.array([[big, -5.0]], dtype="float32")), **a})
    got = exe.forward()[0].asnumpy()
    assert got[0, 0] == onp.float32(big), got
    assert got[0, 1] == 0.0
