"""Runtime recompile sanitizer: warmup semantics, warn/raise modes,
structural signature diffing, the pinned injected-retrace vectors at the
instrumented compile sites (CachedOp aval divergence, trainer fused
closure attr), env wiring, the dp2 CPU-mesh serving lane staying
violation-free under raise, the disabled-path cost bound, and the
provenance reporter + site-stamped cost registry."""
import json
import os
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import gluon, nd, serving, telemetry
from mxnet_tpu.telemetry import retrace
from mxnet_tpu.telemetry.sinks import ListSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_retrace():
    retrace.disable()
    retrace.reset()
    yield
    retrace.disable()
    retrace.reset()
    telemetry.disable()
    telemetry.reset()


# --- structural differ -------------------------------------------------------

def test_diff_names_the_exact_aval_field():
    old = {"args": (((2, 8), "float32", False),), "mesh": None}
    new = {"args": (((3, 8), "float32", False),), "mesh": None}
    assert retrace.diff_components(old, new) == \
        ["args[0].shape: (2, 8) -> (3, 8)"]
    new = {"args": (((2, 8), "bfloat16", False),), "mesh": None}
    assert retrace.diff_components(old, new) == \
        ["args[0].dtype: 'float32' -> 'bfloat16'"]
    new = {"args": (((2, 8), "float32", True),), "mesh": None}
    assert retrace.diff_components(old, new) == \
        ["args[0].weak_type: False -> True"]


def test_diff_scalar_attrs_and_absent_keys():
    d = retrace.diff_components({"rescale_grad": 1.0, "mesh": None},
                                {"rescale_grad": 0.5, "mesh": "dp2"})
    assert "rescale_grad: 1.0 -> 0.5" in d
    assert "mesh: None -> 'dp2'" in d
    d = retrace.diff_components({"a": 1}, {"a": 1, "b": 2})
    assert d == ["b: <absent> -> 2"]


def test_diff_canonicalizes_json_round_trip():
    # JSONL round-trips turn tuples into lists; the differ must see them
    # as structurally equal
    old = {"args": (((2, 8), "float32", False),)}
    new = {"args": [[[2, 8], "float32", False]]}
    assert retrace.diff_components(old, new) == []


def test_cachedop_components_decomposition():
    sig = ((((2, 8), "float32", False),), True, "cpu", (), None, "n0")
    comps = retrace.cachedop_components(sig)
    assert comps == {"args": sig[0], "training": True, "platform": "cpu",
                     "params": (), "mesh": None, "numerics": "n0"}
    assert retrace.cachedop_components("odd") == {"signature": "odd"}


# --- warmup semantics --------------------------------------------------------

def test_first_signature_is_never_a_violation():
    retrace.enable("raise")
    retrace.warm()
    assert retrace.observe("k", 1, {"a": 1}, site="s") is None
    assert retrace.violations() == []
    assert retrace.sites() == {("k", 1): 1}


def test_prewarm_signatures_are_baselines():
    retrace.enable("raise")
    retrace.observe("k", 1, {"a": 1}, site="s")
    retrace.observe("k", 1, {"a": 2}, site="s")   # pre-warm: silent
    assert retrace.violations() == []
    retrace.warm()
    with pytest.raises(retrace.RetraceError):
        retrace.observe("k", 1, {"a": 3}, site="s")
    assert len(retrace.violations()) == 1


def test_replayed_signature_is_not_new():
    retrace.enable("raise")
    retrace.warm()
    retrace.observe("k", 1, {"a": 1}, site="s")
    # a concurrent miss racing a replay re-observes the same components
    assert retrace.observe("k", 1, {"a": 1}, site="s") is None
    assert retrace.sites() == {("k", 1): 1}
    assert retrace.violations() == []


def test_violation_diffs_against_nearest_prior_signature():
    retrace.enable("warn")
    retrace.observe("k", 1, {"a": 1, "b": 1}, site="s")
    retrace.observe("k", 1, {"a": 9, "b": 9}, site="s")
    retrace.warm()
    with pytest.warns(RuntimeWarning):
        retrace.observe("k", 1, {"a": 1, "b": 2}, site="s")
    (v,) = retrace.violations()
    # one field away from signature #0, two away from #1
    assert v["against"]["signature_index"] == 0
    assert v["diff"] == ["b: 1 -> 2"]
    assert v["signature_index"] == 2


def test_warn_mode_warns_raise_mode_raises():
    retrace.enable("warn")
    retrace.warm()
    retrace.observe("k", 1, {"a": 1}, site="mod:site")
    with pytest.warns(RuntimeWarning, match="retrace at mod:site"):
        retrace.observe("k", 1, {"a": 2}, site="mod:site")
    retrace.enable("raise")
    with pytest.raises(retrace.RetraceError) as ei:
        retrace.observe("k", 1, {"a": 3}, site="mod:site")
    msg = str(ei.value)
    assert "retrace at mod:site" in msg
    assert "a: " in msg and "-> 3" in msg
    assert "test_retrace.py" in msg         # python provenance both ways
    assert "diverged from signature #" in msg


def test_warmup_steps_counted_at_telemetry_step_boundaries():
    retrace.enable("warn", warmup_steps=2)
    telemetry.enable()
    assert not retrace.is_warm()
    with telemetry.step():
        pass
    assert not retrace.is_warm()
    with telemetry.step():
        pass
    assert retrace.is_warm()


def test_reset_keeps_mode_but_forgets_history():
    retrace.enable("raise")
    retrace.warm()
    retrace.observe("k", 1, {"a": 1}, site="s")
    retrace.reset()
    assert retrace.is_enabled() and not retrace.is_warm()
    assert retrace.sites() == {}
    # the same site starts over: first signature, no violation
    retrace.warm()
    assert retrace.observe("k", 1, {"a": 2}, site="s") is None


# --- injected retraces at the instrumented sites (pinned vectors) -----------

def test_injected_cachedop_retrace_names_site_and_aval():
    """The acceptance vector: an injected batch-shape change after
    warmup raises a RetraceError naming the CachedOp compile site AND
    the exact diverging aval component."""
    retrace.enable("raise")
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    net(nd.ones((2, 8))).wait_to_read()       # baseline signature
    retrace.warm()
    net(nd.ones((2, 8))).wait_to_read()       # replay: no new compile
    with pytest.raises(retrace.RetraceError) as ei:
        net(nd.ones((3, 8)))
    msg = str(ei.value)
    assert "mxnet_tpu.gluon.block:CachedOp.__call__" in msg
    assert "args[0].shape: (2, 8) -> (3, 8)" in msg
    assert "test_retrace.py" in msg
    (v,) = retrace.violations()
    assert v["kind"] == "cachedop"
    assert v["diff"] == ["args[0].shape: (2, 8) -> (3, 8)"]


def test_injected_trainer_closure_attr_retrace():
    """The closure-attr vector: a changed batch size silently rewrites
    ``optimizer.rescale_grad`` — the fused update retraces and the error
    names that exact attribute with both values."""
    retrace.enable("raise")
    net = gluon.nn.Dense(4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.ones((2, 8))

    def one_step(batch_size):
        with ag.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(batch_size)

    one_step(2)                               # rescale_grad = 0.5
    retrace.warm()
    one_step(2)                               # replay
    with pytest.raises(retrace.RetraceError) as ei:
        one_step(4)                           # rescale_grad -> 0.25
    msg = str(ei.value)
    assert "Trainer._try_fused_update" in msg
    assert "rescale_grad: 0.5 -> 0.25" in msg


def test_trainer_e2e_lane_raise_clean():
    """MXNET_SANITIZE_RETRACE=raise trainer lane: a well-bucketed
    training loop (constant batch schema) runs post-warmup with ZERO
    retraces — warmup declared by step count at telemetry boundaries."""
    retrace.enable("raise", warmup_steps=2)
    telemetry.enable()
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1e-2})
    rs = np.random.RandomState(0)
    xb = nd.array(rs.randn(8, 8).astype(np.float32))
    yb = nd.array(rs.randn(8, 4).astype(np.float32))
    for i in range(5):
        with telemetry.step():
            with ag.record():
                loss = ((net(xb) - yb) ** 2).mean()
            loss.backward()
            trainer.step(8)
            loss.wait_to_read()
        assert retrace.is_warm() == (i >= 1)
    assert retrace.violations() == []
    counts = retrace.sites()
    assert any(k == "cachedop" for k, _ in counts)
    assert all(n == 1 for n in counts.values())


@pytest.mark.slow
def test_serving_dp2_mesh_lane_violation_free():
    """dp2 CPU-mesh generative serving under raise mode: after the
    bucket-warming requests, a steady stream of same-bucket requests
    compiles nothing new on either replica."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.models.llama import llama_tiny
    from mxnet_tpu.serving import ServerConfig

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices (dp2)")
    retrace.enable("raise")
    net = llama_tiny()
    net.initialize()
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2)
    rs = np.random.RandomState(7)
    sizes = (5, 9, 5, 9)
    prompts = [rs.randint(1, 250, size=n) for n in sizes]
    with serving.GenerativeServer(net, cfg, mesh=mesh) as srv:
        # warmup: touch both prompt-length buckets on the routed replica
        for p in prompts[:2]:
            srv.generate(p, max_new_tokens=4)
        retrace.warm()
        # steady state: same buckets — a first compile on the OTHER
        # replica is a first signature (new program), never a retrace
        futs = [srv.submit(p, max_new_tokens=4) for p in prompts[2:]]
        for f in futs:
            f.result(120)
    assert retrace.violations() == []
    assert any(k.startswith("serving_") for k, _ in retrace.sites())


# --- null path ---------------------------------------------------------------

def test_disabled_observe_is_inert_and_cheap():
    assert not retrace.is_enabled()
    assert retrace.observe("k", 1, {"a": 1}, site="s") is None
    assert retrace.sites() == {}
    # the instrumented pattern at every site: one module attribute load
    # behind an already-rare miss branch — 10k iterations must be
    # unmeasurable next to any real dispatch
    t0 = time.perf_counter()
    for _ in range(10_000):
        if retrace._enabled:        # pragma: no cover - disabled path
            retrace.observe("k", 1, {"a": 1}, site="s")
    dt = time.perf_counter() - t0
    assert dt < 0.25, f"disabled retrace guard cost {dt:.3f}s / 10k"


def test_history_and_violation_caps():
    retrace.enable("warn")
    retrace.warm()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for i in range(300):
            retrace.observe("k", 1, {"a": i}, site="s")
    assert retrace.sites()[("k", 1)] <= 64
    assert len(retrace.violations()) <= 256


# --- env wiring --------------------------------------------------------------

def test_env_raise_mode_wires_through_subprocess():
    code = (
        "from mxnet_tpu.telemetry import retrace\n"
        "assert retrace.is_enabled()\n"
        "assert retrace._mode == 'raise'\n"
        "assert retrace._warmup_steps == 3\n"
        "retrace.warm()\n"
        "retrace.observe('k', 1, {'a': 1}, site='env.site')\n"
        "retrace.observe('k', 1, {'a': 2}, site='env.site')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_SANITIZE_RETRACE="raise",
               MXNET_SANITIZE_RETRACE_WARMUP="3")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, cwd=REPO, timeout=120)
    assert r.returncode != 0
    assert "RetraceError" in r.stderr
    assert "retrace at env.site" in r.stderr
    assert "a: 1 -> 2" in r.stderr


def test_env_warn_and_off_modes_wire_through_subprocess():
    code = (
        "import os, warnings\n"
        "from mxnet_tpu.telemetry import retrace\n"
        "mode = os.environ.get('MXNET_SANITIZE_RETRACE', '')\n"
        "if mode == 'warn':\n"
        "    assert retrace.is_enabled() and retrace._mode == 'warn'\n"
        "    retrace.warm()\n"
        "    retrace.observe('k', 1, {'a': 1}, site='env.site')\n"
        "    with warnings.catch_warnings(record=True) as w:\n"
        "        warnings.simplefilter('always')\n"
        "        retrace.observe('k', 1, {'a': 2}, site='env.site')\n"
        "    assert len(w) == 1 and 'a: 1 -> 2' in str(w[0].message)\n"
        "    assert len(retrace.violations()) == 1\n"
        "else:\n"
        "    assert not retrace.is_enabled()\n"
        "    assert retrace.observe('k', 1, {'a': 1}) is None\n"
        "print('OK')\n"
    )
    for mode in ("warn", "off"):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MXNET_SANITIZE_RETRACE=mode)
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           cwd=REPO, timeout=120)
        assert r.returncode == 0, (mode, r.stderr[-2000:])
        assert "OK" in r.stdout


def test_telemetry_enable_retrace_flag():
    telemetry.enable(retrace="raise")
    assert retrace.is_enabled() and retrace._mode == "raise"


# --- observability: JSONL records + flight recorder + reporter ---------------

def _seed_records():
    """One baseline and one violation through the real emit path;
    returns the retrace records the sink saw."""
    telemetry.enable()
    sink = ListSink()
    telemetry.add_sink(sink)
    retrace.enable("warn")
    retrace.warm()
    retrace.observe("k", 7, {"x": ((2, 8), "float32", False), "m": None},
                    site="mod:site")
    with pytest.warns(RuntimeWarning):
        retrace.observe("k", 7,
                        {"x": ((3, 8), "float32", False), "m": None},
                        site="mod:site")
    return [r for r in sink.records if r.get("record") == "retrace"]


def test_jsonl_records_schema():
    recs = _seed_records()
    assert [r["action"] for r in recs] == ["baseline", "warn"]
    base, viol = recs
    for r in recs:
        assert r["site"] == "mod:site" and r["kind"] == "k"
        assert r["instance"] == 7
        assert isinstance(r["where"], str) and "step" in r
        assert isinstance(r["components"], dict)
    assert base["signature_index"] == 0 and "diff" not in base
    assert viol["signature_index"] == 1
    assert viol["diff"] == ["x.shape: (2, 8) -> (3, 8)"]
    assert viol["against"]["signature_index"] == 0
    # components are JSON-clean (lists, not reprs of tuples)
    json.dumps(recs)


def test_violations_feed_the_flight_recorder(tmp_path, monkeypatch):
    dump = str(tmp_path / "flight.json")
    monkeypatch.setenv("MXNET_FLEET_DUMP", dump)
    telemetry.enable()
    telemetry.fleet.clear()
    telemetry.fleet.enable()
    try:
        retrace.enable("warn")
        retrace.warm()
        retrace.observe("k", 1, {"a": 1}, site="mod:site")
        with pytest.warns(RuntimeWarning):
            retrace.observe("k", 1, {"a": 2}, site="mod:site")
    finally:
        telemetry.fleet.disable()
        telemetry.fleet.clear()
    assert os.path.exists(dump)
    doc = json.loads(open(dump).read())
    assert doc["reason"] == "retrace"
    assert doc["context"]["record"] == "retrace"
    assert doc["context"]["diff"] == ["a: 1 -> 2"]


def test_retrace_report_timeline_and_diff(tmp_path, capsys):
    from tools import retrace_report

    recs = _seed_records()
    path = tmp_path / "telemetry.jsonl"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        f.write('{"record": "step", "step": 1}\n')   # mixed stream is fine

    assert retrace_report.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "mod:site" in out
    assert "! sig #1" in out and "baseline" in out
    assert "x.shape: (2, 8) -> (3, 8)" in out

    assert retrace_report.main([str(path), "--site", "mod",
                               "--diff", "0", "1"]) == 0
    out = capsys.readouterr().out
    assert "sig #0 -> sig #1" in out
    assert "x.shape: (2, 8) -> (3, 8)" in out

    # --violations filters baseline-only sites out entirely
    clean = tmp_path / "clean.jsonl"
    with open(clean, "w") as f:
        f.write(json.dumps(dict(recs[0], action="baseline")) + "\n")
    assert retrace_report.main([str(clean), "--violations"]) == 1


def test_retrace_report_reads_flight_dump(tmp_path):
    from tools.retrace_report import load_records

    ctx = {"record": "retrace", "action": "warn", "site": "mod:site",
           "kind": "k", "instance": 1, "where": "w", "step": 3,
           "signature_index": 1, "components": {"a": 2},
           "diff": ["a: 1 -> 2"],
           "against": {"signature_index": 0, "where": "w", "step": 1}}
    dump = tmp_path / "flight.json"
    dump.write_text(json.dumps({"record": "flight_recorder",
                                "reason": "retrace", "context": ctx,
                                "records": []}))
    assert load_records(str(dump)) == [ctx]


# --- cost registry site field ------------------------------------------------

def test_costs_registry_carries_site_and_old_dumps_parse(tmp_path):
    from mxnet_tpu.telemetry import costs
    from tools.bytes_breakdown import registry_breakdown

    telemetry.enable()
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    net(nd.ones((2, 8))).wait_to_read()
    art = [a for a in costs.snapshot() if a["kind"] == "cachedop"][0]
    assert art["site"] == "mxnet_tpu.gluon.block:CachedOp.__call__"

    path = str(tmp_path / "COSTS.json")
    costs.dump(path)
    payload = json.loads(open(path).read())
    bd = registry_breakdown(payload, top=5)
    assert bd["top"][0]["site"]

    # a pre-site registry dump (older writer) must keep parsing, the
    # site column reading None
    for e in payload["entries"]:
        e.pop("site", None)
    old = tmp_path / "OLD_COSTS.json"
    old.write_text(json.dumps(payload))
    bd = registry_breakdown(json.loads(old.read_text()), top=5)
    assert bd["n_artifacts"] >= 1
    assert all(r["site"] is None for r in bd["top"])
