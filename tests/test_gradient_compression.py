"""Gradient compression tests (reference model:
tests/python/unittest/test_kvstore.py gradient compression cases +
tests/nightly/dist_sync_kvstore.py compressed rounds, SURVEY §4)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore import gradient_compression as gc


def test_compress_decompress_values():
    comp = gc.GradientCompression(threshold=0.5)
    g = nd.array([0.6, -0.7, 0.1, 0.0, -0.2, 1.5])
    packed, res = comp.compress(g)
    assert packed.dtype == onp.uint32
    assert packed.shape == (1,)  # 6 codes pack into one word
    out = comp.decompress(packed, (6,)).asnumpy()
    onp.testing.assert_allclose(out, [0.5, -0.5, 0, 0, 0, 0.5])
    # residual keeps what wasn't sent
    onp.testing.assert_allclose(res.asnumpy(),
                                [0.1, -0.2, 0.1, 0, -0.2, 1.0], atol=1e-6)


def test_error_feedback_accumulates():
    comp = gc.GradientCompression(threshold=0.5)
    g = nd.array([0.3])
    out1, res = comp.roundtrip(g)
    assert out1.asnumpy()[0] == 0.0          # below threshold: nothing sent
    out2, res = comp.roundtrip(g, res)
    assert out2.asnumpy()[0] == 0.5          # residual pushed it over
    onp.testing.assert_allclose(res.asnumpy(), [0.1], atol=1e-6)


def test_wire_size_16x():
    comp = gc.GradientCompression()
    g = nd.random.uniform(-1, 1, shape=(1024,))
    packed, _ = comp.compress(g)
    assert packed.shape == (64,)  # 1024 / 16


def test_kvstore_compressed_push():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(3, nd.zeros((4,)))
    kv.push(3, nd.array([1.0, -1.0, 0.2, 0.0]))
    out = nd.zeros((4,))
    kv.pull(3, out=out)
    onp.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
    # second push: residuals (0.5, -0.5, 0.2) carry forward
    kv.push(3, nd.array([0.0, 0.0, 0.2, 0.0]))
    kv.pull(3, out=out)
    onp.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])


def test_trainer_with_compression_converges():
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    net = nn.Dense(1, in_units=4)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(
        net.collect_params(), "sgd", {"learning_rate": 0.2},
        compression_params={"type": "2bit", "threshold": 0.1})
    x = nd.random.uniform(-1, 1, shape=(64, 4))
    w_true = nd.array([[0.8, -0.6, 0.5, 0.7]])
    y = nd.dot(x, nd.transpose(w_true))
    first, best = None, float("inf")
    # fixed ±threshold kicks oscillate near the optimum (inherent to the
    # reference algorithm), so assert on the best loss along the way
    for i in range(100):
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        trainer.step(1)
        cur = float(loss.asscalar())
        first = first if first is not None else cur
        best = min(best, cur)
    assert best < first * 0.1


def test_bad_params_rejected():
    import pytest

    with pytest.raises(mx.MXNetError):
        gc.create({"type": "1bit"})
    with pytest.raises(mx.MXNetError):
        gc.create({"type": "2bit", "bogus": 1})
    with pytest.raises(mx.MXNetError):
        gc.GradientCompression(threshold=-1)
