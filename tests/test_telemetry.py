"""Telemetry subsystem: spans, counters, step records, the two sinks
(profiler chrome-trace + JSONL structured log), and the near-zero
disabled path.

Acceptance shape (ISSUE 2): a hybridized + step-fused training run with
telemetry enabled must produce (a) a chrome trace where trainer-phase
spans and op-dispatch events share one timeline and (b) a JSONL log
whose per-step records carry step_ms, the phase breakdown, CachedOp
cache hits/misses, the host-sync count and allreduce bytes — while
disabled telemetry adds no measurable overhead to the step loop.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry.sinks import ListSink

BATCH = 4


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()


def _net(units=(8, 4), in_dim=6):
    net = gluon.nn.HybridSequential()
    for u in units[:-1]:
        net.add(gluon.nn.Dense(u, activation="relu"))
    net.add(gluon.nn.Dense(units[-1]))
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, in_dim)))  # resolve deferred shapes
    return net


# --- disabled path ----------------------------------------------------------

def test_disabled_span_is_shared_null_singleton():
    assert not telemetry.is_enabled()
    s = telemetry.span("trainer.step")
    assert s is telemetry.span("anything.else")
    with s as inner:
        assert inner is s


class _PoisonLock:
    def __enter__(self):
        raise AssertionError("disabled telemetry path took the lock")

    def __exit__(self, *exc):
        return False

    acquire = __enter__


def test_disabled_recorders_never_lock_or_record(monkeypatch):
    """The disabled fast path is one boolean test — no lock, no state."""
    monkeypatch.setattr(telemetry, "_lock", _PoisonLock())
    telemetry.count("cachedop.cache_miss", 3)
    telemetry.gauge("g", 1.0)
    telemetry.step_begin()
    assert telemetry.step_end(examples=8) is None
    with telemetry.span("x"):
        pass
    with telemetry.step():
        pass
    monkeypatch.undo()
    assert telemetry.counters() == {}
    assert telemetry.gauges() == {}


def test_disabled_overhead_bounded():
    """1e4 disabled span+count pairs must be effectively free (generous
    absolute bound: catches an accidental lock/allocation regression,
    not scheduler noise)."""
    t0 = time.perf_counter()
    for _ in range(10_000):
        with telemetry.span("trainer.step"):
            telemetry.count("host_sync")
    assert time.perf_counter() - t0 < 0.5


# --- spans / counters / step records ----------------------------------------

def test_span_nesting_and_phase_accumulation():
    telemetry.enable()
    with telemetry.span("outer") as outer:
        assert telemetry.current_span() is outer
        with telemetry.span("inner") as inner:
            assert telemetry.current_span() is inner
            time.sleep(0.002)
        assert telemetry.current_span() is outer
    assert telemetry.current_span() is None
    # re-entering a span name accumulates (per-param spans -> one row)
    with telemetry.span("inner"):
        pass
    ph = telemetry.phases()
    assert set(ph) == {"outer", "inner"}
    assert ph["outer"] >= ph["inner"] > 0


def test_counter_aggregation_cumulative_vs_per_step():
    telemetry.enable()
    sink = ListSink()
    telemetry.add_sink(sink)

    telemetry.step_begin()
    telemetry.count("cachedop.cache_miss")
    telemetry.count("host_sync", 2)
    r1 = telemetry.step_end()
    telemetry.step_begin()
    telemetry.count("host_sync")
    r2 = telemetry.step_end()

    assert r1["step"] == 1 and r2["step"] == 2
    # per-step deltas reset at step_begin
    assert r1["counters"]["host_sync"] == 2
    assert r2["counters"]["host_sync"] == 1
    assert "cachedop.cache_miss" not in r2["counters"]
    # cumulative view keeps the running totals
    assert telemetry.counters()["host_sync"] == 3
    assert sink.records == [r1, r2]


def test_hist_summary_nearest_rank_pinned_at_tiny_windows():
    """Nearest-rank percentiles are exact order statistics, so the edge
    cases are pinned (r20 — capacity summaries lean on these): n == 1
    makes every percentile the single observation; n == 2 puts p50 on
    the smaller value and p90/p99 on the larger.  No interpolation
    means p99 can never exceed the observed max."""
    telemetry.enable()
    telemetry.hist("q", 7.5)
    s = telemetry.hist_summary("q")
    assert s["count"] == 1 and s["window"] == 1
    assert s["p50"] == s["p90"] == s["p99"] == 7.5
    assert s["min"] == s["max"] == s["mean"] == 7.5

    telemetry.hist("q", 2.5)  # window is now [2.5, 7.5]
    s = telemetry.hist_summary("q")
    assert s["window"] == 2
    # ceil(50*2/100) - 1 = 0 -> smaller; ceil(90*2/100) - 1 = 1 -> larger
    assert s["p50"] == 2.5
    assert s["p90"] == 7.5 and s["p99"] == 7.5
    assert s["p99"] <= s["max"]


def test_hist_summary_nearest_rank_matches_formula():
    telemetry.enable()
    vals = [5.0, 1.0, 4.0, 2.0, 3.0]
    for v in vals:
        telemetry.hist("lat", v)
    s = telemetry.hist_summary("lat", percentiles=(50, 90, 99))
    ordered = sorted(vals)
    n = len(ordered)
    for p in (50, 90, 99):
        rank = max(0, min(n - 1, -(-p * n // 100) - 1))
        assert s["p%d" % p] == ordered[rank]
    assert s["p50"] == 3.0 and s["p90"] == 5.0


def test_span_thread_safety():
    telemetry.enable()
    errs = []

    def worker(name):
        try:
            for _ in range(200):
                with telemetry.span(name) as s:
                    assert telemetry.current_span() is s
                telemetry.count(name)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(f"t{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    counters = telemetry.counters()
    assert all(counters[f"t{i}"] == 200 for i in range(4))


def test_host_sync_counter_on_asnumpy_and_wait():
    telemetry.enable()
    a = nd.array([1.0, 2.0])
    a.asnumpy()
    a.wait_to_read()
    (a + a).asnumpy()
    assert telemetry.counters()["host_sync"] == 3


def test_nbytes_of_never_syncs():
    a = nd.ones((8, 4))
    assert telemetry.nbytes_of(a) == 8 * 4 * a.dtype.itemsize
    assert telemetry.nbytes_of([a, a]) == 2 * telemetry.nbytes_of(a)
    assert telemetry.nbytes_of(object()) == 0


# --- JSONL sink -------------------------------------------------------------

def test_jsonl_schema(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    telemetry.enable(jsonl_path=path)
    for i in range(3):
        with telemetry.step(examples=BATCH, epoch=0):
            with telemetry.span("trainer.step"):
                telemetry.count("host_sync")
    telemetry.disable()

    records = telemetry.read_jsonl(path)
    assert len(records) == 3
    for i, rec in enumerate(records):
        assert rec["step"] == i + 1
        for key in ("wall_time", "step_ms", "phases_ms", "counters",
                    "gauges", "host_sync", "cachedop_cache_hit",
                    "cachedop_cache_miss", "compile_count",
                    "allreduce_bytes"):
            assert key in rec, key
        assert rec["step_ms"] > 0
        assert rec["phases_ms"]["trainer.step"] > 0
        assert rec["host_sync"] == 1
        assert rec["epoch"] == 0  # extra kwargs land verbatim
        assert rec["examples"] == BATCH
        assert rec["examples_per_sec"] > 0
    # each line is independently parseable (flight-recorder property)
    with open(path) as f:
        for line in f:
            json.loads(line)


def test_jsonl_append_mode(tmp_path):
    path = str(tmp_path / "t.jsonl")
    telemetry.enable(jsonl_path=path)
    with telemetry.step():
        pass
    telemetry.disable()
    telemetry.enable(jsonl_path=path, append=True)
    with telemetry.step():
        pass
    telemetry.disable()
    assert [r["step"] for r in telemetry.read_jsonl(path)] == [1, 1]


# --- profiler bridge (satellites 1 + 2) -------------------------------------

def test_profiler_dumps_json_format():
    from mxnet_tpu import profiler

    profiler.set_state("run")
    try:
        (nd.ones((2, 2)) + 1).asnumpy()  # dispatch at least one op
    finally:
        profiler.set_state("stop")
    payload = json.loads(profiler.dumps(format="json"))
    assert payload, "aggregate table must not be empty"
    row = next(iter(payload.values()))
    assert set(row) == {"count", "total_ms", "min_ms", "max_ms", "avg_ms"}
    # table stays the default; unknown formats are rejected
    assert "Total Count" in profiler.dumps()
    with pytest.raises(MXNetError):
        profiler.dumps(format="yaml")
    profiler.dumps(reset=True)


def test_chrome_trace_shares_timeline_with_op_events(tmp_path):
    """Acceptance (a): telemetry spans and op-dispatch events land in ONE
    traceEvents list, on one clock."""
    from mxnet_tpu import profiler

    trace = str(tmp_path / "profile.json")
    profiler.set_config(filename=trace)
    profiler.dump(finished=True)  # flush any prior events/epoch
    telemetry.enable()
    profiler.set_state("run")
    try:
        net = _net()
        net.hybridize()
        x = nd.ones((BATCH, 6))
        with telemetry.span("trainer.step", attrs={"batch": BATCH}):
            net(x).asnumpy()
    finally:
        profiler.dump(finished=True)
        telemetry.disable()
    events = json.load(open(trace))["traceEvents"]
    cats = {e.get("cat") for e in events}
    assert "telemetry" in cats and "operator" in cats, cats
    span_evt = next(e for e in events if e.get("cat") == "telemetry"
                    and e["name"].endswith("trainer.step"))
    # one timebase: ops dispatched inside the span nest within it
    in_span = [e for e in events if e.get("cat") == "operator" and
               span_evt["ts"] <= e["ts"] <=
               span_evt["ts"] + span_evt["dur"]]
    assert in_span, (span_evt,
                     [e["ts"] for e in events if e.get("cat") == "operator"])
    assert span_evt["args"]["batch"] == str(BATCH)


def test_block_scope_prefixes_op_events(tmp_path):
    """Satellite 2: Block.__call__ wraps forward in profiler.Scope, so op
    events carry the block name path."""
    from mxnet_tpu import profiler

    trace = str(tmp_path / "scoped.json")
    profiler.set_config(filename=trace)
    net = _net()
    profiler.set_state("run")
    try:
        net(nd.ones((2, 6))).wait_to_read()
    finally:
        profiler.dump(finished=True)
    events = json.load(open(trace))["traceEvents"]
    prefixed = [e["name"] for e in events
                if e.get("cat") == "operator" and ":" in e["name"]]
    assert prefixed, [e["name"] for e in events][:10]
    # name path includes the child dense block, not just the container
    assert any("dense" in n for n in prefixed), prefixed[:10]


# --- instrumented subsystems ------------------------------------------------

def test_kvstore_push_pull_instrumented():
    telemetry.enable()
    kv = mx.kv.create("local")
    v = nd.ones((16,))
    kv.init("w", v)
    telemetry.step_begin()
    kv.push("w", nd.ones((16,)))
    out = nd.zeros((16,))
    kv.pull("w", out)
    rec = telemetry.step_end()
    assert rec["phases_ms"]["kvstore.push"] > 0
    assert rec["phases_ms"]["kvstore.pull"] > 0
    nbytes = 16 * v.dtype.itemsize
    assert rec["counters"]["kvstore.push_bytes"] == nbytes
    assert rec["counters"]["kvstore.pull_bytes"] == nbytes


def test_e2e_hybridized_trainer_jsonl(tmp_path):
    """Acceptance (b): a hybridized training loop over dist_tpu_sync
    yields per-step records with phase breakdown, cache hit/miss,
    host-sync count and allreduce bytes."""
    path = str(tmp_path / "train.jsonl")
    net = _net()
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05},
                            kvstore="dist_tpu_sync")
    rng = np.random.RandomState(0)
    telemetry.enable(jsonl_path=path)
    for _ in range(3):
        x = nd.array(rng.randn(BATCH, 6).astype(np.float32))
        y = nd.array(rng.randint(0, 4, (BATCH,)))
        with telemetry.step(examples=BATCH):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(BATCH)
            loss.asnumpy()  # the eager logging sync every real loop has
    telemetry.disable()

    records = telemetry.read_jsonl(path)
    assert len(records) == 3
    first, later = records[0], records[1:]
    for key in ("trainer.step", "trainer.allreduce", "trainer.update"):
        assert first["phases_ms"].get(key, 0) > 0, (key, first["phases_ms"])
    # step 1 traces (miss + compile); steady state replays from cache
    assert first["cachedop_cache_miss"] >= 1
    assert first["compile_count"] >= 1
    for rec in later:
        assert rec["cachedop_cache_hit"] >= 1
        assert rec["cachedop_cache_miss"] == 0
        assert rec["compile_count"] == 0
    grad_bytes = sum(telemetry.nbytes_of(p.grad())
                     for p in net.collect_params().values())
    for rec in records:
        assert rec["host_sync"] >= 1
        assert rec["allreduce_bytes"] == grad_bytes
        assert rec["step_ms"] > 0 and rec["examples_per_sec"] > 0
    # compile-heavy step 1 dominates the steady-state steps
    assert first["step_ms"] > later[0]["step_ms"]


def test_e2e_step_fusion_build_compile_replay():
    """Step-fusion telemetry: build + compile on the first execution,
    replay afterwards, steps-per-execution gauge."""
    k = 2
    net = _net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    fused = gluon.FusedTrainStep(
        net, trainer, lambda n, x, y: loss_fn(n(x), y),
        steps_per_execution=k, batch_size=BATCH, stacked_inputs=True)
    rng = np.random.RandomState(1)
    xs = nd.array(rng.randn(k, BATCH, 6).astype(np.float32))
    ys = nd.array(rng.randint(0, 4, (k, BATCH)))

    telemetry.enable()
    sink = ListSink()
    telemetry.add_sink(sink)
    with telemetry.step(examples=k * BATCH):
        fused(xs, ys)
    with telemetry.step(examples=k * BATCH):
        fused(xs, ys)
    telemetry.disable()

    first, second = sink.records
    assert first["counters"]["step_fusion.cache_miss"] == 1
    assert first["phases_ms"]["step_fusion.build"] > 0
    assert first["phases_ms"]["step_fusion.compile"] > 0
    assert first["compile_count"] >= 1
    assert second["counters"].get("step_fusion.cache_miss", 0) == 0
    assert second["phases_ms"]["step_fusion.replay"] > 0
    assert "step_fusion.compile" not in second["phases_ms"]
    assert second["gauges"]["step_fusion.steps_per_execution"] == k
    assert first["counters"]["step_fusion.steps"] == k


def test_monitor_toc_single_batched_sync():
    """Satellite 3: Monitor.toc syncs its whole queue in ONE device_get
    instead of one asnumpy per monitored layer."""
    from mxnet_tpu.monitor import Monitor

    net = _net()
    net(nd.ones((2, 6)))  # init before monitoring
    mon = Monitor(interval=1, pattern=".*")
    mon.install(net)
    telemetry.enable()
    mon.tic()
    net(nd.ones((2, 6)))
    rows = mon.toc()
    mon.uninstall()
    assert rows, "monitor recorded no stats"
    assert all(isinstance(s, str) and not s.startswith("<unreadable")
               for _, _, s in rows), rows
    assert telemetry.counters().get("host_sync", 0) == 1


def test_env_autostart(tmp_path):
    """MXNET_TELEMETRY=1 enables at import; MXNET_TELEMETRY_JSONL names
    the log (mirrors MXNET_PROFILER_AUTOSTART)."""
    import subprocess
    import sys
    import os

    path = str(tmp_path / "auto.jsonl")
    env = dict(os.environ)
    env.update(MXNET_TELEMETRY="1", MXNET_TELEMETRY_JSONL=path,
               JAX_PLATFORMS="cpu")
    code = (
        "from mxnet_tpu import telemetry\n"
        "assert telemetry.is_enabled()\n"
        "with telemetry.step():\n"
        "    pass\n"
        "telemetry.disable()\n"
    )
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert len(telemetry.read_jsonl(path)) == 1
