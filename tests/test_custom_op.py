"""CustomOp + control-flow op tests (reference model:
tests/python/unittest/test_operator.py::test_custom_op and
test_contrib_control_flow.py, SURVEY §4)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


# --- CustomOp ---------------------------------------------------------------

class Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = 1.0 / (1.0 + onp.exp(-x))
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        gy = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(gy * y * (1 - y)))


@mx.operator.register("test_sigmoid")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Sigmoid()


def test_custom_op_forward_backward():
    x = nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="test_sigmoid")
        loss = y.sum()
    loss.backward()
    expect = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(y.asnumpy(), expect, rtol=1e-6)
    onp.testing.assert_allclose(x.grad.asnumpy(), expect * (1 - expect),
                                rtol=1e-5)


def test_custom_op_registry():
    assert "test_sigmoid" in mx.operator.get_all_registered_operators()
    with pytest.raises(mx.MXNetError):
        nd.Custom(nd.ones((2,)), op_type="not_registered")


def test_custom_op_in_jit():
    import jax

    def step(raw):
        x = nd.NDArray(raw)
        return nd.Custom(x, op_type="test_sigmoid")._data

    out = jax.jit(step)(nd.array([0.0, 2.0])._data)
    onp.testing.assert_allclose(
        onp.asarray(out), 1 / (1 + onp.exp(-onp.array([0.0, 2.0]))),
        rtol=1e-6)


def test_custom_op_grad_in_jit():
    import jax

    def loss_fn(raw):
        x = nd.NDArray(raw)
        y = nd.Custom(x, op_type="test_sigmoid")
        return y._data.sum()

    g = jax.grad(loss_fn)(nd.array([0.5, -0.5])._data)
    s = 1 / (1 + onp.exp(-onp.array([0.5, -0.5])))
    onp.testing.assert_allclose(onp.asarray(g), s * (1 - s), rtol=1e-5)


# --- control flow -----------------------------------------------------------

def test_foreach_eager():
    data = nd.array(onp.arange(6, dtype=onp.float32).reshape(3, 2))
    init = nd.zeros((2,))

    def body(x, state):
        new = state + x
        return new * 2, new

    outs, final = nd.contrib.foreach(body, data, init)
    assert outs.shape == (3, 2)
    # state accumulates rows: [0,1], [2,4], [6,9] → outputs are 2x
    onp.testing.assert_allclose(final.asnumpy(), [6, 9])
    onp.testing.assert_allclose(outs.asnumpy()[-1], [12, 18])


def test_foreach_grad():
    data = nd.array([[1.0], [2.0], [3.0]])
    data.attach_grad()
    init = nd.zeros((1,))
    with autograd.record():
        outs, final = nd.contrib.foreach(
            lambda x, s: (x * x, s + x), data, init)
        loss = outs.sum()
    loss.backward()
    onp.testing.assert_allclose(data.grad.asnumpy(), [[2.0], [4.0], [6.0]])


def test_foreach_traced():
    import jax

    def step(raw):
        data = nd.NDArray(raw)
        init = nd.NDArray(raw[0] * 0)
        outs, final = nd.contrib.foreach(
            lambda x, s: (x + s, s + x), data, init)
        return outs._data

    raw = nd.array([[1.0], [2.0], [3.0]])._data
    out = jax.jit(step)(raw)
    onp.testing.assert_allclose(onp.asarray(out), [[1.0], [3.0], [6.0]])


def test_while_loop_eager():
    # sum integers until total >= 10, max 20 iters
    def cond_fn(i, total):
        return total < 10

    def body_fn(i, total):
        return i, (i + 1, total + i)

    outs, (fi, ftotal) = nd.contrib.while_loop(
        cond_fn, body_fn, (nd.array([1.0]), nd.array([0.0])),
        max_iterations=20)
    # 1+2+3+4 = 10 → 4 iterations
    assert float(ftotal.asscalar()) == 10.0
    assert outs.shape == (20, 1)
    onp.testing.assert_allclose(outs.asnumpy()[:4, 0], [1, 2, 3, 4])
    assert onp.all(outs.asnumpy()[4:] == 0)  # padded rows


def test_while_loop_traced():
    import jax

    def step(raw):
        i0 = nd.NDArray(raw)
        t0 = nd.NDArray(raw * 0)
        outs, fv = nd.contrib.while_loop(
            lambda i, t: t < 10, lambda i, t: (i, (i + 1, t + i)),
            (i0, t0), max_iterations=20)
        return fv[1]._data

    out = jax.jit(step)(nd.array([1.0])._data)
    assert float(out[0]) == 10.0


def test_cond():
    x = nd.array([2.0])
    out = nd.contrib.cond(x.sum() > 1, lambda: x * 10, lambda: x - 1)
    assert float(out.asscalar()) == 20.0
    out = nd.contrib.cond(x.sum() > 5, lambda: x * 10, lambda: x - 1)
    assert float(out.asscalar()) == 1.0


def test_cond_traced():
    import jax

    def step(raw):
        x = nd.NDArray(raw)
        return nd.contrib.cond(x.sum() > 1, lambda: x * 10,
                               lambda: x - 1)._data

    assert float(jax.jit(step)(nd.array([2.0])._data)[0]) == 20.0
    assert float(jax.jit(step)(nd.array([0.5])._data)[0]) == -0.5
