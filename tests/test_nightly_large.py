"""Nightly large-array tests (reference: tests/nightly/test_large_array.py
— int64-range shapes, SURVEY §4 nightly row).

Gated behind ``MXT_TEST_NIGHTLY=1``: the arrays exceed 2**31 elements and
need multi-GB host RAM, so they run as a nightly tier, same as the
reference's.  (``MXNET_TEST_LARGE=1`` is accepted as a legacy alias so
existing invocations keep working.)
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

pytestmark = pytest.mark.skipif(
    not (os.environ.get("MXT_TEST_NIGHTLY")
         or os.environ.get("MXNET_TEST_LARGE")),
    reason="large-array nightly tier; set MXT_TEST_NIGHTLY=1")

# > int32 element count, int8 payload (~2.2 GB)
LARGE = 2 ** 31 + 7


def test_large_elementwise_and_reduce():
    x = nd.ones((LARGE,), dtype="int8")
    assert x.shape == (LARGE,)
    # indexing beyond int32 offsets
    assert int(x[LARGE - 1].asscalar()) == 1
    s = x.astype("float32").sum()
    np.testing.assert_allclose(float(s.asscalar()), float(LARGE), rtol=1e-6)


def test_large_slice_and_write():
    x = nd.zeros((LARGE,), dtype="int8")
    x[LARGE - 5:] = 3
    tail = x[LARGE - 8:].asnumpy()
    assert tail.tolist() == [0, 0, 0, 3, 3, 3, 3, 3]


def test_large_2d_matvec():
    # (2**16 x 2**15) f32 = 8 GB FLOP-light matvec; checks int64 strides
    rows, cols = 2 ** 16, 2 ** 15
    x = nd.ones((rows, cols), dtype="float32")
    v = nd.ones((cols, 1), dtype="float32")
    out = nd.dot(x, v)
    assert out.shape == (rows, 1)
    np.testing.assert_allclose(out.asnumpy()[::7919].ravel(), cols,
                               rtol=1e-5)
