"""Aux-subsystem tests: profiler, monitor, visualization, runtime features,
util flags (reference model: tests/python/unittest/test_profiler.py and
the misc util tests, SURVEY §4/§5)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_profiler_events_and_dump(tmp_path):
    from mxnet_tpu import profiler

    fname = str(tmp_path / "profile.json")
    profiler.set_config(profile_all=True, filename=fname,
                        aggregate_stats=True)
    profiler.set_state("run")
    a = nd.random.uniform(shape=(8, 8))
    b = nd.dot(a, a)
    with profiler.Scope("myscope"):
        c = nd.relu(b)
    c.wait_to_read()
    profiler.set_state("stop")
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    names = [e["name"] for e in trace["traceEvents"]]
    assert any("dot" in n for n in names)
    assert any(n.startswith("myscope:") for n in names)
    table = profiler.dumps(reset=True)
    assert "Total Count" in table and "dot" in table


def test_profiler_marker():
    from mxnet_tpu import profiler

    profiler.set_state("run")
    profiler.Marker("hello").mark()
    profiler.set_state("stop")


def test_profiler_rejects_bad_config():
    from mxnet_tpu import profiler

    with pytest.raises(mx.MXNetError):
        profiler.set_config(bogus_key=1)


def test_monitor_gluon():
    from mxnet_tpu import monitor
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    mon = monitor.Monitor(interval=1, pattern=".*dense.*")
    mon.install(net)
    mon.tic()
    net(nd.ones((2, 3)))
    rows = mon.toc()
    assert len(rows) >= 2
    assert all(r[0] == 1 for r in rows)  # step is 1-based after tic()
    mon.uninstall()
    mon.tic()
    net(nd.ones((2, 3)))
    assert mon.toc() == []


def test_forward_hooks():
    from mxnet_tpu.gluon import nn

    layer = nn.Dense(2, in_units=3)
    layer.initialize()
    calls = []
    h1 = layer.register_forward_pre_hook(
        lambda blk, inp: calls.append("pre"))
    h2 = layer.register_forward_hook(
        lambda blk, inp, out: calls.append("post"))
    layer(nd.ones((1, 3)))
    assert calls == ["pre", "post"]
    h1.detach()
    h2.detach()
    layer(nd.ones((1, 3)))
    assert calls == ["pre", "post"]


def test_block_apply():
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    seen = []
    net.apply(lambda b: seen.append(type(b).__name__))
    assert seen.count("Dense") == 2


def test_visualization_print_summary(capsys):
    import mxnet_tpu.symbol as sym

    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    total = mx.visualization.print_summary(net, shape={"data": (1, 4)})
    out = capsys.readouterr().out
    assert "fc1" in out and "fc2" in out
    # fc1: 4*8+8, fc2: 8*2+2
    assert total == (4 * 8 + 8) + (8 * 2 + 2)


def test_visualization_plot_network():
    import mxnet_tpu.symbol as sym

    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    dot = mx.viz.plot_network(net, title="net")
    assert dot.startswith("digraph")
    assert '"fc1"' in dot and '"data" -> "fc1"' in dot
    assert "fc1_weight" not in dot  # hidden weights
    dot2 = mx.viz.plot_network(net, hide_weights=False)
    assert "fc1_weight" in dot2


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert not feats.is_enabled("CUDA")
    assert isinstance(mx.runtime.feature_list(), list)
    with pytest.raises(RuntimeError):
        feats.is_enabled("NOT_A_FEATURE")


def test_util_np_flags():
    assert not mx.util.is_np_shape()
    prev = mx.util.set_np_shape(True)
    assert prev is False and mx.util.is_np_shape()
    mx.util.reset_np()
    assert not mx.util.is_np_shape() and not mx.util.is_np_array()

    @mx.util.use_np
    def inner():
        return mx.util.is_np_shape(), mx.util.is_np_array()

    assert inner() == (True, True)
    assert not mx.util.is_np_shape()

    with mx.util.np_shape(True):
        assert mx.util.is_np_shape()
    assert not mx.util.is_np_shape()
