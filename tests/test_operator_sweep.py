"""Broad operator sweep: numpy cross-checks + finite-difference gradients
across the op families (reference model: tests/python/unittest/
test_operator.py — the reference's single most important correctness gate,
SURVEY §4).  Small shapes keep the O(n) finite-difference loops cheap."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient)

_RNG = onp.random.RandomState(7)


def _rand(shape, lo=-2.0, hi=2.0):
    return _RNG.uniform(lo, hi, shape).astype(onp.float64)


_UNARY = [
    # (mx op name, numpy fn, domain lo, hi)
    ("relu", lambda x: onp.maximum(x, 0), -2, 2),
    ("sigmoid", lambda x: 1 / (1 + onp.exp(-x)), -3, 3),
    ("tanh", onp.tanh, -2, 2),
    ("exp", onp.exp, -2, 1),
    ("log", onp.log, 0.2, 3),
    ("sqrt", onp.sqrt, 0.2, 4),
    ("square", onp.square, -2, 2),
    ("abs", onp.abs, 0.3, 2),          # keep away from the kink
    ("cbrt", onp.cbrt, 0.2, 3),
    ("rsqrt", lambda x: 1 / onp.sqrt(x), 0.3, 3),
    ("reciprocal", lambda x: 1 / x, 0.4, 3),
    ("sin", onp.sin, -2, 2),
    ("cos", onp.cos, -2, 2),
    ("arctan", onp.arctan, -2, 2),
    ("arcsinh", onp.arcsinh, -2, 2),
    ("expm1", onp.expm1, -1, 1),
    ("log1p", onp.log1p, -0.5, 2),
    ("erf", None, -2, 2),
    ("gamma", None, 0.5, 3),
    ("gammaln", None, 0.5, 3),
]


@pytest.mark.parametrize("name,ref,lo,hi",
                         _UNARY, ids=[u[0] for u in _UNARY])
def test_unary_forward_and_grad(name, ref, lo, hi):
    op = getattr(nd, name)
    x = _rand((3, 4), lo, hi)
    got = op(nd.array(x, dtype="float64")).asnumpy()
    if ref is not None:
        onp.testing.assert_allclose(got, ref(x), rtol=1e-6, atol=1e-8)
    check_numeric_gradient(lambda a: op(a), [x], eps=1e-4, rtol=2e-2,
                           atol=1e-4)


_BINARY = [
    ("broadcast_add", onp.add),
    ("broadcast_sub", onp.subtract),
    ("broadcast_mul", onp.multiply),
    ("broadcast_div", onp.divide),
    ("broadcast_maximum", onp.maximum),
    ("broadcast_minimum", onp.minimum),
    ("broadcast_power", onp.power),
    ("broadcast_hypot", onp.hypot),
]


@pytest.mark.parametrize("name,ref", _BINARY, ids=[b[0] for b in _BINARY])
def test_binary_broadcast_forward_and_grad(name, ref):
    op = getattr(nd, name)
    a = _rand((3, 1, 4), 0.5, 2.0)
    b = _rand((1, 2, 4), 0.5, 2.0)
    got = op(nd.array(a, dtype="float64"),
             nd.array(b, dtype="float64")).asnumpy()
    onp.testing.assert_allclose(got, ref(a, b), rtol=1e-6)
    check_numeric_gradient(lambda x, y: op(x, y), [a, b], eps=1e-4,
                           rtol=2e-2, atol=1e-4)


_REDUCE = [
    ("sum", onp.sum),
    ("mean", onp.mean),
    ("prod", onp.prod),
    ("max", onp.max),
    ("min", onp.min),
]


@pytest.mark.parametrize("name,ref", _REDUCE, ids=[r[0] for r in _REDUCE])
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 2)])
def test_reductions(name, ref, axis):
    op = getattr(nd, name)
    x = _rand((2, 3, 4), 0.5, 2.0)
    got = op(nd.array(x, dtype="float64"), axis=axis).asnumpy()
    onp.testing.assert_allclose(onp.squeeze(got),
                                onp.squeeze(ref(x, axis=axis)), rtol=1e-6)
    check_numeric_gradient(lambda a: op(a, axis=axis), [x], eps=1e-4,
                           rtol=2e-2, atol=1e-4)


def test_keepdims_reductions():
    x = _rand((2, 3))
    got = nd.sum(nd.array(x), axis=1, keepdims=True)
    assert got.shape == (2, 1)


_SHAPE_OPS = [
    ("transpose", dict(axes=(1, 0, 2)),
     lambda x: onp.transpose(x, (1, 0, 2))),
    ("reshape", dict(shape=(4, 6)), lambda x: x.reshape(4, 6)),
    ("flip", dict(axis=1), lambda x: onp.flip(x, 1)),
    ("tile", dict(reps=(2, 1, 1)), lambda x: onp.tile(x, (2, 1, 1))),
    ("repeat", dict(repeats=2, axis=0), lambda x: onp.repeat(x, 2, 0)),
    ("expand_dims", dict(axis=1), lambda x: x[:, None]),
    ("squeeze", None, None),
]


@pytest.mark.parametrize("name,kwargs,ref", _SHAPE_OPS,
                         ids=[s[0] for s in _SHAPE_OPS])
def test_shape_ops_forward_and_grad_flow(name, kwargs, ref):
    if name == "squeeze":
        x = _rand((2, 1, 3))
        got = nd.squeeze(nd.array(x, dtype="float64")).asnumpy()
        onp.testing.assert_allclose(got, onp.squeeze(x))
        return
    op = getattr(nd, name)
    x = _rand((2, 3, 4))
    got = op(nd.array(x, dtype="float64"), **kwargs).asnumpy()
    onp.testing.assert_allclose(got, ref(x), rtol=1e-7)
    check_numeric_gradient(lambda a: op(a, **kwargs), [x], eps=1e-4,
                           rtol=2e-2, atol=1e-4)


def test_dot_batchdot_grads():
    a = _rand((3, 4), 0.2, 1)
    b = _rand((4, 5), 0.2, 1)
    onp.testing.assert_allclose(
        nd.dot(nd.array(a, dtype="float64"),
               nd.array(b, dtype="float64")).asnumpy(), a @ b, rtol=1e-6)
    check_numeric_gradient(lambda x, y: nd.dot(x, y), [a, b], eps=1e-4,
                           rtol=2e-2, atol=1e-4)
    ba = _rand((2, 3, 4), 0.2, 1)
    bb = _rand((2, 4, 2), 0.2, 1)
    onp.testing.assert_allclose(
        nd.batch_dot(nd.array(ba, dtype="float64"),
                     nd.array(bb, dtype="float64")).asnumpy(), ba @ bb,
        rtol=1e-6)


def test_softmax_family_grads():
    x = _rand((3, 5), -2, 2)
    s = nd.softmax(nd.array(x, dtype="float64"), axis=-1).asnumpy()
    e = onp.exp(x - x.max(-1, keepdims=True))
    onp.testing.assert_allclose(s, e / e.sum(-1, keepdims=True), rtol=1e-6)
    w = nd.array(_rand((3, 5)), dtype="float64")  # fixed weighting
    check_numeric_gradient(
        lambda a: nd.softmax(a, axis=-1) * w,
        [x], eps=1e-4, rtol=2e-2, atol=1e-4)
    check_numeric_gradient(
        lambda a: nd.log_softmax(a, axis=-1) * w,
        [x], eps=1e-4, rtol=2e-2, atol=1e-4)


def test_softmax_cross_entropy_fused():
    """The logsumexp-form CE with dtype-preserving custom vjp
    (nn_ops._softmax_ce_sum): forward equals -sum(log_softmax picked),
    backward equals softmax - onehot, and a bf16 logits tensor gets a
    bf16 cotangent (the bandwidth contract PERF_NOTES r5 cont. 6 relies
    on — no f32 materialization of (rows, vocab))."""
    from mxnet_tpu import autograd

    x = _rand((6, 11), -3, 3).astype(onp.float32)
    lab = onp.array([0, 3, 10, 5, 5, 1])
    e = onp.exp(x - x.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    want = -onp.sum(onp.log(sm)[onp.arange(6), lab])
    got = nd.softmax_cross_entropy(nd.array(x), nd.array(lab))
    onp.testing.assert_allclose(float(got.asscalar()), want, rtol=1e-5)

    xv = nd.array(x)
    xv.attach_grad()
    with autograd.record():
        loss = nd.softmax_cross_entropy(xv, nd.array(lab))
    loss.backward()
    onehot = onp.eye(11, dtype=onp.float32)[lab]
    onp.testing.assert_allclose(xv.grad.asnumpy(), sm - onehot,
                                rtol=1e-5, atol=1e-6)

    xb = nd.array(x).astype("bfloat16")
    xb.attach_grad()
    with autograd.record():
        loss = nd.softmax_cross_entropy(xb, nd.array(lab))
    loss.backward()
    assert xb.grad.dtype.name == "bfloat16"
    onp.testing.assert_allclose(
        xb.grad.asnumpy().astype(onp.float32), sm - onehot,
        rtol=0.1, atol=0.02)  # bf16 input + bf16 cotangent rounding


def test_norm_layers_grads():
    x = _rand((2, 3, 4), -1, 1)
    g = _rand((3,), 0.5, 1.5)
    b = _rand((3,), -0.5, 0.5)

    def ln(a, gg, bb):
        return nd.layer_norm(a, gg, bb, axis=-1)

    check_numeric_gradient(ln, [x.transpose(0, 2, 1), g, b], eps=1e-4,
                           rtol=3e-2, atol=2e-4)


def test_take_gather_scatter():
    x = _rand((5, 3))
    idx = onp.array([0, 2, 4])
    got = nd.take(nd.array(x, dtype="float64"),
                  nd.array(idx, dtype="int32")).asnumpy()
    onp.testing.assert_allclose(got, x[idx])
    check_numeric_gradient(
        lambda a: nd.take(a, nd.array(idx, dtype="int32")), [x],
        eps=1e-4, rtol=2e-2, atol=1e-4)
    # mxnet gather_nd: indices (ndim, N)
    gnd = nd.gather_nd(nd.array(x, dtype="float64"),
                       nd.transpose(nd.array([[0, 1], [2, 0]],
                                             dtype="int32")))
    assert gnd.shape == (2,)


def test_where_clip_grads():
    x = _rand((3, 4), -2, 2)
    check_numeric_gradient(
        lambda a: nd.clip(a, -1.0, 1.0) * 2, [x], eps=1e-4, rtol=3e-2,
        atol=1e-3)
    cond = (onp.abs(x) > 1).astype(onp.float64)
    y = _rand((3, 4))
    check_numeric_gradient(
        lambda a, b: nd.where(nd.array(cond), a, b), [x, y], eps=1e-4,
        rtol=2e-2, atol=1e-4)


def test_linalg_ops_vs_numpy():
    a = _rand((3, 4), 0.2, 1)
    b = _rand((4, 5), 0.2, 1)
    onp.testing.assert_allclose(
        nd.linalg_gemm2(nd.array(a, dtype="float64"),
                        nd.array(b, dtype="float64")).asnumpy(), a @ b,
        rtol=1e-6)
    spd = onp.eye(3) * 2 + 0.3
    l = nd.linalg_potrf(nd.array(spd, dtype="float64")).asnumpy()
    onp.testing.assert_allclose(l @ l.T, spd, rtol=1e-6)
    s = nd.linalg_syrk(nd.array(a, dtype="float64")).asnumpy()
    onp.testing.assert_allclose(s, a @ a.T, rtol=1e-6)


def test_topk_sort_argsort():
    x = onp.array([[3.0, 1.0, 2.0], [0.0, 5.0, -1.0]])
    top = nd.topk(nd.array(x), k=2, ret_typ="value").asnumpy()
    onp.testing.assert_allclose(top, [[3, 2], [5, 0]])
    srt = nd.sort(nd.array(x), axis=1).asnumpy()
    onp.testing.assert_allclose(srt, onp.sort(x, 1))
    arg = nd.argsort(nd.array(x), axis=1).asnumpy()
    onp.testing.assert_allclose(arg, onp.argsort(x, 1))


def test_one_hot_pick():
    idx = nd.array([0, 2], dtype="int32")
    oh = nd.one_hot(idx, depth=3).asnumpy()
    onp.testing.assert_allclose(oh, [[1, 0, 0], [0, 0, 1]])
    x = nd.array([[1.0, 2, 3], [4, 5, 6]])
    p = nd.pick(x, nd.array([2, 0]), axis=1).asnumpy()
    onp.testing.assert_allclose(p, [3, 4])


def test_random_moments():
    mx.random.seed(3)
    u = nd.random.uniform(0, 1, shape=(20000,)).asnumpy()
    assert abs(u.mean() - 0.5) < 0.02
    assert abs(u.var() - 1 / 12) < 0.01
    n = nd.random.normal(1.0, 2.0, shape=(20000,)).asnumpy()
    assert abs(n.mean() - 1.0) < 0.06
    assert abs(n.std() - 2.0) < 0.06
    p = nd.random.poisson(4.0, shape=(20000,)).asnumpy()
    assert abs(p.mean() - 4.0) < 0.1


def test_boolean_mask():
    x = onp.arange(12.0).reshape(4, 3)
    mask = onp.array([1, 0, 1, 1])
    out = nd.contrib.boolean_mask(nd.array(x), nd.array(mask)).asnumpy()
    onp.testing.assert_allclose(out, x[mask.astype(bool)])
    # axis=1 and the all-false edge (empty result, shape preserved elsewhere)
    out1 = nd.contrib.boolean_mask(nd.array(x), nd.array([0, 1, 0]),
                                   axis=1).asnumpy()
    onp.testing.assert_allclose(out1, x[:, 1:2])
    empty = nd.contrib.boolean_mask(nd.array(x),
                                    nd.array([0, 0, 0, 0])).asnumpy()
    assert empty.shape == (0, 3)


def test_registry_sweep_invariants():
    """Every registered op: callable, documented (public names), alias
    metadata self-consistent, and no registration ever shadowed another.
    The static half of this lives in tools/lint (rule T3)."""
    from mxnet_tpu.ops import registry

    assert registry.duplicate_registrations() == []
    names = registry.list_ops()
    assert len(names) == len(set(names))
    for name in names:
        fn = registry.get_op(name)
        assert callable(fn), name
        meta = registry.op_meta(name)
        assert meta, f"{name} registered without metadata"
        canonical = meta["canonical"]
        assert registry.get_op(canonical) is fn, name
        if not canonical.startswith("_"):
            assert (fn.__doc__ or "").strip(), f"{canonical} undocumented"


def test_no_grad_ops_backward_matches_zero_grad():
    """no_grad-marked ops skip the vjp trace; gradients THROUGH them
    accumulate nothing — observably identical to the zero cotangents the
    vjp produced before the markers existed."""
    x = nd.array([-1.5, 0.5, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        z = (nd.floor(x) + x * 3.0).sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0, 3.0])
