"""Request-scoped tracing, the live metrics endpoint, and the SLO
flight recorder (r12): span propagation across the three serving
threads, /metrics scrape agreement with ``server.stats()``, automatic
flight dumps on replica failure and overload, goodput math, and the
near-zero disabled path."""
import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry
from mxnet_tpu.serving import ServerConfig, ServerOverloadedError
from mxnet_tpu.serving.metrics import SLOTracker, prometheus_text
from mxnet_tpu.telemetry import tracing
from mxnet_tpu.telemetry.sinks import ListSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _telemetry_on():
    telemetry.enable(memory=False, cost=False, trace=True)
    sink = ListSink()
    telemetry.add_sink(sink)
    return sink


def _telemetry_off():
    telemetry.disable()
    telemetry.reset()
    tracing.clear()


# --- unit: the Trace object ------------------------------------------------

def test_trace_structure_and_finish_record():
    tracing.enable()
    try:
        tr = tracing.start_trace(request_id=7, tenant="acme")
        assert tr is not None and tr.request_id == 7
        t0 = time.perf_counter()
        sid = tr.add("queue", t0, t0 + 0.001)
        tr.add("prefill", t0 + 0.001, t0 + 0.002, parent=sid, replica=0)
        tr.event("evict", slot=3)
        with tr.span("extra"):
            pass
        rec = tracing.finish(tr, status="ok", lane="decode")
        assert rec["record"] == "trace" and rec["tenant"] == "acme"
        spans = rec["spans"]
        assert [s["name"] for s in spans] == \
            ["queue", "prefill", "evict", "extra", "request"]
        root = spans[-1]
        assert root["id"] == tr.root_id and root["parent"] is None
        assert root["tags"] == {"lane": "decode"}
        ids = {s["id"] for s in spans}
        # connected: every non-root parent resolves, default parent is
        # the root
        for s in spans[:-1]:
            assert s["parent"] in ids
        by_name = {s["name"]: s for s in spans}
        assert by_name["queue"]["parent"] == root["id"]
        assert by_name["prefill"]["parent"] == sid
        assert by_name["evict"]["dur_ms"] == 0.0
        # the ring holds it for the flight recorder
        assert tracing.recent()[-1]["trace_id"] == rec["trace_id"]
    finally:
        _telemetry_off()


def test_tracing_disabled_is_inert(tmp_path):
    """The off path: no Trace objects, no ring growth, no incident
    dumps — the serving call sites all guard on ``req.trace is None``
    so this is the entire disabled cost."""
    _telemetry_off()
    assert tracing.start_trace(request_id=1) is None
    assert tracing.finish(None) is None
    assert tracing.incident("overload_rejection") is None
    assert tracing.recent() == []
    assert not (tmp_path / "flight.json").exists()


# --- unit: SLO goodput math -------------------------------------------------

def test_slo_tracker_goodput_math():
    s = SLOTracker({"ttft_ms": 100.0, "tpot_ms": 10.0}, window=4)
    # flat targets land on the "default" tenant
    assert s.target_for(None) == {"ttft_ms": 100.0, "tpot_ms": 10.0}
    assert s.observe(ttft_ms=50.0, tpot_ms=5.0) is True
    assert s.observe(ttft_ms=150.0, tpot_ms=5.0) is False
    assert s.observe(ttft_ms=50.0, tpot_ms=50.0) is False
    assert s.goodput() == pytest.approx(1 / 3)
    # rolling window forgets the old misses
    for _ in range(4):
        s.observe(ttft_ms=1.0, tpot_ms=1.0)
    snap = s.snapshot()["tenants"]["default"]
    assert snap["window_goodput"] == 1.0
    assert snap["total"] == 7 and snap["goodput"] == pytest.approx(5 / 7)

    # per-tenant targets + unknown tenant falls back to default
    m = SLOTracker({"default": {"ttft_ms": 10.0},
                    "gold": {"ttft_ms": 1.0}})
    assert m.observe(tenant="gold", ttft_ms=5.0) is False
    assert m.observe(tenant="bronze", ttft_ms=5.0) is True
    # a metric the target doesn't name is not judged
    assert m.observe(tenant="gold", tpot_ms=99.0) is None


# --- unit: Prometheus text rendering ---------------------------------------

def test_prometheus_text_labels_and_types():
    telemetry.enable(memory=False, cost=False)
    try:
        telemetry.count("serving.completed", 3)
        telemetry.count("serving.completed|replica=1", 2)
        telemetry.hist("serving.ttft_ms|replica=1", 4.0)
        telemetry.hist("serving.ttft_ms|replica=1", 8.0)
        txt = prometheus_text(extra_gauges={"serving.queue_depth": 5})
        lines = txt.strip().splitlines()
        # exposition format: every non-comment line is  name{labels} value
        for ln in lines:
            if ln.startswith("#"):
                assert ln.startswith("# TYPE mxt_")
                continue
            name, value = ln.rsplit(" ", 1)
            float(value)
            assert name.startswith("mxt_")
        assert "mxt_serving_completed_total 3" in lines
        assert 'mxt_serving_completed_total{replica="1"} 2' in lines
        assert "mxt_serving_queue_depth 5" in lines
        assert 'mxt_serving_ttft_ms{quantile="0.5",replica="1"} 4' \
            in lines
        assert 'mxt_serving_ttft_ms_count{replica="1"} 2' in lines
        assert 'mxt_serving_ttft_ms_sum{replica="1"} 12' in lines
    finally:
        _telemetry_off()


# --- acceptance: one trace across the three lane threads (dp2) --------------

def test_generative_trace_tree_metrics_endpoint_dp2():
    """THE r12 acceptance path: a dp2 CPU-mesh paged server with
    tracing on yields one connected span tree per request spanning
    queue → prefill → handoff → >=2 decode steps across >=2 threads;
    the live /metrics scrape parses as Prometheus text and agrees with
    ``server.stats()``; /healthz and /requests respond; and
    tools/trace_report.py renders the tree + chrome trace."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.models.llama import llama_tiny

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices (dp2)")
    import trace_report

    net = llama_tiny()
    net.initialize()
    rs = np.random.RandomState(5)
    prompts = [rs.randint(1, 250, size=n) for n in (5, 9, 12, 7)]
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=4, http_port=0,
                       slo={"acme": {"ttft_ms": 1e6, "tpot_ms": 1e6}})
    sink = _telemetry_on()
    try:
        srv = serving.GenerativeServer(net, cfg, mesh=mesh)
        with srv:
            url = srv.metrics_url
            assert url is not None
            futs = [srv.submit(p, max_new_tokens=6, tenant="acme")
                    for p in prompts]
            for f in futs:
                f.result(120)
            mtxt = urllib.request.urlopen(url + "/metrics").read() \
                .decode()
            health = json.loads(
                urllib.request.urlopen(url + "/healthz").read())
            reqs = json.loads(
                urllib.request.urlopen(url + "/requests").read())
            stats = srv.stats()
        assert srv.metrics_url is None   # endpoint dies with the server

        # -- the span tree ----------------------------------------------------
        traces = [r for r in sink.records if r.get("record") == "trace"]
        assert len(traces) == 4
        for t in traces:
            assert t["status"] == "ok" and t["tenant"] == "acme"
            names = [s["name"] for s in t["spans"]]
            for need in ("queue", "prefill", "handoff", "evict",
                         "request"):
                assert need in names
            assert names.count("decode.step") >= 2
            assert len({s["thread"] for s in t["spans"]}) >= 2
            ids = {s["id"] for s in t["spans"]}
            root = [s for s in t["spans"] if s["parent"] is None]
            assert len(root) == 1 and root[0]["name"] == "request"
            for s in t["spans"]:
                if s["parent"] is not None:
                    assert s["parent"] in ids   # connected tree
            pre = next(s for s in t["spans"] if s["name"] == "prefill")
            assert pre["tags"]["replica"] in (0, 1)
            assert "slot" in pre["tags"] and "kv_blocks" in pre["tags"]
            step = next(s for s in t["spans"]
                        if s["name"] == "decode.step")
            assert step["tags"]["batch"] >= 1

        # -- request records carry the r12 fields -----------------------------
        recs = [r for r in sink.records
                if r.get("record") == "serving.request"]
        assert len(recs) == 4
        for r in recs:
            assert r["status"] == "ok" and r["lane"] == "decode"
            assert r["replica"] in (0, 1)
            assert r["trace_id"] in {t["trace_id"] for t in traces}
            assert r["tpot_ms"] > 0 and r["ttft_ms"] > 0
            assert r["slo_met"] is True
        # labeled per-replica histograms exist alongside the global ones
        hists = telemetry.hists()
        assert "serving.ttft_ms" in hists and "serving.tpot_ms" in hists
        assert any(h.startswith("serving.ttft_ms|replica=")
                   for h in hists)

        # -- /metrics agreement with stats() ----------------------------------
        lines = [ln for ln in mtxt.splitlines() if ln]
        for ln in lines:
            if not ln.startswith("#"):
                float(ln.rsplit(" ", 1)[1])     # parses as exposition
        done = next(ln for ln in lines
                    if ln.startswith("mxt_serving_completed_total "))
        assert int(float(done.rsplit(" ", 1)[1])) == stats["completed"]
        assert any(ln.startswith("mxt_serving_kv_occupancy")
                   for ln in lines)
        assert any('tenant="acme"' in ln for ln in lines)  # goodput

        # -- /healthz + /requests ---------------------------------------------
        assert health["status"] == "ok"
        assert len(health["replicas"]) == 2
        for rep in health["replicas"]:
            assert rep["prefill_alive"] and rep["decode_alive"]
            assert "kv_utilization" in rep
        assert isinstance(reqs, list)   # likely drained already

        # -- stats slo block ---------------------------------------------------
        slo = stats["slo"]["tenants"]["acme"]
        assert slo["total"] == 4 and slo["window_goodput"] == 1.0

        # -- trace_report renders stream + chrome ------------------------------
        t = traces[0]
        text = trace_report.render_tree(t)
        assert t["trace_id"] in text and "decode.step" in text
        roots = trace_report.build_tree(t)
        assert len(roots) == 1
        assert {c["span"]["name"] for c in roots[0]["children"]} >= \
            {"queue", "prefill", "handoff", "decode.step", "evict"}
        chrome = trace_report.chrome_trace(traces)
        evs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
        assert len(evs) == sum(len(t["spans"]) for t in traces)
        assert all(e["dur"] >= 0 and "trace_id" in e["args"]
                   for e in evs)
    finally:
        _telemetry_off()


# --- flight recorder: automatic dumps ---------------------------------------

def test_flight_recorder_dump_on_replica_failure(tmp_path,
                                                 monkeypatch):
    """An injected prefill exception fails the request (future raises,
    ``status="error"`` record with replica+lane) and triggers one
    flight-recorder dump."""
    from mxnet_tpu.models.llama import llama_tiny

    dump_path = tmp_path / "flight.json"
    monkeypatch.setenv("MXNET_TRACE_DUMP", str(dump_path))
    net = llama_tiny()
    net.initialize()
    sink = _telemetry_on()
    try:
        cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                           num_slots=2, summary_every=1 << 30)
        srv = serving.GenerativeServer(net, cfg)
        with srv:
            # one good request fills the ring so the dump has content
            srv.generate(np.arange(1, 6, dtype=np.int32),
                         max_new_tokens=2)

            def boom(*a, **k):
                raise RuntimeError("injected prefill failure")

            monkeypatch.setattr(srv.replicas[0].engine,
                                "prefill_rows", boom)
            fut = srv.submit(np.arange(1, 8, dtype=np.int32),
                             max_new_tokens=2)
            with pytest.raises(RuntimeError, match="injected"):
                fut.result(60)
        assert dump_path.exists()
        report = json.loads(dump_path.read_text())
        assert report["record"] == "flight_recorder"
        assert report["reason"] == "replica_exception"
        assert report["context"]["lane"] == "prefill"
        assert report["context"]["replica"] == 0
        assert len(report["traces"]) >= 1   # the good request's trace
        # the failed request still landed in the JSONL stream, tagged
        errs = [r for r in sink.records
                if r.get("record") == "serving.request"
                and r.get("status") == "error"]
        assert len(errs) == 1
        assert errs[0]["lane"] == "prefill" and errs[0]["replica"] == 0
        assert "injected" in errs[0]["error"]
        # ... and its sealed trace reports the error status
        bad = [t for t in sink.records if t.get("record") == "trace"
               and t.get("status") == "error"]
        assert len(bad) == 1
        assert srv.replicas[0].failed == 1
    finally:
        _telemetry_off()


def test_flight_recorder_dump_on_overload(tmp_path, monkeypatch):
    """Queue-full rejection emits a tagged ``status="rejected"`` record
    and dumps the flight record (rate-limited: an overload storm writes
    once)."""
    dump_path = tmp_path / "flight.json"
    monkeypatch.setenv("MXNET_TRACE_DUMP", str(dump_path))

    def slow_model(batch):
        time.sleep(0.2)
        return [batch["data"]]

    sink = _telemetry_on()
    try:
        cfg = ServerConfig(max_batch=1, max_length=16, min_length=8,
                           queue_capacity=1, batch_window_ms=0.0)
        srv = serving.InferenceServer(slow_model, cfg)
        with srv:
            rejected = 0
            for _ in range(8):
                try:
                    srv.submit(np.zeros((4, 3), np.float32))
                except ServerOverloadedError:
                    rejected += 1
            assert rejected >= 1
        assert dump_path.exists()
        report = json.loads(dump_path.read_text())
        assert report["reason"] == "overload_rejection"
        assert report["context"]["queue_capacity"] == 1
        rej = [r for r in sink.records
               if r.get("record") == "serving.request"
               and r.get("status") == "rejected"]
        assert len(rej) == rejected
        assert all(r["lane"] == "queue" and "trace_id" in r
                   for r in rej)
        # rejected traces are sealed with the rejected status
        sealed = [t for t in sink.records if t.get("record") == "trace"
                  and t.get("status") == "rejected"]
        assert len(sealed) == rejected
        # rate limit: one dump despite several rejections
        assert telemetry.counters().get("tracing.flight_dump") == 1
    finally:
        _telemetry_off()


def test_memwatch_postmortem_embeds_recent_traces(tmp_path):
    """The OOM post-mortem joins the flight recorder: when tracing is
    on, ``write_postmortem`` embeds the recent completed traces."""
    from mxnet_tpu.telemetry import memwatch

    tracing.enable()
    try:
        tr = tracing.start_trace(request_id=9)
        tracing.finish(tr, status="ok")
        path = memwatch.write_postmortem(
            path=str(tmp_path / "oom.json"), context="test",
            error="RESOURCE_EXHAUSTED")
        report = json.loads(open(path).read())
        assert [t["request_id"] for t in report["recent_traces"]] == [9]
    finally:
        _telemetry_off()


# --- trace_report CLI --------------------------------------------------------

def test_trace_report_cli_roundtrip(tmp_path):
    """load_traces reads both a JSONL stream and a flight dump; the CLI
    selects by trace id and emits tree/chrome formats."""
    import subprocess

    import trace_report

    tracing.enable()
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    try:
        for rid in (1, 2):
            tr = tracing.start_trace(request_id=rid)
            t0 = time.perf_counter()
            tr.add("queue", t0, t0 + 0.001)
            tr.add("decode.step", t0 + 0.001, t0 + 0.002, step=1)
            tracing.finish(tr, status="ok")
        stream = tmp_path / "stream.jsonl"
        with open(stream, "w") as f:
            for r in sink.records:
                f.write(json.dumps(r) + "\n")
        dump = tracing.dump(path=str(tmp_path / "dump.json"),
                            reason="test")

        got = trace_report.load_traces(str(stream))
        assert [t["request_id"] for t in got] == [1, 2]
        from_dump = trace_report.load_traces(dump)
        assert [t["request_id"] for t in from_dump] == [1, 2]
        tid = got[0]["trace_id"]
        assert [t["trace_id"] for t in
                trace_report.select(got, trace_id=tid)] == [tid]
        assert [t["request_id"] for t in
                trace_report.select(got, request_id=2)] == [2]

        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "trace_report.py"),
             str(stream), "--trace-id", tid],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0 and tid in out.stdout
        chrome_out = tmp_path / "chrome.json"
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "trace_report.py"),
             str(stream), "--format", "chrome", "--out",
             str(chrome_out)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0
        doc = json.loads(chrome_out.read_text())
        assert {e["ph"] for e in doc["traceEvents"]} == {"M", "X"}
        # no-match exits 1
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "trace_report.py"),
             str(stream), "--trace-id", "nope"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 1
    finally:
        _telemetry_off()
