"""Partition-rule engine tests: rule matching/ordering/coverage, the
dp×tp-equals-single-device oracle through a stock ``gluon.Trainer``,
sharding-preserving checkpoint round trips, and elastic data assignment
under an active mesh.  Everything runs on the conftest's 8 virtual CPU
devices and stays in the tier-1 fast lane — tiny models, few compiles."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import partition as pt


# --- rule matching / ordering / coverage ------------------------------------

def test_first_match_wins_and_scalars_replicate():
    rules = pt.PartitionRules((
        (r"weight$", ("tp", None)),
        (r"0_weight$", (None, "tp")),   # shadowed for *_0_weight too
        (r".*", ()),
    ))
    assert rules.match("dense0_weight", (8, 4)) == (r"weight$", ("tp", None))
    assert rules.match("scale", ()) == (None, ())   # scalar: replicate
    assert rules.match("bias", (8,)) == (r".*", ())


def test_unmatched_without_catch_all():
    rules = pt.PartitionRules(((r"weight$", ("tp", None)),))
    assert rules.match("running_mean", (8,)) == (None, None)
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    cov = rules.coverage({"weight": (8, 4), "running_mean": (8,)}, mesh)
    assert cov.unmatched == ["running_mean"]
    err_rules = pt.PartitionRules(((r"weight$", ("tp", None)),),
                                  on_unmatched="error")
    with pytest.raises(MXNetError, match="running_mean"):
        err_rules.specs({"running_mean": (8,)}, mesh)


def test_invalid_regex_and_empty_table_raise():
    with pytest.raises(MXNetError, match="invalid partition-rule regex"):
        pt.PartitionRules(((r"(q|k", ("tp", None)),))
    with pytest.raises(MXNetError, match="empty"):
        pt.PartitionRules(())
    with pytest.raises(MXNetError, match="on_unmatched"):
        pt.PartitionRules(((r".*", ()),), on_unmatched="warn")
    with pytest.raises(MXNetError, match="unknown model family"):
        pt.PartitionRules.for_family("gpt17")


def test_rank_guard_routes_flat_moe_names():
    """The 3-D expert-bank rule precedes the dense 2-D rule; the rank
    guard is what keeps the flat dense name from taking the bank spec."""
    rules = pt.PartitionRules.for_family("mixtral")
    mesh = parallel.make_mesh({"dp": 2, "ep": 2, "tp": 2})
    cov = pt.Coverage()
    specs = rules.specs({
        "moe_gate_weight": (4, 16, 8),    # (E, I, H) expert bank
        "mlp_gate_weight": (16, 8),       # dense 2-D, same suffix
        "router_weight": (4, 8),
        "ln_in_weight": (8,),
    }, mesh, coverage=cov)
    assert specs["moe_gate_weight"] == ("ep", "tp", None)
    assert specs["mlp_gate_weight"] == ("tp", None)
    assert ("mlp_gate_weight",
            r"(^|[._])(gate|up)_weight$") in cov.rank_skips
    assert "router_weight" not in specs      # explicitly replicated
    assert "ln_in_weight" not in specs       # norms replicate


def test_structural_and_flat_names_take_the_same_layout():
    rules = pt.PartitionRules.for_family("llama")
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    pairs = [("model.layers.0.self_attn.q_proj.weight",
              "model_layers_0_attn_q_weight", (8, 8)),
             ("model.layers.0.mlp.down_proj.weight",
              "model_layers_0_mlp_down_weight", (8, 16)),
             ("model.embed_tokens.weight", "model_embed_weight", (32, 8))]
    for dotted, flat, shape in pairs:
        specs = rules.specs({dotted: shape, flat: shape}, mesh)
        assert specs[dotted] == specs[flat], (dotted, flat)


def test_resolve_drops_absent_size1_indivisible():
    rules = pt.PartitionRules.for_family("llama")
    dp_only = parallel.make_mesh({"dp": 8})
    cov = pt.Coverage()
    specs = rules.specs({"q_weight": (8, 8)}, dp_only, coverage=cov)
    assert specs == {}                       # degrades to replication
    assert ("q_weight", "tp", "absent") in cov.dropped
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    cov = pt.Coverage()
    specs = rules.specs({"q_weight": (7, 8)}, mesh, coverage=cov)
    assert specs == {}
    assert ("q_weight", "tp", "indivisible") in cov.dropped


def test_coverage_reports_unused_rules_and_summary():
    rules = pt.PartitionRules.for_family("llama")
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    cov = rules.coverage({"q_weight": (8, 8), "norm_weight": (8,)}, mesh)
    assert r"(^|[._])lm_head[._]weight$" in cov.unused
    assert cov.summary() == {"mesh_shape": {"dp": 4, "tp": 2},
                             "sharded_params": 1, "replicated_params": 1}
    assert "shard q_weight" in cov.render()


def test_stacked_spec_and_as_rules():
    assert pt.stacked_spec(("tp", None)) == (None, "tp", None)
    assert pt.stacked_spec((), stack_axes=2) == (None, None)
    assert pt.as_rules(None) is None
    r = pt.PartitionRules(((r".*", ()),))
    assert pt.as_rules(r) is r
    assert pt.as_rules("llama").rules[0][0] == pt.LLAMA_RULES[0][0]
    assert pt.as_rules([(r".*", ())]).rules[0][2] == ()


# --- dp×tp step == single-device oracle through stock Trainer ---------------

_HIDDEN, _OUT, _BATCH, _STEPS = 32, 8, 16, 4


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(_HIDDEN, activation="relu"), nn.Dense(_OUT))
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, _HIDDEN)))
    net.hybridize(static_alloc=True)
    return net


def _mlp_rules(net):
    ws = [p.name for p in net.collect_params().values()
          if p.name.endswith("weight")]
    return [(rf"^{ws[0]}$", ("tp", None)), (rf"^{ws[1]}$", (None, "tp")),
            (r".*", ())]


def _train(net, trainer, x, y, loss_fn, steps):
    losses = []
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(_BATCH)
        losses.append(float(loss.asscalar()))
    return losses


def test_dp_tp_step_matches_single_device_oracle(tmp_path):
    from mxnet_tpu import sanitizer

    loss_fn = gluon.loss.L2Loss()
    xs = onp.random.RandomState(0).randn(_BATCH, _HIDDEN).astype("float32")
    ys = onp.random.RandomState(1).randn(_BATCH, _OUT).astype("float32")

    # oracle: single device, no mesh
    parallel.set_mesh(None)
    oracle = _mlp()
    oracle.save_parameters(str(tmp_path / "init.params"))
    otr = gluon.Trainer(oracle.collect_params(), "sgd",
                        {"learning_rate": 0.1})
    oracle_losses = _train(oracle, otr, nd.array(xs), nd.array(ys),
                           loss_fn, _STEPS)
    oracle_params = {name: p.data().asnumpy() for name, p in
                     oracle._collect_params_with_prefix().items()}

    # same init, dp4×tp2 mesh, stock Trainer with partition_rules; the
    # donation sanitizer rides along: the sharded fused update must not
    # read donated buffers
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    telemetry.enable()
    sanitizer.enable()
    try:
        net = _mlp()
        net.load_parameters(str(tmp_path / "init.params"))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1},
                                partition_rules=_mlp_rules(net), mesh=mesh)
        assert trainer.placement.summary()["sharded_params"] == 2
        x = parallel.shard_batch(nd.array(xs), mesh)
        y = parallel.shard_batch(nd.array(ys), mesh)
        miss_per_step = []
        sharded_losses = []
        for _ in range(_STEPS):
            with telemetry.step(examples=_BATCH) as scope:
                with autograd.record():
                    loss = loss_fn(net(x), y).mean()
                loss.backward()
                trainer.step(_BATCH)
                nd.waitall()
            sharded_losses.append(float(loss.asscalar()))
            miss_per_step.append(
                scope.record["counters"].get("trainer.fused_cache_miss", 0))
        sharded_params = {name: p.data().asnumpy() for name, p in
                          net._collect_params_with_prefix().items()}
        import jax

        w0 = net.collect_params().values()
        shardings = [p.data()._data.sharding for p in w0
                     if p.name.endswith("weight")]
        assert all(isinstance(s, jax.sharding.NamedSharding)
                   for s in shardings)
    finally:
        sanitizer.reset()
        sanitizer.disable()
        telemetry.disable()
        parallel.set_mesh(None)

    onp.testing.assert_allclose(sharded_losses, oracle_losses,
                                rtol=1e-5, atol=1e-6)
    for name in oracle_params:
        onp.testing.assert_allclose(sharded_params[name],
                                    oracle_params[name],
                                    rtol=1e-5, atol=1e-6, err_msg=name)
    # one fused-update compile, every later step replays from the cache
    assert sum(miss_per_step) == miss_per_step[0] >= 1, miss_per_step
    assert all(m == 0 for m in miss_per_step[1:]), miss_per_step


def test_trainer_mesh_only_means_pure_dp():
    mesh = parallel.make_mesh({"dp": 8})
    try:
        net = _mlp()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, mesh=mesh)
        s = trainer.placement.summary()
        assert s["sharded_params"] == 0 and s["replicated_params"] == 4
    finally:
        parallel.set_mesh(None)


def test_trainer_partition_rules_without_mesh_raises():
    parallel.set_mesh(None)
    net = _mlp()
    with pytest.raises(MXNetError, match="mesh"):
        gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                      partition_rules=[(r".*", ())])


# --- checkpoint round trip preserves shardings ------------------------------

def test_checkpoint_roundtrip_preserves_shardings(tmp_path):
    import jax

    from mxnet_tpu import checkpoint

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    try:
        net = _mlp()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1},
                                partition_rules=_mlp_rules(net), mesh=mesh)
        first_w = next(p for p in net.collect_params().values()
                       if p.name.endswith("weight"))
        spec_before = first_w.data()._data.sharding.spec
        saved = {name: p.data().asnumpy() for name, p in
                 net._collect_params_with_prefix().items()}
        checkpoint.save_checkpoint(str(tmp_path), 7, net, trainer)

        # perturb, then resume: values restore AND placement survives the
        # set_data path (no silent collapse to single-device)
        for p in net.collect_params().values():
            p.set_data(p.data() + 1.0)
        step, _extra = checkpoint.resume(str(tmp_path), net, trainer)
        assert step == 7
        for name, p in net._collect_params_with_prefix().items():
            onp.testing.assert_allclose(p.data().asnumpy(), saved[name],
                                        rtol=1e-6, err_msg=name)
        sh = first_w.data()._data.sharding
        assert isinstance(sh, jax.sharding.NamedSharding)
        assert sh.spec == spec_before
        assert sh.mesh.shape == mesh.shape
    finally:
        parallel.set_mesh(None)


def test_set_data_respects_existing_sharding():
    import jax

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    try:
        net = _mlp()
        parallel.place_params(net.collect_params(), _mlp_rules(net),
                              mesh=mesh)
        w = next(p for p in net.collect_params().values()
                 if p.name.endswith("weight"))
        spec = w.data()._data.sharding.spec
        w.set_data(nd.ones(w.shape))
        sh = w.data()._data.sharding
        assert isinstance(sh, jax.sharding.NamedSharding)
        assert sh.spec == spec
        onp.testing.assert_allclose(w.data().asnumpy(),
                                    onp.ones(w.shape, "float32"))
    finally:
        parallel.set_mesh(None)


# --- elastic data assignment is layout-independent --------------------------

def test_elastic_shard_for_step_unchanged_under_mesh():
    from mxnet_tpu import elastic

    base = [elastic.shard_for_step(103, 16, s, 4, 1) for s in range(3)]
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    try:
        parallel.set_mesh(mesh)
        under = [elastic.shard_for_step(103, 16, s, 4, 1) for s in range(3)]
    finally:
        parallel.set_mesh(None)
    for a, b in zip(base, under):
        onp.testing.assert_array_equal(a, b)


# --- placement telemetry -----------------------------------------------------

def test_place_params_records_last_placement():
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    try:
        net = _mlp()
        cov = parallel.place_params(net.collect_params(), _mlp_rules(net),
                                    mesh=mesh)
        assert cov.summary() == parallel.last_placement()
        assert parallel.last_placement()["sharded_params"] == 2
    finally:
        parallel.set_mesh(None)
