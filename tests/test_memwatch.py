"""Memory & cost observability (ISSUE 5): the live-buffer ledger, the
executable cost registry, per-step MFU/memory JSONL fields, and the OOM
post-mortem.

Acceptance shape: a hybridized + fused train loop under telemetry emits
JSONL steps whose ``live_bytes`` matches the sum over reachable NDArray
buffers (exact, shape×itemsize), whose ``model_flops`` matches the
compiled artifacts' ``cost_analysis()``, with ZERO device syncs from
recording (the ``host_sync`` counter in the same record stays 0); an
injected allocation failure produces a post-mortem naming the largest
live buffer by parameter path; the disabled path stays one
module-global boolean per hook.
"""
import gc
import json
import os
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd, telemetry
from mxnet_tpu.telemetry import costs, memwatch
from mxnet_tpu.telemetry.sinks import ListSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BATCH = 4
IN_DIM = 6


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    telemetry.reset()
    costs.set_peak_flops(None)
    yield
    telemetry.disable()
    telemetry.reset()
    costs.set_peak_flops(None)


def _net(units=(8, 4), in_dim=IN_DIM):
    net = gluon.nn.HybridSequential()
    for u in units[:-1]:
        net.add(gluon.nn.Dense(u, activation="relu"))
    net.add(gluon.nn.Dense(units[-1]))
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, in_dim)))  # resolve deferred shapes
    return net


def _nbytes(raw):
    n = 1
    for s in raw.shape:
        n *= int(s)
    return n * np.dtype(raw.dtype).itemsize


def _train_steps(net, trainer, loss_fn, x, y, n):
    for _ in range(n):
        with telemetry.step():
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(BATCH)
        del loss
        gc.collect()


# --- the ledger --------------------------------------------------------------

def test_ledger_matches_reachable_buffers_exactly():
    """live_bytes == the shape×itemsize sum over every reachable NDArray
    buffer, with shared handles counted once."""
    telemetry.enable()
    net = _net()
    net.hybridize()
    x = nd.ones((BATCH, IN_DIM))
    out = net(x)
    gc.collect()  # shape-resolution intermediates die -> weakrefs fire
    reachable = {}
    for p in net.collect_params().values():
        reachable[id(p.data()._data)] = p.data()._data
        if p.grad_req != "null":
            reachable[id(p.grad()._data)] = p.grad()._data
    for a in (x, out):
        reachable[id(a._data)] = a._data
    assert memwatch.ledger_size() == len(reachable)
    assert memwatch.live_bytes() == sum(
        _nbytes(r) for r in reachable.values())
    # a detached alias shares the buffer: ledger must not double count
    before = memwatch.live_bytes()
    alias = out.detach()
    assert memwatch.live_bytes() == before
    del alias


def test_no_leak_across_train_steps():
    """Steady-state training neither leaks nor loses ledger entries:
    live_bytes after step 10 == after step 3."""
    telemetry.enable()
    net = _net()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    x = nd.ones((BATCH, IN_DIM))
    y = nd.ones((BATCH, 4))
    _train_steps(net, trainer, loss_fn, x, y, 3)
    at3 = memwatch.live_bytes()
    n3 = memwatch.ledger_size()
    _train_steps(net, trainer, loss_fn, x, y, 7)
    assert memwatch.live_bytes() == at3
    assert memwatch.ledger_size() == n3


def test_peak_watermark_under_bulking():
    """The per-step peak keeps the high-water mark even after the
    intermediates of a bulked segment are collected."""
    telemetry.enable()
    x = nd.ones((64, 64))
    gc.collect()
    memwatch.step_mark(1)
    base = memwatch.live_bytes()
    with engine.bulk(8):
        y = x + 1.0
        z = y * 2.0
        w = z - 3.0
    for a in (y, z, w):  # materialize -> the ledger sees the buffers
        a.wait_to_read()
    grown = memwatch.live_bytes()
    assert grown >= base + 3 * _nbytes(x._data)
    del y, z, w, a
    gc.collect()
    assert memwatch.live_bytes() == base
    assert memwatch.peak_live_bytes() >= grown  # watermark survives


def test_donation_releases_old_buffers_early():
    """A donating optimizer update releases the old weight/state buffers
    from the ledger at dispatch, even while a python alias lingers."""
    telemetry.enable()
    w = nd.ones((32, 32))
    g = nd.ones((32, 32))
    optzr = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    state = optzr.create_state(0, w)
    gc.collect()
    before = memwatch.live_bytes()
    old_raw = w._data  # keep the donated buffer's python handle alive
    optzr.update(0, w, g, state)
    gc.collect()
    # old buffers were donated (released early) and the new results were
    # tracked: same shapes, so the ledger balances exactly — it would
    # read `before + nbytes(old_raw)` if donation were not accounted
    assert memwatch.live_bytes() == before
    assert id(old_raw) not in memwatch._ledger


# --- the cost registry -------------------------------------------------------

def test_cost_registry_hit_on_cachedop_replay():
    """First dispatch per compiled graph analyzes once; replays are
    registry hits that still bump the execution count."""
    telemetry.enable()
    net = _net()
    net.hybridize()
    x = nd.ones((BATCH, IN_DIM))
    net(x)
    s0 = costs.stats()
    assert s0["analyzed"] >= 1
    net(x)
    s1 = costs.stats()
    assert s1["analyzed"] == s0["analyzed"]  # replay never re-analyzes
    assert s1["hits"] == s0["hits"] + 1
    arts = [a for a in costs.snapshot() if a["kind"] == "cachedop"]
    assert len(arts) == 1
    assert arts[0]["executions"] == 2
    assert arts[0]["error"] is None
    assert arts[0]["flops"] > 0


def test_registry_covers_fused_trainer_and_backward():
    telemetry.enable()
    net = _net()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    _train_steps(net, trainer, loss_fn, nd.ones((BATCH, IN_DIM)),
                 nd.ones((BATCH, 4)), 2)
    kinds = {a["kind"] for a in costs.snapshot()}
    assert {"cachedop", "cachedop_bwd", "trainer_fused"} <= kinds


def test_registry_covers_engine_bulk_segments():
    telemetry.enable()
    x = nd.ones((8, 8))
    with engine.bulk(4):
        y = (x + 1.0) * 2.0
    y.wait_to_read()
    assert any(a["kind"] == "engine_bulk" for a in costs.snapshot())


# --- per-step JSONL fields ---------------------------------------------------

def test_e2e_jsonl_memory_and_cost_fields():
    """The acceptance loop: hybridized + fused training emits records
    with live_bytes/peak_live_bytes/model_flops/mfu populated, zero
    host syncs, and model_flops equal to the executed artifacts'
    cost_analysis() sum."""
    telemetry.enable()
    costs.set_peak_flops(1e12)
    sink = ListSink()
    telemetry.add_sink(sink)
    net = _net()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    x = nd.ones((BATCH, IN_DIM))
    y = nd.ones((BATCH, 4))
    _train_steps(net, trainer, loss_fn, x, y, 3)
    execs_before = {(a["kind"], a["key"]): a["executions"]
                    for a in costs.snapshot()}
    _train_steps(net, trainer, loss_fn, x, y, 1)
    last = sink.records[-1]
    # the record was cut while the step's loss scalar was still alive,
    # so it can only be >= the post-gc ledger total
    assert last["live_bytes"] >= memwatch.live_bytes() > 0
    assert last["peak_live_bytes"] >= last["live_bytes"]
    assert last["live_bytes_by_device"]
    # model_flops == sum of cost_analysis() flops over the artifacts the
    # step actually executed (execution-count delta), exactly
    expected = sum(
        a["flops"] * (a["executions"] -
                      execs_before.get((a["kind"], a["key"]), 0))
        for a in costs.snapshot())
    assert last["model_flops"] == pytest.approx(expected)
    assert last["model_flops"] > 0
    assert last["bytes_accessed"] > 0
    dur_s = last["step_ms"] / 1e3
    assert last["mfu"] == pytest.approx(
        last["model_flops"] / (dur_s * 1e12), rel=1e-6)
    # recording added ZERO device syncs
    assert last["host_sync"] == 0


def test_mfu_null_without_peak():
    telemetry.enable()
    sink = ListSink()
    telemetry.add_sink(sink)
    if costs.peak_flops() is not None:
        pytest.skip("host has a detectable peak-FLOPs entry")
    with telemetry.step():
        nd.ones((2, 2)) + 1.0
    assert sink.records[-1]["mfu"] is None


def test_profiler_counter_track(tmp_path):
    """Ledger updates mirror chrome-trace counter samples while the
    profiler runs — the Perfetto live-memory track."""
    from mxnet_tpu import profiler

    telemetry.enable()
    path = str(tmp_path / "trace.json")
    profiler.set_config(profile_all=True, filename=path)
    profiler.set_state("run")
    a = nd.ones((16, 16))
    a.wait_to_read()
    profiler.dump(finished=True)
    events = json.load(open(path))["traceEvents"]
    counters = [e for e in events if e.get("ph") == "C" and
                e["name"] == "memwatch.live_bytes"]
    assert counters
    assert counters[-1]["args"]["total"] > 0
    del a


# --- OOM post-mortem ---------------------------------------------------------

def test_oom_postmortem_names_largest_buffer(tmp_path):
    report = str(tmp_path / "oom.json")
    telemetry.enable()
    memwatch.enable(report_path=report)  # re-enable with a report path
    net = _net(units=(16, 4))
    net.hybridize()
    x = nd.ones((BATCH, IN_DIM))
    net(x)  # build the compiled graph
    g = list(net._cached_op._graphs.values())[0]

    def boom(*a, **k):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 99999 bytes")

    g._fwd = boom
    g._compiled.add("fwd")
    with pytest.raises(memwatch.OOMError) as ei:
        net(x)
    assert report in str(ei.value)  # the raised error names the file
    assert isinstance(ei.value.__cause__, RuntimeError)
    rep = json.load(open(report))
    assert rep["live_bytes"] == memwatch.live_bytes()
    assert rep["n_live_buffers"] == memwatch.ledger_size()
    buffers = rep["buffers"]
    assert buffers == sorted(buffers, key=lambda b: -b["nbytes"])
    # the largest live buffer is the big dense weight, named by its
    # parameter path
    params = net.collect_params()
    largest = max(params.values(), key=lambda p: np.prod(p.shape))
    assert buffers[0]["owner"] in (largest.name, largest.name + ".grad")
    assert buffers[0]["nbytes"] == int(np.prod(largest.shape)) * 4
    assert "top_artifacts_by_temp_bytes" in rep


def test_non_oom_errors_pass_through():
    telemetry.enable()
    with pytest.raises(ValueError):
        try:
            raise ValueError("shape mismatch")
        except ValueError as e:
            memwatch.annotate_oom(e, context="test")  # returns silently
            raise


# --- offline tools: --from-registry ------------------------------------------

def test_tools_from_registry_agrees_with_lowering(tmp_path):
    """The runtime registry's numbers equal what the offline tools'
    fallback (lower+compile+cost_analysis) computes for the same
    compiled program on a small model."""
    from tools.mfu_audit import load_registry, registry_report
    from tools.bytes_breakdown import registry_breakdown

    telemetry.enable()
    net = _net()
    net.hybridize()
    x = nd.ones((BATCH, IN_DIM))
    net(x)
    net(x)
    art = [a for a in costs.snapshot() if a["kind"] == "cachedop"][0]

    # the fallback path: re-lower the same jit at the same avals, as the
    # offline audit does, and price it independently
    from mxnet_tpu import random as mxrand

    g = list(net._cached_op._graphs.values())[0]
    p_raws = [p.data()._data for p in net.collect_params().values()]
    ca = g._fwd.lower(p_raws, [x._data], mxrand.next_key()) \
        .compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    assert art["flops"] == pytest.approx(float(ca["flops"]))

    path = str(tmp_path / "COSTS.json")
    costs.dump(path)
    payload = load_registry(path)
    assert payload is not None
    rep = registry_report(payload, step_time_s=None)
    assert rep["per_kind"]["cachedop"]["flops_per_execution"] == \
        pytest.approx(float(ca["flops"]))
    assert rep["flops_per_step"] == pytest.approx(sum(
        a["flops"] for a in costs.snapshot()))
    bd = registry_breakdown(payload, top=5)
    assert bd["n_artifacts"] == len(costs.snapshot())
    assert bd["top"][0]["bytes"] == max(
        a["bytes_accessed"] for a in costs.snapshot())


def test_tools_from_registry_fallback_on_missing_dump(tmp_path):
    from tools.mfu_audit import load_registry

    assert load_registry(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert load_registry(str(bad)) is None
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"entries": []}))
    assert load_registry(str(empty)) is None


# --- read_jsonl truncation tolerance -----------------------------------------

def test_read_jsonl_tolerates_truncated_final_line(tmp_path):
    p = tmp_path / "crashed.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"step": 0, "step_ms": 1.0}) + "\n")
        f.write(json.dumps({"step": 1, "step_ms": 1.1}) + "\n")
        f.write('{"step": 2, "step_m')  # writer died mid-record
    records = telemetry.read_jsonl(str(p))
    assert [r["step"] for r in records] == [0, 1]
    assert records.truncated is True

    clean = tmp_path / "clean.jsonl"
    with open(clean, "w") as f:
        f.write(json.dumps({"step": 0}) + "\n")
    ok = telemetry.read_jsonl(str(clean))
    assert [r["step"] for r in ok] == [0]
    assert ok.truncated is False

    # corruption mid-file is data loss, not a crash artifact: still raise
    corrupt = tmp_path / "corrupt.jsonl"
    with open(corrupt, "w") as f:
        f.write('{"step": 0, "ste\n')
        f.write(json.dumps({"step": 1}) + "\n")
    with pytest.raises(json.JSONDecodeError):
        telemetry.read_jsonl(str(corrupt))


# --- disabled path -----------------------------------------------------------

class _PoisonLock:
    def __enter__(self):
        raise AssertionError("disabled recorder acquired the lock")

    def __exit__(self, *exc):
        return False

    acquire = __enter__


def test_disabled_hooks_never_lock_or_record(monkeypatch):
    """Disabled memwatch/costs hooks are one boolean test — no lock, no
    allocation, no state."""
    assert not memwatch._enabled and not costs._enabled
    size_before = costs.stats()["size"]  # entries survive disable() by design
    monkeypatch.setattr(memwatch, "_lock", _PoisonLock())
    monkeypatch.setattr(costs, "_lock", _PoisonLock())
    raw = nd.ones((4,))._data
    memwatch.track(raw)
    memwatch.donated((raw,))
    memwatch.adopt(nd.ones((1,)), "x")
    memwatch.step_mark(7)
    memwatch.annotate_oom(RuntimeError("RESOURCE_EXHAUSTED"), "test")
    assert costs.note("k", 1, None, ()) is None
    monkeypatch.undo()
    assert memwatch.ledger_size() == 0
    assert costs.stats()["size"] == size_before


def test_disabled_overhead_bounded():
    """Matches test_telemetry's guard: 1e4 disabled hook invocations
    must be effectively free (generous absolute bound — catches an
    accidental lock/allocation regression, not scheduler noise)."""
    raw = nd.ones((4,))._data
    t0 = time.perf_counter()
    for _ in range(10_000):
        memwatch.track(raw)
        memwatch.donated((raw,))
        costs.note("k", 1, None, ())
    assert time.perf_counter() - t0 < 0.5
