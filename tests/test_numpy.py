"""mx.np / mx.npx front-end tests (reference model:
tests/python/unittest/test_numpy_ndarray.py + test_numpy_op.py — numpy
cross-checks over the np-semantics array type, SURVEY §4)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ndarray import NDArray

np = mx.np
npx = mx.npx


def test_array_creation_and_types():
    a = np.array([[1.0, 2], [3, 4]])
    assert type(a).__name__ == "ndarray"
    assert isinstance(a, NDArray)  # np arrays flow through gluon unchanged
    assert a.dtype == onp.float32  # classic default dtype
    assert np.array([1, 2]).dtype in (onp.int32, onp.int64)
    z = np.zeros((2, 3))
    assert z.shape == (2, 3) and z.dtype == onp.float32
    assert np.ones((2,), dtype="float64").dtype == onp.float64
    assert np.arange(5).shape == (5,)
    assert np.linspace(0, 1, 11).shape == (11,)
    assert np.eye(3).shape == (3, 3)


def test_zero_dim_and_zero_size():
    z = np.zeros(())
    assert z.shape == ()
    assert float(z.item()) == 0.0
    e = np.zeros((0, 3))
    assert e.shape == (0, 3) and e.size == 0
    s = np.sum(np.ones((2, 2)))
    assert s.shape == ()  # true scalar, not (1,)


def test_operators_stay_np_typed():
    a = np.array([1.0, 2.0])
    b = np.array([3.0, 4.0])
    for r in (a + b, a - b, a * b, a / b, a ** 2, -a, abs(a), a + 1, 2 * a):
        assert type(r).__name__ == "ndarray"
    onp.testing.assert_allclose((a * b).asnumpy(), [3, 8])


def test_elemwise_and_reductions_match_numpy():
    rng = onp.random.RandomState(0)
    x = rng.uniform(0.5, 2.0, (3, 4)).astype(onp.float32)
    a = np.array(x)
    for name in ["exp", "log", "sqrt", "sin", "cos", "tanh", "square",
                 "sign", "floor", "ceil"]:
        got = getattr(np, name)(a).asnumpy()
        want = getattr(onp, name)(x)
        onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(np.sum(a, axis=1).asnumpy(), x.sum(axis=1),
                                rtol=1e-5)
    onp.testing.assert_allclose(np.mean(a).item(), x.mean(), rtol=1e-5)
    assert np.argmax(a).item() == x.argmax()
    onp.testing.assert_allclose(np.cumsum(a, axis=0).asnumpy(),
                                x.cumsum(axis=0), rtol=1e-5)


def test_shape_manipulation():
    a = np.arange(12).reshape((3, 4)) if hasattr(np.arange(12), "reshape") \
        else np.reshape(np.arange(12), (3, 4))
    a = np.reshape(np.arange(12), (3, 4))
    assert a.shape == (3, 4)
    assert np.transpose(a).shape == (4, 3)
    assert np.expand_dims(a, 0).shape == (1, 3, 4)
    assert np.squeeze(np.expand_dims(a, 0)).shape == (3, 4)
    b = np.concatenate([a, a], axis=0)
    assert b.shape == (6, 4)
    s = np.split(b, 2, axis=0)
    assert len(s) == 2 and s[0].shape == (3, 4)
    assert np.stack([a, a]).shape == (2, 3, 4)
    assert np.tile(a, (2, 1)).shape == (6, 4)
    assert np.broadcast_to(np.ones((1, 4)), (3, 4)).shape == (3, 4)
    assert np.where(a > 5, a, np.zeros_like(a)).shape == (3, 4)


def test_matmul_dot_einsum():
    a = np.array(onp.arange(6).reshape(2, 3).astype(onp.float32))
    b = np.array(onp.arange(12).reshape(3, 4).astype(onp.float32))
    onp.testing.assert_allclose(
        np.matmul(a, b).asnumpy(), a.asnumpy() @ b.asnumpy())
    onp.testing.assert_allclose(
        np.dot(a, b).asnumpy(), a.asnumpy() @ b.asnumpy())
    onp.testing.assert_allclose(
        np.einsum("ij,jk->ik", a, b).asnumpy(), a.asnumpy() @ b.asnumpy(),
        rtol=1e-5)


def test_linalg():
    x = onp.array([[4.0, 2], [2, 3]], dtype=onp.float32)
    a = np.array(x)
    onp.testing.assert_allclose(np.linalg.norm(a).item(),
                                onp.linalg.norm(x), rtol=1e-5)
    onp.testing.assert_allclose(np.linalg.inv(a).asnumpy(),
                                onp.linalg.inv(x), rtol=1e-4)
    onp.testing.assert_allclose(np.linalg.det(a).item(),
                                onp.linalg.det(x), rtol=1e-5)
    l = np.linalg.cholesky(a).asnumpy()
    onp.testing.assert_allclose(l @ l.T, x, rtol=1e-5)


def test_random():
    np.random.seed(0)
    u = np.random.uniform(size=(100,))
    assert u.shape == (100,)
    assert 0 <= float(u.asnumpy().min()) and float(u.asnumpy().max()) <= 1
    n = np.random.normal(0, 1, size=(50, 2))
    assert n.shape == (50, 2)
    r = np.random.randint(0, 10, size=(20,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10
    assert np.random.rand(2, 3).shape == (2, 3)
    c = np.random.choice(5, size=(10,))
    assert c.shape == (10,)
    g = np.random.gamma(2.0, 1.0, size=(10,))
    assert (g.asnumpy() > 0).all()


def test_autograd_through_np():
    x = np.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = np.sum(x * x * 3)
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy())


def test_npx_ops_and_np_mode():
    a = np.array([-1.0, 2.0])
    onp.testing.assert_allclose(npx.relu(a).asnumpy(), [0, 2])
    sm = npx.softmax(np.array([[1.0, 1.0]]))
    onp.testing.assert_allclose(sm.asnumpy(), [[0.5, 0.5]], rtol=1e-6)
    npx.set_np()
    try:
        assert mx.util.is_np_array() and mx.util.is_np_shape()
    finally:
        npx.reset_np()
    assert not mx.util.is_np_array()


def test_npx_save_load(tmp_path):
    f = str(tmp_path / "arrs.npz")
    npx.save(f, {"w": np.array([1.0, 2.0])})
    back = npx.load(f)
    assert type(back["w"]).__name__ == "ndarray"
    onp.testing.assert_allclose(back["w"].asnumpy(), [1, 2])


def test_conversion_nd_np():
    a = np.array([1.0, 2.0])
    nd_a = a.as_nd_ndarray()
    assert type(nd_a) is NDArray
    back = np._np(nd_a)
    assert type(back).__name__ == "ndarray"
    # shared storage
    assert nd_a._data is a._data


def test_np_interops_with_gluon():
    from mxnet_tpu.gluon import nn

    net = nn.Dense(3, in_units=2)
    net.initialize()
    out = net(np.ones((4, 2)))
    assert out.shape == (4, 3)
