"""Fast-lane model-family smokes (VERDICT r2 #10: one cheap smoke per
family in the <5-min core lane, while the heavy configs sit behind the
``heavy`` marker).  Each case is a tiny-config forward(+backward) that
proves the family's code path wires up — coverage depth stays in the
heavy suites."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def test_vision_resnet_smoke():
    net = gluon.model_zoo.vision.get_model("resnet18_v1", classes=10)
    net.initialize(mx.init.Xavier())
    with autograd.record():
        out = net(nd.random.uniform(shape=(2, 3, 32, 32)))
        loss = (out ** 2).mean()
    loss.backward()
    assert out.shape == (2, 10) and np.isfinite(float(loss.asscalar()))


def test_bert_smoke():
    from mxnet_tpu.models import bert

    net = bert.bert_tiny(vocab_size=128)
    net.initialize(mx.init.Xavier())
    ids = nd.array(np.random.RandomState(0).randint(0, 128, (2, 12)),
                   dtype="int32")
    seg = nd.zeros((2, 12), dtype="int32")
    with autograd.record():
        outs = net(ids, seg)
        loss = (outs[-1] ** 2).mean()
    loss.backward()
    assert np.isfinite(float(loss.asscalar()))


def test_llama_smoke():
    from mxnet_tpu.models import llama

    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize()
    ids = nd.array(np.random.RandomState(1).randint(0, 256, (2, 12)),
                   dtype="int32")
    with autograd.record():
        logits = net(ids)
        loss = nd.softmax_cross_entropy(
            logits.reshape((-1, 256)), ids.reshape((-1,))).mean()
    loss.backward()
    assert logits.shape == (2, 12, 256)
    assert np.isfinite(float(loss.asscalar()))


def test_moe_smoke():
    from mxnet_tpu.models import llama

    net = llama.mixtral_tiny(attn_mode="sdpa")
    net.initialize()
    ids = nd.array(np.random.RandomState(2).randint(0, 256, (2, 12)),
                   dtype="int32")
    with autograd.record():
        logits = net(ids)
        loss = (logits ** 2).mean()
    loss.backward()
    assert np.isfinite(float(loss.asscalar()))


def test_detection_ops_smoke():
    # the detection families hinge on box ops; one NMS + ROIAlign pass
    boxes = nd.array([[[0.1, 0.1, 0.4, 0.4, 0.9],
                       [0.12, 0.12, 0.42, 0.42, 0.8],
                       [0.6, 0.6, 0.9, 0.9, 0.7]]])
    kept = nd.contrib.box_nms(boxes, overlap_thresh=0.5)
    assert kept.shape == boxes.shape
    feat = nd.random.uniform(shape=(1, 4, 8, 8))
    rois = nd.array([[0, 1, 1, 6, 6]])
    out = nd.ROIAlign(feat, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 4, 2, 2)


def test_llama_scan_layers_smoke():
    """Fast-lane guard for the scanned decoder (r4): one forward+step,
    loss finite — full equivalences live in tests/test_llama.py."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.models import llama

    mx.random.seed(0)
    net = llama.llama_tiny(num_layers=2, attn_mode="sdpa",
                           scan_layers=True)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    ids = nd.array(np.random.RandomState(0).randint(0, 256, (2, 8)),
                   dtype="int32")
    with autograd.record():
        loss = (net(ids).astype("float32") ** 2).mean()
    loss.backward()
    trainer.step(2)
    assert np.isfinite(float(loss.asscalar()))
