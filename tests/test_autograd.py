"""Autograd tape tests (reference: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import assert_almost_equal


def test_record_pause_nesting():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording() and autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        assert autograd.is_recording()
    assert not autograd.is_recording()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_attach_grad_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 2)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 20.0]))
    assert_almost_equal(x.grad, np.array([30.0, 60.0]))


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward(retain_graph=False)
    assert_almost_equal(x.grad, np.array([6.0]))


def test_grad_req_write_overwrites():
    x = nd.array([1.0])
    x.attach_grad()
    for _ in range(2):
        with autograd.record():
            y = x * 5
        y.backward()
    assert_almost_equal(x.grad, np.array([5.0]))


def test_retain_graph():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    assert_almost_equal(x.grad, np.array([4.0]))
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))
    with pytest.raises(MXNetError):
        y.backward()  # graph freed now


def test_detach_blocks_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([9.0]))  # only d(cx)/dx = c = x^2


def test_stop_gradient_op():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        z = nd.BlockGrad(x * x) * x
    z.backward()
    assert_almost_equal(x.grad, np.array([9.0]))


def test_multiple_heads_sum():
    x = nd.array([1.0, 1.0])
    x.attach_grad()
    with autograd.record():
        y1 = x * 2
        y2 = x * 3
    autograd.backward([y1, y2])
    assert_almost_equal(x.grad, np.array([5.0, 5.0]))


def test_shared_subexpression():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        h = x * x          # used twice
        y = h * h          # y = x^4, dy/dx = 4x^3 = 32
    y.backward()
    assert_almost_equal(x.grad, np.array([32.0]))


def test_grad_function():
    x = nd.array([1.0, 2.0])
    with autograd.record():
        y = nd.exp(x.detach())  # not attached → no tape
    assert y._node is None

    x.attach_grad()
    g = autograd.grad(
        _rec(lambda: nd.exp(x)), [x], retain_graph=True)
    assert_almost_equal(g[0], np.exp(x.asnumpy()))
    # .grad untouched by autograd.grad
    assert_almost_equal(x.grad, np.zeros(2))


def _rec(fn):
    with autograd.record():
        return fn()


def test_mark_variables():
    x = nd.array([1.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 7
    y.backward()
    assert_almost_equal(x.grad, np.array([7.0]))


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, sig * (1 - sig))


def test_training_flag_drives_dropout():
    x = nd.ones((10, 10))
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    assert set(np.unique(y.asnumpy())).issubset({0.0, 2.0})
    with autograd.record(train_mode=False):
        y = nd.Dropout(x, p=0.5)
    assert_almost_equal(y, x.asnumpy())


def test_getitem_grad():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = x[0] * 2
    y.backward()
    assert_almost_equal(x.grad, np.array([[2.0, 2.0], [0.0, 0.0]]))
