"""Metric + callback tests (reference: tests/python/unittest/test_metric.py:?)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_accuracy():
    m = mx.metric.Accuracy()
    m.update(nd.array([0, 1, 2]), nd.array([[1, 0, 0], [0, 1, 0],
                                            [0, 0, 1]]))
    assert m.get() == ("accuracy", 1.0)
    m.update(nd.array([0, 0]), nd.array([[0, 1], [0, 1]]))
    assert np.isclose(m.get()[1], 3 / 5)
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk_accuracy():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    m.update(nd.array([2, 1]), pred)  # both in top-2
    assert np.isclose(m.get()[1], 1.0)
    m.update(nd.array([0]), nd.array([[0.1, 0.5, 0.4]]))
    assert np.isclose(m.get()[1], 2 / 3)


def test_f1_and_mcc():
    f1 = mx.metric.F1()
    mcc = mx.metric.MCC()
    label = nd.array([1, 0, 1, 1])
    pred = nd.array([[0.2, 0.8], [0.9, 0.1], [0.4, 0.6], [0.6, 0.4]])
    f1.update(label, pred)
    mcc.update(label, pred)
    # tp=2 fp=0 fn=1 tn=1 → precision 1, recall 2/3, f1 = 0.8
    assert np.isclose(f1.get()[1], 0.8)
    assert -1 <= mcc.get()[1] <= 1


def test_mae_mse_rmse():
    label = nd.array([1.0, 2.0])
    pred = nd.array([1.5, 1.0])
    mae = mx.metric.MAE()
    mae.update(label, pred)
    assert np.isclose(mae.get()[1], 0.75)
    mse = mx.metric.MSE()
    mse.update(label, pred)
    assert np.isclose(mse.get()[1], (0.25 + 1.0) / 2)
    rmse = mx.metric.RMSE()
    rmse.update(label, pred)
    assert np.isclose(rmse.get()[1], np.sqrt(0.625))


def test_perplexity_and_crossentropy():
    probs = nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = nd.array([0, 0])
    p = mx.metric.Perplexity()
    p.update(label, probs)
    expect = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert np.isclose(p.get()[1], expect, atol=1e-5)
    ce = mx.metric.CrossEntropy()
    ce.update(label, probs)
    assert np.isclose(ce.get()[1], -(np.log(0.5) + np.log(0.9)) / 2,
                      atol=1e-5)


def test_composite_and_create():
    m = mx.metric.create(["acc", "ce"])
    m.update([nd.array([1])], [nd.array([[0.1, 0.9]])])
    names, values = m.get()
    assert names == ["accuracy", "cross-entropy"]
    m2 = mx.metric.create("top_k_accuracy", top_k=3)
    assert m2.top_k == 3


def test_custom_metric():
    def feval(label, pred):
        return float(np.abs(label - pred).sum())

    m = mx.metric.CustomMetric(feval, name="absdiff")
    m.update(nd.array([1.0]), nd.array([0.5]))
    assert np.isclose(m.get()[1], 0.5)


def test_pearson():
    m = mx.metric.PearsonCorrelation()
    m.update(nd.array([1.0, 2, 3, 4]), nd.array([1.1, 2.2, 2.9, 4.3]))
    assert m.get()[1] > 0.99


def test_loss_metric():
    m = mx.metric.Loss()
    m.update(None, nd.array([1.0, 3.0]))
    assert np.isclose(m.get()[1], 2.0)


def test_speedometer_runs(caplog):
    import logging

    from mxnet_tpu.callback import Speedometer, BatchEndParam

    sp = Speedometer(batch_size=4, frequent=1)
    metric = mx.metric.Accuracy()
    metric.update(nd.array([0]), nd.array([[1.0, 0.0]]))
    with caplog.at_level(logging.INFO):
        sp(BatchEndParam(epoch=0, nbatch=0, eval_metric=metric))
        sp(BatchEndParam(epoch=0, nbatch=1, eval_metric=metric))
    assert any("samples/sec" in r.message for r in caplog.records)
