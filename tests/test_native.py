"""Native runtime (libmxtpu) tests: dependency engine semantics, RecordIO
byte-compat, prefetcher ordering.

The engine tests are the python analog of the reference's
tests/cpp/engine/threaded_engine_test.cc (push/wait/var ordering): writes
on one var serialize, reads run concurrently, WaitForVar observes every
earlier op on the var.
"""
import time

import numpy as np
import pytest

from mxnet_tpu import _native, recordio

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason="native library unavailable")


def test_engine_write_serialization():
    """Non-atomic read-modify-write on a shared cell stays exact because
    write-ops on one var are serialized."""
    eng = _native.Engine(nthreads=4)
    var = eng.new_var()
    cell = {"v": 0}

    def bump():
        cur = cell["v"]
        time.sleep(0.001)
        cell["v"] = cur + 1

    for _ in range(50):
        eng.push(bump, write_vars=[var])
    eng.wait_all()
    assert cell["v"] == 50
    assert eng.var_version(var) == 50


def test_engine_reads_parallel_writes_serial():
    eng = _native.Engine(nthreads=4)
    var = eng.new_var()

    t0 = time.time()
    for _ in range(4):
        eng.push(lambda: time.sleep(0.1), read_vars=[var])
    eng.wait_all()
    read_elapsed = time.time() - t0
    assert read_elapsed < 0.35, "reads on one var should run concurrently"

    t0 = time.time()
    for _ in range(4):
        eng.push(lambda: time.sleep(0.05), write_vars=[var])
    eng.wait_all()
    write_elapsed = time.time() - t0
    assert write_elapsed >= 0.2, "writes on one var must serialize"


def test_engine_wait_for_var():
    eng = _native.Engine(nthreads=2)
    var = eng.new_var()
    log = []
    eng.push(lambda: (time.sleep(0.05), log.append("w")), write_vars=[var])
    eng.wait_for_var(var)
    assert log == ["w"]
    eng.wait_all()


def test_engine_independent_vars_parallel():
    eng = _native.Engine(nthreads=4)
    t0 = time.time()
    for _ in range(4):
        eng.push(lambda: time.sleep(0.1), write_vars=[eng.new_var()])
    eng.wait_all()
    assert time.time() - t0 < 0.35


def test_engine_read_write_ordering():
    """r-after-w sees the write; w-after-r waits for the read."""
    eng = _native.Engine(nthreads=4)
    var = eng.new_var()
    seen = []
    eng.push(lambda: (time.sleep(0.05), seen.append("write1")),
             write_vars=[var])
    eng.push(lambda: seen.append("read:" + str("write1" in seen)),
             read_vars=[var])
    eng.push(lambda: seen.append("write2"), write_vars=[var])
    eng.wait_all()
    assert seen == ["write1", "read:True", "write2"]


def _write_recfile(tmp_path, n=20, seed=0):
    path = str(tmp_path / "test.rec")
    rng = np.random.RandomState(seed)
    payloads = [rng.bytes(int(rng.randint(1, 4000))) for _ in range(n)]
    rec = recordio.MXRecordIO(path, "w")
    for p in payloads:
        rec.write(p)
    rec.close()
    return path, payloads


def test_native_reader_matches_python(tmp_path):
    path, payloads = _write_recfile(tmp_path)
    rd = _native.RecordReader(path)
    assert len(rd) == len(payloads)
    for i, p in enumerate(payloads):
        assert rd.read(i) == p
    rd.close()


def test_native_reader_missing_file(tmp_path):
    with pytest.raises(IOError):
        _native.RecordReader(str(tmp_path / "nope.rec"))


def test_prefetcher_schedule_order(tmp_path):
    path, payloads = _write_recfile(tmp_path, n=40, seed=1)
    pf = _native.Prefetcher(path, nthreads=4, capacity=3)
    rng = np.random.RandomState(2)
    order = rng.permutation(40)
    batches = [order[s:s + 8] for s in range(0, 40, 8)]
    for b in batches:
        pf.schedule(b)
    for b in batches:
        got = pf.next()
        assert got == [payloads[i] for i in b]
    assert pf.next() is None
    pf.close()


def test_pool_reuse(tmp_path):
    """A closed consumer returns its buffers to the global pool; the next
    consumer's allocations must HIT instead of malloc'ing fresh (a live
    prefetcher recycles its own buffers without touching the pool, so
    reuse is observable only across consumer lifetimes — asserting on
    one prefetcher's cumulative stats only passed when earlier tests had
    primed the pool)."""
    path, _ = _write_recfile(tmp_path, n=16, seed=3)

    def run_once():
        pf = _native.Prefetcher(path, nthreads=2, capacity=2)
        for _ in range(6):
            pf.schedule(list(range(8)))
        for _ in range(6):
            assert pf.next() is not None
        pf.close()

    run_once()
    h0, _m0 = _native.pool_stats()
    run_once()  # identical buffer sizes: must be served from the pool
    h1, _m1 = _native.pool_stats()
    assert h1 > h0, "second consumer should reuse pooled buffers"


def test_image_record_iter_native_path(tmp_path):
    """End to end: pack images → native streaming iterator → batches match
    the pure-python fallback batch for batch."""
    import mxnet_tpu as mx

    path = str(tmp_path / "img.rec")
    rec = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(4)
    for i in range(12):
        img = (rng.rand(10, 10, 3) * 255).astype(np.uint8)
        rec.write(recordio.pack_img((0, float(i % 3), i, 0), img,
                                    img_fmt=".png"))
    rec.close()

    kw = dict(path_imgrec=path, data_shape=(3, 8, 8), batch_size=4,
              shuffle=False, seed=7)
    it_native = mx.io.ImageRecordIter(**kw)
    it_python = mx.io.ImageRecordIter(no_native=True, **kw)
    assert it_native._records is None, "native path not engaged"
    assert it_python._records is not None
    n = 0
    for b_n, b_p in zip(it_native, it_python):
        np.testing.assert_allclose(b_n.data[0].asnumpy(),
                                   b_p.data[0].asnumpy())
        np.testing.assert_allclose(b_n.label[0].asnumpy(),
                                   b_p.label[0].asnumpy())
        n += 1
    assert n == 3
    # second epoch after reset still streams
    it_native.reset()
    assert sum(1 for _ in it_native) == 3


def test_image_record_iter_midepoch_reset(tmp_path):
    """reset() mid-epoch drains in-flight batches and restarts cleanly on
    the SAME prefetcher (no index rescan, no leaked buffers)."""
    import mxnet_tpu as mx

    path = str(tmp_path / "img2.rec")
    rec = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(5)
    for i in range(20):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        rec.write(recordio.pack_img((0, float(i), i, 0), img,
                                    img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                               batch_size=4, shuffle=False, seed=1)
    next(it)  # consume one batch, leave the rest in flight
    pf_before = it._pf
    it.reset()
    assert it._pf is pf_before
    labels = []
    for b in it:
        labels.extend(b.label[0].asnumpy().tolist())
    assert labels == [float(i) for i in range(20)]


def test_image_record_iter_small_shard_pads_full_batch(tmp_path):
    """A shard smaller than one batch still yields a full-width batch
    (wrap-around tiling), matching provide_data."""
    import mxnet_tpu as mx

    path = str(tmp_path / "img3.rec")
    rec = recordio.MXRecordIO(path, "w")
    rng = np.random.RandomState(6)
    for i in range(3):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        rec.write(recordio.pack_img((0, float(i), i, 0), img,
                                    img_fmt=".png"))
    rec.close()
    for no_native in (False, True):
        it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                                   batch_size=8, shuffle=False,
                                   no_native=no_native)
        b = next(it)
        assert b.data[0].shape == (8, 3, 8, 8)
        assert b.pad == 5
        assert b.label[0].asnumpy().tolist() == \
            [0.0, 1.0, 2.0, 0.0, 1.0, 2.0, 0.0, 1.0]
