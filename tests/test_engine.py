"""NaiveEngine debug-lever tests (reference: MXNET_ENGINE_TYPE=NaiveEngine
serial engine, the bisection tool for async/scheduling bugs — SURVEY §5
race-detection row)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, nd


@pytest.fixture
def naive():
    prev = engine.engine_type()
    engine.set_engine_type("NaiveEngine")
    yield
    engine.set_engine_type(prev)


def _train(n_steps=3):
    mx.random.seed(5)
    net = gluon.nn.Dense(3)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 4)))
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.array(np.random.RandomState(0).randn(4, 4).astype(np.float32))
    for _ in range(n_steps):
        with autograd.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(4)
    return net, net.weight.data().asnumpy()


def test_naive_engine_matches_threaded(naive):
    _, w_naive = _train()
    engine.set_engine_type("ThreadedEnginePerDevice")
    _, w_fast = _train()
    np.testing.assert_allclose(w_naive, w_fast, rtol=1e-5, atol=1e-6)


def test_naive_engine_bypasses_cached_op(naive):
    net, _ = _train()
    assert net._cached_op is None, "NaiveEngine must not build CachedOp"


def test_threaded_engine_builds_cached_op():
    net, _ = _train()
    assert net._cached_op is not None


def test_naive_engine_dispatch_is_synchronous(naive, monkeypatch):
    """NaiveEngine must block on every op result (the mechanism that
    surfaces device errors at the faulting op); threaded mode must not."""
    import jax

    calls = []
    real = jax.block_until_ready

    def spy(x):
        calls.append(1)
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", spy)
    nd.relu(nd.array(np.ones((2, 2), np.float32)))
    assert calls, "naive dispatch did not block on the op result"

    engine.set_engine_type("ThreadedEnginePerDevice")
    calls.clear()
    nd.relu(nd.array(np.ones((2, 2), np.float32)))
    assert not calls, "threaded dispatch must stay asynchronous"


def test_naive_engine_wraps_device_error(naive, monkeypatch):
    """A failure surfacing at block_until_ready is rewrapped as MXNetError
    naming the op."""
    import jax

    def boom(x):
        raise RuntimeError("async device explosion")

    monkeypatch.setattr(jax, "block_until_ready", boom)
    with pytest.raises(mx.MXNetError, match="relu.*NaiveEngine"):
        nd.relu(nd.array(np.ones((2, 2), np.float32)))


def test_engine_type_validation():
    with pytest.raises(mx.MXNetError):
        engine.set_engine_type("WarpEngine")


def test_bulk_compat():
    prev = engine.set_bulk_size(30)
    with engine.bulk(5):
        pass
    engine.set_bulk_size(prev)


def test_bad_env_engine_type_raises_every_call(monkeypatch):
    monkeypatch.setenv("MXT_ENGINE_TYPE", "naive")  # typo'd value
    monkeypatch.setattr(engine, "_type", None)
    with pytest.raises(mx.MXNetError):
        engine.engine_type()
    with pytest.raises(mx.MXNetError):  # not cached as accepted
        engine.is_naive()
    monkeypatch.setattr(engine, "_type", "ThreadedEnginePerDevice")
