"""Serialization tests: MXNet binary .params, StableHLO export/import,
nnvm symbol-json execution (reference: tests/python/unittest/test_ndarray.py
save/load cases + test_gluon.py SymbolBlock cases)."""
import json
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, serialization
from mxnet_tpu.gluon import nn


def test_params_binary_roundtrip(tmp_path):
    f = str(tmp_path / "x.params")
    data = {"w": nd.array(np.random.rand(3, 4).astype(np.float32)),
            "b": nd.array(np.arange(5, dtype=np.float32)),
            "i": nd.array(np.arange(6).reshape(2, 3), dtype=np.int32)}
    nd.save(f, data)
    loaded = nd.load(f)
    assert set(loaded) == {"w", "b", "i"}
    for k in data:
        assert np.array_equal(loaded[k].asnumpy(), data[k].asnumpy()), k
        assert loaded[k].dtype == data[k].dtype


def test_params_binary_list_roundtrip(tmp_path):
    f = str(tmp_path / "l.params")
    nd.save(f, [nd.ones((2, 2)), nd.zeros((3,))])
    loaded = nd.load(f)
    assert isinstance(loaded, list) and len(loaded) == 2
    assert np.allclose(loaded[0].asnumpy(), 1)


def test_params_binary_layout_is_mxnet_compatible(tmp_path):
    """Byte-level check of the container header the reference C++ reader
    expects (kMXAPINDListMagic, V2 array magic, int64 dims)."""
    f = str(tmp_path / "h.params")
    nd.save(f, {"x": nd.ones((2, 3))})
    raw = open(f, "rb").read()
    magic, reserved, n = struct.unpack("<QQQ", raw[:24])
    assert magic == 0x112 and reserved == 0 and n == 1
    arr_magic, stype, ndim = struct.unpack("<IiI", raw[24:36])
    assert arr_magic == 0xF993FAC9 and stype == 0 and ndim == 2
    dims = struct.unpack("<2q", raw[36:52])
    assert dims == (2, 3)
    dev_type, dev_id, type_flag = struct.unpack("<iii", raw[52:64])
    assert type_flag == 0  # float32
    payload = np.frombuffer(raw[64:64 + 24], dtype=np.float32)
    assert np.allclose(payload, 1.0)


def test_params_v1_read(tmp_path):
    """Hand-write a V1 (uint32 dims) file; reader must accept it."""
    f = str(tmp_path / "v1.params")
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = struct.pack("<QQQ", 0x112, 0, 1)
    out += struct.pack("<I", 0xF993FAC8)  # V1: no stype
    out += struct.pack("<I", 2) + struct.pack("<2I", 2, 3)
    out += struct.pack("<ii", 1, 0) + struct.pack("<i", 0)
    out += arr.tobytes()
    out += struct.pack("<Q", 1) + struct.pack("<Q", 3) + b"old"
    open(f, "wb").write(out)
    loaded = nd.load(f)
    assert np.array_equal(loaded["old"].asnumpy(), arr)


def test_gluon_save_load_through_binary(tmp_path):
    net = nn.Dense(4, in_units=3)
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.Dense(4, in_units=3)
    net2.load_parameters(f)
    x = mx.random.uniform(shape=(2, 3))
    assert np.allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    serialization.save_checkpoint(
        prefix, 3, None,
        {"fc_weight": nd.ones((2, 2))}, {"bn_mean": nd.zeros((2,))})
    sym, args, aux = serialization.load_checkpoint(prefix, 3)
    assert sym is None
    assert np.allclose(args["fc_weight"].asnumpy(), 1)
    assert "bn_mean" in aux


def test_export_import_stablehlo(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu", in_units=5),
                nn.Dense(3, in_units=8))
    net.initialize()
    net.hybridize()
    x = mx.random.uniform(shape=(2, 5))
    expect = net(x).asnumpy()

    prefix = str(tmp_path / "model")
    net.export(prefix, epoch=0)
    assert (tmp_path / "model-symbol.json").exists()
    assert (tmp_path / "model-0000.params").exists()
    assert (tmp_path / "model-0000.stablehlo").exists()

    sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0000.params")
    got = sb(x).asnumpy()
    assert np.allclose(got, expect, atol=1e-5)


def test_import_reference_nnvm_json(tmp_path):
    """Execute a hand-built reference-style symbol.json (the format real
    MXNet exports) against the op registry."""
    graph = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc0_weight", "inputs": []},
            {"op": "null", "name": "fc0_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc0",
             "attrs": {"num_hidden": "4", "no_bias": "False"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "relu0",
             "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
            {"op": "null", "name": "sm_label", "inputs": []},
            {"op": "SoftmaxOutput", "name": "softmax", "attrs": {},
             "inputs": [[4, 0, 0], [5, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2, 5],
        "node_row_ptr": list(range(8)),
        "heads": [[6, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10700]},
    }
    sym_path = str(tmp_path / "ref-symbol.json")
    with open(sym_path, "w") as f:
        json.dump(graph, f)
    w = np.random.rand(4, 3).astype(np.float32)
    b = np.zeros(4, np.float32)
    nd.save(str(tmp_path / "ref-0000.params"),
            {"arg:fc0_weight": nd.array(w), "arg:fc0_bias": nd.array(b)})

    sb = gluon.SymbolBlock.imports(sym_path, ["data"],
                                   str(tmp_path / "ref-0000.params"))
    x = np.random.rand(2, 3).astype(np.float32)
    got = sb(nd.array(x)).asnumpy()
    logits = x @ w.T
    relu = np.maximum(logits, 0)
    expect = np.exp(relu) / np.exp(relu).sum(-1, keepdims=True)
    assert np.allclose(got, expect, atol=1e-5)


def test_import_nnvm_conv_bn_graph(tmp_path):
    """Conv + BatchNorm + Pooling graph — the serving shape of a real CNN
    export (BatchNorm uses aux moving stats at inference)."""
    graph = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "conv_weight", "inputs": []},
            {"op": "Convolution", "name": "conv",
             "attrs": {"kernel": "(3, 3)", "num_filter": "2",
                       "pad": "(1, 1)", "no_bias": "True"},
             "inputs": [[0, 0, 0], [1, 0, 0]]},
            {"op": "null", "name": "bn_gamma", "inputs": []},
            {"op": "null", "name": "bn_beta", "inputs": []},
            {"op": "null", "name": "bn_moving_mean", "inputs": []},
            {"op": "null", "name": "bn_moving_var", "inputs": []},
            {"op": "BatchNorm", "name": "bn",
             "attrs": {"eps": "0.001", "fix_gamma": "False"},
             "inputs": [[2, 0, 0], [3, 0, 0], [4, 0, 0], [5, 0, 0],
                        [6, 0, 0]]},
            {"op": "Pooling", "name": "pool",
             "attrs": {"kernel": "(2, 2)", "pool_type": "max",
                       "stride": "(2, 2)"},
             "inputs": [[7, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 3, 4, 5, 6],
        "heads": [[8, 0, 0]],
    }
    sym_path = str(tmp_path / "cnn-symbol.json")
    with open(sym_path, "w") as f:
        json.dump(graph, f)
    rng = np.random.RandomState(0)
    params = {
        "arg:conv_weight": nd.array(rng.rand(2, 3, 3, 3).astype(np.float32)),
        "arg:bn_gamma": nd.ones((2,)),
        "arg:bn_beta": nd.zeros((2,)),
        "aux:bn_moving_mean": nd.zeros((2,)),
        "aux:bn_moving_var": nd.ones((2,)),
    }
    nd.save(str(tmp_path / "cnn-0000.params"), params)
    sb = gluon.SymbolBlock.imports(sym_path, ["data"],
                                   str(tmp_path / "cnn-0000.params"))
    out = sb(nd.array(rng.rand(1, 3, 8, 8).astype(np.float32)))
    assert out.shape == (1, 2, 4, 4)


def test_resnet_export_import_roundtrip(tmp_path):
    net = gluon.model_zoo.vision.get_model("resnet18_v1", classes=10,
                                           thumbnail=True)
    net.initialize()
    net.hybridize()
    x = mx.random.uniform(shape=(1, 3, 16, 16))
    expect = net(x).asnumpy()
    prefix = str(tmp_path / "resnet")
    net.export(prefix)
    sb = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                   prefix + "-0000.params")
    assert np.allclose(sb(x).asnumpy(), expect, atol=1e-4)
