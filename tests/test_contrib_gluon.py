"""gluon.contrib layers + Estimator + higher-order grad + DLPack tests
(reference model: tests/python/unittest/test_gluon_contrib.py,
test_gluon_estimator.py, test_higher_order_grad.py — SURVEY §4)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import contrib, nn


def test_hybrid_concurrent():
    net = contrib.nn.HybridConcurrent(axis=1)
    net.add(nn.Dense(3, in_units=4), nn.Dense(2, in_units=4),
            contrib.nn.Identity())
    net.initialize()
    out = net(nd.ones((2, 4)))
    assert out.shape == (2, 3 + 2 + 4)
    net.hybridize()
    out2 = net(nd.ones((2, 4)))
    onp.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=1e-5)


def test_identity():
    layer = contrib.nn.Identity()
    x = nd.random.uniform(shape=(2, 3))
    onp.testing.assert_array_equal(layer(x).asnumpy(), x.asnumpy())


def test_pixel_shuffle_2d():
    layer = contrib.nn.PixelShuffle2D(2)
    x = nd.array(onp.arange(16, dtype=onp.float32).reshape(1, 4, 2, 2))
    out = layer(x)
    assert out.shape == (1, 1, 4, 4)
    # spot check: channel blocks interleave into space
    o = out.asnumpy()[0, 0]
    assert o[0, 0] == 0.0 and o[0, 1] == 4.0
    assert o[1, 0] == 8.0 and o[1, 1] == 12.0


def test_pixel_shuffle_1d_3d_shapes():
    x1 = nd.random.uniform(shape=(2, 6, 5))
    assert contrib.nn.PixelShuffle1D(3)(x1).shape == (2, 2, 15)
    x3 = nd.random.uniform(shape=(1, 8, 2, 3, 4))
    assert contrib.nn.PixelShuffle3D(2)(x3).shape == (1, 1, 4, 6, 8)


def test_pixel_shuffle_channel_major_ordering():
    # C=2, f=2: reference/torch ordering splits channels channel-major
    x = nd.array(onp.arange(8, dtype=onp.float32).reshape(1, 4, 2))
    out = contrib.nn.PixelShuffle1D(2)(x).asnumpy()[0]
    onp.testing.assert_array_equal(out, [[0, 2, 1, 3], [4, 6, 5, 7]])


def test_sync_batchnorm_alias():
    assert contrib.nn.SyncBatchNorm is nn.SyncBatchNorm


def test_estimator_fit_and_handlers(tmp_path):
    from mxnet_tpu import gluon, metric
    from mxnet_tpu.gluon.contrib import estimator as est

    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"),
            nn.Dense(2, in_units=8))
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    x = rng.uniform(-1, 1, (64, 4)).astype(onp.float32)
    y = (x[:, 0] > 0).astype(onp.float32)
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    loader = DataLoader(ArrayDataset(x, y), batch_size=16)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.02})
    e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                      train_metrics=[metric.Accuracy()], trainer=trainer)
    ckpt = est.CheckpointHandler(str(tmp_path), save_every=1)
    e.fit(loader, epochs=8, event_handlers=[ckpt])
    import os

    assert os.path.exists(str(tmp_path / "model-0008.params"))
    name, acc = e.train_metrics[0].get()
    assert acc > 0.6


def test_estimator_early_stopping():
    from mxnet_tpu import gluon, metric
    from mxnet_tpu.gluon.contrib import estimator as est

    net = nn.Dense(2, in_units=4)
    net.initialize()
    x = onp.zeros((8, 4), onp.float32)
    y = onp.zeros((8,), onp.float32)
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    loader = DataLoader(ArrayDataset(x, y), batch_size=8)
    acc = metric.Accuracy()
    e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                      train_metrics=[acc])
    stop = est.EarlyStoppingHandler(acc, patience=0, mode="max")
    e.fit(loader, epochs=50, event_handlers=[stop])
    # constant data: metric never improves after epoch 1 → stops early
    assert stop.stop_training


def test_higher_order_grad_polynomial():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        gx, = autograd.grad(y, [x], create_graph=True)
        z = gx.sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy(),
                                rtol=1e-5)


def test_higher_order_grad_trig():
    x = nd.array([0.3, 0.7])
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x)
        g1, = autograd.grad(y, [x], create_graph=True)
        s = (g1 * g1).sum()
    s.backward()
    xa = x.asnumpy()
    onp.testing.assert_allclose(x.grad.asnumpy(),
                                -2 * onp.cos(xa) * onp.sin(xa), rtol=1e-5)


def test_higher_order_through_network():
    """Gradient-penalty style double backward through Dense layers."""
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4, activation="tanh"),
            nn.Dense(1, in_units=8))
    net.initialize(mx.init.Xavier())
    x = nd.random.uniform(-1, 1, shape=(4, 4))
    x.attach_grad()
    params = list(net.collect_params().values())
    with autograd.record():
        out = net(x).sum()
        gx, = autograd.grad(out, [x], create_graph=True)
        penalty = (gx * gx).sum()
    penalty.backward()
    g = params[0].grad()
    assert float(nd.abs(g).sum().asscalar()) > 0


def test_grad_without_create_graph_unchanged():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    gx, = autograd.grad(y, [x])
    assert float(gx.asscalar()) == 4.0


def test_dlpack_roundtrip():
    import jax.numpy as jnp

    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    cap = nd.to_dlpack_for_read(x)
    back = nd.from_dlpack(cap)
    onp.testing.assert_array_equal(back.asnumpy(), x.asnumpy())
    # direct jax interop
    j = jnp.asarray([1.0, 5.0])
    nd2 = nd.from_dlpack(j)
    onp.testing.assert_array_equal(nd2.asnumpy(), onp.array([1.0, 5.0]))
