"""Gluon Block/HybridBlock/Parameter tests.

Modeled on the reference's tests/python/unittest/test_gluon.py:? — layer
shape/output checks, deferred init, hybridize parity with imperative
execution, save/load roundtrips.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init=mx.init.One())
    assert p.data().shape == (10, 10)
    assert float(p.data().sum().asscalar()) == 100.0
    assert p.grad().shape == (10, 10)


def test_parameter_deferred_init():
    dense = nn.Dense(5)
    dense.initialize()
    with pytest.raises(Exception):
        dense.weight.data()
    out = dense(nd.ones((2, 3)))
    assert out.shape == (2, 5)
    assert dense.weight.shape == (5, 3)


def test_parameter_sharing():
    d1 = nn.Dense(5, in_units=5, prefix="dense_")
    d2 = nn.Dense(5, in_units=5, params=d1.collect_params())
    d1.initialize()
    x = mx.random.uniform(shape=(2, 5))
    assert np.allclose(d1(x).asnumpy(), d2(x).asnumpy())


def test_name_scope_prefixes():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4))
        net.add(nn.Dense(4))
    names = list(net.collect_params().keys())
    assert all(n.startswith("model_dense") for n in names)
    assert len(set(names)) == 4


def test_dense_forward_values():
    layer = nn.Dense(3, in_units=2, use_bias=True)
    layer.initialize(init=mx.init.One())
    out = layer(nd.array([[2.0, 3.0]]))
    # weight all ones, bias zeros: each output = 5
    assert np.allclose(out.asnumpy(), [[5.0, 5.0, 5.0]])


def test_hybridize_matches_imperative():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.random.uniform(shape=(5, 8))
    imp = net(x).asnumpy()
    net.hybridize()
    hyb = net(x).asnumpy()
    assert np.allclose(imp, hyb, atol=1e-5)


def test_hybridize_gradients_match():
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh", in_units=4),
                    nn.Dense(2, in_units=8))
        return net

    mx.random.seed(7)
    net1 = build()
    net1.initialize(mx.init.Xavier())
    mx.random.seed(7)
    net2 = build()
    net2.initialize(mx.init.Xavier())
    net2.hybridize()
    x = mx.random.uniform(shape=(3, 4))
    for net in (net1, net2):
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
    for (k1, p1), (k2, p2) in zip(net1.collect_params().items(),
                                  net2.collect_params().items()):
        assert np.allclose(p1.grad().asnumpy(), p2.grad().asnumpy(),
                           atol=1e-5), k1


def test_hybridize_batchnorm_aux_update():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=4), nn.BatchNorm(axis=-1))
    net.initialize()
    net.hybridize()
    bn = net[1]
    x = mx.random.uniform(shape=(8, 4))
    with autograd.record():
        net(x)
    m1 = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    m2 = bn.running_mean.data().asnumpy()
    assert not np.allclose(m1, 0)
    assert not np.allclose(m1, m2)


def test_batchnorm_train_vs_eval():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = mx.random.normal(shape=(4, 3, 2, 2), scale=5.0)
    with autograd.record():
        y_train = bn(x)
    y_eval = bn(x)
    # training normalizes batch stats; eval uses (barely moved) moving stats
    assert abs(y_train.asnumpy().mean()) < 1e-3
    assert not np.allclose(y_train.asnumpy(), y_eval.asnumpy())


def test_conv2d_shapes():
    layer = nn.Conv2D(16, kernel_size=3, strides=2, padding=1)
    layer.initialize()
    out = layer(nd.ones((2, 3, 32, 32)))
    assert out.shape == (2, 16, 16, 16)
    assert layer.weight.shape == (16, 3, 3, 3)


def test_conv_transpose_roundtrip_shape():
    layer = nn.Conv2DTranspose(8, kernel_size=4, strides=2, padding=1,
                               in_channels=3)
    layer.initialize()
    out = layer(nd.ones((1, 3, 16, 16)))
    assert out.shape == (1, 8, 32, 32)


def test_pooling_shapes():
    x = nd.ones((2, 3, 8, 8))
    assert nn.MaxPool2D()(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(pool_size=3, strides=1, padding=1)(x).shape == \
        (2, 3, 8, 8)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True)(x).shape == \
        (2, 3, 4, 4)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    out = emb(nd.array([[1, 2], [3, 4]]))
    assert out.shape == (2, 2, 4)
    with autograd.record():
        loss = emb(nd.array([0, 1])).sum()
    loss.backward()
    g = emb.weight.grad().asnumpy()
    assert np.allclose(g[0], 1) and np.allclose(g[2], 0)


def test_layernorm_values():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = mx.random.normal(shape=(3, 6), scale=4.0)
    y = ln(x).asnumpy()
    assert np.allclose(y.mean(-1), 0, atol=1e-5)
    assert np.allclose(y.std(-1), 1, atol=2e-2)


def test_activations():
    x = nd.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    assert np.allclose(nn.Activation("relu")(x).asnumpy(),
                       [0, 0, 0, 0.5, 2.0])
    assert np.allclose(nn.LeakyReLU(0.1)(x).asnumpy(),
                       [-0.2, -0.05, 0, 0.5, 2.0], atol=1e-6)
    y = nn.SELU()(x).asnumpy()
    assert y[3] > 0.5 and y[0] < 0
    sw = nn.Swish()(x).asnumpy()
    assert np.allclose(sw, x.asnumpy() / (1 + np.exp(-x.asnumpy())),
                       atol=1e-5)


def test_sequential_slicing():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4), nn.Dense(5), nn.Dense(6))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert len(net[1:]) == 2


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net2.load_parameters(f)
    x = mx.random.uniform(shape=(2, 4))
    assert np.allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_save_load_deferred(tmp_path):
    net = nn.Dense(3)
    net.initialize()
    net(nd.ones((1, 5)))
    f = str(tmp_path / "d.params")
    net.save_parameters(f)
    net2 = nn.Dense(3)
    net2.load_parameters(f)
    assert net2.weight.shape == (3, 5)


def test_losses():
    L = gluon.loss
    pred = nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = nd.array([[1.5, 2.0], [3.0, 3.0]])
    l2 = L.L2Loss()(pred, label).asnumpy()
    assert np.allclose(l2, [0.0625, 0.25])
    l1 = L.L1Loss()(pred, label).asnumpy()
    assert np.allclose(l1, [0.25, 0.5])
    h = L.HuberLoss(rho=0.3)(pred, label).asnumpy()
    assert h.shape == (2,)

    logits = nd.array([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
    ce = L.SoftmaxCrossEntropyLoss()(logits, nd.array([0, 1])).asnumpy()
    assert np.all(ce < 1e-3)
    ce_dense = L.SoftmaxCrossEntropyLoss(sparse_label=False)(
        logits, nd.array([[1.0, 0, 0], [0, 1.0, 0]])).asnumpy()
    assert np.allclose(ce, ce_dense, atol=1e-5)

    bce = L.SigmoidBinaryCrossEntropyLoss()
    p = nd.array([[100.0], [-100.0]])
    y = nd.array([[1.0], [0.0]])
    assert np.all(bce(p, y).asnumpy() < 1e-3)


def test_loss_backward_through_net():
    net = nn.Dense(3, in_units=4)
    net.initialize()
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.random.uniform(shape=(2, 4))
    with autograd.record():
        l = lossfn(net(x), nd.array([0, 2]))
    l.backward()
    assert net.weight.grad().asnumpy().shape == (3, 4)
    assert np.abs(net.weight.grad().asnumpy()).sum() > 0


def test_split_and_load():
    data = nd.arange(0, 12).reshape((6, 2))
    parts = gluon.utils.split_data(data, 3)
    assert [p.shape for p in parts] == [(2, 2)] * 3
    loaded = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert len(loaded) == 2
    assert np.allclose(
        np.concatenate([p.asnumpy() for p in loaded]), data.asnumpy())


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    new_norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert np.isclose(new_norm, 1.0, atol=1e-5)
    assert total > 1.0


def test_block_cast():
    import jax.numpy as jnp

    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.cast("float16")
    assert net.weight.data().dtype == np.float16
    out = net(nd.ones((1, 3)).astype(np.float16))
    assert out.dtype == np.float16


def test_summary_runs(capsys):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=2))
    net.initialize()
    net.summary()
    assert "Total params" in capsys.readouterr().out


def test_constant_parameter():
    const = gluon.Constant("c", nd.array([1.0, 2.0]))
    const.initialize()
    assert np.allclose(const.data().asnumpy(), [1.0, 2.0])
    assert const.grad_req == "null"


def test_hybridize_retrace_on_new_shape():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    a = net(nd.ones((2, 3)))
    b = net(nd.ones((5, 3)))
    assert a.shape == (2, 4) and b.shape == (5, 4)
    assert len(net._cached_op._graphs) == 2


def test_hybridize_remat_matches_plain():
    """remat=True (activation checkpointing) must change memory, not
    math: identical outputs and gradients."""
    import numpy as onp

    from mxnet_tpu import autograd

    def run(remat):
        mx.random.seed(11)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"),
                nn.Dense(8, activation="relu"), nn.Dense(4))
        net.initialize(mx.init.Xavier())
        net(nd.ones((1, 5)))
        net.hybridize(static_alloc=True, remat=remat)
        x = nd.array(onp.random.RandomState(3).randn(6, 5)
                     .astype(onp.float32))
        x.attach_grad()
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        return (float(loss.asscalar()), x.grad.asnumpy(),
                net[0].weight.grad().asnumpy())

    l0, xg0, wg0 = run(False)
    l1, xg1, wg1 = run(True)
    assert l0 == pytest.approx(l1, rel=1e-6)
    onp.testing.assert_allclose(xg0, xg1, rtol=1e-6)
    onp.testing.assert_allclose(wg0, wg1, rtol=1e-6)


def test_batch_norm_training_gradients_finite_difference():
    """The training BatchNorm backward is a hand-written custom vjp
    (ops/nn_ops.py _bn_train, the bf16-clean TPU path) — pin its dx /
    dgamma / dbeta against central finite differences so a future edit
    to the formula cannot pass silently."""
    r = np.random.RandomState(0)
    x0 = r.randn(4, 3, 5, 5).astype(np.float32)
    g0 = (np.abs(r.randn(3)) + 0.5).astype(np.float32)
    b0 = r.randn(3).astype(np.float32)
    coef = r.randn(4, 3, 5, 5).astype(np.float32)
    c = nd.array(coef)

    def loss_val(xv, gv, bv):
        with autograd.record():
            out = nd.BatchNorm(xv, gv, bv, nd.zeros(3), nd.ones(3))[0]
            return float(((out * c).sum()).asscalar())

    x, g, b = nd.array(x0), nd.array(g0), nd.array(b0)
    for v in (x, g, b):
        v.attach_grad()
    with autograd.record():
        out = nd.BatchNorm(x, g, b, nd.zeros(3), nd.ones(3))[0]
        loss = (out * c).sum()
    loss.backward()

    eps = 1e-3
    rs = np.random.RandomState(1)
    for name, base, grad in (("x", x0, x.grad), ("g", g0, g.grad),
                             ("b", b0, b.grad)):
        an = grad.asnumpy()
        k = min(5, base.size)
        for flat in rs.choice(base.size, k, replace=False):
            idx = np.unravel_index(flat, base.shape)
            ap, am = base.copy(), base.copy()
            ap[idx] += eps
            am[idx] -= eps
            args_p = {"x": (nd.array(ap), g, b),
                      "g": (x, nd.array(ap), b),
                      "b": (x, g, nd.array(ap))}[name]
            args_m = {"x": (nd.array(am), g, b),
                      "g": (x, nd.array(am), b),
                      "b": (x, g, nd.array(am))}[name]
            fd = (loss_val(*args_p) - loss_val(*args_m)) / (2 * eps)
            assert abs(fd - an[idx]) <= 2e-2 * max(1.0, abs(fd)), \
                (name, idx, fd, an[idx])


def test_layer_norm_gradients_finite_difference():
    """layer_norm backward is also a hand-written custom vjp
    (ops/nn_ops.py _ln_train) — pin dx / dgamma / dbeta the same way."""
    r = np.random.RandomState(2)
    x0 = r.randn(3, 4, 6).astype(np.float32)
    g0 = (np.abs(r.randn(6)) + 0.5).astype(np.float32)
    b0 = r.randn(6).astype(np.float32)
    c = nd.array(r.randn(3, 4, 6).astype(np.float32))

    def loss_val(xv, gv, bv):
        out = nd.LayerNorm(xv, gv, bv, axis=-1)
        return float(((out * c).sum()).asscalar())

    x, g, b = nd.array(x0), nd.array(g0), nd.array(b0)
    for v in (x, g, b):
        v.attach_grad()
    with autograd.record():
        out = nd.LayerNorm(x, g, b, axis=-1)
        loss = (out * c).sum()
    loss.backward()

    eps = 1e-3
    rs = np.random.RandomState(3)
    for name, base, grad in (("x", x0, x.grad), ("g", g0, g.grad),
                             ("b", b0, b.grad)):
        an = grad.asnumpy()
        for flat in rs.choice(base.size, min(5, base.size),
                              replace=False):
            idx = np.unravel_index(flat, base.shape)
            ap, am = base.copy(), base.copy()
            ap[idx] += eps
            am[idx] -= eps
            args_p = {"x": (nd.array(ap), g, b),
                      "g": (x, nd.array(ap), b),
                      "b": (x, g, nd.array(ap))}[name]
            args_m = {"x": (nd.array(am), g, b),
                      "g": (x, nd.array(am), b),
                      "b": (x, g, nd.array(am))}[name]
            fd = (loss_val(*args_p) - loss_val(*args_m)) / (2 * eps)
            assert abs(fd - an[idx]) <= 2e-2 * max(1.0, abs(fd)), \
                (name, idx, fd, an[idx])


def test_embedding_matmul_lookup_matches_take():
    """matmul_lookup=True (the vocab-parallel one-hot-matmul lowering,
    r4 scale-proof finding) must be numerically identical to the gather
    path — forward and weight gradient."""
    import numpy as np

    rs = np.random.RandomState(3)
    w0 = rs.randn(11, 6).astype(np.float32)
    ids = nd.array(rs.randint(0, 11, (4, 5)), dtype="int32")

    outs, grads = [], []
    for matmul in (False, True):
        emb = gluon.nn.Embedding(11, 6, matmul_lookup=matmul)
        emb.initialize()
        emb(ids)  # resolve
        emb.weight.set_data(nd.array(w0))
        with autograd.record():
            y = emb(ids)
            loss = (y * y).sum()
        loss.backward()
        outs.append(y.asnumpy())
        grads.append(emb.weight.grad().asnumpy())
    np.testing.assert_allclose(outs[1], outs[0], rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(grads[1], grads[0], rtol=1e-5, atol=1e-6)
