"""On-chip-lane HARNESS guard: every tests_tpu case must execute
cleanly cpu-vs-cpu (tpu aliased to cpu) — a harness bug would void the
entire 251-case on-chip run, which only happens when real chip time is
available and can't be cheaply retried."""
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

_BODY = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax; jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {repo!r}); sys.path.insert(0, {tpudir!r})
import mxnet_tpu as mx

mx.tpu = mx.cpu  # cpu-vs-cpu: harness-path validation, not numerics

import test_tpu_parity as tp
import test_tpu_parity_ext as te

rec = lambda f, n, e: None
fails = []
for p in tp.CASES:
    family, name, fn, inputs, rtol, atol = p.values
    try:
        tp.test_op_parity(family, name, fn, inputs, rtol, atol, rec)
    except Exception as e:
        fails.append((family, name, repr(e)))
for p in te.CASES:
    family, name, fn, inputs, rtol, atol, mxu = p.values
    try:
        te.test_op_parity_ext(family, name, fn, inputs, rtol, atol,
                              mxu, rec)
    except Exception as e:
        fails.append((family, name, repr(e)))
print(f"CASES={{len(tp.CASES) + len(te.CASES)}} FAILS={{len(fails)}}")
for f in fails[:5]:
    print("FAIL", f)
assert not fails
"""


@pytest.mark.slow
@pytest.mark.heavy
def test_parity_lane_harness_executes_cpu_vs_cpu():
    code = _BODY.format(repo=os.path.abspath(REPO),
                        tpudir=os.path.abspath(
                            os.path.join(REPO, "tests_tpu")))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, env=env,
                       timeout=1800)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
    assert "FAILS=0" in r.stdout, r.stdout[-1500:]
