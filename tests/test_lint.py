"""mxlint analyzer tests: per-rule positives/negatives on the seeded
fixtures, the baseline (waiver) gate, CLI exit codes, and the live
op-registry invariants (no duplicate aliases, every op callable and
documented, no_grad markers honoured by autograd)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint.analyzer import analyze_paths  # noqa: E402
from tools.lint.baseline import (apply_baseline, load_baseline,  # noqa: E402
                                 save_baseline)
from tools.lint.registry_check import run_registry_check  # noqa: E402

FIXTURES = os.path.join("tools", "lint", "fixtures")


def _analyze(fixture):
    return analyze_paths([os.path.join(FIXTURES, fixture)], REPO)


def _rule(violations, rule):
    return [v for v in violations if v.rule == rule]


# --- rule families: one positive and one negative each ----------------------

def test_t1_flags_syncs_in_traced_regions():
    vs = _rule(_analyze("t1_host_sync.py"), "T1")
    errors = {v.context: v.message for v in vs if v.severity == "error"}
    assert any("asnumpy" in m for c, m in errors.items()
               if "hybrid_forward" in c)
    assert any("float()" in m for c, m in errors.items() if c == "bad_step")
    assert any("asarray" in m for c, m in errors.items()
               if c == "bad_scan_body")
    # eager sync downgrades to a warning
    assert any(v.severity == "warning" and v.context == "eager_glue"
               for v in vs)


def test_t1_inline_suppression():
    vs = _rule(_analyze("t1_host_sync.py"), "T1")
    assert not any(v.context.startswith("suppressed_sync") for v in vs)


def test_t1_engine_flush_is_a_sync_site():
    vs = _rule(_analyze("t1_engine_flush.py"), "T1")
    # flush() inside a jitted function is a hard error...
    assert any(v.severity == "error" and v.context == "bad_jitted_step"
               and "engine.flush" in v.message for v in vs)
    # ...but an eager segment boundary is legitimate use
    assert not any(v.context == "eager_boundary" for v in vs)


def test_t1_async_materialization_points_are_sync_sites():
    vs = _rule(_analyze("t1_engine_async.py"), "T1")
    # wait_to_read (worker-event wait) inside a jitted fn is an error
    assert any(v.severity == "error" and v.context == "bad_jitted_wait"
               and "wait_to_read" in v.message for v in vs)
    # ticket-style .result() join inside a jitted fn is an error
    assert any(v.severity == "error" and v.context == "bad_jitted_ticket"
               and "result" in v.message for v in vs)
    # eager drains / ticket joins are legitimate use — .result() must
    # not warn in eager glue (checkpoint drain paths rely on it)
    assert not any(v.context == "eager_drain" for v in vs)
    assert not any(v.context == "eager_ticket_join" for v in vs)


def test_t1_serving_materialize_def_is_exempt():
    vs = _rule(_analyze("t1_serving.py"), "T1")
    # the designated materialization def carries no eager warning
    assert not any(v.context == "_materialize" for v in vs)
    assert not any(v.context == "scheduler_demux" for v in vs)
    # the same sync outside the designated def still warns
    assert any(v.severity == "warning" and v.context == "leaky_sync"
               and "asnumpy" in v.message for v in vs)
    # and inside a traced region it is an error, exemption or not
    assert any(v.severity == "error"
               and v.context == "bad_traced_materialize" for v in vs)
    assert any(v.severity == "error"
               and v.context == "_hot_materialize" for v in vs)


def test_t1_lane_materialize_def_is_exempt():
    """The lanes' sync point (serving/lanes.py ``_lane_materialize``)
    gets the same scoped exemption as the scheduler's ``_materialize``
    — and only the eager half of it."""
    vs = _rule(_analyze("t1_serving_lanes.py"), "T1")
    assert not any(v.context == "_lane_materialize" for v in vs)
    assert not any(v.context == "decode_drain" for v in vs)
    assert any(v.severity == "warning" and v.context == "leaky_lane_sync"
               and "asnumpy" in v.message for v in vs)
    assert any(v.severity == "error"
               and v.context == "_hot_lane_materialize" for v in vs)


def test_t1_data_prefetch_def_is_exempt():
    """The data plane's transfer-thread wait (data/prefetch.py
    ``_prefetch``) gets the same scoped exemption as serving's
    ``_materialize`` — eager only."""
    vs = _rule(_analyze("t1_data_prefetch.py"), "T1")
    assert not any(v.context == "_prefetch" for v in vs)
    assert not any(v.context == "loader_loop" for v in vs)
    assert any(v.severity == "warning" and v.context == "leaky_wait"
               and "block_until_ready" in v.message for v in vs)
    assert any(v.severity == "error"
               and v.context == "bad_traced_prefetch" for v in vs)
    assert any(v.severity == "error"
               and v.context == "_hot_prefetch" for v in vs)


def test_t2_flags_control_flow_on_traced_values():
    vs = _rule(_analyze("t2_control_flow.py"), "T2")
    kinds = {(v.context, v.message.split("`")[1]) for v in vs}
    assert ("BadBlock.hybrid_forward", "if") in kinds
    assert ("bad_loss", "while") in kinds
    assert ("bad_loss", "assert") in kinds
    # config dispatch / static metadata in GoodBlock must NOT flag
    assert not any("GoodBlock" in v.context for v in vs)


def test_t3_flags_registry_inconsistencies():
    vs = _rule(_analyze("t3_registry.py"), "T3")
    msgs = {v.context: v.message for v in vs}
    assert "no_grad" in msgs["fix_argmax"]
    assert "docstring" in msgs["fix_undocumented"]
    assert any("duplicate" in v.message for v in vs)
    # documented + no_grad-marked op is clean
    assert "fix_sign" not in msgs


def test_t4_flags_nondeterminism_in_traces():
    vs = _rule(_analyze("t4_nondet.py"), "T4")
    contexts = {v.context for v in vs}
    assert "bad_dropout" in contexts
    assert "NoisyBlock.hybrid_forward" in contexts
    # keyed jax PRNG and eager host code must NOT flag
    assert "good_dropout" not in contexts
    assert "eager_logger" not in contexts


def test_t5_flags_host_view_mutation():
    vs = _rule(_analyze("t5_mutation.py"), "T5")
    contexts = [v.context for v in vs]
    assert contexts.count("clobber_weights") == 2
    assert contexts.count("clobber_fresh_view") == 3
    assert "fill_view" in contexts
    # mutating an explicit np.array() copy is fine
    assert "good_update" not in contexts


def test_recording_calls_allowed_in_hot_paths():
    vs = _analyze("t6_recording.py")
    contexts = {v.context for v in vs}
    # recording helper + telemetry/profiler calls must NOT flag, even
    # though instrumented_step is jitted and count() reads the clock
    assert "count" not in contexts
    assert "instrumented_step" not in contexts
    # a direct wall-clock read in a traced body still flags
    assert any(v.rule == "T4" and v.context == "bad_timed" for v in vs)


def test_tracing_calls_allowed_in_hot_paths():
    vs = _analyze("t6_tracing.py")
    contexts = {v.context for v in vs}
    # tracing.incident + the same-module span helper (whose
    # perf_counter stamp is the point) must NOT flag in the hot tick
    assert "add_span" not in contexts
    assert "traced_decode_tick" not in contexts
    # a real host sync next to the span bookkeeping still flags
    assert any(v.rule == "T1" and v.context == "bad_synced_tick"
               for v in vs)


def test_fleet_calls_allowed_in_hot_paths():
    vs = _analyze("t6_fleet.py")
    contexts = {v.context for v in vs}
    # fleet.incident + the same-module step hook (whose perf_counter
    # stamp is the point) must NOT flag in the hot training tick
    assert "on_step_record" not in contexts
    assert "traced_train_tick" not in contexts
    # the stride-allgather def is MATERIALIZE_DEFS-exempt: its eager
    # asnumpy is the intentional exchange boundary
    assert "_fleet_exchange" not in contexts
    # a real host sync in the jitted step body still flags
    assert any(v.rule == "T1" and v.context == "bad_synced_tick"
               for v in vs)


def test_capacity_hooks_allowed_in_hot_paths():
    vs = _analyze("t6_capacity.py")
    contexts = {v.context for v in vs}
    # capacity.note_* / lane_busy + the same-module hook helper (whose
    # perf_counter fallback is part of the contract) must NOT flag in
    # the hot decode tick
    assert "note_tick" not in contexts
    assert "traced_decode_tick" not in contexts
    # a real host sync in the jitted tick body still flags
    assert any(v.rule == "T1" and v.context == "bad_synced_tick"
               for v in vs)


def test_numerics_taps_allowed_in_hot_paths():
    vs = _analyze("t6_numerics.py")
    contexts = {v.context for v in vs}
    # numerics.tap / stats_of / record_compiled and the same-module tap
    # helper are pure in-trace stat math — must NOT flag in a jitted step
    assert "_tap_activations" not in contexts
    assert "traced_step" not in contexts
    # the tier's stride-boundary fetch is MATERIALIZE_DEFS-exempt
    assert "_materialize" not in contexts
    # a real host sync next to a tap still flags
    assert any(v.rule == "T1" and v.context == "bad_stat_tick"
               for v in vs)


def test_memwatch_hooks_allowed_in_hot_paths():
    vs = _analyze("t6_memwatch.py")
    contexts = {v.context for v in vs}
    # memwatch/costs hooks (track/donated/note) and the same-module
    # ledger helper must not flag in dispatch hot paths, and handing
    # just-donated handles to _mw.donated must not trip T6
    assert "dispatch" not in contexts
    assert "track" not in contexts
    assert not any(v.rule == "T6" for v in vs)
    # a real host sync next to the hooks still flags
    assert any(v.rule == "T1" and v.context == "bad_synced_dispatch"
               for v in vs)


def test_clean_fixture_has_no_violations():
    assert _analyze("clean.py") == []


def test_t6_flags_use_after_donation():
    vs = _rule(_analyze("t6_donation.py"), "T6")
    contexts = [v.context for v in vs]
    # every donating-binding shape seeds one true positive
    assert "local_binding_read_after" in contexts
    assert "loop_carried" in contexts
    assert "branch_partial_rebind" in contexts
    assert "Stepper.run" in contexts
    assert "factory_read_after" in contexts
    assert "inline_read_after" in contexts
    # messages name the donating call and position for triage
    msg = next(v.message for v in vs
               if v.context == "local_binding_read_after")
    assert "donate" in msg and "position 0" in msg


def test_t6_false_positive_traps_stay_quiet():
    vs = _rule(_analyze("t6_donation.py"), "T6")
    contexts = {v.context for v in vs}
    for clean in ("local_binding_rebound", "read_before_call",
                  "loop_rebound", "branch_full_rebind",
                  "Stepper.run_clean", "sanitizer_handoff"):
        assert clean not in contexts, sorted(contexts)


def test_t7_flags_donation_aliasing():
    vs = _rule(_analyze("t7_donation.py"), "T7")
    contexts = [v.context for v in vs]
    assert "same_name_donated_and_read" in contexts
    assert "same_name_double_donation" in contexts
    assert "view_aliases_parent" in contexts
    assert "member_aliases_container" in contexts
    assert "closure_captures_donated" in contexts
    assert contexts.count("unpack_aliases") == 2  # both members flag


def test_t7_false_positive_traps_stay_quiet():
    vs = _rule(_analyze("t7_donation.py"), "T7")
    contexts = {v.context for v in vs}
    for clean in ("distinct_elements_ok", "fresh_math_ok", "copy_ok",
                  "closure_clean"):
        assert clean not in contexts, sorted(contexts)


def test_t8_flags_rule_table_hazards():
    vs = _rule(_analyze("t8_partition.py"), "T8")
    msgs = [(v.severity, v.message) for v in vs]
    assert any(s == "error" and "does not compile" in m for s, m in msgs)
    assert any(s == "error" and "unreachable" in m for s, m in msgs)
    assert any(s == "error" and "duplicate pattern" in m for s, m in msgs)
    # the Trainer(partition_rules=NAME) site resolves the module-level
    # table and flags the silent-replicate fallback
    assert any(s == "warning" and "silently replicate" in m
               for s, m in msgs)
    assert len(vs) == 4, [v.to_dict() for v in vs]


def test_t8_negatives_stay_quiet():
    vs = _rule(_analyze("t8_partition.py"), "T8")
    lines = {v.line for v in vs}
    src = open(os.path.join(FIXTURES, "t8_partition.py")).read()
    good_line = src[:src.index("GOOD = ")].count("\n") + 1
    policy_line = src[:src.index("return place_params")].count("\n") + 1
    assert good_line not in lines       # terminal catch-all is clean
    assert policy_line not in lines     # on_unmatched= policy is clean


def test_t8_engine_and_builtin_tables_clean():
    # the engine's own family tables and every in-tree consumer must
    # pass the rule they taught the linter
    vs = analyze_paths(
        ["mxnet_tpu/parallel/partition.py", "mxnet_tpu/gluon/trainer.py",
         "mxnet_tpu/models/llama.py"], REPO, rules={"T8"})
    assert vs == [], [v.to_dict() for v in vs]


def test_t9_flags_policy_bypass_and_dropped_verdicts():
    vs = _rule(_analyze("t9_memory.py"), "T9")
    errors = [v for v in vs if v.severity == "error"]
    warnings = [v for v in vs if v.severity == "warning"]
    # hand-rolled remat primitives inside a hybrid block are errors
    assert any(v.context == "HandRolledBlock.hybrid_forward"
               and "jax.checkpoint" in v.message for v in errors)
    assert any(v.context == "HandRolledBlock.remat_forward"
               and "jax.remat" in v.message for v in errors)
    # planner verdicts discarded as bare statements are warnings
    assert len([v for v in warnings
                if v.context == "dropped_verdicts"]) == 3
    # the sanctioned checkpoint_wrap route and consumed verdicts stay
    # quiet
    assert not any("PolicyRoutedBlock" in v.context for v in vs)
    assert not any(v.context == "gated_verdicts" for v in vs)


def test_t9_clean_on_real_model_and_policy_code():
    # the policy engine itself (the one sanctioned jax.checkpoint site)
    # and the models that route remat through it must pass their own rule
    vs = analyze_paths(
        ["mxnet_tpu/models/llama.py", "mxnet_tpu/gluon/block.py",
         "mxnet_tpu/memory/policy.py", "mxnet_tpu/memory/lowering.py"],
        REPO, rules={"T9"})
    assert vs == [], [v.to_dict() for v in vs]


def test_t6_t7_clean_on_real_donation_sites():
    # the real donating call sites (fused trainer update, K-step fusion,
    # per-param optimizer update, llama decode cache) follow the
    # donation contract: rebind-from-results + sanitizer handoff only
    vs = analyze_paths(
        ["mxnet_tpu/gluon/trainer.py", "mxnet_tpu/gluon/step_fusion.py",
         "mxnet_tpu/optimizer/__init__.py", "mxnet_tpu/models/llama.py"],
        REPO, rules={"T6", "T7"})
    assert vs == [], [v.to_dict() for v in vs]


# --- concurrency tier (T10-T12) ---------------------------------------------

def test_t10_flags_bare_access_to_guarded_state():
    vs = _rule(_analyze("t10_locks.py"), "T10")
    errors = {v.context for v in vs if v.severity == "error"}
    warnings = {v.context for v in vs if v.severity == "warning"}
    assert "Ledger.drop" in errors          # bare write
    assert "cache_del" in errors            # bare module-global write
    assert "Ledger.peek" in warnings        # bare read
    assert len(vs) == 3
    # __init__ seeding, the _locked-suffix escape hatch, and the
    # lock-consistent paths all stay quiet
    assert not any("__init__" in v.context or "drain_locked" in v.context
                   or "record" in v.context or "Unthreaded" in v.context
                   for v in vs)


def test_t11_flags_cycles_and_blocking_under_lock():
    vs = _rule(_analyze("t11_order.py"), "T11")
    errors = [v for v in vs if v.severity == "error"]
    warnings = [v for v in vs if v.severity == "warning"]
    assert len(errors) == 1 and "lock-order cycle" in errors[0].message
    assert "_LOCK_A" in errors[0].message and "_LOCK_B" in errors[0].message
    blocked = {v.context for v in warnings}
    assert blocked == {"blocked_get", "blocked_put", "blocked_result"}
    # bounded and non-blocking calls under a lock stay quiet
    assert not any(v.context in ("bounded_get", "nonblocking_put")
                   for v in vs)


def test_t12_flags_thread_lifecycle_hazards():
    vs = _rule(_analyze("t12_lifecycle.py"), "T12")
    sev = {v.context: v.severity for v in vs}
    assert sev.get("unnamed") == "warning"       # no name=
    assert sev.get("unjoined") == "error"        # non-daemon, never joined
    assert sev.get("silent_worker") == "warning"  # loop, no try/except
    assert len(vs) == 3
    assert not any(v.context in ("good_worker", "good_joined")
                   for v in vs)


def test_t13_flags_retrace_hazards():
    vs = _rule(_analyze("t13_retrace.py"), "T13")
    sev = {v.context: v.severity for v in vs}
    # a. baked python scalar in a traced closure
    assert sev.get("make_scaled_step.step") == "error"
    assert any(v.context == "make_scaled_step.step" and "scale" in v.message
               and "float(optzr.rescale_grad)" in v.message for v in vs)
    # b. shape / ndim branches inside hybrid_forward (one of each)
    pad = [v for v in vs if v.context == "PadBlock.hybrid_forward"]
    assert {m.split(" on ")[1].split(" ")[0] for m in
            (v.message for v in pad)} == {"shape", "ndim"}
    assert all(v.severity == "warning" for v in pad)
    # c./d. formatted-string and dict-ordered compile keys
    assert sev.get("formatted_key") == "warning"
    assert sev.get("attr_key") == "warning"
    # e. engine-lifted float cells are exempt, int cells are not
    assert sev.get("scalar_op_int_capture.<lambda>") == "error"
    assert len(vs) == 6
    # negatives: keyed bake, runtime-arg lift, canonical keys, float lift
    for ok in ("make_keyed_step", "make_lifted_step", "tuple_key",
               "attr_key_sorted", "scalar_op_lifted"):
        assert not any(ok in v.context for v in vs), ok


def test_t14_flags_compile_site_churn():
    vs = _rule(_analyze("t14_compile_sites.py"), "T14")
    msg = {v.context: v.message for v in vs}
    assert "constructed and immediately invoked" in msg["per_call_jit"]
    assert "inside a loop" in msg["per_item_grid"]
    assert "hybridize" in msg["Stack.rewrap"]
    assert all(v.severity == "error" for v in vs)
    assert len(vs) == 3
    # negatives: sanctioned build defs, __init__ grids, warm* helpers
    assert "_build_grid" not in msg
    assert "Stack.__init__" not in msg
    assert "Stack.warm_modes" not in msg


def test_t15_budget_declaration_checks():
    vs = _rule(_analyze("t15_budget.py"), "T15")
    msgs = [v.message for v in vs]
    assert any("'unbudgeted' is registered" in m and "missing" in m
               for m in msgs)
    assert any("'stale_kind'" in m and "never registers" in m
               for m in msgs)
    assert any("'bad_budget' must be a positive int" in m for m in msgs)
    # the well-formed formula entry raises nothing
    assert not any("fused_step" in m for m in msgs)
    assert len(vs) == 3

    # a missing declaration on a site-owning module is an error...
    vs = _rule(_analyze("t15_budget_missing.py"), "T15")
    assert [v.severity for v in vs] == ["error"]
    assert "no __compile_signatures__" in vs[0].message
    # ...and the inline one-site annotation form satisfies it
    assert _rule(_analyze("t15_budget_inline.py"), "T15") == []


def test_compile_tier_clean_on_real_compile_owners():
    # every module that stores a jit or registers a costs kind now either
    # declares its __compile_signatures__ budget or carries a reviewed
    # waiver; the five remaining T13s are waived with whys in baseline
    vs = analyze_paths(
        ["mxnet_tpu/engine.py", "mxnet_tpu/gluon/block.py",
         "mxnet_tpu/gluon/step_fusion.py", "mxnet_tpu/gluon/trainer.py",
         "mxnet_tpu/optimizer/__init__.py", "mxnet_tpu/predictor.py",
         "mxnet_tpu/serving/generative.py", "mxnet_tpu/io/__init__.py"],
        REPO, rules={"T14", "T15"})
    assert vs == [], [v.to_dict() for v in vs]


def test_concurrency_tier_clean_on_real_threaded_modules():
    # the instrumented runtime (serving lanes, checkpoint writer, data
    # plane, parameter server) passes its own tier outright; engine.py
    # and telemetry/fleet.py carry the few justified fast-path waivers
    # in the committed baseline instead
    vs = analyze_paths(
        ["mxnet_tpu/serving/lanes.py", "mxnet_tpu/serving/scheduler.py",
         "mxnet_tpu/serving/generative.py", "mxnet_tpu/checkpoint.py",
         "mxnet_tpu/data/prefetch.py", "mxnet_tpu/io/__init__.py",
         "mxnet_tpu/kvstore/dist_async.py",
         "mxnet_tpu/gluon/data/dataloader.py"],
        REPO, rules={"T10", "T11", "T12"})
    assert vs == [], [v.to_dict() for v in vs]


def test_t11_cross_file_graph_is_acyclic_on_the_tree():
    vs = analyze_paths(["mxnet_tpu"], REPO, rules={"T11"})
    cycles = [v for v in vs if "lock-order cycle" in v.message]
    assert cycles == [], [v.to_dict() for v in cycles]


# --- baseline gate ----------------------------------------------------------

def test_baseline_waives_known_and_gates_new(tmp_path):
    vs = analyze_paths([FIXTURES], REPO)
    assert vs, "fixtures must seed violations"
    path = str(tmp_path / "baseline.json")
    save_baseline(path, vs)
    baseline = load_baseline(path)
    new, waived, stale = apply_baseline(vs, baseline)
    assert new == [] and len(waived) == len(vs) and stale == []
    # dropping one waiver makes exactly that violation "new" again
    victim = vs[0].fingerprint()
    short = {fp: n for fp, n in baseline.items() if fp != victim}
    new, _, _ = apply_baseline(vs, short)
    assert [v.fingerprint() for v in new] == [victim]
    # a fixed violation shows up as a stale waiver, never a failure
    _, _, stale = apply_baseline([v for v in vs if
                                  v.fingerprint() != victim], baseline)
    assert victim in stale


def test_fingerprint_ignores_line_numbers():
    from tools.lint.core import Violation

    a = Violation("T1", "error", "p.py", 10, 0, "f", "m", "x.asnumpy()")
    b = Violation("T1", "error", "p.py", 99, 4, "f", "m", "x.asnumpy()")
    assert a.fingerprint() == b.fingerprint()


# --- CLI --------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args], cwd=REPO,
        capture_output=True, text=True)


def test_cli_clean_against_committed_baseline():
    # the repo must lint clean: new violations fail CI here
    r = _run_cli("mxnet_tpu")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_fails_on_seeded_fixtures_with_json():
    r = _run_cli(FIXTURES, "--no-baseline", "--no-registry", "--json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    by_rule = payload["summary"]["by_rule"]
    for rule in ("T1", "T2", "T3", "T4", "T5", "T6", "T7", "T8", "T9",
                 "T10", "T11", "T12", "T13", "T14", "T15"):
        assert by_rule.get(rule, 0) > 0, f"{rule} missing from {by_rule}"
    assert "cache" in payload["summary"]


def test_cli_sarif_format():
    r = _run_cli(FIXTURES, "--no-baseline", "--no-registry",
                 "--format", "sarif")
    assert r.returncode == 1  # exit code still gates
    sarif = json.loads(r.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "mxlint"
    rule_ids = {rl["id"] for rl in run["tool"]["driver"]["rules"]}
    assert {"T1", "T2", "T3", "T4", "T5", "T6", "T7", "T8", "T9",
            "T10", "T11", "T12", "T13", "T14", "T15"} <= rule_ids
    results = run["results"]
    assert results and all(r_["ruleId"] in rule_ids for r_ in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].startswith("tools/lint/fixtures")
    assert loc["region"]["startLine"] >= 1
    assert all("partialFingerprints" in r_ for r_ in results)


def test_cli_sarif_marks_waived_as_unchanged(tmp_path):
    # waived violations appear with baselineState=unchanged, new without
    fixture = os.path.join(FIXTURES, "t6_donation.py")
    base = str(tmp_path / "b.json")
    r = _run_cli(fixture, "--no-registry", "--baseline", base,
                 "--update-baseline")
    assert r.returncode == 0
    r = _run_cli(fixture, "--no-registry", "--baseline", base,
                 "--format", "sarif")
    assert r.returncode == 0
    results = json.loads(r.stdout)["runs"][0]["results"]
    assert results
    assert all(r_.get("baselineState") == "unchanged" for r_ in results)


def test_cli_changed_mode():
    # no changed .py files under a docs-only root: clean no-op exit
    r = _run_cli("--changed", "HEAD", "docs")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no changed .py files" in r.stdout
    # a partial file set cannot regenerate the full-tree baseline
    r = _run_cli("--changed", "HEAD", "--update-baseline")
    assert r.returncode == 2
    assert "full tree" in r.stderr


# --- per-file analysis cache -------------------------------------------------

def test_cache_hits_on_unchanged_files(tmp_path):
    from tools.lint.cache import AnalysisCache, analyzer_salt

    path = str(tmp_path / "cache.json")
    salt = analyzer_salt(None)
    cold = AnalysisCache(path, salt)
    vs1 = analyze_paths([FIXTURES], REPO, cache=cold)
    assert cold.hits == 0 and cold.misses > 0
    cold.save()
    warm = AnalysisCache(path, salt)
    vs2 = analyze_paths([FIXTURES], REPO, cache=warm)
    assert warm.misses == 0 and warm.hits == cold.misses
    # cached results are byte-identical, cross-file passes included
    assert [v.to_dict() for v in vs1] == [v.to_dict() for v in vs2]


def test_cache_invalidates_on_content_and_salt_change(tmp_path):
    from tools.lint.cache import AnalysisCache, analyzer_salt

    f = tmp_path / "mod.py"
    f.write_text("import numpy as np\n")
    path = str(tmp_path / "cache.json")
    salt = analyzer_salt(None)
    c1 = AnalysisCache(path, salt)
    analyze_paths([str(f)], str(tmp_path), cache=c1)
    c1.save()
    # content change: stale digest misses
    f.write_text("import numpy as np  # edited\n")
    c2 = AnalysisCache(path, salt)
    analyze_paths([str(f)], str(tmp_path), cache=c2)
    assert c2.hits == 0 and c2.misses == 1
    # salt change (different rule set): whole cache drops
    c3 = AnalysisCache(path, analyzer_salt({"T1"}))
    assert c3._files == {}


def test_cli_reports_cache_in_json_and_honors_no_cache():
    fixture = os.path.join(FIXTURES, "t10_locks.py")
    r = _run_cli(fixture, "--no-baseline", "--no-registry", "--json")
    cache1 = json.loads(r.stdout)["summary"]["cache"]
    r = _run_cli(fixture, "--no-baseline", "--no-registry", "--json")
    cache2 = json.loads(r.stdout)["summary"]["cache"]
    assert cache1["hits"] + cache1["misses"] == 1
    assert cache2 == {"hits": 1, "misses": 0}
    r = _run_cli(fixture, "--no-baseline", "--no-registry", "--json",
                 "--no-cache")
    assert "cache" not in json.loads(r.stdout)["summary"]


# --- live registry invariants ----------------------------------------------

def test_registry_has_no_duplicates_and_all_callable_documented():
    assert run_registry_check() == []


def test_registry_no_grad_metadata():
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.ops import registry

    assert registry.duplicate_registrations() == []
    for name in ("argmax", "argmin", "argsort", "sign", "floor", "equal",
                 "one_hot", "shape_array"):
        assert registry.op_meta(name).get("no_grad") is True, name
    for name in ("add", "exp", "sum", "dot", "softmax"):
        assert registry.op_meta(name).get("no_grad") is False, name
    # aliases resolve to the same callable and metadata as the canonical
    for name in registry.list_ops():
        meta = registry.op_meta(name)
        if meta and meta["canonical"] != name:
            assert registry.get_op(name) is \
                registry.get_op(meta["canonical"])


def test_no_grad_ops_skip_vjp_but_stay_on_tape():
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    x = nd.array([-2.0, 3.0, 0.5])
    x.attach_grad()
    with mx.autograd.record():
        s = nd.sign(x)          # no_grad op: tape node, no vjp trace
        z = (s * x).sum()       # sign(x) * x == |x|
    z.backward()
    # d|x|/dx contributes only through the differentiable product path
    np.testing.assert_allclose(x.grad.asnumpy(), np.sign([-2.0, 3.0, 0.5]))
