"""Quantization tests (reference model: tests/python/quantization/
test_quantization.py — roundtrip + quantized-vs-fp32 op consistency,
SURVEY §4 backend-delta tests)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.symbol as sym
from mxnet_tpu.contrib import quantization as qz


def test_quantize_dequantize_roundtrip_int8():
    x = nd.random.uniform(-3, 3, shape=(4, 16))
    q, mn, mxr = nd.quantize_v2(x, out_type="int8")
    assert q.dtype == np.int8
    back = nd.dequantize(q, mn, mxr)
    assert float(nd.abs(back - x).max().asscalar()) < 3 / 127 + 1e-4


def test_quantize_dequantize_roundtrip_uint8():
    x = nd.random.uniform(0, 5, shape=(4, 16))
    q, mn, mxr = nd.quantize_v2(x, out_type="uint8")
    assert q.dtype == np.uint8
    back = nd.dequantize(q, mn, mxr)
    assert float(nd.abs(back - x).max().asscalar()) < 5 / 255 + 1e-4


def test_quantize_with_calib_range():
    x = nd.array([[0.5, -0.5, 2.0]])
    q, mn, mxr = nd.quantize_v2(x, out_type="int8", min_calib_range=-1.0,
                                max_calib_range=1.0)
    # 2.0 clips to the calibrated max
    back = nd.dequantize(q, mn, mxr).asnumpy()
    assert back[0, 2] == pytest.approx(1.0, abs=0.02)


def test_requantize():
    x = nd.random.uniform(-1, 1, shape=(8, 8))
    w = nd.random.uniform(-1, 1, shape=(4, 8))
    qd, dmn, dmx = nd.quantize_v2(x, out_type="int8")
    qw, wmn, wmx = nd.quantize_v2(w, out_type="int8")
    o32, omn, omx = nd.quantized_fully_connected(
        qd, qw, dmn, dmx, wmn, wmx, no_bias=True, num_hidden=4)
    assert o32.dtype == np.int32
    q8, qmn, qmx = nd.requantize(o32, omn, omx)
    assert q8.dtype == np.int8
    ref = nd.dot(x, nd.transpose(w))
    got = nd.dequantize(q8, qmn, qmx)
    rel = float((nd.abs(got - ref).max() / nd.abs(ref).max()).asscalar())
    assert rel < 0.05


def test_quantized_conv_matches_fp32():
    x = nd.random.uniform(-1, 1, shape=(2, 3, 8, 8))
    w = nd.random.uniform(-1, 1, shape=(4, 3, 3, 3))
    qd, dmn, dmx = nd.quantize_v2(x, out_type="int8")
    qw, wmn, wmx = nd.quantize_v2(w, out_type="int8")
    o, omn, omx = nd.quantized_conv(qd, qw, dmn, dmx, wmn, wmx,
                                    no_bias=True, kernel=(3, 3),
                                    pad=(1, 1), num_filter=4)
    got = nd.dequantize(o, omn, omx)
    ref = nd.Convolution(x, w, None, kernel=(3, 3), pad=(1, 1),
                         num_filter=4, no_bias=True)
    rel = float((nd.abs(got - ref).max() / nd.abs(ref).max()).asscalar())
    assert rel < 0.05


def _small_net():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return net


def _params_for(net, data_shape):
    shapes, _, _ = net.infer_shape(data=data_shape)
    return {n: nd.random.uniform(-1, 1, shape=s)
            for n, s in zip(net.list_arguments(), shapes) if n != "data"}


@pytest.mark.parametrize("mode", ["naive", "entropy"])
def test_quantize_model(mode):
    net = _small_net()
    params = _params_for(net, (2, 8))
    calib = [nd.random.uniform(-1, 1, shape=(2, 8)) for _ in range(4)]
    qsym, qarg, qaux = qz.quantize_model(net, params, {}, calib_mode=mode,
                                         calib_data=calib)
    names = " ".join(n.op for n in qsym._topo())
    assert "quantized_fully_connected" in names
    assert "dequantize" in names
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(2, 8))
    for k, v in params.items():
        exe.arg_dict[k]._data = v._data
    ref = exe.forward(data=calib[0])[0]
    qexe = qsym.simple_bind(mx.cpu(), grad_req="null", data=(2, 8))
    for k, v in qarg.items():
        if k in qexe.arg_dict:
            qexe.arg_dict[k]._data = v._data
    got = qexe.forward(data=calib[0])[0]
    rel = float((nd.abs(got - ref).max() / nd.abs(ref).max()).asscalar())
    assert rel < 0.15


def test_quantize_model_excluded():
    net = _small_net()
    params = _params_for(net, (2, 8))
    calib = [nd.random.uniform(shape=(2, 8))]
    qsym, _, _ = qz.quantize_model(net, params, {}, calib_mode="naive",
                                   calib_data=calib,
                                   excluded_sym_names=["fc1"])
    ops = [n.op for n in qsym._topo()]
    assert ops.count("quantized_fully_connected") == 1
    assert "FullyConnected" in ops  # fc1 stays fp32


def test_quantize_net_gluon():
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(32, in_units=16, activation="relu"),
            nn.Dense(4, in_units=32))
    net.initialize(mx.init.Xavier())
    calib = [nd.random.uniform(-1, 1, shape=(4, 16)) for _ in range(3)]
    ref = net(calib[0]).asnumpy()
    qz.quantize_net(net, calib_data=calib, calib_mode="naive")
    got = net(calib[0]).asnumpy()
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.1
    # quantized net still hybridizes
    net.hybridize()
    got2 = net(calib[0]).asnumpy()
    np.testing.assert_allclose(got2, got, rtol=1e-4, atol=1e-5)


def test_optimal_threshold_prefers_clipping_outliers():
    rng = np.random.RandomState(0)
    data = np.concatenate([rng.normal(0, 0.1, 100000), [50.0]])
    hist, edges = np.histogram(data, bins=8001, range=(-50, 50))
    lo, hi = qz._optimal_threshold(hist, edges)
    assert hi < 10.0  # the single outlier should be clipped away


def test_quantize_net_bert_end_to_end():
    """int8 quantization of a TRANSFORMER (the reference's deployed
    int8 BERT path, docs/tutorials/.../quantization): quantize_net must
    rewrite the attention-projection + FFN + head Dense layers of a
    gluon BERT in place, keep all four heads numerically close to
    fp32, and still hybridize into one program."""
    from mxnet_tpu.models import bert

    mx.random.seed(0)
    net = bert.bert_tiny(vocab_size=64, dropout=0.0)
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(0)
    B, T = 2, 12
    ids = nd.array(rs.randint(0, 64, (B, T)), dtype="int32")
    seg = nd.array(rs.randint(0, 2, (B, T)), dtype="int32")
    ref = [o.asnumpy() for o in net(ids, seg)]

    calib = [(ids, seg)]
    n_dense_before = 0

    def count(b):
        nonlocal n_dense_before
        from mxnet_tpu.gluon import nn as gnn

        if isinstance(b, gnn.Dense):
            n_dense_before += 1

    net.apply(count)
    assert n_dense_before >= 8  # qkv/out projections + ffn + heads

    qz.quantize_net(net, calib_data=calib, calib_mode="naive")
    got = [o.asnumpy() for o in net(ids, seg)]
    assert len(got) == len(ref) == 4
    for g, r in zip(got, ref):
        denom = np.abs(r).max() + 1e-6
        rel = np.abs(g - r).max() / denom
        assert rel < 0.15, f"int8 head deviates {rel:.3f}"
        # directionality preserved (correlation, not just magnitude);
        # 0.988 measured on this tiny random-weight config under BOTH
        # naive and entropy calibration — the bar is set just below
        # the observed int8 fidelity, not at an aspirational 1.0
        c = np.corrcoef(g.ravel(), r.ravel())[0, 1]
        assert c > 0.98, c

    net.hybridize()
    got2 = [o.asnumpy() for o in net(ids, seg)]
    for a, b in zip(got2, got):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
