"""Llama family + flash attention tests (new capability vs the reference —
SURVEY §5 long-context ABSENT; test strategy mirrors the reference's op
unit tests + consistency cross-checks, SURVEY §4)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel
from mxnet_tpu.models import llama


def _ids(b, t, vocab=256, seed=0):
    return nd.array(onp.random.RandomState(seed).randint(0, vocab, (b, t)),
                    dtype="int32")


def test_flash_attention_matches_reference():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.flash_attention import (_sdpa_ref,
                                               flash_attention_raw)

    rng = onp.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 4, 64, 32)).astype("f"))
               for _ in range(3))
    for causal in (False, True):
        out = flash_attention_raw(q, k, v, causal, None)
        ref = _sdpa_ref(q, k, v, causal, 1 / onp.sqrt(32))
        assert float(jnp.abs(out - ref).max()) < 1e-4
        grads = jax.grad(
            lambda a, b, c: (flash_attention_raw(a, b, c, causal,
                                                 None) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        refg = jax.grad(
            lambda a, b, c: (_sdpa_ref(a, b, c, causal,
                                       1 / onp.sqrt(32)) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(grads, refg):
            assert float(jnp.abs(g - r).max()) < 1e-4


def test_flash_attention_chunked_backward():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import flash_attention as fa

    rng = onp.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 2, 256, 16)).astype("f"))
               for _ in range(3))
    g = jnp.asarray(rng.normal(size=(1, 2, 256, 16)).astype("f"))
    o = fa._sdpa_ref(q, k, v, True, 0.25)
    # small block forces the multi-block scan path
    dq, dk, dv = fa._fa_backward(q, k, v, o, g, True, 0.25, block=64)
    dq2, dk2, dv2 = fa._fa_backward_dense(
        q, k, v, g, q, k, v, True, 0.25, 256, 256)
    for a, b in ((dq, dq2), (dk, dk2), (dv, dv2)):
        assert float(jnp.abs(a - b).max()) < 1e-4


def test_flash_attention_degenerate_fully_masked_rows():
    """Causal with tq > tk leaves the leading (tq - tk) query rows with
    ZERO visible keys.  The flash convention (and the pallas kernel's
    online softmax) outputs ZEROS for such rows; the dense softmax
    reference produces NaN (0/0).  Pin the zero-output semantics so the
    TPU kernel and the chunked CPU fallback stay aligned and the
    behavior change vs a NaN-propagating dense path is a documented
    contract, not an accident (ADVICE r4)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.flash_attention import (_fa_forward_chunked,
                                               flash_attention_raw)

    rng = onp.random.RandomState(2)
    tq, tk = 8, 5
    q = jnp.asarray(rng.normal(size=(1, 2, tq, 16)).astype("f"))
    k = jnp.asarray(rng.normal(size=(1, 2, tk, 16)).astype("f"))
    v = jnp.asarray(rng.normal(size=(1, 2, tk, 16)).astype("f"))
    n_masked = tq - tk
    for out in (flash_attention_raw(q, k, v, True, None),
                _fa_forward_chunked(q, k, v, True, 0.25, block=4)):
        out = onp.asarray(out)
        assert onp.isfinite(out).all(), "NaN leaked from masked rows"
        assert (out[:, :, :n_masked] == 0).all(), \
            "fully-masked query rows must be exactly zero"
        assert (onp.abs(out[:, :, n_masked:]) > 0).any()


def test_rmsnorm():
    ln = llama.RMSNorm(8)
    ln.initialize()
    x = nd.random.uniform(-2, 2, shape=(2, 3, 8))
    out = ln(x).asnumpy()
    xa = x.asnumpy()
    want = xa / onp.sqrt((xa ** 2).mean(-1, keepdims=True) + 1e-5)
    onp.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_rope_rotation_properties():
    from mxnet_tpu.models.llama import _apply_rope, _rope_tables
    import jax.numpy as jnp

    cos, sin = _rope_tables(16, 8, 10000.0)
    x = jnp.asarray(onp.random.RandomState(0).normal(
        size=(1, 2, 16, 8)).astype("f"))
    out = _apply_rope(x, cos[None, None], sin[None, None])
    # norms preserved (rotation)
    onp.testing.assert_allclose(
        onp.asarray((out ** 2).sum(-1)), onp.asarray((x ** 2).sum(-1)),
        rtol=1e-4)
    # position 0 is identity
    onp.testing.assert_allclose(onp.asarray(out[:, :, 0]),
                                onp.asarray(x[:, :, 0]), rtol=1e-6)


def test_llama_tiny_forward_and_train():
    net = llama.llama_tiny()
    net.initialize(mx.init.Xavier())
    ids = _ids(2, 32)
    logits = net(ids)
    assert logits.shape == (2, 32, 256)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    labels = _ids(2, 32, seed=1)
    first = None
    for _ in range(5):
        with autograd.record():
            lg = net(ids)
            loss = nd.softmax_cross_entropy(
                lg.reshape((-1, 256)), labels.reshape((-1,))).mean()
        loss.backward()
        trainer.step(1)
        first = first if first is not None else float(loss.asscalar())
    assert float(loss.asscalar()) < first


def test_llama_hybridize_consistent():
    net = llama.llama_tiny()
    net.initialize(mx.init.Xavier())
    ids = _ids(1, 16)
    eager = net(ids).asnumpy()
    net.hybridize()
    hybrid = net(ids).asnumpy()
    onp.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-5)


def test_llama_gqa_heads():
    cfg = llama.LlamaConfig(**{**llama.LLAMA_CONFIGS["llama_tiny"],
                               "num_kv_heads": 1})
    net = llama.LlamaForCausalLM(cfg)
    net.initialize(mx.init.Xavier())
    out = net(_ids(1, 8))
    assert out.shape == (1, 8, 256)
    attn = net.model.layers[0].self_attn
    assert attn.k_proj.weight.shape[0] == cfg.head_dim  # 1 kv head


def test_llama_generate():
    net = llama.llama_tiny()
    net.initialize(mx.init.Xavier())
    out = net.generate(_ids(2, 4), max_new_tokens=3)
    assert out.shape == (2, 7)
    assert out.asnumpy()[:, :4].tolist() == _ids(2, 4).asnumpy().tolist()


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_llama_sequence_parallel_modes(mode):
    mesh = parallel.make_mesh({"dp": 2, "sp": 4})
    with parallel.mesh_scope(mesh):
        net = llama.llama_tiny(attn_mode=mode)
        net.initialize(mx.init.Xavier())
        llama.shard_llama(net, mesh)
        ids = parallel.shard_batch(_ids(2, 32), mesh)
        with autograd.record():
            lg = net(ids)
            loss = nd.softmax_cross_entropy(
                lg.reshape((-1, 256)),
                nd.zeros((2 * 32,), dtype="int32")).mean()
        loss.backward()
        assert onp.isfinite(float(loss.asscalar()))


def test_llama_tp_matches_single_device():
    ids = _ids(2, 16)
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    ref = net(ids).asnumpy()
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    with parallel.mesh_scope(mesh):
        llama.shard_llama(net, mesh)
        got = net(parallel.shard_batch(ids, mesh)).asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-4)


def test_llama3_8b_config():
    cfg = llama.LlamaConfig(**llama.LLAMA_CONFIGS["llama3_8b"])
    assert cfg.head_dim == 128
    assert cfg.num_kv_heads == 8
    assert cfg.vocab_size == 128256


def test_kv_cache_decoder_logits_parity():
    """Jitted KV-cache decode must produce the same logits as the full
    forward at every position (the anti-drift pin for LlamaDecoder)."""
    mx.random.seed(0)
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    ids = _ids(2, 12)
    ref = net(ids).asnumpy()                       # (B, T, V)
    dec = llama.LlamaDecoder(net, max_len=12)
    got = dec.logits_at(ids.asnumpy())
    onp.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_kv_cache_generate_matches_oracle():
    mx.random.seed(1)
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    prompt = _ids(2, 5, seed=3)
    slow = net.generate(prompt, max_new_tokens=6, use_cache=False)
    fast = net.generate(prompt, max_new_tokens=6, use_cache=True)
    assert fast.shape == slow.shape == (2, 11)
    assert fast.asnumpy().tolist() == slow.asnumpy().tolist()


def test_kv_cache_rejects_overflow_and_moe():
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    dec = llama.LlamaDecoder(net, max_len=6)
    with pytest.raises(mx.MXNetError):
        dec.generate(_ids(1, 4).asnumpy(), max_new_tokens=5)
    moe_net = llama.mixtral_tiny(attn_mode="sdpa")
    moe_net.initialize(mx.init.Xavier())
    with pytest.raises(mx.MXNetError):
        llama.LlamaDecoder(moe_net, max_len=8)
    # MoE generate falls back to the oracle path
    out = moe_net.generate(_ids(1, 3), max_new_tokens=2)
    assert out.shape == (1, 5)


def test_kv_cache_zero_tokens_and_bucket_reuse():
    mx.random.seed(2)
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    p = _ids(1, 4)
    out = net.generate(p, max_new_tokens=0)
    assert out.asnumpy().tolist() == p.asnumpy().tolist()

    # nearby prompt lengths / token counts share one compiled program.
    # Assert the DELTA, not the absolute count: jax's global jit cache
    # evicts entries under the full suite's compile churn, so absolute
    # sizes are environment-dependent (second call may even recompile
    # after eviction — what must never happen is a NEW signature).
    dec = llama.LlamaDecoder(net, max_len=64)
    # the eviction-proof invariant: both calls resolve to the SAME
    # (prompt, steps) buckets, so they share one compiled signature
    assert dec._bucket(5) == dec._bucket(7)
    assert dec._bucket(3) == dec._bucket(4)
    r5 = dec.generate(_ids(1, 5, seed=5).asnumpy(), 3)
    after_first = dec._gen._cache_size()
    r7 = dec.generate(_ids(1, 7, seed=7).asnumpy(), 4)
    assert r5.shape == (1, 8) and r7.shape == (1, 11)
    assert dec._gen._cache_size() <= after_first, \
        "bucketing failed: second generate added a new compiled signature"
    # padded-prompt result must equal exact-shape decode
    import jax as _jax
    import jax.numpy as _jnp
    import numpy as _np

    dec_exact = llama.LlamaDecoder(net, max_len=64)
    exact = dec_exact._gen(dec_exact._weights(),
                           _jnp.asarray(_ids(1, 5, seed=5).asnumpy(),
                                        _jnp.int32),
                           _jnp.int32(5), _jax.random.PRNGKey(0),
                           _jnp.float32(1.0), _jnp.float32(1.0),
                           3, 0, False, False)
    _np.testing.assert_array_equal(r5[:, 5:], _np.asarray(exact)[:, :3])


def test_sampling_modes():
    mx.random.seed(3)
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    p = _ids(2, 6, seed=9)
    greedy = net.generate(p, max_new_tokens=8)

    # temperature -> 0 converges to greedy
    cold = net.generate(p, max_new_tokens=8, do_sample=True,
                        temperature=1e-4, seed=0)
    assert cold.asnumpy().tolist() == greedy.asnumpy().tolist()
    # top_k=1 is argmax regardless of temperature
    k1 = net.generate(p, max_new_tokens=8, do_sample=True,
                      temperature=5.0, top_k=1, seed=1)
    assert k1.asnumpy().tolist() == greedy.asnumpy().tolist()
    # same seed reproduces; sampling is well-formed with top_p
    s_a = net.generate(p, max_new_tokens=8, do_sample=True,
                       temperature=1.0, top_p=0.9, seed=42)
    s_b = net.generate(p, max_new_tokens=8, do_sample=True,
                       temperature=1.0, top_p=0.9, seed=42)
    assert s_a.asnumpy().tolist() == s_b.asnumpy().tolist()
    assert s_a.shape == (2, 14)
    # sampled ids stay in-vocab
    assert int(s_a.asnumpy().max()) < 256 and int(s_a.asnumpy().min()) >= 0


def test_greedy_generate_leaves_rng_untouched():
    from mxnet_tpu import random as mx_random

    mx.random.seed(11)
    net = llama.llama_tiny(attn_mode="sdpa")
    net.initialize(mx.init.Xavier())
    mx.random.seed(11)
    before = mx_random.uniform(shape=(4,)).asnumpy()
    mx.random.seed(11)
    net.generate(_ids(1, 4), max_new_tokens=2)  # greedy: no RNG draw
    after = mx_random.uniform(shape=(4,)).asnumpy()
    onp.testing.assert_array_equal(before, after)


def test_generate_rejects_beyond_context():
    """prompt + max_new_tokens past cfg.max_seq_len must error, not
    silently build RoPE/KV state outside the trained window."""
    net = llama.llama_tiny()  # max_seq_len=128
    net.initialize(mx.init.Xavier())
    with pytest.raises(mx.MXNetError, match="max_seq_len"):
        net.generate(_ids(1, 4), max_new_tokens=200)


def test_scan_layers_matches_loop():
    """cfg.scan_layers (lax.scan over the stacked decoder, r4): loss
    and EVERY parameter gradient must equal the python layer loop —
    eager AND hybridized."""
    import numpy as np

    rs = np.random.RandomState(0)
    ids = nd.array(rs.randint(0, 256, (2, 16)), dtype="int32")
    labels = nd.array(rs.randint(0, 256, (2, 16)), dtype="int32")

    results = {}
    for scan in (False, True):
        mx.random.seed(5)
        net = llama.llama_tiny(num_layers=4, attn_mode="sdpa",
                               scan_layers=scan)
        net.initialize()
        with autograd.record():
            logits = net(ids)
            loss = nd.softmax_cross_entropy(
                logits.reshape((-1, 256)),
                labels.reshape((-1,))).mean()
        loss.backward()
        grads = {k: p.grad().asnumpy()
                 for k, p in net._collect_params_with_prefix().items()
                 if p.grad_req != "null"}
        results[scan] = (float(loss.asscalar()), grads, net)

    l0, g0, _ = results[False]
    l1, g1, net_scan = results[True]
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    assert g0.keys() == g1.keys()
    for k in g0:
        np.testing.assert_allclose(g1[k], g0[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)

    # hybridized scan path: same logits, and a Trainer step stays finite
    net_scan.hybridize(static_alloc=True)
    logits_h = net_scan(ids).asnumpy()
    mx.random.seed(5)
    net_ref = llama.llama_tiny(num_layers=4, attn_mode="sdpa")
    net_ref.initialize()
    np.testing.assert_allclose(logits_h, net_ref(ids).asnumpy(),
                               rtol=1e-4, atol=1e-5)
    trainer = gluon.Trainer(net_scan.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    with autograd.record():
        loss = nd.softmax_cross_entropy(
            net_scan(ids).reshape((-1, 256)),
            labels.reshape((-1,))).mean()
    loss.backward()
    trainer.step(2)
    for k, p in net_scan._collect_params_with_prefix().items():
        assert np.isfinite(p.data().asnumpy()).all(), k


def test_scan_layers_on_tp_mesh_matches_loop():
    """scan_layers must compose with GSPMD sharding: the scanned stack
    over megatron-TP-sharded params on a dp x tp mesh produces the same
    loss and gradients as the python layer loop on the same mesh."""
    import numpy as np

    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, 256, (4, 16))
    labels_np = rs.randint(0, 256, (4, 16))

    results = {}
    mesh = parallel.make_mesh({"dp": 2, "tp": 2})
    for scan in (False, True):
        with parallel.mesh_scope(mesh):
            mx.random.seed(9)
            net = llama.llama_tiny(num_layers=4, attn_mode="sdpa",
                                   scan_layers=scan)
            net.initialize()
            llama.shard_llama(net, mesh)
            ids = parallel.shard_batch(nd.array(ids_np, dtype="int32"))
            labels = parallel.shard_batch(
                nd.array(labels_np, dtype="int32"))
            with autograd.record():
                logits = net(ids)
                loss = nd.softmax_cross_entropy(
                    logits.reshape((-1, 256)),
                    labels.reshape((-1,))).mean()
            loss.backward()
            grads = {k: p.grad().asnumpy()
                     for k, p in
                     net._collect_params_with_prefix().items()
                     if p.grad_req != "null"}
            results[scan] = (float(loss.asscalar()), grads)

    l0, g0 = results[False]
    l1, g1 = results[True]
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    assert g0.keys() == g1.keys()
    for k in g0:
        np.testing.assert_allclose(g1[k], g0[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_scan_layers_ring_attention_on_mesh():
    """scan_layers x ring attention (dp x tp x sp): the scanned stack's
    jitted program must host the shard_map-based ring layers (eager
    scan evaluation of a shard_map body is NotImplemented in jax — the
    machinery jits the scan exactly for this) and match the loop."""
    import numpy as np

    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, 256, (4, 32))
    mesh = parallel.make_mesh({"dp": 2, "tp": 2, "sp": 2})
    res = {}
    for scan in (False, True):
        with parallel.mesh_scope(mesh):
            mx.random.seed(9)
            net = llama.llama_tiny(num_layers=2, attn_mode="ring",
                                   scan_layers=scan)
            net.initialize()
            llama.shard_llama(net, mesh)
            ids = parallel.shard_batch(nd.array(ids_np, dtype="int32"))
            with autograd.record():
                loss = (net(ids).astype("float32") ** 2).mean()
            loss.backward()
            g = net.model.layers[1].mlp.down_proj.weight.grad().asnumpy()
            res[scan] = (float(loss.asscalar()), g)
    np.testing.assert_allclose(res[True][0], res[False][0], rtol=1e-5)
    np.testing.assert_allclose(res[True][1], res[False][1], rtol=1e-4,
                               atol=1e-5)


@pytest.mark.parametrize("mode,mesh_shape,factory", [
    ("ulysses", {"dp": 2, "sp": 4},
     lambda scan: llama.llama_tiny(num_layers=2, attn_mode="ulysses",
                                   scan_layers=scan)),
    ("moe", {"dp": 2, "ep": 2, "tp": 2},
     lambda scan: llama.mixtral_tiny(attn_mode="sdpa",
                                     moe_router="expert_choice",
                                     scan_layers=scan)),
], ids=["ulysses", "moe"])
def test_scan_layers_composes(mode, mesh_shape, factory):
    """scan_layers x {Ulysses sequence parallelism, MoE expert bank}:
    the scanned stack (the (L, E, ...) stacked expert weights included)
    must match the python loop on the sharded mesh."""
    import numpy as np

    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, 256, (4, 16))
    mesh = parallel.make_mesh(mesh_shape)
    res = {}
    for scan in (False, True):
        with parallel.mesh_scope(mesh):
            mx.random.seed(9)
            net = factory(scan)
            net.initialize()
            llama.shard_llama(net, mesh)
            ids = parallel.shard_batch(nd.array(ids_np, dtype="int32"))
            with autograd.record():
                loss = (net(ids).astype("float32") ** 2).mean()
            loss.backward()
            # representative LAYER-STACKED grads: layer-1's mlp (the
            # (L, E, ...) expert bank for moe) + attention o_proj
            mlp = net.model.layers[1].mlp
            gw = (mlp.down_weight if hasattr(mlp, "down_weight")
                  else mlp.down_proj.weight).grad().asnumpy()
            go = net.model.layers[1].self_attn.o_proj.weight \
                .grad().asnumpy()
            res[scan] = (float(loss.asscalar()), gw, go)
    np.testing.assert_allclose(res[True][0], res[False][0], rtol=1e-5)
    np.testing.assert_allclose(res[True][1], res[False][1], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(res[True][2], res[False][2], rtol=1e-4,
                               atol=1e-5)


def test_scan_layers_checkpoint_interop(tmp_path):
    """Parameters are per-layer regardless of scan_layers, so a
    checkpoint written by a loop-mode net must load into a scan-mode
    net (and vice versa) with identical outputs — users can flip the
    idiom without converting checkpoints."""
    import numpy as np

    rs = np.random.RandomState(0)
    ids = nd.array(rs.randint(0, 256, (2, 16)), dtype="int32")

    mx.random.seed(3)
    loop_net = llama.llama_tiny(num_layers=4, attn_mode="sdpa")
    loop_net.initialize()
    ref = loop_net(ids).asnumpy()
    pfile = str(tmp_path / "w.params")
    loop_net.save_parameters(pfile)

    mx.random.seed(99)  # different init — must be fully overwritten
    scan_net = llama.llama_tiny(num_layers=4, attn_mode="sdpa",
                                scan_layers=True)
    scan_net.initialize()
    scan_net.load_parameters(pfile)
    np.testing.assert_allclose(scan_net(ids).asnumpy(), ref,
                               rtol=1e-5, atol=1e-6)


def test_flash_pallas_shard_map_routing(monkeypatch):
    """GSPMD cannot auto-partition mosaic custom-calls: under a dp x tp
    mesh the pallas flash path must route through shard_map (batch over
    dp, heads over tp) and match the unsharded oracle.  On the CPU mesh
    the kernel body is stubbed with the chunked implementation — what's
    under test is the shard_map wiring (specs, divisibility fallback),
    which is exactly what real chips need (round-5 offline-topology
    find: the un-wrapped kernel fails to compile for any dp/tp mesh)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import parallel
    from mxnet_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    calls = {"sharded": 0}
    real_chunked = fa._fa_forward_chunked
    B, H, T, D = 4, 4, 128, 16

    def fake_pallas(q, k, v, causal, scale, **kw):
        calls["sharded"] += 1
        # PROOF the call executed under shard_map: the kernel must see
        # SHARD-LOCAL shapes (B/dp, H/tp), not the global ones — an
        # unwrapped call (the pre-fix bug) would pass every other
        # assert in this test
        assert q.shape == (B // 2, H // 2, T, D), q.shape
        return real_chunked(q, k, v, causal, scale)

    monkeypatch.setattr(fa, "_fa_forward_pallas", fake_pallas)

    rng = onp.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f"))
               for _ in range(3))
    oracle = fa._sdpa_ref(q, k, v, True, 0.25)

    mesh = parallel.make_mesh({"dp": 2, "tp": 2})
    with parallel.mesh_scope(mesh):
        out = jax.jit(lambda a, b, c: fa.flash_attention_raw(
            a, b, c, True, 0.25))(q, k, v)
    assert calls["sharded"] >= 1, "pallas path never engaged"
    assert float(jnp.abs(out - oracle).max()) < 1e-4

    # indivisible head count -> chunked fallback, still correct
    with parallel.mesh_scope(parallel.make_mesh({"dp": 2, "tp": 4})):
        q3 = q[:, :3]
        out3 = jax.jit(lambda a, b, c: fa.flash_attention_raw(
            a, b, c, True, 0.25))(q3, k[:, :3], v[:, :3])
    oracle3 = fa._sdpa_ref(q3, k[:, :3], v[:, :3], True, 0.25)
    assert float(jnp.abs(out3 - oracle3).max()) < 1e-4


def test_flash_inside_shard_map_body_no_nested_wrap(monkeypatch):
    """flash_attention_raw reached from INSIDE a shard_map body (the
    ring/ulysses sequence-parallel route) must call the kernel
    directly — wrapping a second shard_map over the same mesh is a
    trace-time ValueError (round-5 review repro)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu import parallel
    from mxnet_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    monkeypatch.setattr(
        fa, "_fa_forward_pallas",
        lambda q, k, v, c, s, **kw: fa._fa_forward_chunked(q, k, v, c, s))

    rng = onp.random.RandomState(6)
    B, H, T, D = 4, 2, 128, 16
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f"))
               for _ in range(3))
    oracle = fa._sdpa_ref(q, k, v, True, 0.25)

    mesh = parallel.make_mesh({"dp": 2, "sp": 2})
    spec = P("dp", None, None, None)
    with parallel.mesh_scope(mesh):
        out = jax.jit(jax.shard_map(
            lambda a, b, c: fa.flash_attention_raw(a, b, c, True, 0.25),
            mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec))(q, k, v)
    assert float(jnp.abs(out - oracle).max()) < 1e-4


def _interp_kernels(monkeypatch):
    """Force the pallas path with interpret-mode kernels (CPU)."""
    import functools as _ft

    from mxnet_tpu.ops import flash_attention as fa

    monkeypatch.setattr(fa, "_on_tpu", lambda: True)
    monkeypatch.setattr(
        fa, "_fa_forward_pallas",
        _ft.partial(fa._fa_forward_pallas, interpret=True))
    monkeypatch.setattr(
        fa, "_fa_backward_pallas",
        _ft.partial(fa._fa_backward_pallas, interpret=True))
    return fa


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_backward_kernels_match_oracle(monkeypatch, causal):
    """The full custom-vjp path with PALLAS kernels both directions
    (interpret mode): forward saves lse, backward runs the two-kernel
    dq/dkv design, gradients match the dense vjp oracle."""
    import jax
    import jax.numpy as jnp

    fa = _interp_kernels(monkeypatch)
    rng = onp.random.RandomState(3)
    B, H, T, D = 2, 2, 256, 32
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f"))
               for _ in range(3))
    scale = 1 / float(onp.sqrt(D))

    def loss(fn):
        return lambda a, b, c: (fn(a, b, c) ** 2).sum()

    out = fa.flash_attention_raw(q, k, v, causal, scale)
    ref = fa._sdpa_ref(q, k, v, causal, scale)
    assert float(jnp.abs(out - ref).max()) < 1e-4
    g = jax.grad(loss(lambda a, b, c: fa.flash_attention_raw(
        a, b, c, causal, scale)), argnums=(0, 1, 2))(q, k, v)
    r = jax.grad(loss(lambda a, b, c: fa._sdpa_ref(
        a, b, c, causal, scale)), argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g, r):
        assert float(jnp.abs(got - want).max()) < 2e-4


def test_flash_pallas_backward_sharded(monkeypatch):
    """The pallas backward under a dp x tp GSPMD mesh: fwd and bwd both
    route through shard_map with shard-local kernels, grads match the
    unsharded oracle."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import parallel

    fa = _interp_kernels(monkeypatch)
    rng = onp.random.RandomState(4)
    B, H, T, D = 4, 4, 128, 16
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f"))
               for _ in range(3))
    scale = 0.25

    def loss(a, b, c):
        return (fa.flash_attention_raw(a, b, c, True, scale) ** 2).sum()

    r = jax.grad(lambda a, b, c: (fa._sdpa_ref(
        a, b, c, True, scale) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    mesh = parallel.make_mesh({"dp": 2, "tp": 2})
    with parallel.mesh_scope(mesh):
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for got, want in zip(g, r):
        assert float(jnp.abs(got - want).max()) < 2e-4


def test_flash_pallas_backward_kill_switch(monkeypatch):
    """MXT_PALLAS_FLASH_BWD=0 keeps the chunked backward (the on-chip
    A/B lever) — gradients still correct."""
    import jax
    import jax.numpy as jnp

    fa = _interp_kernels(monkeypatch)
    monkeypatch.setenv("MXT_PALLAS_FLASH_BWD", "0")
    rng = onp.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 2, 128, 16)).astype("f"))
               for _ in range(3))
    g = jax.grad(lambda a, b, c: (fa.flash_attention_raw(
        a, b, c, True, 0.25) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    r = jax.grad(lambda a, b, c: (fa._sdpa_ref(
        a, b, c, True, 0.25) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g, r):
        assert float(jnp.abs(got - want).max()) < 2e-4


def test_ulysses_gradient_through_pallas_kernels(monkeypatch):
    """Sequence-parallel ulysses with the flash custom-vjp INSIDE the
    shard_map body: the backward must route to the pallas kernels
    directly (manual-mesh guard) and match the unsharded oracle."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.ring import ulysses_attention_raw

    fa = _interp_kernels(monkeypatch)
    # spy on the kernels: gradient parity alone would stay green if a
    # gate change silently rerouted to the jax.nn fallback
    calls = {"fwd": 0, "bwd": 0}
    real_fwd, real_bwd = fa._fa_forward_pallas, fa._fa_backward_pallas

    def spy_fwd(*a, **kw):
        calls["fwd"] += 1
        return real_fwd(*a, **kw)

    def spy_bwd(*a, **kw):
        calls["bwd"] += 1
        return real_bwd(*a, **kw)

    monkeypatch.setattr(fa, "_fa_forward_pallas", spy_fwd)
    monkeypatch.setattr(fa, "_fa_backward_pallas", spy_bwd)

    rng = onp.random.RandomState(7)
    B, H, T, D = 2, 4, 256, 16
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)).astype("f"))
               for _ in range(3))
    scale = 0.25

    r = jax.grad(lambda a, b, c: (fa._sdpa_ref(
        a, b, c, True, scale) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)

    mesh = parallel.make_mesh({"sp": 4})
    with parallel.mesh_scope(mesh):
        g = jax.jit(jax.grad(
            lambda a, b, c: (ulysses_attention_raw(
                a, b, c, causal=True, scale=scale,
                mesh=mesh) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
    assert calls["fwd"] >= 1 and calls["bwd"] >= 1, calls
    for got, want in zip(g, r):
        assert float(jnp.abs(got - want).max()) < 2e-4
