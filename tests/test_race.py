"""Deterministic-interleaving tests (tools/race.py): the seeded harness
drives real package concurrency — the prefill→decode KV handoff,
AsyncCheckpointer backpressure, DevicePrefetcher shutdown — and every
schedule replays bit-identically from its seed.

Harness rules exercised here (see tools/race.py docstring): managed
threads park at ``point()`` and (when ``park_locks``) at sanitizer lock
boundaries; a managed thread blocks for real only when it unblocks
autonomously, or inside ``external()``; adopted foreign threads signal
an Event after adopting and before their first park.  The checkpoint
and prefetcher scenarios run ``park_locks=False`` because unmanaged
package threads (the ckpt writer committing, the prefetch loop) take
the same wrapped locks on timing-dependent paths."""
import os
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, nd, sanitizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import race                                   # noqa: E402
from tools.race import DeadlockError, Harness            # noqa: E402


# ---------------------------------------------------------------------------
# harness mechanics
# ---------------------------------------------------------------------------

def test_same_seed_replays_bit_identically():
    def run(seed):
        h = Harness(seed)
        log = []

        def worker(me):
            for step in ("a", "b"):
                h.point(step)
                log.append(f"{me}.{step}")

        h.spawn("x", worker, "x")
        h.spawn("y", worker, "y")
        trace = h.run()
        return trace, log

    t1, l1 = run(11)
    t2, l2 = run(11)
    assert t1 == t2 and l1 == l2
    distinct = {tuple(run(s)[0]) for s in range(8)}
    assert len(distinct) >= 2, \
        "eight seeds should explore more than one schedule"


def test_harness_witnesses_lock_deadlock():
    def build(seed):
        # fresh locks per run: a witnessed deadlock leaves its parked
        # threads holding the old pair forever (daemon zombies)
        a = sanitizer.wrap_lock(threading.Lock(), "test.race.A")
        b = sanitizer.wrap_lock(threading.Lock(), "test.race.B")
        h = Harness(seed)

        def fwd():
            with a:
                h.point("mid")
                with b:
                    pass

        def bwd():
            with b:
                h.point("mid")
                with a:
                    pass

        h.spawn("fwd", fwd)
        h.spawn("bwd", bwd)
        return h

    outcomes = {}
    for seed in range(8):
        try:
            build(seed).run(timeout=20.0)
            outcomes[seed] = "ok"
        except DeadlockError:
            outcomes[seed] = "deadlock"
    assert "deadlock" in outcomes.values(), \
        f"no schedule hit the seeded lock inversion: {outcomes}"
    # and the witnessed outcome itself replays deterministically
    bad = next(s for s, o in outcomes.items() if o == "deadlock")
    with pytest.raises(DeadlockError):
        build(bad).run(timeout=20.0)
    sanitizer.reset_locks()   # the ok-schedules recorded the A<->B cycle


# ---------------------------------------------------------------------------
# scenario 1: prefill→decode KV handoff (serving/lanes.py)
# ---------------------------------------------------------------------------

class _StubAllocator:
    blocks_in_use = 0


class _StubMgr:
    def __init__(self, budgets):
        self.allocator = _StubAllocator()
        self._left = dict(budgets)     # slot -> decode steps remaining

    def advance(self, slot):
        pass

    def evict(self, slot):
        self._left.pop(slot, None)

    def consume(self, slot):
        self._left[slot] -= 1
        return self._left[slot] <= 0


class _StubEngine:
    def __init__(self):
        self.steps = 0

    def step(self, active):
        self.steps += 1
        return {s: 100 * (s + 1) + self.steps for s in active}

    def clear_slot(self, slot):
        pass


class _StubReq:
    def __init__(self, rid):
        self.id = rid
        self.t_first = 0.0
        self.t_handoff = None
        self.trace = None
        self.max_new_tokens = 3


class _StubReplica:
    index = 0

    def __init__(self, budgets):
        self.engine = _StubEngine()
        self.mgr = _StubMgr(budgets)
        self.capacity_evt = threading.Event()
        self.batches = 0
        self.finished = []

    def finish(self, req, tokens):
        self.finished.append((req.id, tuple(tokens)))

    def fail(self, req, exc, lane=None):
        raise AssertionError(f"unexpected lane failure: {exc}")


def _run_handoff(seed):
    from mxnet_tpu.serving.lanes import DecodeLane, _Handoff

    r = _StubReplica({0: 2, 1: 2, 2: 2})
    lane = DecodeLane(r)
    h = Harness(seed)

    def prefill():
        for slot in (0, 1, 2):
            lane.hand_off(_Handoff(_StubReq(f"req{slot}"), slot, slot))
            h.point("handed")

    def decode():
        while len(r.finished) < 3:
            lane._adopt()
            with lane._hand_lock:
                busy = bool(lane._seqs)
            if busy:
                lane._tick()
            h.point("decode-idle")

    h.spawn("prefill", prefill)
    h.spawn("decode", decode)
    trace = h.run()
    return trace, sorted(r.finished)


def test_kv_handoff_interleavings_replay_from_seed():
    sanitizer.reset_locks()
    for seed in (3, 4):
        t1, done1 = _run_handoff(seed)
        t2, done2 = _run_handoff(seed)
        assert t1 == t2, f"seed {seed} did not replay bit-identically"
        assert done1 == done2
        # every request fully decoded regardless of the interleaving:
        # the handoff's first token plus two decode ticks
        assert [rid for rid, _ in done1] == ["req0", "req1", "req2"]
        assert all(len(toks) == 3 for _, toks in done1)
    ta, _ = _run_handoff(3)
    tb, _ = _run_handoff(4)
    assert ta != tb, "seeds 3 and 4 chose the same schedule"
    # the handoff lock was parked on and recorded; the order stayed clean
    assert any(lbl == "lock:lanes.DecodeLane._hand_lock"
               for kind, _, lbl in ta if kind == "grant")
    assert sanitizer.lock_order_violations() == []


# ---------------------------------------------------------------------------
# scenario 2: AsyncCheckpointer backpressure under a slow writer
# ---------------------------------------------------------------------------

def _net():
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net(nd.ones((2, 6)))
    return net


def _run_backpressure(seed, tmp_path, net):
    real_write = checkpoint._write_snapshot
    entered = threading.Event()

    def slow_write(tmp, snap):
        # write #1's writer thread adopts into the harness and parks so
        # the saver's second save meets a genuinely in-flight oldest
        # ticket; write #2 runs unmanaged (commits autonomously)
        if snap.step == 1:
            with race.managed("writer1"):
                entered.set()
                race.point("write")
                real_write(tmp, snap)
        else:
            real_write(tmp, snap)

    checkpoint._write_snapshot = slow_write
    try:
        ckpt = checkpoint.AsyncCheckpointer(max_pending=1)
        h = Harness(seed, park_locks=False)
        events = []

        def saver():
            d = str(tmp_path)
            ckpt.save(d, 1, net)
            entered.wait(60)
            events.append("saved1")
            h.point("saved1")
            # max_pending=1: this save blocks on write #1 committing,
            # which needs the scheduler to grant the adopted writer
            with race.external("backpressure"):
                ckpt.save(d, 2, net)
            events.append("saved2")
            h.point("saved2")
            with race.external("drain"):
                ckpt.wait(60)
            events.append("drained")

        h.spawn("saver", saver)
        trace = h.run(timeout=90.0)
        ckpt.close()
        assert events == ["saved1", "saved2", "drained"]
        assert ckpt.pending() == 0
        return trace
    finally:
        checkpoint._write_snapshot = real_write


def test_async_checkpoint_backpressure_replays(tmp_path):
    net = _net()
    traces = {}
    for seed in (0, 1, 2, 3):
        t1 = _run_backpressure(seed, tmp_path / f"a{seed}", net)
        t2 = _run_backpressure(seed, tmp_path / f"b{seed}", net)
        assert t1 == t2, f"seed {seed} did not replay bit-identically"
        traces[seed] = tuple(t1)
        # backpressure ordering held: write #1 was granted before
        # save #2 returned
        grants = [e for e in t1 if e[0] == "grant"]
        w1 = grants.index(("grant", "writer1", "write"))
        s2 = grants.index(("grant", "saver", "saved2"))
        assert w1 < s2, "save #2 returned before write #1 was scheduled"
    assert len(set(traces.values())) >= 2, \
        f"seeds 0-3 all chose the same schedule: {traces}"


# ---------------------------------------------------------------------------
# scenario 3: DevicePrefetcher shutdown mid-transfer
# ---------------------------------------------------------------------------

def _run_prefetch_shutdown(seed):
    from mxnet_tpu.data import DevicePrefetcher

    def batches():
        i = 0
        while True:
            yield np.full((2, 2), float(i), dtype=np.float32)
            i += 1

    entered = threading.Event()
    h = Harness(seed, park_locks=False)
    events = []
    holder = {}

    def driver():
        # built inside the harness so the lazily-started prefetch
        # thread's first transfer sees the active harness
        pf = DevicePrefetcher(batches(), depth=2)
        holder["pf"] = pf
        real_put = pf._put_device
        parked_once = []

        def slow_put(arr):
            # first transfer parks mid-flight on the prefetch thread;
            # later transfers run unmanaged (close() must unwind them)
            if not parked_once:
                parked_once.append(True)
                with race.managed("transfer"):
                    entered.set()
                    race.point("mid-transfer")
            return real_put(arr)

        pf._put_device = slow_put
        with race.external("get"):
            first = pf.get(timeout=30)
        entered.wait(60)
        events.append(float(np.asarray(first.asnumpy()).ravel()[0]))
        h.point("got1")
        with race.external("close"):
            pf.close()
        events.append("closed")
        h.point("closed")

    h.spawn("driver", driver)
    trace = h.run(timeout=60.0)
    pf = holder["pf"]
    assert events == [0.0, "closed"]
    assert pf._closed
    pf._thread.join(timeout=10)
    assert not pf._thread.is_alive(), \
        "prefetch thread leaked past close()"
    return trace


def test_prefetcher_shutdown_mid_transfer_replays():
    t1 = _run_prefetch_shutdown(2)
    t2 = _run_prefetch_shutdown(2)
    assert t1 == t2, "prefetcher shutdown did not replay bit-identically"
    assert ("grant", "transfer", "mid-transfer") in t1


# ---------------------------------------------------------------------------
# runtime vs static lock-order graph cross-check
# ---------------------------------------------------------------------------

def test_runtime_edges_union_static_graph_acyclic(tmp_path):
    """The sanitizer's observed edges and the analyzer's static T11
    graph describe the same discipline: their union has no cycle."""
    from tools.lint.analyzer import analyze_paths, iter_py_files
    from tools.lint.concurrency import build_lock_graph, _find_cycles
    from tools.lint.core import FileSource
    from tools.lint.rules import FileChecker

    sanitizer.reset_locks()
    was = sanitizer.locks_enabled()
    sanitizer.enable_locks()
    try:
        # real runtime activity across instrumented subsystems
        _run_handoff(1)
        from mxnet_tpu import engine
        engine.async_stats()
        ckpt = checkpoint.AsyncCheckpointer()
        ckpt.save(str(tmp_path / "c"), 1, _net())
        ckpt.wait(60)
        ckpt.close()
        runtime_edges = set(sanitizer.lock_order_edges())
        assert sanitizer.lock_order_violations() == [], \
            "runtime lock sanitizer observed an order inversion"
    finally:
        if not was:
            sanitizer.disable_locks()
        sanitizer.reset_locks()

    violations = analyze_paths(["mxnet_tpu"], REPO, rules={"T11"})
    assert not [v for v in violations if "cycle" in v.message], \
        "static lock-order cycle on the tree"
    lock_facts = []
    for abspath, relpath in iter_py_files(["mxnet_tpu"], REPO):
        try:
            src = FileSource.parse(abspath, relpath)
        except (SyntaxError, UnicodeDecodeError):
            continue
        checker = FileChecker(src, enabled={"T11"})
        checker.run()
        lock_facts.append(checker.lock_facts)
    static_edges = set(build_lock_graph(lock_facts))
    adj = {}
    for a, b in static_edges | runtime_edges:
        adj.setdefault(a, set()).add(b)
    assert _find_cycles(adj) == [], \
        "runtime edges union static graph has a lock-order cycle"
