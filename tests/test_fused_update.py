"""Fused multi-tensor optimizer update tests: the single-dispatch Trainer
path must be numerically identical to the per-param eager path (reference
model: multi_sgd_update vs sgd_update consistency, SURVEY §2.2
optimizer-ops row)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _net_and_data(seed=0, dtype="float32"):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"),
            nn.BatchNorm(),
            nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    if dtype != "float32":
        net.cast(dtype)
    x = nd.random.uniform(-1, 1, shape=(8, 8)).astype(dtype)
    y = nd.array(onp.arange(8) % 4)
    return net, x, y


def _train(opt_name, opt_args, fused, steps=4, dtype="float32"):
    net, x, y = _net_and_data(dtype=dtype)
    trainer = gluon.Trainer(net.collect_params(), opt_name, dict(opt_args))
    if not fused:
        trainer._try_fused_update = lambda: False  # force eager path
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
    # positional (auto-generated names differ between instantiations)
    return [v.data().asnumpy().astype(onp.float64)
            for v in net.collect_params().values()], \
        float(loss.mean().asscalar())


@pytest.mark.parametrize("opt,args", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-2}),
    ("adamw", {"learning_rate": 1e-2, "wd": 1e-2}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
])
def test_fused_matches_eager(opt, args):
    fused_params, fused_loss = _train(opt, args, fused=True)
    eager_params, eager_loss = _train(opt, args, fused=False)
    assert len(fused_params) == len(eager_params)
    for i, (f, e) in enumerate(zip(fused_params, eager_params)):
        onp.testing.assert_allclose(f, e, rtol=1e-5, atol=1e-6,
                                    err_msg=f"param {i}")
    assert fused_loss == pytest.approx(eager_loss, rel=1e-5)


def test_fused_multi_precision_bf16():
    args = {"learning_rate": 0.05, "momentum": 0.9,
            "multi_precision": True}
    fused_params, _ = _train("sgd", args, fused=True, dtype="bfloat16")
    eager_params, _ = _train("sgd", args, fused=False, dtype="bfloat16")
    for i, (f, e) in enumerate(zip(fused_params, eager_params)):
        onp.testing.assert_allclose(f, e, rtol=1e-2, atol=1e-3,
                                    err_msg=f"param {i}")


def test_fused_single_dispatch_and_cache():
    net, x, y = _net_and_data()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(3):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
    assert len(trainer._fused_cache) == 1  # one trace, reused every step


def test_fused_respects_lr_schedule_without_retrace():
    from mxnet_tpu import lr_scheduler

    net, x, y = _net_and_data()
    sched = lr_scheduler.FactorScheduler(step=1, factor=0.5,
                                         base_lr=0.2)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.2,
                             "lr_scheduler": sched})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net(x)  # resolve deferred BN shapes before reading params
    before = {k: v.data().asnumpy().copy()
              for k, v in net.collect_params().items()}
    deltas = []
    for _ in range(3):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
        after = list(net.collect_params().values())[0].data().asnumpy()
        deltas.append(onp.abs(
            after - before[list(net.collect_params())[0]]).sum())
    assert len(trainer._fused_cache) == 1  # lr is traced, not baked
    assert onp.isfinite(deltas[-1])


def test_sparse_grads_fall_back():
    """A trainer whose params carry row_sparse grads must skip the fused
    path entirely (lazy eager updates) — exercised through the Trainer."""
    from mxnet_tpu.ndarray import sparse as sp
    from mxnet_tpu.gluon import Parameter

    p = Parameter("emb_weight", shape=(6, 3))
    p.initialize(init="zeros")
    p.data()._data = nd.random.uniform(shape=(6, 3))._data
    trainer = gluon.Trainer([p], "sgd", {"learning_rate": 0.5})
    # hand the param a row_sparse gradient (grad lives on the NDArray)
    g = sp.RowSparseNDArray(nd.ones((2, 3)), nd.array([1, 4]), (6, 3))
    p.data()._grad = g
    before = p.data().asnumpy().copy()
    trainer.step(1)
    assert trainer._fused_cache == {}     # fused path declined
    after = p.data().asnumpy()
    assert not onp.allclose(after[1], before[1])   # touched rows updated
    onp.testing.assert_allclose(after[0], before[0])  # others untouched
