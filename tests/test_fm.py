"""Factorization machine + LibSVM pipeline tests (BASELINE config 4;
reference model: example/sparse/factorization_machine + the sparse
kvstore push/row_sparse_pull tests, SURVEY §4)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, nd
from mxnet_tpu.models import fm
from mxnet_tpu.ndarray import sparse as sp


def _toy_libsvm(path, n=40, nfeat=16, seed=0):
    """Separable data: label = 1 iff feature 0 present."""
    rng = onp.random.RandomState(seed)
    with open(path, "w") as f:
        for i in range(n):
            label = i % 2
            feats = {0: 1.0} if label else {1: 1.0}
            for _ in range(3):
                feats[int(rng.randint(2, nfeat))] = float(
                    rng.uniform(0.5, 1.0))
            toks = " ".join(f"{k}:{v}" for k, v in sorted(feats.items()))
            f.write(f"{label} {toks}\n")


def test_libsvm_iter(tmp_path):
    path = str(tmp_path / "data.libsvm")
    _toy_libsvm(path, n=10, nfeat=16)
    it = io.LibSVMIter(data_libsvm=path, data_shape=(16,), batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    b0 = batches[0]
    assert isinstance(b0.data[0], sp.CSRNDArray)
    assert b0.data[0].shape == (4, 16)
    assert b0.label[0].shape == (4,)
    assert batches[-1].pad == 2  # 10 rows → pad last batch of 4
    dense = b0.data[0].todense().asnumpy()
    assert dense.shape == (4, 16)
    assert (dense != 0).sum() >= 8
    it.reset()
    assert len(list(it)) == 3


def test_fm_forward_matches_dense_formula():
    rng = onp.random.RandomState(0)
    dense = (rng.uniform(size=(4, 8)) < 0.4) * rng.uniform(size=(4, 8))
    dense = dense.astype(onp.float32)
    csr = sp.cast_storage(nd.array(dense), "csr")
    model = fm.FMModel(8, factor_dim=3, seed=1)
    out = model(csr).asnumpy().ravel()
    w0 = model.w0.asnumpy()[0]
    w = model.w.asnumpy()
    v = model.v.asnumpy()
    xv = dense @ v
    want = (w0 + dense @ w[:, 0]
            + 0.5 * ((xv ** 2) - (dense ** 2) @ (v ** 2)).sum(1))
    onp.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_fm_trains_on_libsvm(tmp_path):
    path = str(tmp_path / "train.libsvm")
    _toy_libsvm(path, n=64, nfeat=16)
    it = io.LibSVMIter(data_libsvm=path, data_shape=(16,), batch_size=16)
    model = fm.FMModel(16, factor_dim=4, lr=0.5)
    losses = []
    for _epoch in range(15):
        it.reset()
        for batch in it:
            losses.append(model.step(batch.data[0], batch.label[0]))
    assert losses[-1] < losses[0] * 0.7
    it.reset()
    batch = next(iter(it))
    assert model.accuracy(batch.data[0], batch.label[0]) >= 0.9


def test_fm_rowsparse_grad_shape():
    dense = onp.zeros((2, 10), onp.float32)
    dense[0, 3] = 1.0
    dense[1, 7] = 2.0
    csr = sp.cast_storage(nd.array(dense), "csr")
    model = fm.FMModel(10, factor_dim=2)
    rows = model._touched_rows(csr).asnumpy()
    assert sorted(rows.tolist()) == [3, 7]
    g = model._rowslice(nd.array(onp.arange(20, dtype=onp.float32)
                                 .reshape(10, 2)), model._touched_rows(csr))
    assert isinstance(g, sp.RowSparseNDArray)
    assert g.data.shape == (2, 2)


def test_fm_with_kvstore_optimizer():
    """update_on_kvstore path: server-side optimizer + row_sparse_pull."""
    from mxnet_tpu import optimizer as opt

    dense = onp.zeros((4, 6), onp.float32)
    dense[:, 0] = [1, 0, 1, 0]
    dense[:, 1] = [0, 1, 0, 1]
    csr = sp.cast_storage(nd.array(dense), "csr")
    labels = nd.array([1.0, 0, 1, 0])
    kv = mx.kv.create("local")
    kv.set_optimizer(opt.SGD(learning_rate=0.5))
    model = fm.FMModel(6, factor_dim=2, kvstore=kv)
    first = model.step(csr, labels)
    for _ in range(30):
        last = model.step(csr, labels)
    assert last < first
