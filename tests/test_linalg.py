"""linalg family + reshape special codes + op-attribute validation.

Reference test model: tests/python/unittest/test_operator.py test_laop*
(reconstruction identities + finite-difference gradients against
src/operator/tensor/la_op.cc) and test_reshape_new (matrix_op.cc
ReshapeShape vocabulary).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _spd(n, batch=(), seed=0):
    rs = np.random.RandomState(seed)
    a = rs.randn(*batch, n, n)
    return a @ np.swapaxes(a, -1, -2) + n * np.eye(n)


# --- trsm / trmm ------------------------------------------------------------

@pytest.mark.parametrize("transpose", [False, True])
@pytest.mark.parametrize("lower", [False, True])
def test_trsm_rightside(transpose, lower):
    rs = np.random.RandomState(0)
    a = np.tril(rs.randn(4, 4)) + 4 * np.eye(4)
    if not lower:
        a = a.T
    b = rs.randn(3, 4)
    x = nd.linalg_trsm(nd.array(a), nd.array(b), transpose=transpose,
                       rightside=True, lower=lower, alpha=2.0).asnumpy()
    op_a = a.T if transpose else a
    np.testing.assert_allclose(x @ op_a, 2.0 * b, rtol=1e-4, atol=1e-5)


def test_trsm_left_matches_solve():
    rs = np.random.RandomState(1)
    a = np.tril(rs.randn(4, 4)) + 4 * np.eye(4)
    b = rs.randn(4, 3)
    x = nd.linalg_trsm(nd.array(a), nd.array(b)).asnumpy()
    np.testing.assert_allclose(a @ x, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("rightside", [False, True])
def test_trmm(rightside):
    rs = np.random.RandomState(2)
    a = rs.randn(4, 4)  # dirty upper half: op must take the triangle
    b = rs.randn(4, 4)
    out = nd.linalg_trmm(nd.array(a), nd.array(b), rightside=rightside,
                         alpha=0.5).asnumpy()
    tri = np.tril(a)
    want = 0.5 * (b @ tri if rightside else tri @ b)
    np.testing.assert_allclose(out, want, rtol=1e-5)


# --- potrf / potri / sumlogdiag --------------------------------------------

def test_potri_is_spd_inverse():
    a = _spd(4, seed=3)
    l = np.linalg.cholesky(a)
    inv = nd.linalg_potri(nd.array(l)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(a), rtol=1e-4, atol=1e-5)


def test_sumlogdiag_and_gradient():
    a = _spd(3, seed=4)
    out = nd.linalg_sumlogdiag(nd.array(a)).asnumpy()
    np.testing.assert_allclose(out, np.log(np.diag(a)).sum(), rtol=1e-6)
    check_numeric_gradient(lambda x: nd.linalg_sumlogdiag(x), [a])


# --- diag / trian pack-unpack -----------------------------------------------

def test_extractdiag_makediag_roundtrip():
    rs = np.random.RandomState(5)
    a = rs.randn(4, 4)
    for k in (-1, 0, 1):
        d = nd.linalg_extractdiag(nd.array(a), offset=k).asnumpy()
        np.testing.assert_allclose(d, np.diagonal(a, k))
        m = nd.linalg_makediag(nd.array(d), offset=k).asnumpy()
        np.testing.assert_allclose(np.diagonal(m, k), d)
        assert m.sum() == pytest.approx(d.sum(), rel=1e-5)


@pytest.mark.parametrize("lower", [False, True])
@pytest.mark.parametrize("offset", [0, 1, -1])
def test_extracttrian_maketrian_roundtrip(lower, offset):
    """Reference semantics: offset>0 always packs the upper band, <0 the
    lower band; ``lower`` only matters at offset=0."""
    rs = np.random.RandomState(6)
    a = rs.randn(2, 4, 4)
    v = nd.linalg_extracttrian(nd.array(a), offset=offset, lower=lower)
    m = nd.linalg_maketrian(v, offset=offset, lower=lower).asnumpy()
    if offset > 0:
        tri = np.triu(a, offset)
    elif offset < 0:
        tri = np.tril(a, offset)
    else:
        tri = np.tril(a) if lower else np.triu(a)
    np.testing.assert_allclose(m, tri, rtol=1e-6)


# --- factorizations ---------------------------------------------------------

def test_gelqf_reconstructs():
    rs = np.random.RandomState(7)
    a = rs.randn(3, 5)  # m <= n
    L, Q = nd.linalg_gelqf(nd.array(a))
    L, Q = L.asnumpy(), Q.asnumpy()
    np.testing.assert_allclose(L @ Q, a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(L, np.tril(L), atol=1e-6)  # L is lower


def test_syevd_reconstructs():
    a = _spd(4, batch=(2,), seed=8)
    U, lam = nd.linalg_syevd(nd.array(a))
    U, lam = U.asnumpy(), lam.asnumpy()
    # A = U^T diag(lam) U, eigenvalues ascending
    rec = np.swapaxes(U, -1, -2) @ (lam[..., None] * U)
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-4)
    assert (np.diff(lam, axis=-1) >= -1e-6).all()


def test_gesvd_reconstructs():
    rs = np.random.RandomState(9)
    a = rs.randn(3, 6)
    UT, L, V = nd.linalg_gesvd(nd.array(a))
    rec = UT.asnumpy() @ (L.asnumpy()[..., None] * V.asnumpy())
    np.testing.assert_allclose(rec, a, rtol=1e-4, atol=1e-5)


def test_inverse_det_slogdet():
    a = _spd(3, seed=10)
    np.testing.assert_allclose(nd.linalg_inverse(nd.array(a)).asnumpy(),
                               np.linalg.inv(a), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(nd.linalg_det(nd.array(a)).asnumpy(),
                               np.linalg.det(a), rtol=1e-4)
    sign, logdet = nd.linalg_slogdet(nd.array(a))
    np.testing.assert_allclose(float(sign.asscalar()), 1.0)
    np.testing.assert_allclose(float(logdet.asscalar()),
                               np.linalg.slogdet(a)[1], rtol=1e-5)


def test_linalg_gemm_and_gradient():
    rs = np.random.RandomState(11)
    a, b, c = rs.randn(3, 4), rs.randn(5, 4), rs.randn(3, 5)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         transpose_b=True, alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2 * a @ b.T + 0.5 * c, rtol=1e-5)
    check_numeric_gradient(
        lambda x, y, z: nd.linalg_gemm(x, y, z, transpose_b=True),
        [a, b, c])


def test_trsm_gradient():
    a = np.tril(_spd(3, seed=12))
    b = np.random.RandomState(12).randn(3, 2)
    check_numeric_gradient(
        lambda x, y: nd.linalg_trsm(x, y, rightside=False), [a, b],
        rtol=2e-2, atol=1e-3)


# --- reshape special codes --------------------------------------------------

@pytest.mark.parametrize("in_shape,spec,want", [
    ((2, 3, 4), (4, 0, 2), (4, 3, 2)),
    ((2, 3, 4), (2, 0, 0), (2, 3, 4)),
    ((2, 3, 4), (6, 1, -1), (6, 1, 4)),
    ((2, 3, 4), (3, -1, 2), (3, 4, 2)),
    ((2, 3, 4), (-2,), (2, 3, 4)),
    ((2, 3, 4), (2, -2), (2, 3, 4)),
    ((2, 3, 4), (-2, 1, 1), (2, 3, 4, 1, 1)),
    ((2, 3, 4), (-3, 4), (6, 4)),
    ((2, 3, 4), (-3, -2), (6, 4)),
    ((2, 3, 4), (0, -3), (2, 12)),
    ((2, 3, 4, 5), (-3, -3), (6, 20)),
    ((2, 3, 4), (-4, 1, 2, -2), (1, 2, 3, 4)),
    ((2, 3, 4), (2, -4, -1, 3, -2), (2, 1, 3, 4)),
])
def test_reshape_special_codes(in_shape, spec, want):
    x = nd.zeros(in_shape)
    out = nd.reshape(x, shape=spec)
    assert out.shape == tuple(want), (spec, out.shape)


def test_reshape_reverse():
    # reference example: (10, 5, 4) + shape=(-1, 0) reverse=True -> (50, 4)
    x = nd.zeros((10, 5, 4))
    assert nd.reshape(x, shape=(-1, 0), reverse=True).shape == (50, 4)
    assert nd.reshape(x, shape=(-1, 0)).shape == (40, 5)


def test_reshape_bad_codes_raise():
    x = nd.zeros((2, 3, 4))
    with pytest.raises(mx.MXNetError):
        nd.reshape(x, shape=(-1, -1, 4))
    with pytest.raises(mx.MXNetError):
        nd.reshape(x, shape=(-4, 5, 5, -2))  # 5*5 != 2
    with pytest.raises(mx.MXNetError):
        nd.reshape(x, shape=(-5, 4))


# --- op-attribute validation ------------------------------------------------

def test_unknown_op_attribute_raises():
    """The dmlc-Parameter role: a typo'd attribute must raise, not vanish
    (round-1 VERDICT Missing #6)."""
    x = nd.ones((2, 2))
    with pytest.raises(mx.MXNetError, match="unknown attribute"):
        nd.softmax(x, axiss=1)
    with pytest.raises(mx.MXNetError, match="unknown attribute"):
        nd.contrib.box_iou(nd.zeros((1, 4)), nd.zeros((1, 4)),
                           formatt="corner")
    with pytest.raises(mx.MXNetError, match="unknown attribute"):
        nd.reshape(x, shape=(4, 1), revrese=True)


def test_known_attrs_still_pass():
    x = nd.ones((2, 2))
    nd.softmax(x, axis=1)                       # real attr
    nd.reshape(x, shape=(4, 1), name="r")       # common junk tolerated
    # legacy MXNet json checkpoints carry backend perf hints on conv
    # nodes; they must pass validation (no TPU meaning, harmless)
    nd.convolution(nd.ones((1, 2, 5, 5)), nd.ones((3, 2, 3, 3)),
                   kernel=(3, 3), num_filter=3, no_bias=True,
                   workspace=1024, cudnn_tune="off", cudnn_off=True)


def test_linalg_gemm_axis():
    rs = np.random.RandomState(13)
    a, b, c = rs.randn(3, 2, 4), rs.randn(4, 2, 5), rs.randn(3, 2, 5)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         axis=0).asnumpy()
    want = np.moveaxis(np.moveaxis(a, 0, -2) @ np.moveaxis(b, 0, -2)
                       + np.moveaxis(c, 0, -2), -2, 0)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_channels_last_layout_rejected_not_swallowed():
    """layout is tolerated only at its channel-first default; NHWC on an
    op without a layout param must raise, not silently mis-pool."""
    x = nd.ones((1, 2, 4, 4))
    nd.pooling(x, kernel=(2, 2), layout="NCHW")  # default: fine
    with pytest.raises(mx.MXNetError, match="channel-first"):
        nd.pooling(x, kernel=(2, 2), layout="NHWC")
