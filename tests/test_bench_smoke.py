"""Driver-entry-point guards: bench.py must print ONE parseable JSON
line with the tracked keys, and __graft_entry__.entry() must return a
jittable fn — a silent break in either loses the round's numbers (the
driver runs them unattended on the chip)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.slow
def test_bench_py_emits_one_json_line():
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="2", BENCH_WARMUP="1",
               BENCH_REPEATS="1", BENCH_BATCH="2", BENCH_IMAGE="64",
               BENCH_BERT_BATCH="2", BENCH_SEQ="16",
               BENCH_DATA_STEPS="2")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=1500)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "resnet50_v1_train_images_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["vs_baseline"] is None
    assert "bert_base_samples_per_sec_per_chip" in rec, rec
    assert "resnet50_v1_recordio_images_per_sec_per_chip" in rec, rec


# Faithful simulation of the accelerator environment whose downed tunnel
# cost round 4 its multichip artifact (MULTICHIP_r04 rc=124): the real
# sitecustomize registers an 'axon' PJRT backend and — crucially — sets
# jax config jax_platforms='axon,cpu', which OVERRIDES the JAX_PLATFORMS
# env var.  Any backend init then tries axon first and blocks (~25 min
# observed).  The fake backend factory blocks 600s; only a subsequent
# jax.config.update('jax_platforms', 'cpu') avoids it, exactly like the
# real conftest/tooling route.
_BLOCKED_SITECUSTOMIZE = """\
import jax
from jax._src import xla_bridge as _xb


def _blocked_factory(*a, **k):
    import sys, time
    sys.stderr.write('SIMULATED TUNNEL HANG\\n')
    sys.stderr.flush()
    time.sleep(600)


_xb.register_backend_factory('axon', _blocked_factory, priority=400,
                             experimental=True)
jax.config.update('jax_platforms', 'axon,cpu')
"""


def test_dryrun_multichip_tunnel_proof(tmp_path):
    """With the driver's exact env shape (JAX_PLATFORMS=axon env var,
    xla_force_host_platform_device_count in XLA_FLAGS, a sitecustomize
    whose 'axon' backend init blocks), phase 1 must print within 60s —
    i.e. dryrun_multichip must pin jax_platforms='cpu' at the config
    level before any backend touch instead of querying devices."""
    (tmp_path / "sitecustomize.py").write_text(_BLOCKED_SITECUSTOMIZE)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "axon"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    code = (
        f"import sys\nsys.path.insert(0, {REPO!r})\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(2)\n"
    )
    # ~26s measured idle; 180s gives CI-load headroom while still
    # cleanly discriminating from the 600s simulated hang (and the real
    # ~25-min one).  The stderr assert below catches any backend touch
    # regardless of timing.
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=180)
    assert "SIMULATED TUNNEL HANG" not in r.stderr, \
        "dryrun initialized the blocked accelerator backend"
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "dryrun_multichip(2)" in r.stdout and "OK" in r.stdout, r.stdout


@pytest.mark.slow
def test_dryrun_multichip_bootstrap_tunnel_proof(tmp_path):
    """Same blocked-backend simulation, no XLA_FLAGS at all: the probe
    subprocess hangs (killed at MXT_PROBE_TIMEOUT), and the bootstrap
    child must still run the phases under its own cpu pin."""
    (tmp_path / "sitecustomize.py").write_text(_BLOCKED_SITECUSTOMIZE)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "axon"
    env.pop("XLA_FLAGS", None)
    env["MXT_PROBE_TIMEOUT"] = "5"
    code = (
        f"import sys\nsys.path.insert(0, {REPO!r})\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(2)\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=120)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "dryrun_multichip(2)" in r.stdout and "OK" in r.stdout, r.stdout


def test_dryrun_multichip_inprocess_smoke(monkeypatch, capfd):
    """Core-lane guard (VERDICT r4 #10): drive the REAL
    __graft_entry__.dryrun_multichip entry path end-to-end on the test
    session's virtual mesh — no future round may ship a red
    MULTICHIP artifact undetected."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
        g.dryrun_multichip(2)
    finally:
        sys.path.remove(REPO)
    out = capfd.readouterr().out
    assert "dryrun_multichip(2)" in out and "OK" in out, out


def test_serving_latency_bench_emits_artifact(tmp_path):
    """benchmark/serving_latency.py at toy load must produce the
    SERVING_LATENCY artifact with the predictor lanes, the generative
    r8-vs-paged rate sweep, percentile blocks, and a passing
    signature-ceiling acceptance — a silent break loses the round-11
    serving numbers."""
    out = tmp_path / "serving_latency.json"
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BENCH_SERVING_REQUESTS="8",
               BENCH_SERVING_CLIENTS="2", BENCH_SERVING_RATE="500",
               BENCH_SERVING_MAX_BATCH="4", BENCH_SERVING_MAX_LEN="16",
               BENCH_SERVING_GEN_REQUESTS="6", BENCH_SERVING_GEN_RATE="50",
               BENCH_SERVING_GEN_RATES="50", BENCH_SERVING_GEN_MAX_NEW="4",
               BENCH_SERVING_AB_REQUESTS="4", BENCH_SERVING_AB_MAX_NEW="8",
               BENCH_SERVING_AB_REPEATS="2",
               BENCH_SERVING_SPEC_REQUESTS="3", BENCH_SERVING_SPEC_K="3",
               BENCH_SERVING_SPEC_MAX_NEW="6", BENCH_SERVING_SPEC_PREFIX="48",
               BENCH_SERVING_SPEC_MAX_LEN="128",
               BENCH_SERVING_CAP_BURST="12",
               BENCH_SERVING_CAP_AB_REQUESTS="4",
               BENCH_SERVING_CAP_AB_REPEATS="2",
               MXT_SERVING_LATENCY_OUT=str(out))
    env.pop("XLA_FLAGS", None)   # the bench forces its own 8-device flag
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "serving_latency.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "serving_open_loop_p99_ms"
    assert rec["value"] > 0
    for lane in ("closed_loop", "open_loop"):
        ln = rec["lanes"][lane]
        assert ln["completed"] == 8
        assert ln["total_ms"]["p50"] <= ln["total_ms"]["p99"]
        assert ln["queue_wait_ms"]["p99"] is not None
        assert ln["throughput_req_per_s"] > 0
        assert sum(ln["batch_size_dist"].values()) == 8
        assert 1 <= ln["cache"]["signatures"] <= \
            rec["bucket_config"]["signature_ceiling"]
    assert rec["acceptance"]["signatures_within_ceiling"]
    # generative sweep: both engines ran every rung, completed all
    # requests, and report the saturation verdicts
    gen = rec["generative"]["engines"]
    assert gen["slots_r8"]["replicas"] == 1
    assert gen["paged"]["replicas"] == 2     # dp2 on the virtual mesh
    for eng in ("slots_r8", "paged"):
        for s in gen[eng]["rates"].values():
            assert s["completed"] == 6 and s["rejected"] == 0
            assert s["total_ms"]["p50"] <= s["total_ms"]["p99"]
            assert s["ttft_ms"]["p99"] is not None
            # r12: TPOT percentiles + goodput-vs-SLO per rate rung
            assert s["tpot_ms"]["p99"] is not None
            assert 0.0 <= s["goodput_vs_slo"] <= 1.0
            assert s["slo_met"] <= s["completed"]
            assert s["tokens_per_s_per_chip"] > 0
            assert isinstance(s["sustained"], bool)
        assert gen[eng]["kv_cache"]["occupancy"] == 0
    assert gen["paged"]["decode_steps"] > 0
    for key in ("gen_queue_wait_p99_reduced_vs_r8",
                "gen_max_sustainable_rate_higher"):
        assert key in rec["acceptance"]
    # r12: the tracing on/off A/B ran and reports a bounded overhead
    ab = rec["tracing_ab"]
    assert ab["step_ms_off"] > 0 and ab["step_ms_on"] > 0
    assert len(ab["step_ms_off_all"]) == len(ab["step_ms_on_all"]) == 2
    assert isinstance(ab["overhead_frac"], float)
    assert "tracing_step_overhead_under_3pct" in rec["acceptance"]
    # r19: the spec × radix 2x2 sweep ran, stayed token-exact and
    # compile-clean, and the robust gates hold even at toy knobs (the
    # wall-clock prefill-ms ratio is asserted only at default scale)
    arms = rec["spec_radix"]
    assert set(arms) >= {"base", "base+radix", "spec", "spec+radix"}
    assert arms["token_equal_across_arms"] is True
    # r20: the capacity lanes ran — the A/B has both arms, the burst
    # lane reached a verdict, the paged sweep carries live λ/μ/ρ reads
    # and the agreement block names its measurement rung (the TRUTH of
    # the gates is asserted at default scale, committed in the r20
    # artifact — toy knobs only prove the lanes execute end to end)
    cab = rec["capacity_ab"]
    assert cab["step_ms_off"] > 0 and cab["step_ms_on"] > 0
    assert len(cab["step_ms_off_all"]) == len(cab["step_ms_on_all"]) == 2
    burst = rec["saturation_burst"]
    assert isinstance(burst["saturation_precedes_breach"], bool)
    assert burst["saturation_events"] >= 0
    for s in gen["paged"]["rates"].values():
        assert "capacity" in s and "predicted_max_rate_rps" in s["capacity"]
    agree = rec["capacity_agreement"]
    assert agree["measured_at_rate"] in agree["rate_grid"]
    for key in ("capacity_live_prediction_within_one_step",
                "saturation_precedes_queue_wait_breach",
                "capacity_overhead_under_1pct"):
        assert key in rec["acceptance"]
    for name in ("base", "base+radix", "spec", "spec+radix"):
        arm = arms[name]
        assert arm["requests"] == 3
        assert arm["compile_sig_delta"] == 0
        assert arm["retrace_violations"] == 0
        # at drain only radix-cache-held blocks may remain live
        expect_blocks = (arm["radix"]["cached_tokens"] // 16
                         if "radix" in arm else 0)
        assert arm["kv_cache"]["blocks_in_use"] == expect_blocks
    assert arms["spec"]["target_forwards_per_token"] < 0.5
    assert arms["spec"]["accept_rate"] >= 0.7
    assert arms["base"]["prefilled_tokens"] >= \
        2 * arms["base+radix"]["prefilled_tokens"]
    assert arms["base+radix"]["prefix_hit_tokens"] > 0
    for key in ("spec_radix_token_equal",
                "spec_forwards_per_token_under_half",
                "radix_prefilled_tokens_reduced_2x",
                "spec_radix_compile_once"):
        assert rec["acceptance"][key], key


def test_sharded_step_bench_emits_artifact(tmp_path):
    """benchmark/sharded_step.py on the 8-device CPU mesh must emit the
    SHARDED_STEP artifact with both models x both meshes, zero
    steady-state compile misses, and the per-device-peak win for dp×tp —
    the round-9 evidence that partition_rules buys memory, not just
    placement metadata."""
    out = tmp_path / "sharded_step.json"
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="3", BENCH_WARMUP="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               MXT_SHARDED_STEP_OUT=str(out))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "sharded_step.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "sharded_step_per_device_peak_ratio"
    assert 0 < rec["value"] < 1
    for model in ("mlp", "llama_tiny"):
        pair = rec["lanes"][model]
        for lane in pair.values():
            assert lane["compile_miss_steady"] == 0
            assert lane["compile_miss_warmup"] > 0
            assert len(lane["peak_live_bytes_by_device"]) == 8
        assert pair["dp4xtp2"]["placement"]["sharded_params"] > 0
        assert pair["dp8"]["placement"]["sharded_params"] == 0
        assert pair["dp4xtp2"]["per_device_peak_max"] < \
            pair["dp8"]["per_device_peak_max"]
        assert all(rec["acceptance"][model].values())


@pytest.mark.slow
def test_dispatch_bench_retrace_sanitized_lane(tmp_path):
    """benchmark/dispatch_overhead.py under MXNET_SANITIZE_RETRACE=raise:
    every compile site is observed, each mode declares warmup over
    before its timed window, and the run completes — i.e. zero
    post-warmup retraces anywhere on the dispatch paths, enforced by the
    runtime sanitizer on top of the shared compile gates."""
    out = tmp_path / "dispatch.json"
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", BENCH_CHAIN_ITERS="2",
               BENCH_MLP_ITERS="2", BENCH_REPEATS="1",
               MXNET_SANITIZE_RETRACE="raise",
               BENCH_DISPATCH_OUT=str(out))
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "dispatch_overhead.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert "RetraceError" not in r.stderr, r.stderr[-2000:]
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    # the shared gate ran on every mode: caches report zero steady misses
    assert rec["segment_cache"]["miss"] > 0       # warmup compiles exist
    assert rec["chain64_usec_per_op"]["hybridized"] > 0


def test_race_harness_report_is_green():
    """python -m tools.race --report: the deterministic-interleaving
    harness's self-check — every built-in scenario replays
    bit-identically from its seed, the seeded deadlock is witnessed,
    and the runtime lock-order graph stays clean."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "tools.race", "--report"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    report = json.loads(r.stdout)
    assert report["ok"]
    by_name = {sc["name"]: sc for sc in report["scenarios"]}
    assert by_name["points"]["replay_identical"]
    assert by_name["points"]["seed_changes_schedule"]
    assert by_name["locks"]["replay_identical"]
    assert by_name["locks"]["order_violations"] == []
    assert by_name["deadlock"]["witnessed_at_seed"] is not None
    assert by_name["deadlock"]["replay_identical"]


def test_fleet_overhead_bench_emits_artifact(tmp_path):
    """benchmark/sharded_step.py --fleet-overhead must emit the
    FLEET_OVERHEAD artifact: the off/stride16/stride1 A/B lanes, the
    per-step hook microbench, the stride-1 exchange cost, and a passing
    <1% acceptance — the round-13 evidence that fleet observability is
    free at the default stride."""
    out = tmp_path / "fleet_overhead.json"
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="3", BENCH_WARMUP="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               MXT_FLEET_OVERHEAD_OUT=str(out))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "sharded_step.py"),
         "--fleet-overhead"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "fleet_overhead_pct_stride16"
    assert 0 <= rec["value"] < 1.0
    assert set(rec["lanes"]) == {"off", "stride16", "stride1"}
    for lane in rec["lanes"].values():
        assert lane["step_ms_median"] > 0
    assert rec["lanes"]["off"]["fleet_exchanges"] == 0
    # stride 1 exchanges every measured step and reports its cost
    assert rec["lanes"]["stride1"]["fleet_exchanges"] >= 3
    assert rec["exchange_ms_stride1"] is not None
    assert rec["hook_ms_stride16"] > 0
    assert rec["hook_ms_stride1"] >= rec["hook_ms_stride16"] * 0.5
    assert rec["acceptance"]["fleet_overhead_under_1pct"]


def test_numerics_overhead_bench_emits_artifact(tmp_path):
    """benchmark/sharded_step.py --numerics-overhead must emit the
    NUMERICS_OVERHEAD artifact: the off / stats / stats+capture-armed
    A/B lanes over llama_tiny (the tapped model), the per-step
    record_compiled+step_summary microbench, and a passing <1%
    acceptance at stride 16 — the round-17 evidence that in-compile
    tensor stats are free at the default stride."""
    out = tmp_path / "numerics_overhead.json"
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="3", BENCH_WARMUP="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               MXT_NUMERICS_OVERHEAD_OUT=str(out))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "sharded_step.py"),
         "--numerics-overhead"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "numerics_overhead_pct_stride16"
    assert 0 <= rec["value"] < 1.0
    assert set(rec["lanes"]) == {"off", "stats", "stats_capture_armed"}
    for lane in rec["lanes"].values():
        assert lane["step_ms_median"] > 0
    # the off lane must not harvest anything; the stats lanes must
    # actually land per-path stat bundles (taps + grad/update stats)
    assert rec["lanes"]["off"]["harvested_paths"] == 0
    assert rec["lanes"]["stats"]["harvested_paths"] > 0
    assert rec["lanes"]["stats_capture_armed"]["harvested_paths"] > 0
    assert rec["lanes"]["stats_capture_armed"]["capture_armed"]
    assert rec["hook_ms_stride16"] > 0
    # stride 1 materializes every step; stride 16 must not cost more
    assert rec["hook_ms_stride1"] >= rec["hook_ms_stride16"] * 0.5
    assert rec["acceptance"]["numerics_overhead_under_1pct"]


def test_data_plane_bench_emits_artifact(tmp_path):
    """benchmark/input_pipeline.py --data-plane on the 8-device CPU mesh
    must emit the DATA_PLANE artifact with both trainer-fed lanes (image
    + packed LLM), steady-state data_wait_ms p50 ~ 0 (prefetch overlap
    holds), >= 85% packing efficiency, and zero steady compile misses
    (ONE (B, T) signature over a mixed-length corpus) — the round-14
    evidence the streaming data plane keeps a stock Trainer fed."""
    out = tmp_path / "data_plane.json"
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="3", BENCH_WARMUP="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               MXT_DATA_PLANE_OUT=str(out))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark",
                                      "input_pipeline.py"),
         "--data-plane"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "data_plane_data_wait_ms_p50"
    assert set(rec["lanes"]) == {"image", "packed_llm"}
    for lane in rec["lanes"].values():
        assert lane["compile_miss_steady"] == 0
        assert lane["compile_miss_warmup"] > 0
        assert lane["data_wait_ms_p50"] <= lane["data_wait_ms_p99"]
        assert lane["step_ms_median"] > 0
    assert rec["lanes"]["image"]["images_per_sec"] > 0
    pk = rec["lanes"]["packed_llm"]
    assert pk["packed_tokens_per_sec"] > 0
    assert pk["packing"]["efficiency"] >= 0.85
    assert pk["packing"]["docs_packed"] > 0
    assert all(rec["acceptance"].values()), rec["acceptance"]


def test_remat_ab_bench_emits_artifact(tmp_path):
    """benchmark/remat_ab.py at toy step counts must emit the REMAT_AB
    artifact with every tier lane for both models, bit-identical loss
    trajectories, zero steady-state compile misses, and an auto lane
    that resolved to a concrete tier — the round-10 evidence that the
    remat policy engine recomputes without renumbering."""
    out = tmp_path / "remat_ab.json"
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="3", BENCH_WARMUP="1",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               MXT_REMAT_AB_OUT=str(out))
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark", "remat_ab.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["metric"] == "remat_auto_vs_layer_step_ratio"
    assert rec["value"] > 0
    for model in ("mlp", "llama_tiny"):
        by_tier = rec["lanes"][model]
        assert set(by_tier) == {"none", "dots", "layer", "auto"}
        ref = by_tier["layer"]["loss_trajectory"]
        for lane in by_tier.values():
            assert lane["compile_miss_steady"] == 0
            assert lane["compile_miss_warmup"] > 0
            assert lane["loss_trajectory"] == ref
        # per-layer checkpointing saves strictly fewer residuals to the
        # backward than saving everything
        assert by_tier["layer"]["bwd_residual_bytes_max"] < \
            by_tier["none"]["bwd_residual_bytes_max"]
        auto = by_tier["auto"]
        assert auto["resolved_tier"] in ("none", "dots", "layer")
        assert auto["policy_mode"] == "auto"
        assert auto["remat_policy_jsonl_field"] == auto["resolved_tier"]
        assert all(rec["acceptance"][model].values())


def test_telemetry_disabled_step_overhead():
    """Telemetry instrumentation rides the trainer/CachedOp/kvstore hot
    path; disabled it must be within noise of the seed path.  Compare
    the shipped (instrumented, telemetry off) step loop against the same
    loop with every recorder stubbed to a bare no-op — best-of-repeats
    to shed scheduler noise; the generous ratio bound catches a lock or
    allocation sneaking onto the disabled path, not microsecond drift."""
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd, telemetry

    telemetry.disable()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(8, 6).astype(np.float32))
    y = nd.array(rng.randint(0, 4, (8,)))

    def steps(n):
        for _ in range(n):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(8)
        loss.wait_to_read()

    def best_of(repeats, n):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            steps(n)
            best = min(best, time.perf_counter() - t0)
        return best

    steps(3)  # pay trace+compile before any timing
    instrumented = best_of(3, 20)

    class _Null:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    null = _Null()
    noop = lambda *a, **k: None  # noqa: E731
    saved = {name: getattr(telemetry, name)
             for name in ("span", "count", "gauge", "is_enabled")}
    try:
        telemetry.span = lambda *a, **k: null
        telemetry.count = noop
        telemetry.gauge = noop
        telemetry.is_enabled = lambda: False
        steps(3)
        stubbed = best_of(3, 20)
    finally:
        for name, fn in saved.items():
            setattr(telemetry, name, fn)

    assert instrumented < stubbed * 3 + 0.01, (instrumented, stubbed)


@pytest.mark.slow
def test_graft_entry_compiles():
    """entry() returns (fn, args) that jit-lowers (what the driver
    compile-checks single-chip)."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=2'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "jax.jit(fn).lower(*args)\n"
        "print('ENTRY_OK')\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, env=env,
                       timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ENTRY_OK" in r.stdout


# --- perf gate: the regression ledger over committed artifacts ---------------

PERF_GATE = os.path.join(REPO, "tools", "perf_gate.py")


def _gate(*args):
    return subprocess.run([sys.executable, PERF_GATE, *args],
                          capture_output=True, text=True, timeout=120)


def test_perf_gate_committed_artifacts_pass():
    """Every family's latest committed FAMILY_rNN.json must clear the
    committed benchmark/PERF_BASELINE.json manifest — the ledger's
    standing acceptance claim."""
    r = _gate("--check-all")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "perf_gate: clean" in r.stdout


def test_perf_gate_trend_reports_every_family():
    r = _gate("--trend", "--json")
    assert r.returncode == 0, r.stderr
    entries = json.loads(r.stdout)
    fams = {e["family"] for e in entries}
    assert {"SERVING_LATENCY", "FLEET_OVERHEAD", "BENCH"} <= fams
    sl = next(e for e in entries if e["family"] == "SERVING_LATENCY")
    assert sl["direction"] == "lower"
    assert [rnd for rnd, _ in sl["rounds"]] == sorted(
        rnd for rnd, _ in sl["rounds"])


def test_perf_gate_fails_injected_regression(tmp_path):
    """Toy corpus: a 2x latency regression (and separately a flipped
    acceptance flag) must fail the gate; an in-noise wobble passes."""
    base = {"metric": "toy_latency_ms", "value": 10.0, "unit": "ms",
            "acceptance": {"compile_once": True}}
    (tmp_path / "TOY_LATENCY_r01.json").write_text(json.dumps(base))
    manifest = str(tmp_path / "PERF_BASELINE.json")
    r = _gate("--update-baseline", "--root", str(tmp_path),
              "--baseline", manifest)
    assert r.returncode == 0, r.stdout + r.stderr

    # in-noise wobble (+10% on a 25% band): passes
    ok = dict(base, value=11.0)
    p_ok = tmp_path / "TOY_LATENCY_r02.json"
    p_ok.write_text(json.dumps(ok))
    r = _gate("--check", str(p_ok), "--baseline", manifest)
    assert r.returncode == 0, r.stdout + r.stderr

    # 2x latency: fails on the metric gate
    slow = dict(base, value=20.0)
    p_slow = tmp_path / "TOY_LATENCY_r03.json"
    p_slow.write_text(json.dumps(slow))
    r = _gate("--check", str(p_slow), "--baseline", manifest)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout and "toy_latency_ms" in r.stdout

    # lost acceptance flag: fails even with the metric flat
    lost = dict(base, acceptance={"compile_once": False})
    p_lost = tmp_path / "TOY_LATENCY_r04.json"
    p_lost.write_text(json.dumps(lost))
    r = _gate("--check", str(p_lost), "--baseline", manifest)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "compile_once" in r.stdout

    # min-of-repeats: a noisy repeat list whose BEST value is in-band
    # passes (the gate compares best-of, not worst-of)
    noisy = dict(base, value=30.0, value_all=[30.0, 10.4, 14.0])
    p_noisy = tmp_path / "TOY_LATENCY_r05.json"
    p_noisy.write_text(json.dumps(noisy))
    r = _gate("--check", str(p_noisy), "--baseline", manifest)
    assert r.returncode == 0, r.stdout + r.stderr
