"""Driver-entry-point guards: bench.py must print ONE parseable JSON
line with the tracked keys, and __graft_entry__.entry() must return a
jittable fn — a silent break in either loses the round's numbers (the
driver runs them unattended on the chip)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.slow
def test_bench_py_emits_one_json_line():
    env = dict(os.environ)
    env.update(BENCH_PLATFORM="cpu", BENCH_STEPS="2", BENCH_WARMUP="1",
               BENCH_REPEATS="1", BENCH_BATCH="2", BENCH_IMAGE="64",
               BENCH_BERT_BATCH="2", BENCH_SEQ="16",
               BENCH_DATA_STEPS="2")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=1500)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "resnet50_v1_train_images_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["vs_baseline"] is None
    assert "bert_base_samples_per_sec_per_chip" in rec, rec
    assert "resnet50_v1_recordio_images_per_sec_per_chip" in rec, rec


@pytest.mark.slow
def test_graft_entry_compiles():
    """entry() returns (fn, args) that jit-lowers (what the driver
    compile-checks single-chip)."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['XLA_FLAGS'] = "
        "'--xla_force_host_platform_device_count=2'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "jax.jit(fn).lower(*args)\n"
        "print('ENTRY_OK')\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, env=env,
                       timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "ENTRY_OK" in r.stdout
