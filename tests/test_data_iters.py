"""BucketSentenceIter + ImageDetIter tests (reference model:
tests/python/unittest/test_io.py + test_image.py detection cases,
SURVEY §2.5)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_bucket_sentence_iter_shapes_and_buckets():
    rng = onp.random.RandomState(0)
    sentences = [list(rng.randint(1, 50, rng.randint(3, 30)))
                 for _ in range(64)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[8, 16, 32])
    seen_keys = set()
    n = 0
    for batch in it:
        t = batch.bucket_key
        seen_keys.add(t)
        assert batch.data[0].shape == (8, t)
        assert batch.label[0].shape == (8, t)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        # label is data shifted left by one
        onp.testing.assert_array_equal(l[:, :-1], d[:, 1:])
        n += 1
    assert n >= 2 and len(seen_keys) >= 2
    it.reset()
    assert sum(1 for _ in it) == n


def test_bucket_sentence_iter_discards_too_long():
    sentences = [[1] * 4, [1] * 100]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=1, buckets=[8])
    batches = list(it)
    assert len(batches) == 1
    assert batches[0].data[0].shape == (1, 8)


def test_bucket_iter_feeds_bucketing_module():
    """End-to-end: BucketSentenceIter + BucketingModule (reference
    example/rnn bucketing pattern)."""
    import mxnet_tpu.symbol as sym

    rng = onp.random.RandomState(0)
    sentences = [list(rng.randint(1, 20, rng.randint(3, 15)))
                 for _ in range(32)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[8, 16])

    def gen(bucket_key):
        data = sym.var("data")
        emb = sym.Embedding(data, input_dim=20, output_dim=8, name="embed")
        fc = sym.FullyConnected(emb, num_hidden=20, flatten=False,
                                name="fc")
        out = sym.reshape(fc, shape=(-1, 20), name="r")
        label = sym.var("softmax_label")
        lab = sym.reshape(label, shape=(-1,), name="rl")
        loss = sym.SoftmaxOutput(out, lab, name="softmax")
        return loss, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(gen, default_bucket_key=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for batch in it:
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    out = mod.get_outputs()[0]
    assert out.shape[-1] == 20


def _det_imglist(n=6):
    rng = onp.random.RandomState(0)
    out = []
    for i in range(n):
        img = rng.randint(0, 255, (20, 24, 3)).astype(onp.uint8)
        boxes = onp.array([[i % 3, 0.2, 0.3, 0.6, 0.8]], onp.float32)
        out.append((img, boxes))
    return out


def test_image_det_iter_batches():
    it = mx.image.ImageDetIter(batch_size=4, data_shape=(3, 16, 16),
                               imglist=_det_imglist(), aug_list=[])
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 16, 16)
    assert batch.label[0].shape == (4, 1, 5)
    lab = batch.label[0].asnumpy()
    assert (lab[:, 0, 0] >= 0).all()


def test_det_flip_aug_flips_boxes():
    from mxnet_tpu.image.detection import DetHorizontalFlipAug

    rng = onp.random.RandomState(0)
    img = onp.arange(2 * 4 * 3).reshape(2, 4, 3).astype(onp.float32)
    boxes = onp.array([[0, 0.1, 0.2, 0.4, 0.9]], onp.float32)
    aug = DetHorizontalFlipAug(p=1.1)  # always flip
    img2, boxes2 = aug(img, boxes, rng)
    onp.testing.assert_array_equal(img2, img[:, ::-1, :])
    onp.testing.assert_allclose(boxes2[0, 1], 1 - 0.4, rtol=1e-6)
    onp.testing.assert_allclose(boxes2[0, 3], 1 - 0.1, rtol=1e-6)
    assert boxes2[0, 2] == 0.2 and boxes2[0, 4] == 0.9


def test_det_crop_aug_clips_and_keeps_centers():
    from mxnet_tpu.image.detection import DetRandomCropAug

    rng = onp.random.RandomState(1)
    img = onp.zeros((40, 40, 3), onp.float32)
    boxes = onp.array([[1, 0.4, 0.4, 0.6, 0.6]], onp.float32)
    aug = DetRandomCropAug(min_crop=0.7)
    img2, boxes2 = aug(img, boxes, rng)
    assert img2.shape[0] <= 40 and img2.shape[1] <= 40
    if len(boxes2):
        assert ((boxes2[:, 1:] >= 0) & (boxes2[:, 1:] <= 1)).all()


def test_image_det_iter_to_multibox_target():
    """Pipeline contract: ImageDetIter labels feed MultiBoxTarget."""
    it = mx.image.ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                               imglist=_det_imglist(4), aug_list=[
                                   mx.image.DetResizeAug(32)])
    batch = next(iter(it))
    anchors = nd.contrib.MultiBoxPrior(batch.data[0], sizes=[0.5],
                                       ratios=[1, 2])
    cls_preds = nd.zeros((2, 4, anchors.shape[1]))
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        anchors, batch.label[0], cls_preds)
    assert loc_t.shape == (2, anchors.shape[1] * 4)
    assert (cls_t.asnumpy() >= 0).all()
