"""AMP tests (reference: tests/python/gpu/test_contrib_amp.py:? — cast-list
behaviour, loss scaling, converted-model inference)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon, nd
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _amp_off():
    yield
    amp.turn_off()


def test_amp_init_casts_matmul_ops():
    amp.init("bfloat16")
    a = nd.ones((4, 8))
    w = nd.ones((3, 8))
    out = nd.fully_connected(a, w, no_bias=True, num_hidden=3)
    assert out.dtype.name == "bfloat16"
    # fp32-pinned op keeps fp32
    s = nd.softmax(nd.ones((2, 3)))
    assert s.dtype == np.float32


def test_amp_training_step_bf16():
    amp.init("bfloat16")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.random.uniform(shape=(8, 8))
    y = nd.array(np.arange(8) % 4)
    losses = []
    for _ in range(10):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0]


def test_amp_softmax_ce_not_precast():
    """softmax_cross_entropy left OUT of FP32_OPS: under AMP the bf16
    logits enter the op uncast (its body computes in f32 internally)
    and the cotangent comes back bf16 — pre-casting a (rows, vocab)
    logits tensor to f32 cost BERT ~6 GB/step (PERF_NOTES r5 cont. 6)."""
    assert "softmax_cross_entropy" not in amp.FP32_OPS
    amp.init("bfloat16")
    x = mx.random.uniform(shape=(4, 7)).astype("bfloat16")
    y = nd.array(np.array([1, 2, 0, 6]))
    x.attach_grad()
    with autograd.record():
        loss = nd.softmax_cross_entropy(x, y)
    loss.backward()
    assert loss.dtype == np.float32  # f32 internal accumulation
    assert x.grad.dtype.name == "bfloat16"
    assert np.isfinite(float(loss.asscalar()))


def test_loss_scaler_dynamics():
    s = amp.LossScaler(init_scale=16, scale_factor=2, scale_window=2)
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 32
    s.update_scale(True)
    assert s.loss_scale == 16


def test_scale_loss_context():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    x = nd.ones((2, 3))
    with autograd.record():
        loss = net(x).sum()
        with amp.scale_loss(loss, trainer) as scaled:
            pass
    assert float(scaled.asscalar()) == pytest.approx(
        float(loss.asscalar()) * trainer._amp_loss_scaler.loss_scale)
    overflow = amp.unscale(trainer)
    assert overflow is False


def test_convert_hybrid_block():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    amp.convert_hybrid_block(net)
    assert net.weight.data().dtype.name == "bfloat16"
    out = net(nd.ones((2, 3)).astype("bfloat16"))
    assert out.dtype.name == "bfloat16"


def test_multi_precision_with_bf16_params():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.cast("bfloat16")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "multi_precision": True})
    with autograd.record():
        loss = net(nd.ones((2, 3)).astype("bfloat16")).sum()
    loss.backward()
    trainer.step(2)
    # master weight is fp32
    master, _ = trainer._states[0]
    assert master.dtype == np.float32
    assert net.weight.data().dtype.name == "bfloat16"
