"""Deferred imperative dispatch (engine op bulking) tests.

The contract under test (docs/engine.md): with bulking on, ``apply_op``
appends to a thread-local pending segment that flushes as ONE
jit-compiled callable; every flush trigger (size, host sync, record
boundary, CachedOp/kvstore dispatch, explicit) resolves pending handles;
each op's result is bit-identical to its eager dispatch; the segment
cache replays compiled segments; NaiveEngine and the disabled path
bypass deferral entirely.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import engine, gluon, nd, telemetry
from mxnet_tpu.engine import _PendingArray


@pytest.fixture(autouse=True)
def _clean_engine():
    engine.flush()
    engine.clear_segment_cache()
    prev = engine.set_bulk_size(15)
    yield
    engine.flush()
    engine.set_bulk_size(prev)


def _pending(a):
    return a._raw.__class__ is _PendingArray


def _arr(shape=(3, 4), seed=0, positive=False):
    data = np.random.RandomState(seed).randn(*shape).astype(np.float32)
    if positive:
        data = np.abs(data) + 0.5
    return nd.array(data)


# --- numerical identity across the op sweep ---------------------------------

SWEEP = [
    ("add", lambda a, b: a + b),
    ("add_scalar", lambda a, b: a + 1.25),
    ("radd_scalar", lambda a, b: 1.25 + a),
    ("sub", lambda a, b: a - b),
    ("rsub_scalar", lambda a, b: 2.5 - a),
    ("mul", lambda a, b: a * b),
    ("mul_scalar", lambda a, b: a * 1.001),
    ("div", lambda a, b: a / (b + 3.0)),
    ("div_scalar", lambda a, b: a / 1.002),
    ("rdiv_scalar", lambda a, b: 1.7 / (a + 3.0)),
    ("pow_scalar", lambda a, b: (a + 3.0) ** 1.5),
    ("neg", lambda a, b: -a),
    ("exp", lambda a, b: nd.exp(a)),
    ("log", lambda a, b: nd.log(a + 3.0)),
    ("sqrt", lambda a, b: nd.sqrt(a + 3.0)),
    ("rsqrt", lambda a, b: nd.rsqrt(a + 3.0)),
    ("tanh", lambda a, b: nd.tanh(a)),
    ("sigmoid", lambda a, b: nd.sigmoid(a)),
    ("relu", lambda a, b: nd.relu(a)),
    ("abs", lambda a, b: nd.abs(a)),
    ("square", lambda a, b: nd.square(a)),
    ("floor", lambda a, b: nd.floor(a)),
    ("sign", lambda a, b: nd.sign(a)),
    ("maximum", lambda a, b: nd.maximum(a, b)),
    ("minimum", lambda a, b: nd.minimum(a, b)),
    ("clip", lambda a, b: nd.clip(a, -0.5, 0.5)),
    ("sum", lambda a, b: nd.sum(a)),
    ("sum_axis", lambda a, b: nd.sum(a, axis=1)),
    ("mean", lambda a, b: nd.mean(a, axis=0)),
    ("max", lambda a, b: nd.max(a, axis=1)),
    ("dot", lambda a, b: nd.dot(a, b.T)),
    ("reshape", lambda a, b: a.reshape((4, 3))),
    ("transpose", lambda a, b: nd.transpose(a)),
    ("softmax", lambda a, b: nd.softmax(a, axis=-1)),
    ("norm", lambda a, b: nd.norm(a)),
]


@pytest.mark.parametrize("name,fn", SWEEP, ids=[n for n, _ in SWEEP])
def test_bulked_bit_identical_to_eager(name, fn):
    a, b = _arr(seed=1), _arr(seed=2)
    ref = fn(a, b).asnumpy()
    with engine.bulk(8):
        got = fn(a, b).asnumpy()
    assert np.array_equal(ref, got), f"{name}: bulked != eager"
    assert ref.dtype == got.dtype


def test_chained_segment_matches_eager():
    a, b = _arr(seed=3), _arr(seed=4)
    ref = nd.tanh(nd.relu(a * b) + a).sum(axis=0).asnumpy()
    with engine.bulk(16):
        out = nd.tanh(nd.relu(a * b) + a).sum(axis=0)
        assert _pending(out)
        got = out.asnumpy()
    np.testing.assert_allclose(ref, got, rtol=0, atol=0)


def test_scalar_attr_change_replays_cached_segment():
    # float attrs are runtime args: new value, same compiled segment
    a = _arr(seed=5)
    engine.clear_segment_cache()
    with engine.bulk(8):
        r1 = (a * 2.5 + 0.1).asnumpy()
    with engine.bulk(8):
        r2 = (a * 3.5 + 0.7).asnumpy()
    stats = engine.segment_cache_stats()
    assert stats["miss"] == 1 and stats["hit"] == 1
    # mul+add fused in ONE segment may fma-contract (docs/engine.md):
    # values match eager to the last ulp, not necessarily bitwise
    np.testing.assert_allclose(r1, ((a * 2.5) + 0.1).asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(r2, ((a * 3.5) + 0.7).asnumpy(), rtol=1e-6)


# --- flush triggers ---------------------------------------------------------

def test_flush_on_asnumpy():
    a = _arr()
    with engine.bulk(8):
        c = a + 1.0
        assert _pending(c) and engine.pending_ops() == 1
        c.asnumpy()
        assert engine.pending_ops() == 0
        assert not _pending(c)


def test_flush_on_wait_to_read():
    a = _arr()
    with engine.bulk(8):
        c = a * 2.0
        assert _pending(c)
        c.wait_to_read()
        assert engine.pending_ops() == 0


def test_flush_on_item():
    a = nd.array(np.float32([[41.0]]))
    with engine.bulk(8):
        c = a + 1.0
        assert _pending(c)
        assert c.item() == 42.0
        assert engine.pending_ops() == 0


def test_flush_on_getitem():
    a = _arr()
    with engine.bulk(8):
        c = a + 1.0
        assert _pending(c)
        seg = c._raw._segment
        row = c[0]
        # the producing segment flushed (the slicing op itself may be
        # deferred into a fresh segment — it is just another op)
        assert seg.results is not None
        np.testing.assert_array_equal(row.asnumpy(), (a + 1.0).asnumpy()[0])


def test_flush_on_bulk_size():
    a = _arr()
    with engine.bulk(3):
        c = a + 1.0
        c = c * 2.0
        assert engine.pending_ops() == 2
        c = c - 3.0  # third op hits the budget: segment flushes
        assert engine.pending_ops() == 0
        # async tier: a size flush SUBMITS the segment (result is still a
        # placeholder until materialized); sync mode executes it inline
        if engine.async_enabled():
            assert c._raw._segment.submitted
        else:
            assert not _pending(c)
    np.testing.assert_array_equal(
        c.asnumpy(), ((a + 1.0) * 2.0 - 3.0).asnumpy())


def test_flush_on_bulk_size_sync_mode():
    prev = engine.set_async_enabled(False)
    try:
        a = _arr()
        with engine.bulk(3):
            c = a + 1.0
            c = c * 2.0
            c = c - 3.0  # third op hits the budget: executes inline
            assert engine.pending_ops() == 0
            assert not _pending(c)
        np.testing.assert_array_equal(
            c.asnumpy(), ((a + 1.0) * 2.0 - 3.0).asnumpy())
    finally:
        engine.set_async_enabled(prev)


def test_flush_on_record_boundary_and_grads_match():
    a = _arr()
    w = nd.array(np.ones((3, 4), np.float32))
    w.attach_grad()
    # eager reference gradient
    with ag.record():
        (w * (a + 1.0)).sum().backward()
    ref_grad = w.grad.asnumpy()

    w2 = nd.array(np.ones((3, 4), np.float32))
    w2.attach_grad()
    with engine.bulk(16):
        pre = a + 1.0
        assert _pending(pre)
        with ag.record():
            # entering record flushed the pending segment; the handle
            # resolves to the computed buffer on its next read
            assert engine.pending_ops() == 0
            assert pre._raw._segment.results is not None
            loss = (w2 * pre).sum()
            # recording dispatches eagerly: nothing re-enters the segment
            assert engine.pending_ops() == 0
        loss.backward()
    np.testing.assert_array_equal(ref_grad, w2.grad.asnumpy())


def test_pause_does_not_flush():
    a = _arr()
    with engine.bulk(8):
        c = a + 1.0
        with ag.pause():
            assert engine.pending_ops() == 1
        assert _pending(c)


def test_explicit_flush_returns_count():
    a = _arr()
    with engine.bulk(8):
        _ = a + 1.0
        _ = a * 2.0
        assert engine.flush() == 2
        assert engine.flush() == 0


def test_flush_on_cachedop_dispatch():
    net = gluon.nn.Dense(4)
    net.initialize()
    x = _arr((2, 3))
    net(x)  # shape-resolve eagerly
    net.hybridize()
    net(x)
    with engine.bulk(8):
        y = x + 1.0
        assert _pending(y)
        net(x)  # CachedOp dispatch is a flush boundary
        assert engine.pending_ops() == 0


def test_flush_on_kvstore_dispatch():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((3, 4)))
    g = _arr(seed=7)
    with engine.bulk(8):
        scaled = g * 0.5
        assert _pending(scaled)
        kv.push("w", scaled)
        assert engine.pending_ops() == 0
    out = nd.zeros((3, 4))
    kv.pull("w", out=out)
    np.testing.assert_array_equal(out.asnumpy(), (g * 0.5).asnumpy())


# --- sanitizer through a deferred segment -----------------------------------

def test_sanitizer_stale_read_through_deferred_segment():
    from mxnet_tpu import sanitizer

    sanitizer.enable()
    try:
        a = _arr()
        raw = a._data
        sanitizer.donate([raw], "test_donating_site")
        with engine.bulk(8):
            c = a + 1.0  # consumes the donated buffer
            assert _pending(c)
            with pytest.raises(sanitizer.DonatedBufferError,
                               match="test_donating_site"):
                engine.flush()
    finally:
        sanitizer.reset()
        sanitizer.disable()
        engine._TLS.segment = None


# --- bypasses ---------------------------------------------------------------

def test_naive_engine_bypasses_bulking():
    prev = engine.engine_type()
    engine.set_engine_type("NaiveEngine")
    try:
        a = _arr()
        with engine.bulk(8):
            c = a + 1.0
            assert not _pending(c)
            assert engine.pending_ops() == 0
    finally:
        engine.set_engine_type(prev)


def test_bulk_size_one_disables_deferral():
    a = _arr()
    with engine.bulk(1):
        c = a + 1.0
        assert not _pending(c)


def test_disabled_path_never_reaches_maybe_defer(monkeypatch):
    # the off path must be ONE boolean test in apply_op: poison
    # maybe_defer and prove it is not consulted
    assert not engine._bulk_on

    def boom(*a, **k):  # pragma: no cover - must not run
        raise AssertionError("maybe_defer called with bulking off")

    monkeypatch.setattr(engine, "maybe_defer", boom)
    a = _arr()
    np.testing.assert_array_equal(
        (a + 1.0).asnumpy(), a.asnumpy() + 1.0)


def test_recording_forces_eager_inside_bulk():
    a = _arr()
    with engine.bulk(8):
        with ag.record():
            c = a + 1.0
            assert not _pending(c)


# --- cache accounting -------------------------------------------------------

def test_segment_cache_hit_miss_accounting():
    a = _arr(seed=8)
    engine.clear_segment_cache()
    with engine.bulk(8):
        (nd.tanh(a) + a).asnumpy()
    s1 = engine.segment_cache_stats()
    assert (s1["miss"], s1["hit"], s1["size"]) == (1, 0, 1)
    with engine.bulk(8):
        (nd.tanh(a) + a).asnumpy()
    s2 = engine.segment_cache_stats()
    assert (s2["miss"], s2["hit"]) == (1, 1)
    # different shape -> different signature -> miss
    b = _arr((5, 2), seed=9)
    with engine.bulk(8):
        (nd.tanh(b) + b).asnumpy()
    s3 = engine.segment_cache_stats()
    assert s3["miss"] == 2 and s3["size"] == 2


def test_cross_segment_pending_input_materializes():
    a = _arr(seed=10)
    with engine.bulk(2):
        c = a + 1.0           # segment 1, pending
        d = c * 2.0           # hits budget: segment 1 executes
        e = d - 0.5           # segment 2, consumes executed result
        assert _pending(e)
        got = e.asnumpy()
    np.testing.assert_array_equal(
        got, ((a + 1.0) * 2.0 - 0.5).asnumpy())


# --- env vars + telemetry ---------------------------------------------------

def _run_py(code, **env):
    full = dict(os.environ, JAX_PLATFORMS="cpu", **env)
    return subprocess.run([sys.executable, "-c", code], env=full,
                          capture_output=True, text=True)


def test_env_bulk_size_honoured_at_startup():
    r = _run_py(
        "from mxnet_tpu import engine;"
        "assert engine._bulk_size == 7, engine._bulk_size;"
        "assert engine.bulk_size() == 7",
        MXNET_ENGINE_BULK_SIZE="7")
    assert r.returncode == 0, r.stderr


def test_env_bulk_size_train_infer_variants():
    r = _run_py(
        "from mxnet_tpu import engine, autograd as ag;"
        "assert engine.bulk_size() == 5;"   # infer mode by default
        "ag.set_training(True);"
        "assert engine.bulk_size() == 9",
        MXNET_ENGINE_BULK_SIZE_IN_TRAIN="9",
        MXNET_ENGINE_BULK_SIZE_IN_INFER="5")
    assert r.returncode == 0, r.stderr


def test_env_bulk_enable_flag():
    r = _run_py(
        "import numpy as np;"
        "from mxnet_tpu import engine, nd;"
        "assert engine.bulk_enabled() and engine._bulk_on;"
        "a = nd.array(np.ones((2, 2), np.float32));"
        "c = a + 1.0;"
        "from mxnet_tpu.engine import _PendingArray;"
        "assert c._raw.__class__ is _PendingArray;"
        "assert (c.asnumpy() == 2).all()",
        MXT_ENGINE_BULK="1")
    assert r.returncode == 0, r.stderr


def test_telemetry_flush_reasons_and_step_record():
    telemetry.enable()
    try:
        a = _arr(seed=11)
        telemetry.step_begin()
        with engine.bulk(8):
            (a + 1.0).asnumpy()          # host_sync flush
            _ = a * 2.0
            engine.flush()               # explicit flush
        rec = telemetry.step_end()
        sc = rec["counters"]
        assert rec["bulk_flush"] == sc["engine.bulk_flush"] >= 2
        assert sc["engine.bulk_flush.host_sync"] >= 1
        assert sc["engine.bulk_flush.explicit"] >= 1
        assert sc["engine.bulk_compile"] >= 1
        assert rec["gauges"]["engine.bulk_segment_ops"] >= 1
        # segment compiles count into the step's compile_count
        assert rec["compile_count"] >= sc["engine.bulk_compile"]
    finally:
        telemetry.disable()


def test_telemetry_size_and_record_reasons():
    telemetry.enable()
    try:
        a = _arr(seed=12)
        telemetry.step_begin()
        with engine.bulk(2):
            c = a + 1.0
            c = c * 2.0                  # size flush
            _ = a - 1.0
            with ag.record():            # record flush
                pass
        rec = telemetry.step_end()
        sc = rec["counters"]
        assert sc["engine.bulk_flush.size"] >= 1
        assert sc["engine.bulk_flush.record"] >= 1
    finally:
        telemetry.disable()


# --- scope state ------------------------------------------------------------

def test_bulk_scope_restores_sizes_and_enable():
    engine.set_bulk_size(30)
    assert not engine.bulk_enabled()
    with engine.bulk(5):
        assert engine.bulk_enabled()
        assert engine.bulk_size() == 5
    assert engine.bulk_size() == 30
    assert not engine.bulk_enabled()
    assert not engine._bulk_on


# --- async tier --------------------------------------------------------------
# Size-flushed segments run on the background executor thread; the caller
# keeps appending.  Errors are captured per-segment and re-raised at the
# next materialization point naming the originating op; flush() is a
# deterministic drain; MXNET_ENGINE_ASYNC=0 restores sync bulking exactly.


@pytest.fixture
def async_on():
    prev = engine.set_async_enabled(True)
    yield
    engine._TLS.segment = None
    engine.set_async_enabled(prev)


@pytest.mark.parametrize("name,fn", SWEEP, ids=[n for n, _ in SWEEP])
def test_async_bulked_bit_identical_to_eager(name, fn, async_on):
    a, b = _arr(seed=1), _arr(seed=2)
    ref = fn(a, b).asnumpy()
    with engine.bulk(2):
        got = fn(a, b).asnumpy()
    assert np.array_equal(ref, got), f"{name}: async bulked != eager"
    assert ref.dtype == got.dtype


def test_async_cross_flush_stitching_matches_eager(async_on, monkeypatch):
    # slow the worker's segment build so consumers always catch producers
    # in flight: every cross-segment ref takes the stitch path
    real = engine._build_segment_fn

    def slow(*a, **k):
        time.sleep(0.01)
        return real(*a, **k)

    monkeypatch.setattr(engine, "_build_segment_fn", slow)
    engine.clear_segment_cache()

    def chain(x):
        # add-then-div per step: no mul+add adjacency, so XLA cannot
        # fma-contract the fused segment and bit-identity to eager holds
        for i in range(12):
            x = x + (0.5 + i)
            x = x / 1.01
        return x

    a = _arr(seed=20)
    ref = chain(a).asnumpy()
    before = engine.async_stats()
    with engine.bulk(4):
        got = chain(a).asnumpy()
    after = engine.async_stats()
    np.testing.assert_array_equal(ref, got)
    assert after["submitted"] > before["submitted"]
    assert after["stitched_segments"] > before["stitched_segments"]
    assert after["stitched_inputs"] > before["stitched_inputs"]


def test_async_worker_exception_names_op_at_materialization(
        async_on, monkeypatch):
    def boom(ops, n_slots, keep):
        raise RuntimeError("injected kernel failure")

    monkeypatch.setattr(engine, "_build_segment_fn", boom)
    engine.clear_segment_cache()
    a = _arr(seed=21)
    with engine.bulk(2):
        c = nd.tanh(a)
        c = c * 2.0  # size flush: submits to the worker, which fails
        # dispatch continued past the failure; the captured exception
        # surfaces here, at the materialization point, naming the op
        with pytest.raises(mx.MXNetError, match="tanh"):
            c.asnumpy()
        with pytest.raises(mx.MXNetError, match="injected kernel failure"):
            engine._materialize(c._raw)


def test_async_flush_is_deterministic_drain(async_on, monkeypatch):
    real = engine._build_segment_fn

    def slow(*a, **k):
        time.sleep(0.01)
        return real(*a, **k)

    monkeypatch.setattr(engine, "_build_segment_fn", slow)
    engine.clear_segment_cache()
    a = _arr(seed=22)
    with engine.bulk(2):
        c = a + 1.0
        c = c * 2.0          # submit 1
        d = c - 3.0
        d = d / 2.0          # submit 2, stitched onto 1
        assert engine._TLS.inflight
        engine.flush()
        # after flush() every submitted segment has executed: no waits
        # left, reads below resolve without touching the worker
        assert not engine._TLS.inflight
        assert c._raw._segment.results is not None
        assert d._raw._segment.results is not None
    np.testing.assert_array_equal(
        d.asnumpy(), ((a.asnumpy() + 1.0) * 2.0 - 3.0) / 2.0)


def test_sanitizer_stale_read_through_async_segment(async_on):
    from mxnet_tpu import sanitizer

    sanitizer.enable()
    try:
        a = _arr()
        sanitizer.donate([a._data], "async_donating_site")
        with engine.bulk(2):
            c = a + 1.0      # consumes the donated buffer
            c = c * 2.0      # size flush: donation check runs on the worker
            with pytest.raises(sanitizer.DonatedBufferError,
                               match="async_donating_site"):
                c.asnumpy()
    finally:
        sanitizer.reset()
        sanitizer.disable()


def test_async_interleaved_record_pause_grads_match():
    # same program under sync and async bulking; async engages the
    # record-path replay cache (cached_vjp), grads must agree
    a = _arr(seed=23)

    def run(use_async):
        prev = engine.set_async_enabled(use_async)
        try:
            w = nd.array(np.ones((3, 4), np.float32))
            w.attach_grad()
            with engine.bulk(4):
                pre = a * 0.5 + 1.0
                with ag.record():
                    y = w * pre
                    with ag.pause():
                        _ = (y + 1.0).sum().asnumpy()  # untracked read
                    loss = nd.tanh(y).sum()
                loss.backward()
                engine.flush()
            return w.grad.asnumpy()
        finally:
            engine.set_async_enabled(prev)

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6, atol=1e-7)


def test_async_queue_backpressure_bounds_depth(async_on, monkeypatch):
    # slow every worker-side execution so submissions outpace the worker
    # and the bounded queue pushes back on the caller
    real = engine._cache_lookup

    def slow(key):
        time.sleep(0.003)
        return real(key)

    monkeypatch.setattr(engine, "_cache_lookup", slow)
    submitted0 = engine.async_stats()["submitted"]
    a = _arr(seed=24)
    with engine.bulk(2):
        x = a
        for _ in range(30):
            x = x + 1.0
            x = x * 1.0  # size flush each iteration
        got = x.asnumpy()
    stats = engine.async_stats()
    assert stats["submitted"] - submitted0 >= 30
    assert stats["max_queue_depth"] <= engine._ASYNC_QUEUE_MAX + 1
    assert engine._EXEC.q.qsize() == 0
    ref = a.asnumpy()
    for _ in range(30):  # sequential, same op order as the chain
        ref = (ref + np.float32(1.0)) * np.float32(1.0)
    np.testing.assert_array_equal(got, ref)


def test_shutdown_async_drains_and_restarts_lazily(async_on):
    a = _arr(seed=25)
    with engine.bulk(2):
        c = a + 1.0
        c = c * 2.0
    engine.shutdown_async()
    assert not engine._TLS.inflight
    assert c._raw._segment.results is not None
    np.testing.assert_array_equal(c.asnumpy(), (a.asnumpy() + 1.0) * 2.0)
    # the executor thread restarts on the next async submit
    with engine.bulk(2):
        d = a - 1.0
        d = d * 3.0
    np.testing.assert_array_equal(d.asnumpy(), (a.asnumpy() - 1.0) * 3.0)
    t = engine._EXEC._thread
    assert t is not None and t.is_alive()


def test_segment_cache_stats_thread_safe_under_async_load(async_on):
    # caller-side stats reads and clears race the worker's LRU inserts;
    # all of them hold the segment lock, so this must never corrupt the
    # cache or miscount
    a = _arr(seed=26)
    with engine.bulk(2):
        x = a
        for i in range(30):
            x = x + 1.0
            x = x * 1.0
            s = engine.segment_cache_stats()
            assert s["size"] >= 0 and s["hit"] >= 0 and s["miss"] >= 0
            if i % 10 == 5:
                engine.clear_segment_cache()
        got = x.asnumpy()
    ref = a.asnumpy()
    for _ in range(30):
        ref = (ref + np.float32(1.0)) * np.float32(1.0)
    np.testing.assert_array_equal(got, ref)


def test_async_wait_accounted_in_telemetry(async_on, monkeypatch):
    real = engine._build_segment_fn

    def slow(*a, **k):
        time.sleep(0.01)
        return real(*a, **k)

    monkeypatch.setattr(engine, "_build_segment_fn", slow)
    engine.clear_segment_cache()
    telemetry.enable()
    try:
        a = _arr(seed=27)
        telemetry.step_begin()
        with engine.bulk(2):
            c = a + 1.0
            c = c * 2.0      # submit; worker is slowed
            c.asnumpy()      # caller stalls on the worker: wait accounted
        rec = telemetry.step_end()
        assert rec["bulk_async_wait_ms"] > 0
        assert rec["gauges"]["engine.async_queue_depth"] >= 1
    finally:
        telemetry.disable()


def test_env_async_disabled_restores_sync_bulking():
    r = _run_py(
        "import numpy as np\n"
        "from mxnet_tpu import engine, nd\n"
        "from mxnet_tpu.engine import _PendingArray\n"
        "assert not engine.async_enabled()\n"
        "a = nd.array(np.ones((2, 2), np.float32))\n"
        "with engine.bulk(2):\n"
        "    c = a + 1.0\n"
        "    c = c * 2.0  # size flush executes inline in sync mode\n"
        "    assert engine.pending_ops() == 0\n"
        "    assert c._raw.__class__ is not _PendingArray\n"
        "assert engine.async_stats()['submitted'] == 0\n"
        "assert engine._EXEC._thread is None\n"
        "assert (c.asnumpy() == 4).all()\n",
        MXNET_ENGINE_ASYNC="0")
    assert r.returncode == 0, r.stderr


def test_env_async_queue_size_honoured():
    r = _run_py(
        "from mxnet_tpu import engine\n"
        "assert engine._ASYNC_QUEUE_MAX == 3, engine._ASYNC_QUEUE_MAX\n"
        "assert engine._EXEC.q.maxsize == 3\n",
        MXNET_ENGINE_ASYNC_QUEUE="3")
    assert r.returncode == 0, r.stderr


def test_naive_engine_bypasses_async(async_on):
    prev = engine.engine_type()
    engine.set_engine_type("NaiveEngine")
    try:
        before = engine.async_stats()["submitted"]
        a = _arr(seed=28)
        with engine.bulk(2):
            c = a + 1.0
            c = c * 2.0
            assert not _pending(c)
        assert engine.async_stats()["submitted"] == before
    finally:
        engine.set_engine_type(prev)
