"""Deferred imperative dispatch (engine op bulking) tests.

The contract under test (docs/engine.md): with bulking on, ``apply_op``
appends to a thread-local pending segment that flushes as ONE
jit-compiled callable; every flush trigger (size, host sync, record
boundary, CachedOp/kvstore dispatch, explicit) resolves pending handles;
each op's result is bit-identical to its eager dispatch; the segment
cache replays compiled segments; NaiveEngine and the disabled path
bypass deferral entirely.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import engine, gluon, nd, telemetry
from mxnet_tpu.engine import _PendingArray


@pytest.fixture(autouse=True)
def _clean_engine():
    engine.flush()
    engine.clear_segment_cache()
    prev = engine.set_bulk_size(15)
    yield
    engine.flush()
    engine.set_bulk_size(prev)


def _pending(a):
    return a._raw.__class__ is _PendingArray


def _arr(shape=(3, 4), seed=0, positive=False):
    data = np.random.RandomState(seed).randn(*shape).astype(np.float32)
    if positive:
        data = np.abs(data) + 0.5
    return nd.array(data)


# --- numerical identity across the op sweep ---------------------------------

SWEEP = [
    ("add", lambda a, b: a + b),
    ("add_scalar", lambda a, b: a + 1.25),
    ("radd_scalar", lambda a, b: 1.25 + a),
    ("sub", lambda a, b: a - b),
    ("rsub_scalar", lambda a, b: 2.5 - a),
    ("mul", lambda a, b: a * b),
    ("mul_scalar", lambda a, b: a * 1.001),
    ("div", lambda a, b: a / (b + 3.0)),
    ("div_scalar", lambda a, b: a / 1.002),
    ("rdiv_scalar", lambda a, b: 1.7 / (a + 3.0)),
    ("pow_scalar", lambda a, b: (a + 3.0) ** 1.5),
    ("neg", lambda a, b: -a),
    ("exp", lambda a, b: nd.exp(a)),
    ("log", lambda a, b: nd.log(a + 3.0)),
    ("sqrt", lambda a, b: nd.sqrt(a + 3.0)),
    ("rsqrt", lambda a, b: nd.rsqrt(a + 3.0)),
    ("tanh", lambda a, b: nd.tanh(a)),
    ("sigmoid", lambda a, b: nd.sigmoid(a)),
    ("relu", lambda a, b: nd.relu(a)),
    ("abs", lambda a, b: nd.abs(a)),
    ("square", lambda a, b: nd.square(a)),
    ("floor", lambda a, b: nd.floor(a)),
    ("sign", lambda a, b: nd.sign(a)),
    ("maximum", lambda a, b: nd.maximum(a, b)),
    ("minimum", lambda a, b: nd.minimum(a, b)),
    ("clip", lambda a, b: nd.clip(a, -0.5, 0.5)),
    ("sum", lambda a, b: nd.sum(a)),
    ("sum_axis", lambda a, b: nd.sum(a, axis=1)),
    ("mean", lambda a, b: nd.mean(a, axis=0)),
    ("max", lambda a, b: nd.max(a, axis=1)),
    ("dot", lambda a, b: nd.dot(a, b.T)),
    ("reshape", lambda a, b: a.reshape((4, 3))),
    ("transpose", lambda a, b: nd.transpose(a)),
    ("softmax", lambda a, b: nd.softmax(a, axis=-1)),
    ("norm", lambda a, b: nd.norm(a)),
]


@pytest.mark.parametrize("name,fn", SWEEP, ids=[n for n, _ in SWEEP])
def test_bulked_bit_identical_to_eager(name, fn):
    a, b = _arr(seed=1), _arr(seed=2)
    ref = fn(a, b).asnumpy()
    with engine.bulk(8):
        got = fn(a, b).asnumpy()
    assert np.array_equal(ref, got), f"{name}: bulked != eager"
    assert ref.dtype == got.dtype


def test_chained_segment_matches_eager():
    a, b = _arr(seed=3), _arr(seed=4)
    ref = nd.tanh(nd.relu(a * b) + a).sum(axis=0).asnumpy()
    with engine.bulk(16):
        out = nd.tanh(nd.relu(a * b) + a).sum(axis=0)
        assert _pending(out)
        got = out.asnumpy()
    np.testing.assert_allclose(ref, got, rtol=0, atol=0)


def test_scalar_attr_change_replays_cached_segment():
    # float attrs are runtime args: new value, same compiled segment
    a = _arr(seed=5)
    engine.clear_segment_cache()
    with engine.bulk(8):
        r1 = (a * 2.5 + 0.1).asnumpy()
    with engine.bulk(8):
        r2 = (a * 3.5 + 0.7).asnumpy()
    stats = engine.segment_cache_stats()
    assert stats["miss"] == 1 and stats["hit"] == 1
    # mul+add fused in ONE segment may fma-contract (docs/engine.md):
    # values match eager to the last ulp, not necessarily bitwise
    np.testing.assert_allclose(r1, ((a * 2.5) + 0.1).asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(r2, ((a * 3.5) + 0.7).asnumpy(), rtol=1e-6)


# --- flush triggers ---------------------------------------------------------

def test_flush_on_asnumpy():
    a = _arr()
    with engine.bulk(8):
        c = a + 1.0
        assert _pending(c) and engine.pending_ops() == 1
        c.asnumpy()
        assert engine.pending_ops() == 0
        assert not _pending(c)


def test_flush_on_wait_to_read():
    a = _arr()
    with engine.bulk(8):
        c = a * 2.0
        assert _pending(c)
        c.wait_to_read()
        assert engine.pending_ops() == 0


def test_flush_on_item():
    a = nd.array(np.float32([[41.0]]))
    with engine.bulk(8):
        c = a + 1.0
        assert _pending(c)
        assert c.item() == 42.0
        assert engine.pending_ops() == 0


def test_flush_on_getitem():
    a = _arr()
    with engine.bulk(8):
        c = a + 1.0
        assert _pending(c)
        seg = c._raw._segment
        row = c[0]
        # the producing segment flushed (the slicing op itself may be
        # deferred into a fresh segment — it is just another op)
        assert seg.results is not None
        np.testing.assert_array_equal(row.asnumpy(), (a + 1.0).asnumpy()[0])


def test_flush_on_bulk_size():
    a = _arr()
    with engine.bulk(3):
        c = a + 1.0
        c = c * 2.0
        assert engine.pending_ops() == 2
        c = c - 3.0  # third op hits the budget: segment executes
        assert engine.pending_ops() == 0
        assert not _pending(c)
    np.testing.assert_array_equal(
        c.asnumpy(), ((a + 1.0) * 2.0 - 3.0).asnumpy())


def test_flush_on_record_boundary_and_grads_match():
    a = _arr()
    w = nd.array(np.ones((3, 4), np.float32))
    w.attach_grad()
    # eager reference gradient
    with ag.record():
        (w * (a + 1.0)).sum().backward()
    ref_grad = w.grad.asnumpy()

    w2 = nd.array(np.ones((3, 4), np.float32))
    w2.attach_grad()
    with engine.bulk(16):
        pre = a + 1.0
        assert _pending(pre)
        with ag.record():
            # entering record flushed the pending segment; the handle
            # resolves to the computed buffer on its next read
            assert engine.pending_ops() == 0
            assert pre._raw._segment.results is not None
            loss = (w2 * pre).sum()
            # recording dispatches eagerly: nothing re-enters the segment
            assert engine.pending_ops() == 0
        loss.backward()
    np.testing.assert_array_equal(ref_grad, w2.grad.asnumpy())


def test_pause_does_not_flush():
    a = _arr()
    with engine.bulk(8):
        c = a + 1.0
        with ag.pause():
            assert engine.pending_ops() == 1
        assert _pending(c)


def test_explicit_flush_returns_count():
    a = _arr()
    with engine.bulk(8):
        _ = a + 1.0
        _ = a * 2.0
        assert engine.flush() == 2
        assert engine.flush() == 0


def test_flush_on_cachedop_dispatch():
    net = gluon.nn.Dense(4)
    net.initialize()
    x = _arr((2, 3))
    net(x)  # shape-resolve eagerly
    net.hybridize()
    net(x)
    with engine.bulk(8):
        y = x + 1.0
        assert _pending(y)
        net(x)  # CachedOp dispatch is a flush boundary
        assert engine.pending_ops() == 0


def test_flush_on_kvstore_dispatch():
    kv = mx.kv.create("local")
    kv.init("w", nd.zeros((3, 4)))
    g = _arr(seed=7)
    with engine.bulk(8):
        scaled = g * 0.5
        assert _pending(scaled)
        kv.push("w", scaled)
        assert engine.pending_ops() == 0
    out = nd.zeros((3, 4))
    kv.pull("w", out=out)
    np.testing.assert_array_equal(out.asnumpy(), (g * 0.5).asnumpy())


# --- sanitizer through a deferred segment -----------------------------------

def test_sanitizer_stale_read_through_deferred_segment():
    from mxnet_tpu import sanitizer

    sanitizer.enable()
    try:
        a = _arr()
        raw = a._data
        sanitizer.donate([raw], "test_donating_site")
        with engine.bulk(8):
            c = a + 1.0  # consumes the donated buffer
            assert _pending(c)
            with pytest.raises(sanitizer.DonatedBufferError,
                               match="test_donating_site"):
                engine.flush()
    finally:
        sanitizer.reset()
        sanitizer.disable()
        engine._TLS.segment = None


# --- bypasses ---------------------------------------------------------------

def test_naive_engine_bypasses_bulking():
    prev = engine.engine_type()
    engine.set_engine_type("NaiveEngine")
    try:
        a = _arr()
        with engine.bulk(8):
            c = a + 1.0
            assert not _pending(c)
            assert engine.pending_ops() == 0
    finally:
        engine.set_engine_type(prev)


def test_bulk_size_one_disables_deferral():
    a = _arr()
    with engine.bulk(1):
        c = a + 1.0
        assert not _pending(c)


def test_disabled_path_never_reaches_maybe_defer(monkeypatch):
    # the off path must be ONE boolean test in apply_op: poison
    # maybe_defer and prove it is not consulted
    assert not engine._bulk_on

    def boom(*a, **k):  # pragma: no cover - must not run
        raise AssertionError("maybe_defer called with bulking off")

    monkeypatch.setattr(engine, "maybe_defer", boom)
    a = _arr()
    np.testing.assert_array_equal(
        (a + 1.0).asnumpy(), a.asnumpy() + 1.0)


def test_recording_forces_eager_inside_bulk():
    a = _arr()
    with engine.bulk(8):
        with ag.record():
            c = a + 1.0
            assert not _pending(c)


# --- cache accounting -------------------------------------------------------

def test_segment_cache_hit_miss_accounting():
    a = _arr(seed=8)
    engine.clear_segment_cache()
    with engine.bulk(8):
        (nd.tanh(a) + a).asnumpy()
    s1 = engine.segment_cache_stats()
    assert (s1["miss"], s1["hit"], s1["size"]) == (1, 0, 1)
    with engine.bulk(8):
        (nd.tanh(a) + a).asnumpy()
    s2 = engine.segment_cache_stats()
    assert (s2["miss"], s2["hit"]) == (1, 1)
    # different shape -> different signature -> miss
    b = _arr((5, 2), seed=9)
    with engine.bulk(8):
        (nd.tanh(b) + b).asnumpy()
    s3 = engine.segment_cache_stats()
    assert s3["miss"] == 2 and s3["size"] == 2


def test_cross_segment_pending_input_materializes():
    a = _arr(seed=10)
    with engine.bulk(2):
        c = a + 1.0           # segment 1, pending
        d = c * 2.0           # hits budget: segment 1 executes
        e = d - 0.5           # segment 2, consumes executed result
        assert _pending(e)
        got = e.asnumpy()
    np.testing.assert_array_equal(
        got, ((a + 1.0) * 2.0 - 0.5).asnumpy())


# --- env vars + telemetry ---------------------------------------------------

def _run_py(code, **env):
    full = dict(os.environ, JAX_PLATFORMS="cpu", **env)
    return subprocess.run([sys.executable, "-c", code], env=full,
                          capture_output=True, text=True)


def test_env_bulk_size_honoured_at_startup():
    r = _run_py(
        "from mxnet_tpu import engine;"
        "assert engine._bulk_size == 7, engine._bulk_size;"
        "assert engine.bulk_size() == 7",
        MXNET_ENGINE_BULK_SIZE="7")
    assert r.returncode == 0, r.stderr


def test_env_bulk_size_train_infer_variants():
    r = _run_py(
        "from mxnet_tpu import engine, autograd as ag;"
        "assert engine.bulk_size() == 5;"   # infer mode by default
        "ag.set_training(True);"
        "assert engine.bulk_size() == 9",
        MXNET_ENGINE_BULK_SIZE_IN_TRAIN="9",
        MXNET_ENGINE_BULK_SIZE_IN_INFER="5")
    assert r.returncode == 0, r.stderr


def test_env_bulk_enable_flag():
    r = _run_py(
        "import numpy as np;"
        "from mxnet_tpu import engine, nd;"
        "assert engine.bulk_enabled() and engine._bulk_on;"
        "a = nd.array(np.ones((2, 2), np.float32));"
        "c = a + 1.0;"
        "from mxnet_tpu.engine import _PendingArray;"
        "assert c._raw.__class__ is _PendingArray;"
        "assert (c.asnumpy() == 2).all()",
        MXT_ENGINE_BULK="1")
    assert r.returncode == 0, r.stderr


def test_telemetry_flush_reasons_and_step_record():
    telemetry.enable()
    try:
        a = _arr(seed=11)
        telemetry.step_begin()
        with engine.bulk(8):
            (a + 1.0).asnumpy()          # host_sync flush
            _ = a * 2.0
            engine.flush()               # explicit flush
        rec = telemetry.step_end()
        sc = rec["counters"]
        assert rec["bulk_flush"] == sc["engine.bulk_flush"] >= 2
        assert sc["engine.bulk_flush.host_sync"] >= 1
        assert sc["engine.bulk_flush.explicit"] >= 1
        assert sc["engine.bulk_compile"] >= 1
        assert rec["gauges"]["engine.bulk_segment_ops"] >= 1
        # segment compiles count into the step's compile_count
        assert rec["compile_count"] >= sc["engine.bulk_compile"]
    finally:
        telemetry.disable()


def test_telemetry_size_and_record_reasons():
    telemetry.enable()
    try:
        a = _arr(seed=12)
        telemetry.step_begin()
        with engine.bulk(2):
            c = a + 1.0
            c = c * 2.0                  # size flush
            _ = a - 1.0
            with ag.record():            # record flush
                pass
        rec = telemetry.step_end()
        sc = rec["counters"]
        assert sc["engine.bulk_flush.size"] >= 1
        assert sc["engine.bulk_flush.record"] >= 1
    finally:
        telemetry.disable()


# --- scope state ------------------------------------------------------------

def test_bulk_scope_restores_sizes_and_enable():
    engine.set_bulk_size(30)
    assert not engine.bulk_enabled()
    with engine.bulk(5):
        assert engine.bulk_enabled()
        assert engine.bulk_size() == 5
    assert engine.bulk_size() == 30
    assert not engine.bulk_enabled()
    assert not engine._bulk_on
