"""tools/ tests: im2rec packing roundtrip + launch.py loopback spawn
(reference model: the nightly dist tests' --launcher local trick +
tools/im2rec.py usage, SURVEY §2.5 / §4)."""
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _make_images(root, n_classes=2, per_class=3):
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    for c in range(n_classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d)
        for i in range(per_class):
            arr = onp.full((10, 12, 3), 40 * c + i, onp.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"im{i}.jpg"))


def test_im2rec_roundtrip(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import im2rec
    finally:
        sys.path.pop(0)
    root = str(tmp_path / "imgs")
    os.makedirs(root)
    _make_images(root)
    prefix = str(tmp_path / "data")
    rec, idx = im2rec.im2rec(_args(prefix, root))
    assert os.path.exists(rec) and os.path.exists(idx)
    reader = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert len(reader.keys) == 6
    header, payload = recordio.unpack(reader.read_idx(reader.keys[0]))
    assert payload[:2] == b"\xff\xd8"  # JPEG magic
    labels = set()
    for k in reader.keys:
        h, _ = recordio.unpack(reader.read_idx(k))
        labels.add(float(h.label))
    assert labels == {0.0, 1.0}
    reader.close()


def _args(prefix, root):
    import argparse

    return argparse.Namespace(prefix=prefix, root=root, recursive=True,
                              shuffle=True, resize=8, center_crop=True,
                              quality=95, encoding=".jpg")


def test_im2rec_feeds_image_record_iter(tmp_path):
    pytest.importorskip("PIL")
    sys.path.insert(0, TOOLS)
    try:
        import im2rec
    finally:
        sys.path.pop(0)
    root = str(tmp_path / "imgs")
    os.makedirs(root)
    _make_images(root)
    prefix = str(tmp_path / "data")
    rec, idx = im2rec.im2rec(_args(prefix, root))
    from mxnet_tpu import io

    it = io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                            data_shape=(3, 8, 8), batch_size=2,
                            shuffle=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 3, 8, 8)


def test_launch_local_spawns_group(tmp_path):
    script = tmp_path / "worker.py"
    out = tmp_path / "out"
    script.write_text(f"""
import os
rank = os.environ["MXT_PROCESS_ID"]
n = os.environ["MXT_NUM_PROCESSES"]
with open(r"{out}" + rank, "w") as f:
    f.write(f"{{rank}}/{{n}}")
""")
    rc = subprocess.call([sys.executable,
                          os.path.join(TOOLS, "launch.py"), "-n", "3",
                          sys.executable, str(script)])
    assert rc == 0
    got = sorted(open(str(out) + str(i)).read() for i in range(3))
    assert got == ["0/3", "1/3", "2/3"]


def test_launch_ssh_emits_commands(capsys):
    sys.path.insert(0, TOOLS)
    try:
        import launch
    finally:
        sys.path.pop(0)
    lines = launch.emit_ssh(["hostA", "hostB"], 4, ["python", "t.py"],
                            "10.0.0.1:1234")
    assert len(lines) == 4
    assert "hostA" in lines[0] and "hostB" in lines[1]
    assert "MXT_PROCESS_ID=3" in lines[3]


def test_launch_ssh_spawns_via_pluggable_transport(tmp_path):
    """--launcher ssh actually spawns (VERDICT r2: 'a launcher that
    launches'): MXT_SSH substitutes a local stub for the ssh binary, the
    env contract arrives exported on the 'remote' shell, and the per-job
    secret is delivered over stdin — never in argv."""
    stub = tmp_path / "fakessh"
    stub.write_text("#!/bin/sh\n"
                    "host=\"$1\"; shift\n"
                    "exec sh -c \"$*\"\n")
    stub.chmod(0o755)
    script = tmp_path / "worker.py"
    out = tmp_path / "out"
    script.write_text(f"""
import os, sys
rank = os.environ["MXT_PROCESS_ID"]
with open(r"{out}" + rank, "w") as f:
    f.write(os.environ["MXT_NUM_PROCESSES"] + ":" +
            os.environ["MXT_COORDINATOR"] + ":" +
            os.environ["MXT_PS_SECRET"])
""")
    env = dict(os.environ)
    env["MXT_SSH"] = str(stub)
    env["MXT_PS_SECRET"] = "sekrit-42"
    rc = subprocess.call(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--launcher", "ssh", "--coordinator", "10.0.0.9:7777",
         sys.executable, str(script)], env=env)
    assert rc == 0
    for i in range(2):
        assert open(str(out) + str(i)).read() == \
            "2:10.0.0.9:7777:sekrit-42"


def test_launch_ssh_dry_run_emits_without_secret(tmp_path):
    env = dict(os.environ)
    env["MXT_PS_SECRET"] = "must-not-leak"
    res = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--launcher", "ssh", "--dry-run", "python", "t.py"],
        env=env, capture_output=True, text=True)
    assert res.returncode == 0
    lines = res.stdout.strip().splitlines()
    assert len(lines) == 2 and lines[0].startswith("ssh ")
    assert "must-not-leak" not in res.stdout
