"""Numerics observability (ISSUE 17): the in-compile tensor-stats tier,
NaN/Inf provenance through the fleet watchdog, and divergence forensics.

Coverage map:
  * tap/step_summary basics: stat bundle schema, first_nan provenance
    (path + layer), grad-norm aggregation, stride gating;
  * disabled-path guard: 10k taps with the tier off stay under the
    house overhead bound and queue nothing;
  * compile-once: a hybridized net + fused trainer update with stats
    enabled keeps exactly one compile signature (replays are 0-compile
    steps), and toggling the tier off/on re-uses both cached programs;
  * CachedOp backward: per-param ``grad.<name>`` stats exit the same
    donated compile;
  * the scanned decoder: stacked per-layer stats exit ``lax.scan`` as
    ys and fan out to ``decoder.<i>`` paths;
  * the acceptance lane: NaN injected into decoder layer 1 on a dp2
    CPU mesh is attributed by the watchdog anomaly record AND the
    flight dump as (layer-1 path, rank), and rides the fleet view's
    ``first_nan_layer`` column;
  * watchdog math: ``growth_streak`` as a pure function, the
    ``grad_norm_explosion`` detector, ``None``-gap tolerance in the
    spike/skew detectors;
  * capture -> replay roundtrip: ``capture_step`` snapshots through the
    async checkpointer, ``numerics_report --replay`` names the first
    poisoned op;
  * report schema: JSONL numerics blocks render to the heatmap and to
    Perfetto counter ("C") tracks;
  * Monitor regression: ``install()`` on a hybridized block records
    rows via the numerics tier (the old "records nothing" warning is
    gone), the eager path is unchanged.
"""
import json
import math
import os
import sys
import time
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd, parallel, telemetry
from mxnet_tpu.models import llama
from mxnet_tpu.monitor import Monitor
from mxnet_tpu.telemetry import fleet, numerics

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _numerics_report():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import numerics_report
    return numerics_report


@pytest.fixture(autouse=True)
def _clean_numerics():
    telemetry.disable()
    telemetry.reset()
    fleet.clear()
    numerics.clear()
    yield
    telemetry.disable()
    telemetry.reset()
    fleet.clear()
    numerics.clear()
    parallel.set_mesh(None)


# --- tap / step_summary basics ----------------------------------------------

def test_tap_step_summary_schema_and_first_nan_provenance():
    numerics.enable(stride=1)
    clean = nd.ones((4, 4))
    bad = nd.array(np.array([[1.0, float("nan")], [2.0, 3.0]]))
    numerics.tap("embed", clean)
    numerics.tap("decoder.1.ffn", bad)
    numerics.tap("grad.decoder.1.ffn.w", nd.ones((2,)) * 3.0)
    numerics.tap("grad.head.w", nd.ones((2,)) * 4.0)
    summary = numerics.step_summary(0)
    assert summary["stride"] == 1
    tensors = summary["tensors"]
    assert set(tensors) == {"embed", "decoder.1.ffn",
                            "grad.decoder.1.ffn.w", "grad.head.w"}
    for st in tensors.values():
        assert set(st) == {"l2", "maxabs", "mean", "nan", "inf"}
        assert isinstance(st["l2"], float)
        assert isinstance(st["nan"], int)
    assert tensors["embed"]["l2"] == pytest.approx(4.0)
    assert tensors["embed"]["nan"] == 0
    assert tensors["decoder.1.ffn"]["nan"] == 1
    # first nan names the first poisoned path IN FORWARD ORDER + layer
    assert summary["first_nan"] == {"path": "decoder.1.ffn", "layer": 1,
                                    "nan": 1, "inf": 0}
    # grad_norm is the l2 of all grad.* bundles: sqrt(18 + 32)
    assert summary["grad_norm"] == pytest.approx(math.sqrt(
        tensors["grad.decoder.1.ffn.w"]["l2"] ** 2
        + tensors["grad.head.w"]["l2"] ** 2))


def test_stride_gates_the_host_sync_and_drops_offstride():
    numerics.enable(stride=4)
    for step in range(1, 4):
        numerics.tap("x", nd.ones((2,)))
        assert numerics.step_summary(step) is None
    assert numerics._pending == []  # off-stride steps drop, not queue
    numerics.tap("x", nd.ones((2,)))
    summary = numerics.step_summary(4)
    assert summary is not None and "x" in summary["tensors"]


def test_layer_of_path_parsing():
    assert numerics.layer_of("decoder.7.ffn") == 7
    assert numerics.layer_of("grad.decoder.3.attn.wq") == 3
    assert numerics.layer_of("embed") == -1
    assert numerics.layer_of("logits") == -1


def test_disabled_tap_overhead_bounded():
    # the PR 2 contract: the disabled path is one boolean test — 10k
    # taps must be effectively free and must queue nothing
    x = nd.ones((8, 8))
    t0 = time.perf_counter()
    for _ in range(10_000):
        numerics.tap("layer", x)
        numerics.step_summary()
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.5, f"disabled numerics cost {elapsed:.3f}s"
    assert numerics._pending == []


# --- compile-once: one signature per mode ------------------------------------

def _mlp():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(8, activation="relu"))
        net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(nd.ones((2, 8)))
    net.hybridize()
    return net


def test_stats_enabled_keeps_one_compile_signature():
    numerics.enable(stride=1)
    telemetry.enable()
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    x, y = nd.ones((2, 8)), nd.ones((2, 4))

    def one_step():
        with telemetry.step(examples=2) as scope:
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            trainer.step(2)
        return scope.record

    first = one_step()
    assert first["compile_count"] > 0  # the one stats-on trace
    kinds = set()
    for _ in range(3):
        rec = one_step()
        # replays: zero compiles with stats still flowing every step
        assert rec["compile_count"] == 0, rec["counters"]
        tensors = rec["numerics"]["tensors"]
        kinds |= {p.split(".", 1)[0] for p in tensors}
    assert {"grad", "update"} <= kinds
    # toggling the tier re-uses BOTH cached signatures: off retraces
    # once into its own cache entry, on replays the original compile
    numerics.disable()
    assert one_step()["compile_count"] > 0
    assert one_step()["compile_count"] == 0
    numerics.enable(stride=1)
    rec = one_step()
    assert rec["compile_count"] == 0, rec["counters"]
    assert rec["numerics"] is not None


def test_cachedop_backward_records_per_param_grad_stats():
    numerics.enable(stride=1)
    net = _mlp()
    with autograd.record():
        out = net(nd.ones((2, 8)))
    out.backward()
    summary = numerics.step_summary(0)
    grads = {p for p in summary["tensors"] if p.startswith("grad.")}
    names = {p.name for p in net.collect_params().values()}
    assert grads == {"grad." + n for n in names}


# --- model taps: plain and scanned decoder paths -----------------------------

def test_llama_plain_path_taps_every_layer():
    numerics.enable(stride=1)
    net = llama.llama_tiny()
    net.initialize(mx.init.Xavier())
    net(nd.array(np.ones((1, 8), dtype="int32")))
    paths = set(numerics.step_summary(0)["tensors"])
    n_layers = len(net.model.layers)
    expected = {"embed", "norm", "logits"} | {
        f"decoder.{i}" for i in range(n_layers)}
    assert expected <= paths


def test_llama_scanned_path_fans_out_stacked_layer_stats():
    numerics.enable(stride=1)
    net = llama.llama_tiny(scan_layers=True)
    net.initialize(mx.init.Xavier())
    net(nd.array(np.ones((1, 8), dtype="int32")))
    summary = numerics.step_summary(0)
    n_layers = len(net.model.layers)
    # the scan emits ONE stacked bundle; the harvest fans it out
    for i in range(n_layers):
        assert f"decoder.{i}" in summary["tensors"]


# --- the acceptance lane: dp2 mesh NaN injection ------------------------------

def test_nan_injected_at_layer1_attributed_with_rank_on_dp2_mesh(tmp_path):
    telemetry.enable()
    fleet.enable(stride=1)
    numerics.enable(stride=1)
    mesh = parallel.make_mesh({"dp": 2})
    net = llama.llama_tiny()
    net.initialize(mx.init.Xavier())
    # poison one weight of decoder layer 1 (layer-k param path) BEFORE
    # mesh placement, so the nan rides the placed copy onto both ranks
    victim = next(iter(net.model.layers[1].collect_params().values()))
    host = np.array(victim.data().asnumpy())
    host.flat[0] = float("nan")
    victim.set_data(nd.array(host))
    gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.01},
                  partition_rules="llama", mesh=mesh)
    ids = parallel.shard_batch(
        nd.array(np.ones((2, 8), dtype="int32")), mesh)
    with telemetry.step(examples=2):
        net(ids)
    anomalies = [r for r in fleet.recent()
                 if r.get("record") == "anomaly"
                 and r.get("kind") == "nan_tensor"]
    assert anomalies, [r.get("kind") for r in fleet.recent()]
    evt = anomalies[-1]
    # the anomaly names (layer-1 path, rank): embed and decoder.0 are
    # clean, decoder.1 is the first poisoned tap in forward order
    assert evt["path"] == "decoder.1"
    assert evt["layer"] == 1
    assert evt["rank"] == 0
    assert evt["nan"] > 0
    assert telemetry.counters().get("fleet.anomaly.nan_tensor", 0) >= 1
    # provenance rides the stride exchange: every rank learns the layer
    view = fleet.last_view()
    assert view["first_nan_layer"] == [1]
    # ... and the flight dump carries the same attribution
    dump_path = fleet.dump(str(tmp_path / "fd.json"), reason="test")
    with open(dump_path) as f:
        doc = json.load(f)
    dumped = [r for r in doc["records"]
              if r.get("record") == "anomaly"
              and r.get("kind") == "nan_tensor"]
    assert dumped and dumped[-1]["path"] == "decoder.1"
    assert dumped[-1]["layer"] == 1 and dumped[-1]["rank"] == 0


# --- watchdog math (pure functions) ------------------------------------------

def test_growth_streak_pure_math():
    assert fleet.growth_streak([1.0, 3.0, 7.0, 20.0], 2.0) == 3
    assert fleet.growth_streak([10.0, 3.0, 7.0, 20.0], 2.0) == 2
    # None gaps (strided records) break the streak
    assert fleet.growth_streak([1.0, 3.0, None, 20.0], 2.0) == 0
    assert fleet.growth_streak([1.0, None, 3.0, 20.0], 2.0) == 1
    # degenerate inputs are quiet
    assert fleet.growth_streak([], 2.0) == 0
    assert fleet.growth_streak([5.0], 2.0) == 0
    # non-positive predecessors never count as growth
    assert fleet.growth_streak([-1.0, 5.0], 2.0) == 0
    assert fleet.growth_streak([0.0, 5.0], 2.0) == 0


def test_watchdog_grad_norm_explosion_after_k_windows():
    wd = fleet.Watchdog(consecutive=3, growth_factor=2.0,
                        min_history=100)  # spike detector stays quiet
    fired = []
    for gn in (1.0, 3.0, 9.0):
        fired += [a for a in wd.observe_step({"grad_norm": gn})
                  if a["kind"] == "grad_norm_explosion"]
    assert fired == []  # streak is 2 after three samples
    fired = [a for a in wd.observe_step({"grad_norm": 27.0})
             if a["kind"] == "grad_norm_explosion"]
    assert fired and fired[0]["windows"] == 3
    assert fired[0]["factor"] == 2.0


def test_watchdog_explosion_reads_numerics_grad_norm_fallback():
    wd = fleet.Watchdog(consecutive=2, growth_factor=2.0,
                        min_history=100)
    out = []
    for gn in (1.0, 3.0, 9.0):
        out += wd.observe_step({"numerics": {"grad_norm": gn,
                                             "first_nan": None}})
    assert any(a["kind"] == "grad_norm_explosion" for a in out)


def test_spike_and_skew_detectors_tolerate_none_gaps():
    hist = [5.0, None, 5.0, 5.0, None, 5.0]
    assert fleet.detect_spike(100.0, hist, factor=3.0, min_history=4)
    assert not fleet.detect_spike(6.0, hist, factor=3.0, min_history=4)
    assert not fleet.detect_spike(None, hist, factor=3.0, min_history=4)
    assert fleet.detect_skew([10.0, None, 40.0, 10.0], 1.5) == [2]
    assert fleet.detect_skew([None, None, None], 1.5) == []


# --- capture -> replay forensics ---------------------------------------------

class _PoisonNet(gluon.HybridBlock):
    """Dense -> log: negative activations poison the log, so the
    bisection must name ``log`` (not the dense) as the first bad op."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.dense = gluon.nn.Dense(4)

    def hybrid_forward(self, F, x):
        return F.log(self.dense(x))


def build_poison_net():
    return _PoisonNet()


def test_capture_replay_names_first_poisoned_op(tmp_path):
    net = build_poison_net()
    net.initialize(mx.init.Xavier())
    x = nd.array(np.linspace(-3.0, 3.0, 12,
                             dtype=np.float32).reshape(2, 6))
    net(x)
    # deterministic poison: all-ones weights make row sums, and row 0
    # of x sums negative -> log(neg) = nan at the log, not the dense
    wp = net.collect_params()[net.dense.weight.name]
    wp.set_data(nd.ones(wp.shape))
    numerics.arm_capture(str(tmp_path))
    assert numerics.capture_armed()
    cdir = numerics.capture_step(
        net, [x], step=42, reason="grad_spike",
        builder="test_numerics:build_poison_net")
    assert cdir == str(tmp_path / "capture-42")
    assert not numerics.capture_armed()  # one-shot disarm
    checkpoint.wait_async()

    # sidecar schema + params landed through the async checkpointer
    with open(os.path.join(cdir, "capture.json")) as f:
        meta = json.load(f)
    assert meta["record"] == "numerics_capture"
    assert meta["step"] == 42 and meta["reason"] == "grad_spike"
    assert meta["builder"] == "test_numerics:build_poison_net"
    assert meta["inputs"] == ["input0"]
    ckpt = checkpoint.latest_checkpoint(cdir)
    assert ckpt is not None
    with open(os.path.join(ckpt, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["extra"]["numerics_capture"]["reason"] == "grad_spike"

    meta2, inputs = numerics.load_capture(cdir)
    assert meta2 == meta
    np.testing.assert_array_equal(inputs[0], x.asnumpy())

    lines, res = _numerics_report().replay(cdir)
    assert res.first is not None
    assert res.first["op"] == "log"
    journal = res.ops[res.first["index"]]
    assert journal["outputs_bad"] and not journal["inputs_bad"]
    assert any("first failing op: log" in ln for ln in lines)


def test_capture_unarmed_is_a_noop():
    net = build_poison_net()
    net.initialize(mx.init.Xavier())
    x = nd.ones((1, 6))
    net(x)
    assert numerics.capture_step(net, [x], step=1) is None


# --- report schema: JSONL heatmap + Perfetto counters ------------------------

def test_report_renders_real_jsonl_numerics_blocks(tmp_path):
    jsonl = str(tmp_path / "rank0.jsonl")
    telemetry.enable(jsonl_path=jsonl)
    numerics.enable(stride=1)
    net = llama.llama_tiny()
    net.initialize(mx.init.Xavier())
    ids = nd.array(np.ones((2, 8), dtype="int32"))
    for _ in range(3):
        with telemetry.step(examples=2):
            net(ids)
    telemetry.disable()

    nr = _numerics_report()
    records = nr.load_records([jsonl])
    rows = nr.numerics_rows(records)
    assert rows, "JSONL step records must carry numerics blocks"
    for _step, rank, _path, st in rows:
        assert rank == 0
        assert set(st) == {"l2", "maxabs", "mean", "nan", "inf"}
    text = nr.heatmap_text(records)
    assert "numerics heatmap: l2" in text
    assert "overflow: none" in text
    doc = nr.chrome_counters(records)
    events = doc["traceEvents"]
    assert events and all(e["ph"] == "C" for e in events)
    assert all(e["name"].startswith("numerics/") for e in events)
    tracked = {e["name"] for e in events}
    assert any(n != "numerics/grad_norm" for n in tracked)
    for e in events:
        if e["name"] != "numerics/grad_norm":
            assert set(e["args"]) == {"l2", "overflow"}


def test_report_heatmap_flags_overflow_cells():
    nr = _numerics_report()
    records = [
        {"step": 16, "rank": 0, "step_ms": 1.0,
         "numerics": {"stride": 16, "grad_norm": 2.0, "first_nan": None,
                      "tensors": {"embed": {"l2": 1.0, "maxabs": 1.0,
                                            "mean": 0.1, "nan": 0,
                                            "inf": 0}}}},
        {"step": 32, "rank": 0, "step_ms": 1.0,
         "numerics": {"stride": 16, "grad_norm": None,
                      "first_nan": {"path": "decoder.1", "layer": 1,
                                    "nan": 4, "inf": 0},
                      "tensors": {"decoder.1": {"l2": 9.0, "maxabs": 9.0,
                                                "mean": 0.0, "nan": 4,
                                                "inf": 0}}}},
    ]
    text = nr.heatmap_text(records)
    assert "9!" in text
    assert "first overflow: step 32 path decoder.1 (layer 1" in text
    assert "first_nan decoder.1 (layer 1)" in text
    doc = nr.chrome_counters(records)
    by_name = {}
    for e in doc["traceEvents"]:
        by_name.setdefault(e["name"], []).append(e)
    assert by_name["numerics/decoder.1"][0]["args"]["overflow"] == 4.0
    assert by_name["numerics/grad_norm"][0]["args"]["grad_norm"] == 2.0


# --- Monitor on the numerics tier --------------------------------------------

def test_monitor_records_on_hybridized_block():
    net = _mlp()
    net(nd.ones((2, 8)))  # traced BEFORE install: hooks must retrace
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the old warning is gone
        mon = Monitor(interval=1).install(net)
    mon.tic()
    net(nd.ones((2, 8)))
    rows = mon.toc()
    assert rows, "hybridized Monitor.install must record rows"
    names = {name for _step, name, _stat in rows}
    assert any(n.endswith("_output") for n in names)
    for _step, _name, stat in rows:
        assert float(stat) > 0.0  # l2 of a live activation
    mon.uninstall()


def test_monitor_eager_path_unchanged():
    net = _mlp()
    net.hybridize(False)
    mon = Monitor(interval=1).install(net)
    mon.tic()
    net(nd.ones((2, 8)))
    rows = mon.toc()
    assert len(rows) >= 2  # one per Dense child
    mon.uninstall()
