"""Standalone predictor (c_predict_api analog) tests.

Reference test model: the MXPredCreate → SetInput → Forward → GetOutput
call sequence (src/c_api/c_predict_api.cc:?, SURVEY §3.5) driven over both
serving formats: gluon export (StableHLO) and legacy nnvm symbol-json
checkpoints.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.predictor import Predictor, create
from mxnet_tpu.test_utils import assert_almost_equal


def _exported_mlp(tmp_path):
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(2, 8).astype(np.float32))
    ref = net(x).asnumpy()
    net.hybridize()
    net(x)
    prefix = str(tmp_path / "mlp")
    net.export(prefix, epoch=0)
    return prefix, x, ref


def test_predict_stablehlo_export(tmp_path):
    prefix, x, ref = _exported_mlp(tmp_path)
    pred = create(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    out = pred.predict(x)
    assert_almost_equal(out, ref, rtol=1e-5, atol=1e-6)


def test_set_input_forward_get_output(tmp_path):
    prefix, x, ref = _exported_mlp(tmp_path)
    pred = Predictor(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    name = pred.input_names[0]
    pred.set_input(name, x)
    pred.forward()
    assert pred.num_outputs == 1
    assert_almost_equal(pred.get_output(0), ref, rtol=1e-5, atol=1e-6)
    with pytest.raises(mx.MXNetError):
        pred.get_output(3)


def test_param_bytes_and_symbol_dict(tmp_path):
    """MXPredCreate-style: symbol passed as parsed JSON (dict) and params
    as raw BYTES; the stablehlo artifact referenced by absolute path."""
    import os

    prefix, x, ref = _exported_mlp(tmp_path)
    with open(f"{prefix}-symbol.json") as f:
        meta = json.load(f)
    with open(f"{prefix}-0000.params", "rb") as f:
        param_bytes = f.read()
    meta["stablehlo_file"] = os.path.abspath(f"{prefix}-0000.stablehlo")
    pred = Predictor(meta, param_bytes)
    out = pred.predict(x)
    assert_almost_equal(out, ref, rtol=1e-5, atol=1e-6)

    # the documented bytes-everything surface: meta dict untouched,
    # artifact shipped via stablehlo=<bytes>
    with open(f"{prefix}-symbol.json") as f:
        meta2 = json.load(f)
    with open(f"{prefix}-0000.stablehlo", "rb") as f:
        hlo_bytes = f.read()
    pred2 = Predictor(meta2, param_bytes, stablehlo=hlo_bytes)
    assert_almost_equal(pred2.predict(x), ref, rtol=1e-5, atol=1e-6)
    # without the artifact, a clear error (not FileNotFoundError)
    with pytest.raises(mx.MXNetError):
        Predictor(json.load(open(f"{prefix}-symbol.json")), param_bytes)


def test_predict_legacy_nnvm_checkpoint(tmp_path):
    """Symbol-graph checkpoint (module save_checkpoint format) serves
    through the same predictor."""
    import mxnet_tpu.symbol as sym

    data = sym.Variable("data")
    w = sym.Variable("fc_weight")
    b = sym.Variable("fc_bias")
    out = sym.FullyConnected(data, w, b, num_hidden=3, name="fc")
    out = sym.Activation(out, act_type="relu")

    rs = np.random.RandomState(1)
    wv = rs.randn(3, 6).astype(np.float32)
    bv = rs.randn(3).astype(np.float32)
    from mxnet_tpu import serialization

    prefix = str(tmp_path / "legacy")
    out.save(f"{prefix}-symbol.json")
    serialization.save_ndarrays(f"{prefix}-0000.params", {
        "arg:fc_weight": nd.array(wv), "arg:fc_bias": nd.array(bv)})

    pred = Predictor(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    assert pred.input_names == ["data"]
    x = rs.randn(5, 6).astype(np.float32)
    got = pred.predict(x).asnumpy()
    want = np.maximum(x @ wv.T + bv, 0.0)
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


def test_input_validation(tmp_path):
    prefix, x, _ = _exported_mlp(tmp_path)
    pred = Predictor(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    with pytest.raises(mx.MXNetError):
        pred.set_input("nope", x)
    with pytest.raises(mx.MXNetError):
        pred.forward()  # nothing staged


def test_cache_stats_and_compile_registration(tmp_path):
    """Per-signature compile cache stats: misses only on new
    (batch, length) signatures, hits on replays, and the telemetry
    counters/cost registry see each compile exactly once."""
    import mxnet_tpu.symbol as sym
    from mxnet_tpu import serialization, telemetry
    from mxnet_tpu.telemetry import costs

    data = sym.Variable("data")
    w = sym.Variable("fc_weight")
    b = sym.Variable("fc_bias")
    out = sym.FullyConnected(data, w, b, num_hidden=4, flatten=False,
                             name="fc")
    rs = np.random.RandomState(2)
    prefix = str(tmp_path / "stats")
    out.save(f"{prefix}-symbol.json")
    serialization.save_ndarrays(f"{prefix}-0000.params", {
        "arg:fc_weight": nd.array(rs.randn(4, 6).astype(np.float32)),
        "arg:fc_bias": nd.array(rs.randn(4).astype(np.float32))})
    pred = Predictor(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    assert pred.cache_stats() == {"hits": 0, "misses": 0, "signatures": 0}

    telemetry.enable(memory=False)
    try:
        pred.predict(rs.randn(2, 6).astype(np.float32))      # miss
        pred.predict(rs.randn(2, 6).astype(np.float32))      # hit
        pred.predict(rs.randn(8, 6).astype(np.float32))      # miss
        pred.predict(rs.randn(2, 3, 6).astype(np.float32))   # miss
        pred.predict(rs.randn(8, 6).astype(np.float32))      # hit
        st = pred.cache_stats()
        assert st["signatures"] == 3
        assert st["misses"] == 3
        assert st["hits"] == 2
        c = telemetry.counters()
        assert c["predictor.compile"] == 3
        assert c["predictor.cache_hit"] == 2
        # each signature registered with the cost registry once, and
        # WITHOUT per-execution attribution (the CachedOp inside is the
        # single source of truth for executed flops)
        ent = [e for e in costs.snapshot() if e["kind"] == "predictor"]
        assert len(ent) == 3
        assert all(e["executions"] == 0 for e in ent)
    finally:
        telemetry.disable()
