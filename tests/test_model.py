"""Legacy mx.model.FeedForward + symbolic-training convergence tests
(reference model: the legacy model API tests + train smoke tests,
SURVEY §2.4 misc row / §4 tests/python/train).

The convergence assertions here are load-bearing: output heads must
auto-create their ``{name}_label`` variable (reference FListInputNames
contract) or Module/FeedForward silently train without labels."""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.symbol as sym


def _toy():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="r1")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 8)).astype("f")
    y = (X[:, 0] > 0).astype("f")
    return net, X, y


def test_output_heads_autocreate_label_vars():
    net, _, _ = _toy()
    assert "softmax_label" in net.list_arguments()
    d = sym.var("d")
    reg = sym.LinearRegressionOutput(sym.FullyConnected(d, num_hidden=1,
                                                        name="f"),
                                     name="lro")
    assert "lro_label" in reg.list_arguments()
    # explicit label symbol still takes precedence
    lab = sym.var("mylabel")
    s2 = sym.SoftmaxOutput(sym.var("x"), lab, name="s2")
    assert "mylabel" in s2.list_arguments()
    assert "s2_label" not in s2.list_arguments()


def test_module_fit_actually_learns():
    """Regression: labels must reach SoftmaxOutput's backward — without
    the auto label var, Module trained on garbage and stayed at chance."""
    net, X, y = _toy()
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=10, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.3})
    acc = mod.score(it, "acc")[0][1]
    assert acc > 0.9, f"symbolic training did not learn (acc={acc})"


def test_feedforward_fit_predict_score():
    net, X, y = _toy()
    model = mx.model.FeedForward(net, num_epoch=10, optimizer="sgd",
                                 initializer=mx.init.Xavier(),
                                 learning_rate=0.3)
    model.fit(mx.io.NDArrayIter(X, y, batch_size=16))
    pred = model.predict(mx.io.NDArrayIter(X, batch_size=16))
    assert pred.shape == (64, 2)
    assert (pred.argmax(1) == y).mean() > 0.9
    acc = model.score(mx.io.NDArrayIter(X, y, batch_size=16))
    assert acc > 0.9


def test_feedforward_save_load(tmp_path):
    net, X, y = _toy()
    model = mx.model.FeedForward(net, num_epoch=3, optimizer="sgd",
                                 initializer=mx.init.Xavier(),
                                 learning_rate=0.3)
    model.fit(mx.io.NDArrayIter(X, y, batch_size=16))
    prefix = str(tmp_path / "ff")
    model.save(prefix, 3)
    loaded = mx.model.FeedForward.load(prefix, 3)
    assert set(loaded.arg_params) == set(model.arg_params)
    onp.testing.assert_allclose(
        loaded.arg_params["fc1_weight"].asnumpy(),
        model.arg_params["fc1_weight"].asnumpy())


def test_softmax_output_label_free_inference():
    """SoftmaxOutput without a bound label still runs forward (reference
    contract: label only feeds backward)."""
    from mxnet_tpu import nd

    x = nd.array([[1.0, 2.0, 0.5]])
    out = nd.SoftmaxOutput(x)
    onp.testing.assert_allclose(out.asnumpy().sum(), 1.0, rtol=1e-6)


def test_regression_output_trains():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=1, name="fc")
    net = sym.LinearRegressionOutput(net, name="lro")
    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 4)).astype("f")
    w = onp.array([1.0, -2.0, 3.0, 0.5], "f")
    y = (X @ w).astype("f")
    it = mx.io.NDArrayIter(X, y.reshape(-1, 1), batch_size=16,
                           label_name="lro_label")
    mod = mx.mod.Module(net, context=mx.cpu(), label_names=["lro_label"])
    mod.fit(it, num_epoch=30, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.2}, eval_metric="mse")
    mse = mod.score(it, "mse")[0][1]
    assert mse < 0.05, f"regression head did not learn (mse={mse})"
