"""Mixture-of-Experts layer + expert parallelism tests.

Reference: NONE — MoE/EP is ABSENT in the reference (SURVEY §2.3 D9);
new TPU-native capability.  Test model: op-level equivalences (identical
experts == dense MLP), routing invariants (capacity, balance), gradient
flow through router and experts, and GSPMD ep-sharding equivalence on the
virtual 8-device mesh.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel
from mxnet_tpu.models import moe
from mxnet_tpu.test_utils import assert_almost_equal


def _mk(router="topk", e=4, k=2, h=16, i=32, cf=8.0):
    mx.random.seed(0)
    blk = moe.MoEMLP(h, i, e, k, cf, router)
    blk.initialize(mx.init.Xavier())
    return blk


def test_forward_shape_and_finite():
    for router in ("topk", "expert_choice"):
        blk = _mk(router)
        x = nd.array(np.random.RandomState(0)
                     .randn(2, 6, 16).astype(np.float32))
        y = blk(x)
        assert y.shape == (2, 6, 16)
        assert np.isfinite(y.asnumpy()).all()


def test_identical_experts_match_dense_mlp():
    """With every expert holding the SAME weights and ample capacity, the
    top-k combine (gates renormalised to sum 1) must equal a single dense
    SwiGLU MLP — routing becomes irrelevant."""
    h, i = 16, 32
    blk = _mk("topk", e=4, k=2, h=h, i=i, cf=16.0)
    rs = np.random.RandomState(1)
    gw = rs.randn(i, h).astype(np.float32) * 0.3
    uw = rs.randn(i, h).astype(np.float32) * 0.3
    dw = rs.randn(h, i).astype(np.float32) * 0.3
    blk.gate_weight.set_data(nd.array(np.tile(gw, (4, 1, 1))))
    blk.up_weight.set_data(nd.array(np.tile(uw, (4, 1, 1))))
    blk.down_weight.set_data(nd.array(np.tile(dw, (4, 1, 1))))
    x = nd.array(rs.randn(2, 5, h).astype(np.float32))
    y = blk(x).asnumpy()

    xn = x.asnumpy()
    g = xn @ gw.T
    dense = (g * (1 / (1 + np.exp(-g))) * (xn @ uw.T)) @ dw.T
    assert_almost_equal(y, dense, rtol=1e-4, atol=1e-5)


def test_expert_choice_balanced_by_construction():
    blk = _mk("expert_choice", e=4, k=2, cf=1.0)
    x = nd.array(np.random.RandomState(2)
                 .randn(2, 16, 16).astype(np.float32))
    y = blk(x)
    assert np.isfinite(y.asnumpy()).all()
    # every expert processes exactly capacity tokens — nothing to assert
    # beyond finiteness + shape here; balance is structural (top_k over
    # the token axis always fills C slots per expert)
    assert y.shape == x.shape


def test_gradients_flow_to_router_and_experts():
    blk = _mk("topk")
    x = nd.array(np.random.RandomState(3)
                 .randn(2, 8, 16).astype(np.float32))
    x.attach_grad()
    with moe.collect_aux() as aux:
        with autograd.record():
            y = blk(x)
            loss = (y ** 2).mean() + 0.01 * aux[0]
        loss.backward()
    assert np.isfinite(x.grad.asnumpy()).all()
    rg = blk.router_weight.grad().asnumpy()
    eg = blk.gate_weight.grad().asnumpy()
    assert np.abs(rg).sum() > 0, "router got no gradient (aux loss path)"
    assert np.abs(eg).sum() > 0, "experts got no gradient"


def test_capacity_drops_overflow_tokens():
    """cf tiny => capacity 1 per expert: most tokens dropped from the
    expert path (output 0 for them), kept tokens still finite."""
    blk = _mk("topk", e=2, k=1, cf=0.01)
    x = nd.array(np.random.RandomState(4)
                 .randn(1, 16, 16).astype(np.float32))
    y = blk(x).asnumpy()
    # at most e*capacity = 2 tokens got expert output; rest must be 0
    nonzero_tokens = (np.abs(y[0]).sum(-1) > 1e-7).sum()
    assert nonzero_tokens <= 2


def test_aux_collect_raises_under_hybridize():
    blk = _mk("topk")
    x = nd.array(np.random.RandomState(5)
                 .randn(1, 4, 16).astype(np.float32))
    blk(x)  # resolve
    blk.hybridize()
    with moe.collect_aux():
        with pytest.raises(mx.MXNetError):
            blk(x)


def test_mixtral_tiny_trains():
    from mxnet_tpu.models import llama

    mx.random.seed(0)
    net = llama.mixtral_tiny(attn_mode="sdpa", moe_router="expert_choice")
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    rs = np.random.RandomState(0)
    ids = nd.array(rs.randint(0, 256, (2, 16)), dtype="int32")
    labels = nd.array(rs.randint(0, 256, (2, 16)), dtype="int32")
    losses = []
    for _ in range(5):
        with autograd.record():
            logits = net(ids)
            loss = nd.softmax_cross_entropy(
                logits.reshape((-1, 256)), labels.reshape((-1,))).mean()
        loss.backward()
        trainer.step(2)
        losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0], f"mixtral loss did not fall: {losses}"


def test_ep_sharding_matches_replicated():
    """GSPMD correctness: expert-parallel sharded forward == replicated
    forward on the 8-device mesh."""
    blk = _mk("topk", e=4, k=2)
    x_np = np.random.RandomState(6).randn(2, 8, 16).astype(np.float32)
    y_ref = blk(nd.array(x_np)).asnumpy()

    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    with parallel.mesh_scope(mesh):
        moe.shard_moe(blk, mesh)
        x = parallel.shard_batch(nd.array(x_np))
        y = blk(x).asnumpy()
    assert_almost_equal(y, y_ref, rtol=1e-4, atol=1e-5)


def test_ep_training_step_on_mesh():
    """Full train step: mixtral-tiny over dp×ep×tp with dist_tpu_sync."""
    from mxnet_tpu.models import llama

    mesh = parallel.make_mesh({"dp": 2, "ep": 2, "tp": 2})
    with parallel.mesh_scope(mesh):
        mx.random.seed(0)
        net = llama.mixtral_tiny(attn_mode="sdpa",
                                 moe_router="expert_choice")
        net.initialize(mx.init.Xavier())
        llama.shard_llama(net, mesh)
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-3},
                                kvstore="dist_tpu_sync")
        rs = np.random.RandomState(0)
        ids = parallel.shard_batch(
            nd.array(rs.randint(0, 256, (4, 16)), dtype="int32"))
        labels = parallel.shard_batch(
            nd.array(rs.randint(0, 256, (4, 16)), dtype="int32"))
        with autograd.record():
            logits = net(ids)
            loss = nd.softmax_cross_entropy(
                logits.reshape((-1, 256)), labels.reshape((-1,))).mean()
        loss.backward()
        trainer.step(4)
        assert np.isfinite(float(loss.asscalar()))


def _train_router_balance(use_aux, steps=40):
    """Train a topk MoE whose router is INITIALIZED COLLAPSED (every
    token prefers expert 0); return the final max expert-assignment
    fraction.  The load-balance aux loss must pull it apart."""
    mx.random.seed(3)
    blk = _mk("topk", e=4, k=1, cf=8.0)
    x0 = nd.array(np.random.RandomState(7)
                  .randn(2, 16, 16).astype(np.float32))
    blk(x0)  # resolve shapes
    # collapse: bias the router hard toward expert 0
    rw = np.array(blk.router_weight.data().asnumpy())
    rw[0] += 2.0
    blk.router_weight.set_data(nd.array(rw))
    trainer = gluon.Trainer(blk.collect_params(), "adam",
                            {"learning_rate": 5e-2})
    rs = np.random.RandomState(11)
    for _ in range(steps):
        x = nd.array(rs.randn(2, 16, 16).astype(np.float32))
        with autograd.record():
            with moe.collect_aux() as aux:
                y = blk(x)
                task = ((y - x) ** 2).mean()  # any well-posed target
                loss = task + 0.5 * sum(aux) if use_aux else task
        loss.backward()
        trainer.step(2)

    # measured assignment distribution on held-out data
    xe = np.random.RandomState(19).randn(4, 32, 16).astype(np.float32)
    logits = xe.reshape(-1, 16) @ blk.router_weight.data().asnumpy().T
    frac = np.bincount(logits.argmax(-1), minlength=4) / logits.shape[0]
    return float(frac.max())


def test_aux_loss_rebalances_collapsed_router():
    """D9 depth (VERDICT r3 weak 7): the Switch-style load-balance aux
    loss must actively fix router collapse — trained WITH the aux term,
    a router initialized to send every token to expert 0 spreads out;
    trained WITHOUT it, it stays collapsed.  This is the property that
    makes topk-MoE training converge at scale, not just compile."""
    with_aux = _train_router_balance(True)
    without_aux = _train_router_balance(False)
    # e=4 ideal balance = 0.25; the aux-trained router must land near it
    # (measured 0.28) while the no-aux control stays visibly skewed
    # (measured 0.43 — task gradients alone reduce but don't fix the
    # collapse)
    assert with_aux < 0.35, (
        f"aux loss failed to rebalance the router: max fraction "
        f"{with_aux} (no-aux control: {without_aux})")
    assert without_aux > with_aux + 0.05, (
        f"aux loss shows no balancing effect over the control: "
        f"{with_aux} vs {without_aux}")
