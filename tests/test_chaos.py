"""Chaos lane (round 6 tentpole, layer 4): tools/chaos.py drives a real
2-rank loopback job while killing random ranks with a mixed
SIGTERM/SIGKILL schedule.  The job must survive on its own — launcher
failure detection + backoff relaunch + checkpoint resume (and, for
SIGTERM, the consensus drain path) — and finish with parameters
byte-identical to an undisturbed run.

This is the tier-1 smoke of the chaos story; the heavier scenarios
(fault-specific assertions, drain byte-identity with a launcher-level
SIGTERM) live in tests/test_fault_injection.py, and the TPU lane in
tests_tpu/test_tpu_chaos.py.
"""
import json
import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
WORKER = os.path.join(REPO, "tests", "_preempt_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run(cmd, env, timeout=420):
    proc = subprocess.Popen(cmd, env=env, start_new_session=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        log, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    return proc.returncode, log


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_chaos_mixed_signals_survives_byte_identically(tmp_path):
    d = str(tmp_path)
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, CKPT_DIR=d + "/ck", TOTAL_STEPS="12",
               OUT_FILE=d + "/out_", STEP_SLEEP="0.25",
               MXT_LAUNCH_PLATFORM="cpu")
    summary_file = d + "/chaos.json"
    # seed 3's schedule delivers one SIGKILL and one SIGTERM — both
    # recovery paths (crash relaunch, consensus drain) in one run
    rc, log = _run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "-n", "2", "--kills", "2", "--mix", "mixed", "--seed", "3",
         "--min-delay", "1.0", "--max-delay", "2.5",
         "--max-restarts", "6", "--backoff-base", "0.1",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         "--summary", summary_file,
         "--", sys.executable, WORKER], env)
    assert rc == 0, log[-3000:]
    with open(summary_file) as f:
        summary = json.load(f)
    assert summary["survived"]
    assert len(summary["injections"]) >= 1, summary
    assert sum(summary["restarts"].values()) >= 1, summary
    assert {i["signal"] for i in summary["injections"]} <= \
        {"SIGTERM", "SIGKILL"}

    # undisturbed oracle, same world size and step count
    env_o = dict(env, CKPT_DIR=d + "/cko", OUT_FILE=d + "/oracle_",
                 STEP_SLEEP="0")
    rc2, log2 = _run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, WORKER], env_o)
    assert rc2 == 0, log2[-3000:]
    for rank in (0, 1):
        got = np.load(d + f"/out_{rank}.npy")
        want = np.load(d + f"/oracle_{rank}.npy")
        assert got.tobytes() == want.tobytes(), \
            f"rank {rank} diverged after chaos"
