"""Fault injection for the distributed paths (VERDICT r4 #9).

The claim "beyond-reference fault tolerance" (SURVEY §2.3 D10) is proven
here under INJECTED failure, not just clean restart:

1. A rank of a 2-process dist_tpu_sync job is SIGKILLed mid-iteration
   (after backward, before the gradient allreduce).  The survivor blocks
   inside the collective — the launcher's failure detection must reap
   the group, relaunch it, and the ranks must resume from the last
   atomic checkpoint and reconverge BYTE-IDENTICALLY to the
   uninterrupted run.  Reference analog: the dmlc tracker tears down the
   job on a dead worker; recovery there was manual.
2. A dist_async worker dies mid-push with a torn frame on the wire.  The
   server must drop the truncated frame AND the dead connection, keep
   every complete previous push, and keep serving the surviving worker.

What is NOT survivable (documented, by design): loss of the checkpoint
directory, and SIGKILL of the parameter server itself (workers surface a
connection error at the next sync point — test_dist_async.py
::test_error_surfaces_at_sync_point).
"""
import os
import signal
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
TOOLS = os.path.join(REPO, "tools")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_TRAIN_WORKER = r"""
import os
import signal
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd

mx.parallel.initialize()
rank, n = jax.process_index(), jax.process_count()

mx.random.seed(42)
net = gluon.nn.Dense(3, use_bias=True)
net.initialize(mx.init.Xavier())
net(nd.ones((1, 5)))
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        kvstore="dist_tpu_sync")

ckpt_dir = os.environ["CKPT_DIR"]
total = int(os.environ["TOTAL_STEPS"])
fault_step = int(os.environ.get("FAULT_STEP", "-1"))
marker = os.environ["FAULT_MARKER"]

start, _ = checkpoint.resume(ckpt_dir, net, trainer)
if start:
    print(f"rank {rank}: resumed from step {start}", flush=True)

full = np.random.RandomState(0).randn(8 * total, 5).astype(np.float32)
for step in range(start, total):
    shard = full[step * 8:(step + 1) * 8][rank * 4:(rank + 1) * 4]
    x = nd.array(shard)
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    if rank == 1 and step == fault_step and not os.path.exists(marker):
        # crash AFTER backward, BEFORE the gradient allreduce: the
        # survivor is left blocking inside the collective
        with open(marker, "w") as f:
            f.write("crashed")
        os.kill(os.getpid(), signal.SIGKILL)
    trainer.step(8)                        # global batch
    if rank == 0:
        checkpoint.save_checkpoint(ckpt_dir, step + 1, net, trainer)

np.save(os.environ["OUT_FILE"] + str(rank) + ".npy",
        np.concatenate([net.weight.data().asnumpy().ravel(),
                        net.bias.data().asnumpy().ravel()]))
"""


def _run_job(tmp_path, tag, fault_step, max_restarts, total=6,
             timeout=420):
    script = tmp_path / "worker.py"
    script.write_text(_TRAIN_WORKER)
    ckpt = str(tmp_path / f"ckpt_{tag}")
    out = str(tmp_path / f"out_{tag}_")
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, CKPT_DIR=ckpt, OUT_FILE=out,
               TOTAL_STEPS=str(total), FAULT_STEP=str(fault_step),
               FAULT_MARKER=str(tmp_path / f"marker_{tag}"),
               MXT_LAUNCH_PLATFORM="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", "2",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         "--max-restarts", str(max_restarts),
         sys.executable, str(script)],
        env=env, start_new_session=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        stdout, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    return proc.returncode, stdout, out


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_rank_kill_is_detected_and_resume_reconverges(tmp_path):
    """The end-to-end fault story: kill rank 1 mid-iteration, launcher
    reaps + relaunches, ranks resume from the atomic checkpoint, final
    params byte-identical to the uninterrupted oracle."""
    rc, log, out = _run_job(tmp_path, "fault", fault_step=3,
                            max_restarts=1)
    assert rc == 0, log[-3000:]
    assert "resumed from step 3" in log, log[-3000:]
    assert "restart 1/1" in log, log[-3000:]

    rc2, log2, oracle_out = _run_job(tmp_path, "oracle", fault_step=-1,
                                     max_restarts=0)
    assert rc2 == 0, log2[-3000:]

    for rank in (0, 1):
        got = np.load(out + f"{rank}.npy")
        want = np.load(oracle_out + f"{rank}.npy")
        assert got.tobytes() == want.tobytes(), \
            f"rank {rank} diverged after fault+resume"


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_rank_failure_without_restart_fails_fast(tmp_path):
    """Failure DETECTION alone: with max_restarts=0 the launcher must
    reap the blocked survivor and exit nonzero promptly — not wedge
    until the outer timeout (the pre-monitor behavior)."""
    t0 = time.time()
    rc, log, _ = _run_job(tmp_path, "nodetect", fault_step=1,
                          max_restarts=0, timeout=240)
    assert rc != 0
    assert time.time() - t0 < 180, "launcher wedged on the dead rank"


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_sigterm_drain_exits_75_and_resumes_byte_identically(tmp_path):
    """Satellite (c) + tentpole layer 2: SIGTERM on the LAUNCHER forwards
    to every rank; the ranks finish their in-flight step, rank 0 cuts a
    drain checkpoint, everyone exits PREEMPTED_EXIT (75) and the
    launcher returns it without burning a crash restart.  A relaunch
    resumes from the drain checkpoint and ends byte-identical to an
    uninterrupted run."""
    worker = os.path.join(REPO, "tests", "_preempt_worker.py")
    marker = str(tmp_path / "mark")
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, CKPT_DIR=str(tmp_path / "ck"),
               TOTAL_STEPS="10", OUT_FILE=str(tmp_path / "out_"),
               STEP_SLEEP="0.3", MARKER_FILE=marker,
               MARKER_AFTER_STEP="1", MXT_LAUNCH_PLATFORM="cpu")

    def launch(n=2, extra_env=None):
        e = dict(env, **(extra_env or {}))
        return subprocess.Popen(
            [sys.executable, os.path.join(TOOLS, "launch.py"), "-n",
             str(n), "--coordinator", f"127.0.0.1:{_free_port()}",
             sys.executable, worker],
            env=e, start_new_session=True, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    proc = launch()
    t0 = time.time()
    while not os.path.exists(marker):
        assert proc.poll() is None, proc.communicate()[0][-3000:]
        assert time.time() - t0 < 180, "no training progress"
        time.sleep(0.05)
    os.kill(proc.pid, signal.SIGTERM)          # "preemption notice"
    log, _ = proc.communicate(timeout=180)
    assert proc.returncode == 75, (proc.returncode, log[-3000:])
    assert "draining at step" in log, log[-3000:]

    proc2 = launch(extra_env={"STEP_SLEEP": "0"})
    log2, _ = proc2.communicate(timeout=180)
    assert proc2.returncode == 0, log2[-3000:]
    assert "resumed from step" in log2, log2[-3000:]

    env_o = dict(env, CKPT_DIR=str(tmp_path / "cko"),
                 OUT_FILE=str(tmp_path / "oracle_"), STEP_SLEEP="0",
                 MARKER_FILE=str(tmp_path / "mark2"))
    proc3 = launch(extra_env={"CKPT_DIR": env_o["CKPT_DIR"],
                              "OUT_FILE": env_o["OUT_FILE"],
                              "STEP_SLEEP": "0",
                              "MARKER_FILE": env_o["MARKER_FILE"]})
    log3, _ = proc3.communicate(timeout=180)
    assert proc3.returncode == 0, log3[-3000:]
    for rank in (0, 1):
        got = np.load(str(tmp_path / f"out_{rank}.npy"))
        want = np.load(str(tmp_path / f"oracle_{rank}.npy"))
        assert got.tobytes() == want.tobytes(), \
            f"rank {rank} diverged after drain+resume"


def test_dist_async_worker_killed_mid_push_server_survives(monkeypatch):
    """Torn-frame injection: a worker dies mid-push leaving a TRUNCATED
    frame on the socket.  The server must discard the partial frame,
    drop that connection, keep all completed pushes, and keep serving
    the other worker."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.kvstore.dist_async import (AsyncPSKVStore, PSServer,
                                              serve_forever)
    from mxnet_tpu.test_utils import assert_almost_equal

    monkeypatch.setenv("MXT_PS_SECRET", "fault-test-secret")
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        w0 = AsyncPSKVStore(root_uri=uri, rank=0, num_workers=2)
        w1 = AsyncPSKVStore(root_uri=uri, rank=1, num_workers=2)
        w0.init("k", nd.zeros((16,)))
        w0.set_optimizer(mx.optimizer.SGD(learning_rate=-1.0))
        for _ in range(5):
            w0.push("k", nd.ones((16,)))
        w0.wait_all()

        # "die mid-push": write a frame header promising 1 MiB, then
        # only a fragment of the body, then sever the socket abruptly —
        # exactly what a SIGKILLed worker's kernel does to its stream.
        sock = w0._chan._sock
        sock.sendall(struct.pack("<Q", 1 << 20))
        sock.sendall(b"\x00" * 100)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))  # RST, no FIN handshake
        sock.close()

        # the survivor keeps working and sees every COMPLETE push
        time.sleep(0.3)
        w1.push("k", nd.ones((16,)))
        w1.wait_all()
        out = nd.zeros((16,))
        w1.pull("k", out=out)
        assert_almost_equal(out, np.full((16,), 6.0))
        w1.close()
    finally:
        srv.shutdown()


def test_dist_async_server_survives_garbage_frames(monkeypatch):
    """Wire fuzz: raw connections feeding junk (random bytes, huge
    length prefixes, valid-length-invalid-body frames) must each be
    dropped without taking the server down or corrupting state for
    authenticated workers."""
    from mxnet_tpu import nd
    from mxnet_tpu.kvstore.dist_async import (AsyncPSKVStore, PSServer,
                                              serve_forever)
    from mxnet_tpu.test_utils import assert_almost_equal

    monkeypatch.setenv("MXT_PS_SECRET", "fuzz-test-secret")
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        w = AsyncPSKVStore(root_uri=uri, rank=0, num_workers=1)
        w.init("k", nd.zeros((8,)))

        rng = np.random.RandomState(0)
        for i in range(12):
            s = socket.socket()
            s.settimeout(5)
            s.connect(("127.0.0.1", port))
            mode = i % 3
            try:
                if mode == 0:      # pure junk
                    s.sendall(rng.bytes(64))
                elif mode == 1:    # absurd length prefix, no body
                    s.sendall(struct.pack("<Q", 1 << 40))
                else:              # plausible length, garbage body
                    s.sendall(struct.pack("<Q", 128) + rng.bytes(128))
            except OSError:
                pass  # server may RST mid-send; that's a pass
            s.close()

        # the real worker is unaffected
        time.sleep(0.3)
        out = nd.zeros((8,))
        w.pull("k", out=out)
        assert_almost_equal(out, np.zeros((8,)))
        w.close()
    finally:
        srv.shutdown()
