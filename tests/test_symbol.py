"""Symbol API tests.

Modeled on the reference's tests/python/unittest/test_symbol.py:? —
composition, introspection, shape inference, json round-trip, bind and
executor forward/backward.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym as S


def _mlp():
    data = S.Variable("data")
    fc1 = S.FullyConnected(data, num_hidden=16, name="fc1")
    act = S.Activation(fc1, act_type="relu", name="relu1")
    fc2 = S.FullyConnected(act, num_hidden=4, name="fc2")
    return S.SoftmaxOutput(fc2, S.Variable("softmax_label"), name="softmax")


def test_compose_and_introspection():
    out = _mlp()
    assert out.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert out.list_outputs() == ["softmax_output"]
    assert out.list_auxiliary_states() == []
    assert out.name == "softmax"


def test_infer_shape_mlp():
    out = _mlp()
    args, outs, aux = out.infer_shape(data=(8, 20), softmax_label=(8,))
    assert args == [(8, 20), (16, 20), (16,), (4, 16), (4,), (8,)]
    assert outs == [(8, 4)]
    assert aux == []


def test_infer_shape_conv_batchnorm():
    data = S.Variable("data")
    c = S.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                      name="conv0")
    b = S.BatchNorm(c, name="bn0")
    p = S.Pooling(b, kernel=(2, 2), stride=(2, 2), pool_type="max")
    args, outs, aux = p.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(p.list_arguments(), args))
    assert d["conv0_weight"] == (8, 3, 3, 3)
    assert d["conv0_bias"] == (8,)
    assert d["bn0_gamma"] == (8,)
    assert dict(zip(p.list_auxiliary_states(), aux)) == {
        "bn0_moving_mean": (8,), "bn0_moving_var": (8,)}
    assert outs == [(2, 8, 4, 4)]
    assert p.list_auxiliary_states() == ["bn0_moving_mean", "bn0_moving_var"]


def test_infer_shape_partial():
    data = S.Variable("data")
    fc = S.FullyConnected(data, num_hidden=4)
    args, outs, aux = fc.infer_shape_partial()
    assert all(a is None for a in args)
    with pytest.raises(mx.MXNetError):
        fc.infer_shape()  # nothing known


def test_variable_shape_attr():
    data = S.Variable("data", shape=(4, 6))
    fc = S.FullyConnected(data, num_hidden=3)
    args, outs, _ = fc.infer_shape()
    assert outs == [(4, 3)]


def test_json_roundtrip(tmp_path):
    out = _mlp()
    js = out.tojson()
    back = S.load_json(js)
    assert back.list_arguments() == out.list_arguments()
    assert back.list_outputs() == out.list_outputs()
    f = tmp_path / "m-symbol.json"
    out.save(str(f))
    again = S.load(str(f))
    a1, o1, _ = again.infer_shape(data=(2, 10), softmax_label=(2,))
    a2, o2, _ = out.infer_shape(data=(2, 10), softmax_label=(2,))
    assert a1 == a2 and o1 == o2


def test_get_internals_and_lookup():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names
    fc1 = internals["fc1_output"]
    args, outs, _ = fc1.infer_shape(data=(8, 20))
    assert outs == [(8, 16)]


def test_arithmetic_and_scalar_ops():
    a = S.Variable("a")
    b = S.Variable("b")
    expr = (a * 2.0 + b) / 4.0 - 1.0
    exe = expr.bind(args={"a": mx.nd.ones((3,)) * 2,
                          "b": mx.nd.ones((3,)) * 4})
    out = exe.forward()[0]
    np.testing.assert_allclose(out.asnumpy(), np.full(3, 1.0), rtol=1e-6)


def test_eval():
    a = S.Variable("a")
    out = (a + 1.0).eval(a=mx.nd.zeros((2, 2)))
    np.testing.assert_allclose(out[0].asnumpy(), np.ones((2, 2)))


def test_group_and_multi_output():
    x = S.Variable("x")
    parts = S.split(x, num_outputs=2, axis=1, name="sp")
    assert parts.num_outputs == 2
    g = S.Group([parts[0], parts[1]])
    exe = g.bind(args={"x": mx.nd.array(np.arange(8).reshape(2, 4))})
    o0, o1 = exe.forward()
    assert o0.shape == (2, 2) and o1.shape == (2, 2)


def test_simple_bind_forward_backward():
    out = _mlp()
    exe = out.simple_bind(grad_req="write", data=(8, 20),
                          softmax_label=(8,))
    rng = np.random.RandomState(0)
    for name in ("fc1_weight", "fc2_weight"):
        arr = exe.arg_dict[name]
        arr._data = mx.nd.array(
            rng.randn(*arr.shape).astype(np.float32) * 0.1)._data
    x = rng.randn(8, 20).astype(np.float32)
    y = rng.randint(0, 4, (8,)).astype(np.float32)
    outs = exe.forward(is_train=True, data=x, softmax_label=y)
    np.testing.assert_allclose(outs[0].asnumpy().sum(axis=1),
                               np.ones(8), rtol=1e-5)
    exe.backward()
    # SoftmaxOutput gradient: softmax - onehot
    p = outs[0].asnumpy()
    oh = np.eye(4)[y.astype(int)]
    # fc2 bias grad equals column sums of (p - onehot)
    np.testing.assert_allclose(exe.grad_dict["fc2_bias"].asnumpy(),
                               (p - oh).sum(axis=0), rtol=1e-4, atol=1e-5)


def test_fluent_methods():
    x = S.Variable("x")
    y = x.reshape(shape=(2, 6)).sum(axis=1)
    exe = y.bind(args={"x": mx.nd.ones((3, 4))})
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), [6.0, 6.0])


def test_regression_outputs():
    x = S.Variable("data")
    lbl = S.Variable("label")
    out = S.LinearRegressionOutput(S.FullyConnected(x, num_hidden=1,
                                                    name="fc"), lbl)
    exe = out.simple_bind(grad_req="write", data=(4, 3), label=(4, 1))
    rng = np.random.RandomState(1)
    exe.arg_dict["fc_weight"]._data = mx.nd.array(
        rng.randn(1, 3).astype(np.float32))._data
    xs = rng.randn(4, 3).astype(np.float32)
    ys = rng.randn(4, 1).astype(np.float32)
    outs = exe.forward(is_train=True, data=xs, label=ys)
    exe.backward()
    pred = outs[0].asnumpy()
    expected = pred - ys  # grad wrt fc output
    np.testing.assert_allclose(exe.grad_dict["fc_bias"].asnumpy(),
                               expected.sum(axis=0), rtol=1e-4, atol=1e-5)


def test_blockgrad_and_makeloss():
    x = S.Variable("x")
    blocked = S.BlockGrad(x * 3.0)
    exe = blocked.simple_bind(grad_req="write", x=(2,))
    exe.forward(is_train=True, x=np.ones(2, np.float32))
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(), np.zeros(2))
