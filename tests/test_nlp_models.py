"""Transformer/BERT model tests (reference: GluonNLP model tests —
forward shapes, masking semantics, gradient flow, hybridize parity)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import bert, transformer


def test_attention_op_matches_manual():
    b, t, h, d = 2, 5, 2, 4
    rng = np.random.RandomState(0)
    q = rng.rand(b, t, h, d).astype(np.float32)
    k = rng.rand(b, t, h, d).astype(np.float32)
    v = rng.rand(b, t, h, d).astype(np.float32)
    out = nd.dot_product_attention(nd.array(q), nd.array(k),
                                   nd.array(v)).asnumpy()
    logits = np.einsum("btnh,bsnh->bnts", q, k) / np.sqrt(d)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    expect = np.einsum("bnts,bsnh->btnh", probs, v)
    assert np.allclose(out, expect, atol=1e-4)


def test_attention_causal():
    b, t, h, d = 1, 4, 1, 2
    q = nd.ones((b, t, h, d))
    k = mx.random.uniform(shape=(b, t, h, d))
    v_np = np.arange(t, dtype=np.float32).reshape(1, t, 1, 1) * \
        np.ones((b, t, h, d), np.float32)
    out = nd.dot_product_attention(q, k, nd.array(v_np),
                                   causal=True).asnumpy()
    # first position can only attend to itself → output == v[0]
    assert np.allclose(out[0, 0], v_np[0, 0], atol=1e-5)


def test_interleaved_selfatt_ops():
    t, b, e, heads = 3, 2, 8, 2
    qkv = mx.random.uniform(shape=(t, b, 3 * e))
    scores = nd.interleaved_matmul_selfatt_qk(qkv, heads=heads)
    assert scores.shape == (b * heads, t, t)
    att = nd.softmax(scores, axis=-1)
    out = nd.interleaved_matmul_selfatt_valatt(qkv, att, heads=heads)
    assert out.shape == (t, b, e)


def test_multi_head_attention_block():
    mha = transformer.MultiHeadAttention(units=16, num_heads=4)
    mha.initialize()
    x = mx.random.uniform(shape=(2, 6, 16))
    out = mha(x, x, x)
    assert out.shape == (2, 6, 16)


def test_transformer_encoder():
    enc = transformer.TransformerEncoder(num_layers=2, units=16,
                                         hidden_size=32, num_heads=2,
                                         max_length=32, dropout=0.0)
    enc.initialize()
    out = enc(mx.random.uniform(shape=(2, 7, 16)))
    assert out.shape == (2, 7, 16)


def test_transformer_mt_forward_backward():
    net = transformer.Transformer(src_vocab_size=50, tgt_vocab_size=60,
                                  num_layers=2, units=16, hidden_size=32,
                                  num_heads=2, max_length=32, dropout=0.0)
    net.initialize()
    src = nd.array(np.random.randint(0, 50, (2, 6)))
    tgt = nd.array(np.random.randint(0, 60, (2, 5)))
    with autograd.record():
        out = net(src, tgt)
        loss = out.sum()
    loss.backward()
    assert out.shape == (2, 5, 60)
    g = net.src_embed.weight.grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_transformer_causal_decode():
    """Changing a future target token must not change earlier logits."""
    net = transformer.Transformer(src_vocab_size=20, tgt_vocab_size=20,
                                  num_layers=1, units=8, hidden_size=16,
                                  num_heads=2, max_length=16, dropout=0.0)
    net.initialize()
    src = nd.array([[1, 2, 3]])
    tgt1 = nd.array([[4, 5, 6]])
    tgt2 = nd.array([[4, 5, 9]])
    o1 = net(src, tgt1).asnumpy()
    o2 = net(src, tgt2).asnumpy()
    assert np.allclose(o1[0, :2], o2[0, :2], atol=1e-5)
    assert not np.allclose(o1[0, 2], o2[0, 2])


def test_bert_tiny_forward():
    net = bert.bert_tiny(vocab_size=100)
    net.initialize()
    tokens = nd.array(np.random.randint(0, 100, (2, 12)))
    segments = nd.array(np.zeros((2, 12)))
    seq, pooled, nsp, mlm = net(tokens, segments)
    assert seq.shape == (2, 12, 128)
    assert pooled.shape == (2, 128)
    assert nsp.shape == (2, 2)
    assert mlm.shape == (2, 12, 100)


def test_bert_valid_length_masking():
    """Padding tokens beyond valid_length must not affect real positions."""
    net = bert.bert_tiny(vocab_size=50, dropout=0.0)
    net.initialize()
    t1 = np.random.randint(1, 50, (1, 8))
    t2 = t1.copy()
    t2[0, 6:] = 3  # change padding region
    vl = nd.array([6.0])
    s1 = net(nd.array(t1), None, vl)[0].asnumpy()
    s2 = net(nd.array(t2), None, vl)[0].asnumpy()
    assert np.allclose(s1[0, :6], s2[0, :6], atol=1e-4)


def test_bert_classifier_train_step():
    base = bert.bert_tiny(vocab_size=60, dropout=0.0)
    net = bert.BERTClassifier(base, num_classes=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tokens = nd.array(np.random.randint(0, 60, (4, 10)))
    labels = nd.array([0, 1, 2, 0])
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(tokens), labels)
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0]


def test_bert_hybridize_parity():
    net = bert.bert_tiny(vocab_size=40, dropout=0.0, use_decoder=False,
                         use_classifier=False)
    net.initialize()
    tokens = nd.array(np.random.randint(0, 40, (2, 6)))
    imp = net(tokens)[0].asnumpy()
    net.hybridize()
    hyb = net(tokens)[0].asnumpy()
    assert np.allclose(imp, hyb, atol=1e-4)
